"""Device-backed placement policies: the ``device='tpu'`` policy variants.

Each policy wraps a fused kernel from :mod:`pivot_tpu.ops.kernels`.  Per
scheduling tick the runtime hands over dense arrays (``TickContext``); the
wrapper pads the task axis to a bucket size (so XLA compiles one program
per (bucket, H) pair, never per tick), pushes the small per-tick inputs to
the device, runs the scan kernel, and pulls back an ``[T] int32`` placement
vector.  The ``[Z, Z]`` topology matrices are pushed once at bind time
(:class:`DeviceTopology`).

Cross-backend parity: these wrappers consume the same Philox uniforms and
the same task pre-ordering as the numpy policies, so on CPU (x64) the
placements are bit-identical; on TPU (f32) near-boundary fits may round
differently, which the acceptance criterion tolerates (BASELINE.md —
identical makespan/cost rankings).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from pivot_tpu.ops.kernels import (
    DeviceTopology,
    best_fit_kernel,
    cost_aware_kernel,
    first_fit_kernel,
    opportunistic_kernel,
)
from pivot_tpu.ops.pallas_kernels import cost_aware_pallas
from pivot_tpu.sched import Policy, TickContext
from pivot_tpu.sched.policies import CostAwarePolicy, _sort_decreasing
from pivot_tpu.sched.rand import tick_uniforms

__all__ = [
    "TpuOpportunisticPolicy",
    "TpuFirstFitPolicy",
    "TpuBestFitPolicy",
    "TpuCostAwarePolicy",
    "pad_bucket",
]

_BUCKETS = (8, 32, 128, 512, 2048, 8192)


def pad_bucket(n: int) -> int:
    """Smallest bucket ≥ n (caps XLA program count at len(_BUCKETS))."""
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + 8191) // 8192) * 8192


class _DevicePolicyBase(Policy):
    """Shared bind/pad machinery for device-backed policies."""

    dtype = jnp.float32

    def __init__(self):
        self.topology: Optional[DeviceTopology] = None
        self._scheduler = None

    def bind(self, scheduler) -> None:
        self._scheduler = scheduler
        self.topology = DeviceTopology.from_cluster(scheduler.cluster, self.dtype)

    def _padded(self, ctx: TickContext, order: Optional[List[int]] = None):
        """(avail [H,4], demands [B,4], valid [B]) device-ready, task axis
        padded to a bucket; ``order`` optionally permutes tasks."""
        T = ctx.n_tasks
        B = pad_bucket(T)
        demands = ctx.demands if order is None else ctx.demands[order]
        # Stage in the policy dtype — an f32 buffer here would quantize
        # demands and break the f64 cross-backend parity contract.
        dem = np.zeros((B, 4), dtype=np.dtype(self.dtype))
        dem[:T] = demands
        valid = np.zeros(B, dtype=bool)
        valid[:T] = True
        avail = jnp.asarray(ctx.avail, dtype=self.dtype)
        return avail, jnp.asarray(dem, dtype=self.dtype), jnp.asarray(valid)

    @staticmethod
    def _unpad(placements, T: int, order: Optional[List[int]] = None) -> np.ndarray:
        out = np.asarray(placements[:T]).astype(np.int64)
        if order is None:
            return out
        unscrambled = np.full(T, -1, dtype=np.int64)
        unscrambled[np.asarray(order)] = out
        return unscrambled


class TpuOpportunisticPolicy(_DevicePolicyBase):
    name = "opportunistic_tpu"

    def place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        avail, dem, valid = self._padded(ctx)
        u = np.zeros(valid.shape[0], dtype=np.float64)
        u[:T] = tick_uniforms(ctx.scheduler.seed or 0, ctx.tick_seq, T)
        placements, _ = opportunistic_kernel(
            avail, dem, valid, jnp.asarray(u, dtype=self.dtype)
        )
        return self._unpad(placements, T)


class TpuFirstFitPolicy(_DevicePolicyBase):
    name = "first_fit_tpu"

    def __init__(self, decreasing: bool = False):
        super().__init__()
        self.decreasing = decreasing

    def place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        order = None
        if self.decreasing:
            order = _sort_decreasing(ctx.demands, list(range(T)))
        avail, dem, valid = self._padded(ctx, order)
        placements, _ = first_fit_kernel(avail, dem, valid, strict=False)
        return self._unpad(placements, T, order)


class TpuBestFitPolicy(_DevicePolicyBase):
    name = "best_fit_tpu"

    def __init__(self, decreasing: bool = False):
        super().__init__()
        self.decreasing = decreasing

    def place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        order = None
        if self.decreasing:
            order = _sort_decreasing(ctx.demands, list(range(T)))
        avail, dem, valid = self._padded(ctx, order)
        placements, _ = best_fit_kernel(avail, dem, valid)
        return self._unpad(placements, T, order)


class TpuCostAwarePolicy(_DevicePolicyBase):
    """Cost-aware (PIVOT) placement on the device.

    Anchor grouping stays host-side (it walks the DAG and is memoized per
    task group — see ``CostAwarePolicy.group_tasks``); everything O(T × H)
    runs in the fused kernel.
    """

    name = "cost_aware_tpu"

    def __init__(
        self,
        bin_pack: str = "first-fit",
        sort_tasks: bool = False,
        sort_hosts: bool = False,
        host_decay: bool = False,
        use_pallas: Optional[bool] = None,
    ):
        super().__init__()
        assert bin_pack in ("first-fit", "best-fit")
        self.bin_pack = bin_pack
        self.sort_tasks = sort_tasks
        self.sort_hosts = sort_hosts
        self.host_decay = host_decay
        # The Pallas greedy kernel keeps the whole tick in VMEM (~5× the
        # scan kernel per tick on a v5e) but is f32-only; auto-enable on
        # the TPU backend, keep the scan kernel for CPU/f64 parity runs.
        self.use_pallas = use_pallas
        # Grouping logic shared verbatim with the CPU policy.
        self._grouper = CostAwarePolicy(
            bin_pack=bin_pack,
            sort_tasks=sort_tasks,
            sort_hosts=sort_hosts,
            host_decay=host_decay,
        )

    def place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        meta = ctx.meta
        storage = ctx.cluster.storage
        groups = self._grouper.group_tasks(ctx)

        order: List[int] = []
        anchor_zone = []
        new_group = []
        for anchor, idxs in groups.items():
            if not hasattr(anchor, "locality"):  # root group → random storage
                anchor = storage[int(ctx.scheduler.randomizer.choice(len(storage)))]
            if self.sort_tasks:
                idxs = _sort_decreasing(ctx.demands, idxs)
            az = meta.zone_index[anchor.locality]
            for j, i in enumerate(idxs):
                order.append(i)
                anchor_zone.append(az)
                new_group.append(j == 0)

        B = pad_bucket(T)
        az_arr = np.zeros(B, dtype=np.int32)
        az_arr[:T] = anchor_zone
        ng_arr = np.zeros(B, dtype=bool)
        ng_arr[:T] = new_group
        avail, dem, valid = self._padded(ctx, order)
        use_pallas = self.use_pallas
        if use_pallas is None:
            import jax

            use_pallas = (
                jax.default_backend() == "tpu" and self.dtype == jnp.float32
            )
        kernel = cost_aware_pallas if use_pallas else cost_aware_kernel
        placements, _ = kernel(
            avail,
            dem,
            valid,
            jnp.asarray(ng_arr),
            jnp.asarray(az_arr),
            self.topology.cost,
            self.topology.bw,
            self.topology.host_zone,
            jnp.asarray(ctx.host_task_counts, dtype=jnp.int32),
            bin_pack=self.bin_pack,
            sort_hosts=self.sort_hosts,
            host_decay=self.host_decay,
        )
        return self._unpad(placements, T, order)
