"""Device-backed placement policies: the ``device='tpu'`` policy variants.

Each policy wraps a fused kernel from :mod:`pivot_tpu.ops.kernels`.  Per
scheduling tick the runtime hands over dense arrays (``TickContext``); the
wrapper pads the task axis to a bucket size (so XLA compiles one program
per (bucket, H) pair, never per tick), pushes the small per-tick inputs to
the device, runs the scan kernel, and pulls back an ``[T] int32`` placement
vector.  The ``[Z, Z]`` topology matrices are pushed once at bind time
(:class:`DeviceTopology`).

Cross-backend parity: these wrappers consume the same Philox uniforms and
the same task pre-ordering as the numpy policies, so on CPU (x64) the
placements are bit-identical; on TPU (f32) near-boundary fits may round
differently, which the acceptance criterion tolerates (BASELINE.md —
identical makespan/cost rankings).

Adaptive dispatch (``adaptive=True``): a remote accelerator has a fixed
per-call latency floor (dispatch + execution + result fetch — 76–86 ms
over this image's tunnel, median 78.5 ms, re-measured on the live chip in
round 2: ``figures/tpu_validate_r02.json``) that dwarfs small ticks, while the
in-process numpy twin costs ~50 ns per task×host cell.  The wrapper keeps
an online affine latency model of both sides — twin: cells × per-cell
cost; device: probed link floor + cells × per-cell cost (the placement
kernels stay sequential over tasks, so device time grows with the batch
too).  Round-6 re-fit for the two-phase kernels: on the CPU backend the
slim phase-2 pass stops at the last VALID task instead of walking the
padded bucket, so the model's device cell count uses the true T there
(bucket-based cells would overcharge a T=600 tick in the 2048 bucket
~3.4×, exactly the early-exit the rewrite bought); non-CPU backends keep
the bucket-padded count (``phase2="auto"`` resolves to the scan form
there — see ``ops/kernels.py``).
Per-cell terms are EMAs of observed calls at meaningful sizes; the floor
is probe-only (folding full call times into it would starve the device
path permanently).  Each tick routes to whichever side the model predicts
decisively faster.  The numpy twins consume the
same RNG draws per tick as the kernels, so the stream stays aligned no
matter which side serves a given tick.

Reproducibility tradeoff: routing depends on measured latencies, so on
the TPU backend (f32 kernels vs f64 twins) two seeded runs of the same
command may round a near-boundary fit differently if machine load shifts
a tick across the crossover.  RNG streams stay aligned either way, and
metric *rankings* are unaffected (the acceptance criterion, BASELINE.md);
when exact bitwise repeatability matters, use ``--device numpy`` /
``naive`` or ``--no-adaptive``, all of which route deterministically.  This is SURVEY.md §7 hard part
(d) — host↔device latency at 5-sim-second ticks — resolved by *not*
paying the link when the tick cannot amortize it.
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from pivot_tpu.ops.kernels import (
    DeviceTopology,
    best_fit_kernel,
    cost_aware_kernel,
    first_fit_kernel,
    opportunistic_kernel,
)
from pivot_tpu.ops.shard import (
    DEAD_AVAIL,
    HOST_AXIS,
    REPLICA_AXIS,
    best_fit_kernel_sharded,
    cost_aware_kernel_sharded,
    elastic_fold_carry,
    elastic_host_extent,
    elastic_pad_rows,
    first_fit_kernel_sharded,
    opportunistic_kernel_sharded,
    sharded_fused_tick_run,
    sharded_resident_carry_init,
    sharded_resident_span_run,
)
from pivot_tpu.infra.faults import DeviceLostError
from pivot_tpu.parallel.mesh import host_axis_size
from pivot_tpu.ops.pallas_kernels import (
    cost_aware_pallas,
    cost_aware_pallas_batched,
)
from pivot_tpu.ops.tickloop import (
    edit_bucket,
    fused_tick_run,
    resident_carry_clone,
    resident_carry_init,
    resident_span_run,
    span_bucket,
)
from pivot_tpu.sched import Policy, TickContext
from pivot_tpu.sched.policies import (
    BestFitPolicy,
    CostAwarePolicy,
    FirstFitPolicy,
    OpportunisticPolicy,
    _sort_decreasing,
    resolve_risk,
    resolve_root_anchor,
    resolve_weights,
)
from pivot_tpu.sched.rand import tick_uniforms
from pivot_tpu.utils import enable_compilation_cache as _enable_compilation_cache

__all__ = [
    "TpuOpportunisticPolicy",
    "TpuFirstFitPolicy",
    "TpuBestFitPolicy",
    "TpuCostAwarePolicy",
    "pad_bucket",
]

_BUCKETS = (8, 32, 128, 512, 2048, 8192)


def pad_bucket(n: int) -> int:
    """Smallest bucket ≥ n (caps XLA program count at len(_BUCKETS))."""
    for b in _BUCKETS:
        if n <= b:
            return b
    return ((n + 8191) // 8192) * 8192


# Shared wedged-tunnel guard (moved to utils in round 2 so the estimator
# CLI flows get the same protection as the policy path).
from pivot_tpu.utils import ensure_live_backend as _ensure_live_backend  # noqa: E402


class _SpanOutcome:
    """A priced span: slot-indexed per-tick placements, host-fetched."""

    __slots__ = ("placements",)

    def __init__(self, placements: np.ndarray):
        self.placements = placements


class _DegradeGuard:
    """Graceful-degradation state machine: closed → degraded → half-open.

    *Closed*: the device kernel serves; ``after`` CONSECUTIVE failures
    trip the guard to *degraded* (any success resets the streak;
    individual failures are served by the CPU twin per-tick).
    *Degraded*: the twin serves every decision.  Every ``probe_every``
    twin-served decisions the guard goes *half-open* for one decision:
    the device kernel is shadow-run and its placements diffed against
    the twin's.  The decision is served by the TWIN either way — a probe
    can never change a placement — and an exact match promotes the
    device back to closed (a transient fault no longer strands the
    policy on CPU forever, the round-20 ``degrade_after`` fix); a
    mismatch or a raise keeps the twin serving and restarts the probe
    countdown.  ``probe_every=None`` restores the permanent fallback.

    ``after=None`` disables the guard entirely — kernel exceptions stay
    fatal (the batch-experiment default)."""

    #: Twin-served decisions between half-open probes.  Small enough
    #: that a recovered device is re-engaged within one serving flush,
    #: large enough that a hard-down device is not shadow-dispatched
    #: (and its raise re-swallowed) every tick.
    PROBE_EVERY = 64

    __slots__ = ("after", "probe_every", "degraded", "kernel_failures",
                 "consecutive", "since_probe", "probes", "promotions")

    def __init__(self, after: Optional[int],
                 probe_every: Optional[int] = PROBE_EVERY):
        self.after = after
        self.probe_every = probe_every
        self.degraded = False
        self.kernel_failures = 0
        self.consecutive = 0
        self.since_probe = 0
        self.probes = 0
        self.promotions = 0

    def note_success(self) -> None:
        self.consecutive = 0

    def note_failure(self, exc: BaseException, logger) -> None:
        self.kernel_failures += 1
        self.consecutive += 1
        if self.consecutive >= self.after:
            self.degraded = True
            self.since_probe = 0
            logger.error(
                "device kernel failed %d times consecutively — degrading "
                "to the CPU twin%s: %s",
                self.consecutive,
                (" permanently" if self.probe_every is None
                 else f" (half-open probe every {self.probe_every})"),
                exc,
            )
        else:
            logger.warning(
                "device kernel failed (%d/%d before degradation): %s",
                self.consecutive, self.after, exc,
            )

    def should_probe(self) -> bool:
        """Call once per degraded (twin-served) decision; True on the
        decision that should shadow-run the device kernel."""
        if not self.degraded or self.probe_every is None:
            return False
        self.since_probe += 1
        if self.since_probe >= self.probe_every:
            self.since_probe = 0
            return True
        return False

    def note_probe(self, ok: bool, logger,
                   exc: Optional[BaseException] = None) -> None:
        self.probes += 1
        if ok:
            self.degraded = False
            self.consecutive = 0
            self.promotions += 1
            logger.info(
                "half-open probe matched the CPU twin — promoting the "
                "device kernel back (probe %d)", self.probes,
            )
        elif exc is not None:
            logger.warning(
                "half-open probe raised — device still down: %s", exc,
            )
        else:
            logger.warning(
                "half-open probe DIVERGED from the CPU twin — keeping "
                "the twin (probe %d)", self.probes,
            )


class _ResidentState:
    """Bookkeeping for the resident span tier (round 20): the pending
    device carry, the splice checkpoint + staged span operands, and the
    once-staged market risk table.  One per policy; reset at bind (new
    cluster = new [H] layout)."""

    __slots__ = ("splice", "carry", "checkpoint", "staging",
                 "risk_table_np", "risk_table_dev", "spans", "splices",
                 "edit_rows")

    def __init__(self, splice: bool):
        self.splice = splice
        self.reset()

    def reset(self) -> None:
        self.carry = None
        self.checkpoint = None
        self.staging = None
        self.risk_table_np = None
        self.risk_table_dev = None
        self.spans = 0
        self.splices = 0
        self.edit_rows = 0


class _SplicePlan:
    """Plan view with a splice's extended slot set — what ``_span_kw``
    rebuilds the per-slot streams from (same grid/horizon, more slots)."""

    __slots__ = ("slots", "arrive", "n_ticks", "grid")

    def __init__(self, slots, arrive, n_ticks, grid):
        self.slots = slots
        self.arrive = arrive
        self.n_ticks = n_ticks
        self.grid = grid


#: Span-kw keys whose device buffers are staged once and reused across
#: spans (bind-time topology, the per-market cost stack / risk table) —
#: excluded from per-dispatch h2d byte counts; everything else in a span
#: dispatch is freshly staged each call.
_SPAN_CACHED_KW = frozenset(
    {"cost_zz", "bw_zz", "host_zone", "totals", "cost_stack",
     "risk_table"}
)


def _staged_nbytes(args, kw) -> int:
    """Freshly staged host→device bytes of one span dispatch: operand
    nbytes minus the cached-buffer keys.  Exact (no sampling) — the
    profiler accumulates it per family on every call."""
    n = 0
    for a in args:
        n += int(getattr(a, "nbytes", 0))
    for k, v in kw.items():
        if k in _SPAN_CACHED_KW:
            continue
        n += int(getattr(v, "nbytes", 0))
    return n


def _dispatch_shape(args, kw) -> dict:
    """Shape labels of one kernel dispatch for the profiler's device
    spans and analytic prediction: H from the [H, 4] availability
    operand, B from the padded batch, K from a fused span's static
    tick count.  Sim-free and clock-free — the profiler owns the wall
    side (obs-boundary contract)."""
    shape = {}
    if args and hasattr(args[0], "shape") and len(args[0].shape) == 2:
        shape["h"] = int(args[0].shape[0])
    if len(args) > 1 and hasattr(args[1], "shape") and args[1].shape:
        shape["b"] = int(args[1].shape[0])
    n_ticks = kw.get("n_ticks")
    if isinstance(n_ticks, int):
        shape["k"] = n_ticks
    return shape


def _probe_device_floor() -> float:
    """Measure the fixed per-call device latency: dispatch + execution of a
    trivial kernel + result fetch (the fetch is what actually waits on the
    remote execution — async dispatch returns immediately)."""
    import jax

    f = jax.jit(lambda x: x + 1.0)
    x = np.zeros((8,), np.float32)
    np.asarray(f(x))  # compile outside the timed reps
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()  # graftcheck: ignore[determinism] -- latency probe seeding the adaptive cost model; route choice is placement-neutral (twin-parity contract, tests/test_tpu_validate.py)
        np.asarray(f(x))
        best = min(best, time.perf_counter() - t0)  # graftcheck: ignore[determinism] -- same probe window
    return best


class _DevicePolicyBase(Policy):
    """Shared bind/pad/adaptive-dispatch machinery for device policies."""

    dtype = jnp.float32

    #: Seed for the numpy-twin cost model: seconds per task×host cell
    #: (refined online from observed twin calls).
    _CELL_COST_SEED = 5e-8
    #: Only ticks at least this many cells update the cell-cost EMA.  The
    #: twin's real cost is affine (constant dispatch overhead + per-cell
    #: work); fitting the linear model on small ticks folds the constant
    #: into the slope and overestimates big ticks several-fold, which made
    #: the device engage in the marginal region where it cannot win.  At
    #: 256k cells the constant (~0.3 ms) is noise against ~12 ms of
    #: per-cell work.
    _CELL_COST_MIN_SAMPLE = 1 << 18
    #: Engage the device only when the predicted twin time beats the
    #: predicted device time by this factor.  Marginal wins cannot repay
    #: the one-time XLA compile of each (bucket, H) program, and prediction
    #: error near the crossover flips the verdict tick to tick.
    _DEVICE_ADVANTAGE = 2.0
    #: Seed for the device per-cell cost (s/cell) — the scan kernel is
    #: sequential over tasks, so device time is floor + cells × this, NOT
    #: a constant.  Measured 1.47e-8 s/cell on the live v5e tunnel
    #: (affine fit over T∈{8..8192}×H=600, round-2 real-chip campaign,
    #: figures/tpu_validate_r02.json); refined online from observed calls.
    _DEVICE_CELL_COST_SEED = 1.5e-8
    #: Every Nth device-routed tick is served by the twin instead, so the
    #: cell-cost model keeps getting samples even when it (possibly
    #: wrongly) predicts the device is faster — without exploration an
    #: overestimating seed would starve the twin for mid-size ticks with
    #: no recovery path (the mirror of device-floor starvation).
    _EXPLORE_EVERY = 16
    #: Exploration only happens in the uncertain region — predicted twin
    #: time within this factor of the device floor.  Far past the
    #: crossover the verdict cannot flip for any plausible model error,
    #: and an unconditional sample there would cost O(cells) for nothing;
    #: this bounds each exploration sample to ~margin × floor seconds.
    _EXPLORE_MARGIN = 8.0

    def __init__(self, adaptive: bool = False, phase2="auto",
                 degrade_after: Optional[int] = None,
                 risk_weight: float = 0.0, rework_cost: float = 1.0,
                 weights=None):
        self.topology: Optional[DeviceTopology] = None
        self._scheduler = None
        self.adaptive = adaptive
        #: The typed scoring-weight vector (round 16,
        #: ``pivot_tpu/search/weights.py``) — the one source of truth
        #: the legacy ``risk_weight``/``rework_cost`` knobs fold into
        #: (``policies.resolve_weights``).  Risk-aware placement
        #: (``infra/market.py``): the per-tick [H] vector is resolved
        #: host-side by the SAME ``policies.resolve_risk`` the CPU
        #: policies use (0.0 weight, no market, or an all-calm tick ⇒
        #: None ⇒ the risk-free compiled program — and today's outputs
        #: — bit for bit).  Score exponents off the default vector are
        #: rejected by the cost-aware subclass (the scan kernels score
        #: with the reference exponent shape — see its __init__).
        self.weights = resolve_weights(weights, risk_weight, rework_cost)
        self.risk_weight = self.weights.risk_weight
        self.rework_cost = self.weights.rework_cost
        # Device-staged market state, reset at bind: per-segment [Z, Z]
        # cost slices (per-tick dispatches) and the [P, Z, Z] stack
        # (fused spans) — staged once per price segment / market, not
        # per tick.
        self._market_cost_dev: dict = {}
        self._market_stack_dev = None
        #: Graceful degradation (serving self-healing, ``serve/driver``):
        #: after ``degrade_after`` CONSECUTIVE device-kernel failures
        #: the policy falls back to its CPU twin — the same numpy oracle
        #: the parity suite holds the kernels to, so placements don't
        #: change, only the backend serving them.  Individual failures
        #: are served by the twin too (per-tick fallback) and counted in
        #: ``kernel_failures``.  Since round 20 the fallback is
        #: HALF-OPEN, not permanent: every ``_DegradeGuard.PROBE_EVERY``
        #: twin decisions the device kernel is shadow-run and promoted
        #: back on an exact placement match.  ``None`` (default) keeps
        #: kernel exceptions fatal — batch experiments must not silently
        #: mask a broken kernel as twin output.
        self._degrade = _DegradeGuard(degrade_after)
        #: Phase-2 mode forwarded to the two-phase kernels
        #: (``ops/kernels.py``): "auto" (slim on CPU, scan elsewhere),
        #: "scan", "slim", or an int chunk size for speculative chunk
        #: commit — the latency-floor-bound shape, where the phase-1
        #: ``totals`` pre-filter steers the fill speculation.
        self.phase2 = phase2
        # Cross-run dispatch coalescing (sched.batch): when a BatchClient
        # is attached, every device-kernel call routes through it so G
        # concurrently-stepped runs share one vmapped dispatch per tick.
        self._batch_client = None
        # Pod-scale host sharding (ops/shard.py): when a mesh is enabled,
        # every placement dispatch — per-tick kernels AND fused spans —
        # runs host-sharded over the mesh's ``host`` axis.
        self._mesh = None
        # Elastic re-layout (round 20): when :meth:`reshard` lands on a
        # ladder rung the true host count does not divide, every staged
        # [H] operand pads to this extent with dead-sentinel rows (inert
        # by masked-argmin — ops/shard.py elastic helpers).  None = no
        # padding (the launch shape, and every dividing rung).
        self._host_extent: Optional[int] = None
        self._padded_host_zone = None  # lazily padded bind-time [H] zone
        # Elastic fault gate (round 20, ``serve/elastic.py``): a callable
        # ``gate(env_now)`` invoked at the top of every dispatch entry
        # point (place / place_span).  The elastic mesh manager installs
        # one that raises DeviceLostError when a DeviceFaultPlan window
        # covers the dispatch instant — deterministic, replayable device
        # loss at the exact boundary a real loss would surface.  None
        # (default) = zero cost, bit-identical to the ungated stack.
        self._fault_gate = None
        # Resident span tier (round 20, ``ops/tickloop.py`` resident
        # section): when enabled, consecutive ``place_span`` calls keep
        # the [H] carry device-resident and ship only deltas.
        self._resident: Optional[_ResidentState] = None
        # Sampled dispatch profiler (``pivot_tpu/obs/profiler.py``):
        # attached via enable_profiler, consulted only on the DIRECT
        # dispatch path in _call_kernel (batched dispatches are timed
        # at the batcher's flush boundary instead — timing here would
        # measure slot park time, not the device).  None = zero cost.
        self._profiler = None
        self._topology_host: Optional[DeviceTopology] = None
        self._cpu_twin: Optional[Policy] = None  # set by subclasses
        self._cpu_cell_cost = self._CELL_COST_SEED
        self._device_floor = 0.0  # per-call latency floor, seconds
        self._device_cell_cost = self._DEVICE_CELL_COST_SEED
        self._device_routed = 0
        self._twin_routed = 0
        # Buckets whose program has already run once: the first call per
        # (bucket) includes XLA compile time, which must not poison the
        # per-cell EMA (one 5 s compile read as per-cell work would starve
        # the device path for the rest of the process).
        self._warm_buckets: set = set()

    # -- degrade-guard views (backward-compat attribute surface) -----------
    # ``serve/session.py`` meters and the chaos suite read these off the
    # policy; the state itself lives in the guard.
    @property
    def degrade_after(self) -> Optional[int]:
        return self._degrade.after

    @degrade_after.setter
    def degrade_after(self, value: Optional[int]) -> None:
        self._degrade.after = value

    @property
    def degraded(self) -> bool:
        return self._degrade.degraded

    @degraded.setter
    def degraded(self, value: bool) -> None:
        self._degrade.degraded = bool(value)

    @property
    def kernel_failures(self) -> int:
        return self._degrade.kernel_failures

    @kernel_failures.setter
    def kernel_failures(self, value: int) -> None:
        self._degrade.kernel_failures = int(value)

    @property
    def _consecutive_failures(self) -> int:
        return self._degrade.consecutive

    @_consecutive_failures.setter
    def _consecutive_failures(self, value: int) -> None:
        self._degrade.consecutive = int(value)

    def apply_weights(self, weights) -> None:
        """Live weight promotion, forwarded to the CPU twin so kernel
        and twin keep scoring from the same vector (adaptive routing and
        per-tick fallback must not change decisions mid-promotion)."""
        super().apply_weights(weights)
        if self._cpu_twin is not None:
            self._cpu_twin.apply_weights(self.weights)

    def bind(self, scheduler) -> None:
        self._scheduler = scheduler
        _ensure_live_backend()
        _enable_compilation_cache()
        self.topology = DeviceTopology.from_cluster(scheduler.cluster, self.dtype)
        self._topology_host = None  # rebind = new cluster; drop the host cache
        self._market_cost_dev = {}  # rebind = new market/meta; drop staging
        self._market_stack_dev = None
        if self._resident is not None:
            self._resident.reset()  # rebind = new [H] layout; drop the carry
        self._padded_host_zone = None  # rebind = new topology buffers
        if self._mesh is not None:
            if self._host_extent is not None:
                # A resharded (elastic) mesh re-derives its pad extent
                # for the new host count instead of demanding
                # divisibility — pad rows are inert either way.
                self._refresh_host_extent()
            else:
                self._check_mesh_hosts(self._mesh)  # rebind: re-validate
        if self._cpu_twin is not None:
            self._cpu_twin.bind(scheduler)
        if self.adaptive:
            self._device_floor = _probe_device_floor()

    # -- cross-run dispatch batching --------------------------------------
    def enable_batching(self, client) -> None:
        """Attach a :class:`pivot_tpu.sched.batch.BatchClient`: device
        kernel calls block at the tick boundary and are coalesced with
        the other grid runs' co-pending ticks into one vmapped dispatch
        (bit-identical placements — see ``sched/batch.py``).

        Composes with host sharding (round 17): when this policy also
        has :meth:`enable_sharding` on, the batcher must carry a 2-D
        ``replica × host`` mesh whose host axis matches this policy's —
        coalesced flushes then run the ``shard_map(vmap(...))`` 2-D
        program (``ops/shard.py``), G runs × S host shards in one
        dispatch.

        Requires deterministic routing: the adaptive twin routes on
        measured latencies, which would make batch membership — and on
        the f32 TPU backend, placements — timing-dependent.
        """
        if self.adaptive:
            raise ValueError(
                "cross-run batching needs deterministic dispatch — "
                "construct the policy with adaptive=False"
            )
        if self._resident is not None:
            raise ValueError(
                "resident span carries cannot ride the cross-run "
                "batcher — it re-stages every operand from host numpy "
                "at the flush boundary (sched/batch.py stacks with "
                "np.asarray), which is exactly the staging the resident "
                "tier eliminates; drop enable_resident() or the batcher"
            )
        if self._mesh is not None:
            self._check_batch_mesh(client)
        self._batch_client = client

    def _check_batch_mesh(self, client) -> None:
        """Composing batching × sharding needs the batcher's 2-D mesh to
        agree with this policy's host mesh: same host-axis size, so the
        coalesced 2-D program and the direct 1-D sharded dispatches
        partition the SAME [H] layout (contiguous blocks per shard)."""
        bmesh = getattr(client, "mesh", None)
        n = host_axis_size(self._mesh)
        if (
            bmesh is None
            or HOST_AXIS not in bmesh.shape
            # No replica axis ⇒ nothing to stack the [G] run axis over:
            # the coalesced 2-D program (and _replica_mesh_for) key on
            # it, so a host-only batcher mesh would fail at flush time.
            or REPLICA_AXIS not in bmesh.shape
            or host_axis_size(bmesh) != n
        ):
            raise ValueError(
                "composing host sharding with cross-run batching needs "
                "a DispatchBatcher built on a 2-D replica x host mesh "
                f"whose host axis matches enable_sharding's ({n} "
                "shards) — build one with parallel.mesh."
                "build_hybrid_mesh(host_parallel=...) and pass it as "
                "DispatchBatcher(mesh=...)"
            )

    # -- pod-scale host sharding (round 10, ``ops/shard.py``) --------------
    def enable_sharding(self, mesh) -> None:
        """Partition the placement hot path's host axis over ``mesh``'s
        ``host`` axis: the [H, 4] availability snapshot, the quarantine
        mask, and every per-step score row live shard-resident, and the
        phase-2 argmin runs as the two-stage (score, global-index)
        reduce — bit-identical placements to the single-device kernels
        (``tests/test_shard.py``).  Fused spans ride the sharded span
        driver with the carry staying shard-resident between ticks.

        Composes with cross-run batching (round 17) when the attached
        batcher carries a matching 2-D ``replica × host`` mesh — see
        :meth:`enable_batching`.

        Requires deterministic routing (no adaptive twin — its latency
        model prices a single-device program) and the scan-family
        kernels (no Pallas, no realtime-bw rows).
        """
        if self.adaptive:
            raise ValueError(
                "host sharding needs deterministic dispatch — construct "
                "the policy with adaptive=False"
            )
        if getattr(self, "use_pallas", False):
            raise ValueError(
                "the Pallas kernel keeps the whole tick in one core's "
                "VMEM — it has no sharded form; drop use_pallas=True"
            )
        if getattr(self, "realtime_bw", False):
            raise ValueError(
                "realtime_bw has no sharded form (per-tick sampled "
                "[G, H] rows would reshard every dispatch)"
            )
        if host_axis_size(mesh) < 1:
            raise ValueError("mesh has an empty host axis")
        if self._batch_client is not None:
            prev, self._mesh = self._mesh, mesh
            try:
                self._check_batch_mesh(self._batch_client)
            except ValueError:
                self._mesh = prev
                raise
        if self.topology is not None:
            self._check_mesh_hosts(mesh)
        self._mesh = mesh

    def _check_mesh_hosts(self, mesh) -> None:
        H = self.topology.n_hosts
        n = host_axis_size(mesh)
        if H % n:
            raise ValueError(
                f"cluster has H={H} hosts, not divisible over the "
                f"mesh's {n} host shards — pad the cluster to a "
                f"multiple of {n} hosts"
            )

    # -- elastic re-layout (round 20, ``serve/elastic.py``) ----------------
    def reshard(self, mesh) -> None:
        """Swap the host-sharding mesh for a NEW shape mid-serve — the
        shrink/regrow primitive of elastic mesh serving.  ``mesh`` is a
        surviving-shard mesh from the declared ladder (or None to
        collapse to the single-device layout).  When the true host count
        does not divide the new shape, every staged [H] operand pads to
        the elastic extent with dead-sentinel rows (DEAD_AVAIL
        availability + False live mask — inert by masked-argmin, so
        placements are bit-identical to an unpadded run; ``ops/shard.py``
        elastic helpers).  A pending resident carry is FOLDED onto the
        new layout (:func:`ops.shard.elastic_fold_carry` — a pure
        re-layout, bit-equal on the true host rows); the splice
        checkpoint is dropped (a splice cannot cross a reshard) and the
        next mirror-diff self-heals any divergence from the DES truth.
        Compile cost is bounded by the ladder: each shape's programs are
        cached (``lru_cache`` keyed on the mesh), so revisiting a rung
        compiles nothing."""
        if self.adaptive:
            raise ValueError(
                "elastic resharding needs deterministic dispatch — "
                "construct the policy with adaptive=False"
            )
        if self._batch_client is not None:
            raise ValueError(
                "elastic resharding does not compose with the cross-run "
                "batcher (its 2-D mesh is fixed at construction) — "
                "detach the batcher first"
            )
        if mesh is not None:
            if getattr(self, "use_pallas", False):
                raise ValueError(
                    "the Pallas kernel has no sharded form; drop "
                    "use_pallas=True"
                )
            if getattr(self, "realtime_bw", False):
                raise ValueError(
                    "realtime_bw has no sharded form (per-tick sampled "
                    "rows would reshard every dispatch)"
                )
            if host_axis_size(mesh) < 1:
                raise ValueError("mesh has an empty host axis")
        self._mesh = mesh
        self._padded_host_zone = None
        self._refresh_host_extent()
        rs = self._resident
        if rs is not None:
            if rs.carry is not None and self.topology is not None:
                rs.carry = elastic_fold_carry(
                    rs.carry, self.topology.n_hosts, mesh
                )
            rs.checkpoint = None
            rs.staging = None
            rs.risk_table_dev = None  # re-staged (padded) on next span

    def enable_fault_gate(self, gate) -> None:
        """Install (or clear, ``None``) the elastic fault gate — a
        callable ``gate(env_now)`` run at the top of every ``place`` /
        ``place_span`` dispatch.  The gate raises
        :class:`~pivot_tpu.infra.faults.DeviceLostError` when the
        dispatch instant falls inside a device-fault window, which
        propagates THROUGH the degradation guard (device loss is a
        mesh-level event, not kernel flakiness) up to the serving
        supervisor, which shrinks the mesh and requeues
        (``serve/elastic.py``)."""
        self._fault_gate = gate

    def _refresh_host_extent(self) -> None:
        """Recompute the elastic pad extent for the current (mesh,
        topology) pair — None when unsharded or when the host count
        divides the mesh (no padding, today's programs untouched)."""
        if self._mesh is None or self.topology is None:
            self._host_extent = None
            return
        H = self.topology.n_hosts
        extent = elastic_host_extent(H, host_axis_size(self._mesh))
        self._host_extent = None if extent == H else extent

    def _pad_avail_np(self, avail):
        """[H, 4] availability padded to the elastic extent with
        DEAD_AVAIL rows (no-op host-side passthrough when unpadded)."""
        if self._host_extent is None:
            return avail
        return elastic_pad_rows(
            np.asarray(avail, dtype=np.dtype(self.dtype)),
            self._host_extent, DEAD_AVAIL,
        )

    def _pad_h(self, arr, fill):
        """[H] host vector padded to the elastic extent with ``fill``."""
        if self._host_extent is None:
            return arr
        return elastic_pad_rows(np.asarray(arr), self._host_extent, fill)

    def _pad_tail(self, arr):
        """[..., H] array zero-padded on its TRAILING axis to the
        elastic extent (the risk-row/table layout)."""
        if self._host_extent is None:
            return arr
        arr = np.asarray(arr)
        pad = self._host_extent - arr.shape[-1]
        if pad <= 0:
            return arr
        widths = [(0, 0)] * (arr.ndim - 1) + [(0, pad)]
        return np.pad(arr, widths, constant_values=0)

    def _host_zone_arg(self, topo):
        """The [H] host→zone row for a dispatch: the bind-time device
        array normally; a padded copy (staged once per reshard) when the
        elastic extent is engaged (zone 0 for pad rows — harmless, a
        dead-sentinel host is unselectable)."""
        if self._host_extent is None:
            return topo.host_zone
        if self._padded_host_zone is None:
            self._padded_host_zone = self._stage(
                elastic_pad_rows(
                    np.asarray(topo.host_zone), self._host_extent, 0
                ),
                jnp.int32,
            )
        return self._padded_host_zone

    def _kernel_for(self, kernel, sharded_kernel):
        """The dispatch rung for one placement call: the single-device
        kernel (through the cross-run batcher when attached), its
        host-sharded twin when only a mesh is enabled, or — batching ×
        sharding composed — the single-device kernel identity routed
        through the batcher, whose 2-D mesh resolves coalesced flushes
        to the ``shard_map(vmap(...))`` program and lone flushes to the
        1-D sharded twin (``sched/batch.py``/``ops/shard.py``)."""
        if self._batch_client is not None:
            return functools.partial(self._call_kernel, kernel)
        if self._mesh is not None:
            return functools.partial(sharded_kernel, self._mesh)
        return functools.partial(self._call_kernel, kernel)

    # -- sampled dispatch profiling (round 15, ``obs/profiler.py``) --------
    def enable_profiler(self, profiler) -> None:
        """Attach a :class:`pivot_tpu.obs.DispatchProfiler`: a
        deterministic 1-in-N sample of this policy's direct device
        dispatches (per-tick kernels through :meth:`_call_kernel`,
        fused spans through :meth:`place_span`'s use of the same rung)
        is timed to completion and published as per-family latency
        summaries + ``device``-lane trace spans.  Placements are
        untouched — the profiler only times; ``None`` detaches.  When
        cross-run batching is enabled the batcher's flush boundary owns
        the timing instead (``DispatchBatcher(profiler=...)``)."""
        self._profiler = profiler

    def _call_kernel(self, kernel, *args, _h2d_bytes=0, **kw):
        """Kernel-call indirection: direct when unbatched, through the
        cross-run batcher when a client is attached.  Array-valued
        keyword arguments (the realtime-bw rows) batch along with the
        positional arrays; plain keywords stay static.  ``_h2d_bytes``
        (underscore: never a kernel kwarg) is the caller's count of
        freshly staged operand bytes, forwarded to the profiler's
        per-family transfer census on the direct path (batched
        dispatches are counted at the flush boundary instead)."""
        if self._batch_client is None:
            prof = self._profiler
            if prof is not None and prof.enabled:
                # The profiler owns the wall capture (obs-boundary:
                # this module stays clock-free) and the sampling
                # decision (deterministic per-family cadence).
                from pivot_tpu.obs.profiler import family_of

                return prof.profile(
                    family_of(kernel),
                    lambda: kernel(*args, **kw),
                    shape=_dispatch_shape(args, kw),
                    h2d_bytes=_h2d_bytes,
                )
            return kernel(*args, **kw)
        arr_kw = {k: v for k, v in kw.items() if hasattr(v, "shape")}
        static_kw = {k: v for k, v in kw.items() if k not in arr_kw}
        return self._batch_client.dispatch(kernel, args, arr_kw, static_kw)

    def _stage(self, x, dtype=None):
        """Per-tick operand staging: device-put for a direct dispatch;
        host numpy when batched — the batcher stacks host arrays and the
        jitted batch call stages them ONCE, whereas handing it device
        buffers would pay a device→host fetch per operand per tick on a
        remote backend (exactly the floor being amortized)."""
        if self._batch_client is not None:
            return (
                np.asarray(x) if dtype is None
                else np.asarray(x, dtype=np.dtype(dtype))
            )
        return jnp.asarray(x) if dtype is None else jnp.asarray(x, dtype=dtype)

    def _staged_topology(self) -> DeviceTopology:
        """Topology operands for a dispatch: the bind-time device arrays
        normally; a host copy (fetched once, cached) when batched."""
        if self._batch_client is None:
            return self.topology
        if self._topology_host is None:
            self._topology_host = DeviceTopology(
                *(np.asarray(a) for a in self.topology)
            )
        return self._topology_host

    # -- quarantine mask ---------------------------------------------------
    def _live_arg(self, ctx: TickContext):
        """The tick's [H] quarantine mask staged for the kernels' ``live``
        argument, or None when every host is live (None keeps the
        all-live compiled program — and today's outputs — untouched)."""
        live = ctx.live_mask
        if self._host_extent is not None:
            # Padded layout: the mask MUST materialize even when every
            # true host is live — None would mean "all live" and include
            # the dead-sentinel pad rows.
            full = np.zeros(self._host_extent, dtype=bool)
            full[: ctx.n_hosts] = (
                True if live is None else np.asarray(live, bool)
            )
            return self._stage(full)
        if live is None:
            return None
        return self._stage(live)

    # -- spot-market risk & prices (``infra/market.py``) -------------------
    def _risk_arg(self, ctx: TickContext):
        """The tick's [H] eviction-risk vector staged for the kernels'
        ``risk`` argument, or None when the term is disengaged
        (``resolve_risk`` — the shared resolver, so the device kernels
        and the CPU twins can never disagree about engagement)."""
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        if risk is None:
            return None
        return self._stage(self._pad_h(risk, 0.0), self.dtype)

    def _market_cost_arg(self, ctx: TickContext):
        """The tick's ``[Z, Z]`` egress-cost operand: the bind-time
        static matrix when no market is attached (today's buffers,
        today's programs), else the market's price-scaled slice for this
        tick's segment — staged once per segment and reused for every
        tick inside it."""
        market = getattr(ctx.scheduler, "market", None)
        if market is None:
            return self._staged_topology().cost
        seg = market.segment(ctx.env_now)
        buf = self._market_cost_dev.get(seg)
        if buf is None:
            buf = self._stage(
                market.cost_matrix_at(ctx.env_now, ctx.meta), self.dtype
            )
            self._market_cost_dev[seg] = buf
        return buf

    def _span_market_kw(self, ctx: TickContext, plan, K: int) -> dict:
        """The fused-span market operands (``ops/tickloop.py`` contract):
        ``risk_rows`` — the [K, H] per-tick risk stack over the span's
        exact grid instants (same per-tick values ``resolve_risk`` feeds
        the per-tick path, so span service and per-tick fallback stay
        placement-identical) — and, for the cost-aware arm, the
        [P, Z, Z] price-scaled ``cost_stack`` plus the per-span [K]
        ``cost_seg`` time-index row (the Philox-row pattern).  Empty dict
        in market-free worlds."""
        market = getattr(ctx.scheduler, "market", None)
        if market is None:
            return {}
        kw = {}
        k_dyn = plan.n_ticks
        if self.risk_weight:
            hz = ctx.host_zones
            w = self.risk_weight * self.rework_cost
            # Built in the POLICY dtype at source (dtype pass,
            # pivot_tpu/analysis/dtype.py): the f64 hazard products round
            # once on assignment — bit-identical to the old
            # cast-at-staging — and an x64 run can no longer stage a
            # double-width [K, H] buffer / fork the compile cache.
            He = self._host_extent or len(hz)
            rows = np.zeros((K, He), dtype=np.dtype(self.dtype))
            # One vectorized [k_dyn] segment lookup + [k_dyn, H] zone
            # gather — the same per-span time-index pattern as cost_seg —
            # instead of k_dyn Python-level hazard_vector calls.  Pad
            # columns (elastic extent) stay zero — their hosts are
            # dead-sentinel and unselectable anyway.
            seg = market.segment_indices(np.asarray(plan.grid[:k_dyn]))
            rows[:k_dyn, : len(hz)] = w * market.hazard[seg][:, hz]
            if rows.any():
                kw["risk_rows"] = self._stage(rows, self.dtype)
        return kw

    # -- graceful degradation ----------------------------------------------
    def _note_kernel_failure(self, exc: BaseException) -> None:
        self._degrade.note_failure(exc, self.logger)

    def _degraded_place(self, ctx: TickContext) -> np.ndarray:
        """A twin-served decision while degraded, with the half-open
        probe: on the probe cadence the device kernel is SHADOW-run and
        its placements diffed against the twin's — an exact match
        promotes the device back (:class:`_DegradeGuard`).  The decision
        returned is always the twin's, so placements are bit-identical
        whether or not this was a probe tick, and whatever the probe's
        verdict."""
        out = self._cpu_twin.place(ctx)
        if self._degrade.should_probe():
            try:
                shadow = self._device_place(ctx)
            except Exception as exc:  # noqa: BLE001 — probe of a dead device
                self._degrade.note_probe(False, self.logger, exc)
            else:
                self._degrade.note_probe(
                    np.array_equal(np.asarray(shadow), np.asarray(out)),
                    self.logger,
                )
        return out

    def _guarded_device_place(self, ctx: TickContext) -> np.ndarray:
        """Device dispatch with the degradation guard: a failing kernel
        call is served by the CPU twin for this tick (bit-identical
        placements — the twin consumes the same per-tick Philox stream);
        ``degrade_after`` consecutive failures make the fallback sticky
        until a half-open probe matches (:class:`_DegradeGuard`).  Guard
        disabled (``degrade_after=None``): exceptions propagate
        unchanged."""
        if self.degrade_after is None or self._cpu_twin is None:
            return self._device_place(ctx)
        try:
            out = self._device_place(ctx)
        except DeviceLostError:
            # Mesh-level loss, not kernel flakiness: the elastic
            # supervisor must see it (shrink + reshard), not the twin.
            raise
        except Exception as exc:  # noqa: BLE001 — the guard's whole point
            self._note_kernel_failure(exc)
            return self._cpu_twin.place(ctx)
        self._degrade.note_success()
        return out

    # -- fused span tier (round 8, ``ops/tickloop.py``) --------------------
    #
    # The routing ladder is now: ``place_span`` (a whole pure tick run as
    # one device program) above ``place`` (one tick per dispatch) above
    # the adaptive CPU twin.  The scheduler extracts spans
    # (``GlobalScheduler._extract_span``) and calls ``place_span`` only
    # when the policy advertises ``span_capable()``; any declined or
    # aborted span falls back to the per-tick path below, bit-identically
    # — placements depend only on per-tick inputs, and the opportunistic
    # Philox stream is stateless (keyed on tick_seq), so serving a tick
    # from the span program, the per-tick kernel, or the CPU twin yields
    # the same decisions on the CPU backend.

    #: Maximum ticks fused per span (the K axis of the tick driver);
    #: bucketed by ``span_bucket`` so XLA compiles one program per
    #: (K-bucket, B-bucket, H, config).
    span_cap = 32

    def span_capable(self) -> bool:
        """Fused spans need deterministic device routing (no adaptive
        twin), a healthy kernel (not degraded), and the scan-form kernel
        family (the Pallas kernel has no tick-loop form)."""
        return not (
            self.adaptive
            or self.degraded
            or getattr(self, "use_pallas", False)
            or getattr(self, "realtime_bw", False)
        )

    def _span_kw(self, ctx: TickContext, plan, dem_host: np.ndarray,
                 B: int, K: int) -> Optional[dict]:
        """Policy-specific driver operands (None declines the span)."""
        raise NotImplementedError

    def place_span(self, ctx: TickContext, plan):
        """Serve a whole pure tick run as ONE fused device dispatch.

        Builds the slot-level span operands (demands, cohort arrival
        ticks, per-policy streams), runs ``ops.tickloop.fused_tick_run``
        — through the cross-run batcher when one is attached, so
        co-pending spans of G grid runs coalesce into a single vmapped
        dispatch exactly like single ticks do — and returns an outcome
        whose ``placements[k, s]`` is slot ``s``'s host index at span
        tick ``k`` (−1 unplaced).  Returns None to decline (the
        scheduler then serves the tick per-tick, bit-identically).

        Ragged coalescing contract (round 18): the operands built here
        are zero-fill-safe past their true extents — ``arrive`` pads at
        the K-bucket (≥ ``k_dyn``, so pad slots never join a ready
        batch), K-axis streams past ``k_dyn`` are never read (the span
        loop exits at ``k == k_dyn``), and ``cost_seg`` pads index row 0
        of ``cost_stack`` harmlessly.  That is what lets the dispatch
        batcher pad co-pending mixed-horizon spans up to a shared
        (K′, B′) and run them as one device program
        (``DispatchBatcher`` ragged mode) with per-request trims bit-
        identical to the solo dispatch.  The static ``n_ticks`` passed
        down is the K-bucket; the true horizon rides as the dynamic
        ``k_dyn`` operand, so a merged bucket never changes results.
        """
        if self._fault_gate is not None:
            self._fault_gate(ctx.env_now)
        if self._resident is not None:
            # Resident tier (round 20): the [H] carry is already on
            # device — ship only this span's delta.  Bit-identical to
            # the re-staged dispatch below (tests/test_resident.py).
            return self._place_span_resident(ctx, plan)
        slots = plan.slots
        S = len(slots)
        B = pad_bucket(S)
        k_dyn = plan.n_ticks
        K = span_bucket(k_dyn)
        dem_host = np.stack([t.demand for t in slots])
        kw = self._span_kw(ctx, plan, dem_host, B, K)
        if kw is None:
            return None
        dem = np.zeros((B, 4), dtype=np.dtype(self.dtype))
        dem[:S] = dem_host
        arrive = np.full(B, K, dtype=np.int32)
        arrive[:S] = plan.arrive
        live_arg = self._live_arg(ctx)
        if live_arg is not None:
            kw["live"] = live_arg
        kw.update(self._span_market_kw(ctx, plan, K))
        span_args = (
            self._stage(self._pad_avail_np(ctx.avail), self.dtype),
            self._stage(dem),
            self._stage(arrive),
            np.int32(k_dyn),
        )
        if self._mesh is not None and self._batch_client is None:
            # Host-sharded span driver: the [H/S, 4] carry stays
            # shard-resident between ticks; bit-identical by the span
            # parity suite.
            res = sharded_fused_tick_run(
                self._mesh, *span_args, n_ticks=K, **kw
            )
        else:
            # Through the batcher when one is attached (co-pending
            # spans of G runs coalesce) — on a 2-D mesh the batcher
            # resolves the group to ``sharded_batched_tick_run`` and a
            # lone span to the 1-D sharded driver (``sched/batch.py``).
            res = self._call_kernel(
                fused_tick_run, *span_args, n_ticks=K,
                _h2d_bytes=_staged_nbytes(span_args, kw), **kw
            )
        # ONE host fetch — the placements matrix is the span's entire
        # host-visible output (meters derive from it in the replay).
        return _SpanOutcome(np.asarray(res.placements))

    # -- resident span tier (round 20, ``ops/tickloop.py``) ----------------

    def enable_resident(self, splice: bool = True) -> None:
        """Keep the span carry DEVICE-RESIDENT between consecutive
        ``place_span`` calls: availability, per-host resident-task
        counts, and the live mask stay on device, donated forward from
        span to span (``ops.tickloop.resident_span_run``), and each span
        ships only a delta — sparse host-row edits from a mirror-diff
        against the DES truth (self-healing: completions, chaos flips,
        and aborted spans all surface as diff rows), the per-slot
        operands, and a [K] market-segment row gathered against a
        once-staged risk table.  Composes with :meth:`enable_sharding`
        (the carry lives shard-resident); rejected alongside the
        cross-run batcher, whose host-numpy stacking would re-stage the
        carry every flush.  Placements stay bit-identical to the
        re-staged span path — the resident parity suite's contract.

        ``splice=True`` additionally keeps a cloned checkpoint of each
        span-entry carry so a qualifying mid-span arrival can be joined
        into the RUNNING span (:meth:`span_splice`) without waiting for
        the flush boundary."""
        if self.adaptive:
            raise ValueError(
                "resident span carries need deterministic dispatch — "
                "construct the policy with adaptive=False"
            )
        if getattr(self, "use_pallas", False):
            raise ValueError(
                "the Pallas kernel has no tick-loop (or resident-span) "
                "form; drop use_pallas=True"
            )
        if getattr(self, "realtime_bw", False):
            raise ValueError(
                "realtime_bw samples per-tick host state — there is no "
                "resident form to carry it in"
            )
        if self._batch_client is not None:
            raise ValueError(
                "resident span carries cannot ride the cross-run "
                "batcher (it re-stages every operand at the flush "
                "boundary) — detach the batcher first"
            )
        self._resident = _ResidentState(bool(splice))

    def _resident_risk_kw(self, ctx: TickContext, plan, K: int) -> dict:
        """The resident form of :meth:`_span_market_kw`'s risk rows: the
        [P, H] per-segment table (hazard × risk_weight × rework_cost,
        rounded ONCE into the policy dtype — the same rounding the
        re-staged [K, H] rows get) staged once per bind, plus this
        span's [K] segment-index row; the device gathers
        ``table[seg[k]]``, bit-identical to the host-rendered row.  The
        all-calm gate mirrors the re-staged arm's ``rows.any()`` on the
        same rounded values, so engagement — and the traced program
        family — can never disagree between the arms."""
        market = getattr(ctx.scheduler, "market", None)
        if market is None or not self.risk_weight:
            return {}
        rs = self._resident
        if rs.risk_table_np is None:
            hz = ctx.host_zones
            w = self.risk_weight * self.rework_cost
            table = np.zeros(
                (market.hazard.shape[0], len(hz)),
                dtype=np.dtype(self.dtype),
            )
            table[:] = w * market.hazard[:, hz]
            rs.risk_table_np = table
        k_dyn = plan.n_ticks
        seg = np.zeros(K, dtype=np.int32)
        seg[:k_dyn] = market.segment_indices(
            np.asarray(plan.grid[:k_dyn])
        )
        if not rs.risk_table_np[seg[:k_dyn]].any():
            return {}
        if rs.risk_table_dev is None:
            # Padded on its host axis when the elastic extent is engaged
            # (the [P, H] table shards over the mesh's host axis).
            rs.risk_table_dev = jnp.asarray(
                self._pad_tail(rs.risk_table_np)
            )
        return {"risk_table": rs.risk_table_dev,
                "risk_seg": self._stage(seg)}

    def _place_span_resident(self, ctx: TickContext, plan):
        """The resident-tier ``place_span``: mirror-diff → edit rows →
        one donated-carry dispatch.  The D2H fetch of the pending carry
        is read-side (the async dispatch has long completed by the next
        span) and does not count against the h2d transfer metric the
        bench row gates on."""
        rs = self._resident
        slots = plan.slots
        S = len(slots)
        B = pad_bucket(S)
        k_dyn = plan.n_ticks
        K = span_bucket(k_dyn)
        dem_host = np.stack([t.demand for t in slots])
        kw = self._span_kw(ctx, plan, dem_host, B, K)
        if kw is None:
            return None
        kw.pop("base_task_counts", None)  # carried device-side
        kw.update(self._resident_risk_kw(ctx, plan, K))
        dtype = np.dtype(self.dtype)
        host_avail = np.asarray(ctx.avail, dtype)
        H = host_avail.shape[0]
        host_counts = np.asarray(ctx.host_task_counts, np.int32)
        lm = ctx.live_mask
        host_live = (
            np.ones(H, bool) if lm is None else np.asarray(lm, bool)
        )
        if self._host_extent is not None:
            # Elastic pad layout: the mirror (and so the carry, the edit
            # drop sentinel, and the geometry check) live at the padded
            # extent; pad rows are dead-sentinel and never diff (their
            # truth never changes).
            He = self._host_extent
            host_avail = elastic_pad_rows(host_avail, He, DEAD_AVAIL)
            host_counts = elastic_pad_rows(host_counts, He, 0)
            host_live = elastic_pad_rows(host_live, He, False)
            H = He
        h2d = 0
        carry = rs.carry
        if carry is not None and carry.avail.shape[0] != H:
            carry = None  # cluster geometry changed — restage
        ekw: dict = {}
        if carry is None:
            # First span (or geometry change): the one full [H] staging
            # the resident path pays.
            if self._mesh is not None:
                carry = sharded_resident_carry_init(
                    self._mesh, host_avail, host_counts, host_live
                )
            else:
                carry = resident_carry_init(
                    host_avail, host_counts, host_live
                )
            h2d += (host_avail.nbytes + host_counts.nbytes
                    + host_live.nbytes)
        else:
            # Mirror-diff: exact (bitwise) comparison of DES truth vs
            # the pending carry.  Steady state (the span's own
            # placements were folded device-side) diffs empty; any
            # divergence — completions, quarantine flips, an aborted
            # span replay — becomes sparse repair rows.
            dev_avail = np.asarray(carry.avail)
            dev_counts = np.asarray(carry.counts)
            dev_live = np.asarray(carry.live)
            diff = (
                (dev_avail != host_avail).any(axis=1)
                | (dev_counts != host_counts)
                | (dev_live != host_live)
            )
            rows = np.nonzero(diff)[0].astype(np.int32)
            if rows.size:
                E = edit_bucket(int(rows.size))
                eidx = np.full(E, H, np.int32)
                eidx[: rows.size] = rows
                eav = np.zeros((E, 4), dtype)
                eav[: rows.size] = host_avail[rows]
                ect = np.zeros(E, np.int32)
                ect[: rows.size] = host_counts[rows]
                elv = np.ones(E, bool)
                elv[: rows.size] = host_live[rows]
                ekw = dict(
                    edit_idx=self._stage(eidx),
                    edit_avail=self._stage(eav),
                    edit_counts=self._stage(ect),
                    edit_live=self._stage(elv),
                )
                rs.edit_rows += int(rows.size)
        dem = np.zeros((B, 4), dtype=dtype)
        dem[:S] = dem_host
        arrive = np.full(B, K, dtype=np.int32)
        arrive[:S] = plan.arrive
        span_args = (
            self._stage(dem), self._stage(arrive), np.int32(k_dyn),
        )
        run_kw = dict(kw)
        run_kw.update(ekw)
        h2d += _staged_nbytes(span_args, run_kw)
        if rs.spans == 0 and rs.risk_table_dev is not None:
            h2d += int(rs.risk_table_np.nbytes)  # once-staged table
        ckpt = resident_carry_clone(carry) if rs.splice else None
        res, new_carry = self._resident_dispatch(
            carry, span_args, K, run_kw, h2d, shape_h=H,
        )
        rs.carry = new_carry
        rs.checkpoint = ckpt
        rs.spans += 1
        rs.staging = (
            dict(
                S=S, B=B, K=K, k_dyn=k_dyn, dem_host=dem_host,
                arrive0=np.asarray(plan.arrive, np.int32), kw=kw,
                ekw=ekw,
            )
            if rs.splice else None
        )
        # ONE host fetch, same as the re-staged arm.
        return _SpanOutcome(np.asarray(res.placements))

    def _resident_dispatch(self, carry, span_args, K, run_kw, h2d,
                           shape_h):
        """One resident span dispatch (1-D or host-sharded), profiled
        under the ``resident_span_run`` family with the exact per-call
        transfer bytes.  ``carry`` is CONSUMED (donated)."""
        if self._mesh is not None:
            def _run():
                return sharded_resident_span_run(
                    self._mesh, carry, *span_args, n_ticks=K, **run_kw
                )
        else:
            def _run():
                return resident_span_run(
                    carry, *span_args, n_ticks=K, **run_kw
                )
        prof = self._profiler
        if prof is not None and prof.enabled:
            shape = _dispatch_shape(span_args, dict(run_kw, n_ticks=K))
            shape["h"] = int(shape_h)
            shape["b"] = int(span_args[0].shape[0])
            return prof.profile(
                "resident_span_run", _run, shape=shape, h2d_bytes=h2d,
            )
        return _run()

    def span_splice(self, ctx: TickContext, plan, k: int, new_tasks):
        """Join ``new_tasks`` into the RUNNING span at tick ``k``.

        Re-dispatches the WHOLE span from the cloned span-entry
        checkpoint with the new slots joined at ``arrive = k`` — the
        inert-join contract (a slot sorts into no batch before its
        arrival tick, the same mechanism pump cohorts ride) makes ticks
        [0, k) of the re-run bit-identical to the committed prefix,
        which is VERIFIED against the committed placements before
        adoption; the in-flight program's pending carry is simply
        discarded.  Returns the spliced [K, B] placements matrix (the
        scheduler re-points ``plan.outcome`` at it), or None to decline
        — a decline leaves the committed span and the pending carry
        exactly as they were.

        ``ctx`` must be the SPAN-START context (``plan.ctx``): the
        opportunistic Philox rows and the cost-aware grouping walk are
        keyed off span-start state, so rebuilding the slot streams from
        a later tick would perturb the committed prefix."""
        rs = self._resident
        if (
            rs is None or not rs.splice or rs.checkpoint is None
            or rs.staging is None
        ):
            return None
        st = rs.staging
        S0, B, K, k_dyn = st["S"], st["B"], st["K"], st["k_dyn"]
        n_new = len(new_tasks)
        if n_new == 0 or S0 + n_new > B or not 0 < k < k_dyn:
            return None
        S1 = S0 + n_new
        dem_host = np.concatenate(
            [st["dem_host"], np.stack([t.demand for t in new_tasks])]
        )
        arrive0 = np.concatenate(
            [st["arrive0"], np.full(n_new, k, np.int32)]
        ).astype(np.int32)
        proxy = _SplicePlan(
            tuple(plan.slots) + tuple(new_tasks), arrive0, k_dyn,
            plan.grid,
        )
        kw = self._span_kw(ctx, proxy, dem_host, B, K)
        if kw is None:
            return None
        kw.pop("base_task_counts", None)
        for key in ("risk_table", "risk_seg"):
            if key in st["kw"]:
                kw[key] = st["kw"][key]
        run_kw = dict(kw)
        run_kw.update(st["ekw"])
        dtype = np.dtype(self.dtype)
        dem = np.zeros((B, 4), dtype=dtype)
        dem[:S1] = dem_host
        arrive = np.full(B, K, dtype=np.int32)
        arrive[:S1] = arrive0
        span_args = (
            self._stage(dem), self._stage(arrive), np.int32(k_dyn),
        )
        carry = resident_carry_clone(rs.checkpoint)
        res, new_carry = self._resident_dispatch(
            carry, span_args, K, run_kw,
            _staged_nbytes(span_args, run_kw),
            shape_h=int(np.asarray(ctx.avail).shape[0]),
        )
        pl = np.asarray(res.placements)
        committed = plan.outcome.placements
        if not np.array_equal(pl[:k], committed[:k]):
            # The extended slot set perturbed a pre-splice tick (e.g. a
            # grouping walk reordered an old bucket) — keep the
            # committed program; the arrival waits for the flush
            # boundary exactly as before.
            return None
        rs.carry = new_carry
        rs.splices += 1
        st["S"] = S1
        st["dem_host"] = dem_host
        st["arrive0"] = arrive0
        st["kw"] = kw
        return pl

    def _span_norms(self, dem_host: np.ndarray, B: int):
        """Host-computed demand norms padded to the slot bucket — the
        ``_sort_decreasing`` keys computed in f64 and rounded ONCE into
        the policy dtype at source (dtype pass: an implicit f64 staging
        buffer would fork the compile cache under x64).  Staging the
        host-computed keys — rather than recomputing norms device-side —
        is what keeps a device sqrt from rounding a tie differently than
        the CPU twin's sort."""
        norms = np.zeros(B, dtype=np.dtype(self.dtype))
        norms[: dem_host.shape[0]] = np.sqrt(
            np.sum(dem_host * dem_host, axis=1)
        )
        return self._stage(norms)

    # -- adaptive dispatch ------------------------------------------------
    def place(self, ctx: TickContext) -> np.ndarray:
        if self._fault_gate is not None:
            self._fault_gate(ctx.env_now)
        if self.degraded and self._cpu_twin is not None:
            return self._degraded_place(ctx)
        if self.adaptive and self._cpu_twin is not None:
            import jax

            cells = ctx.n_tasks * ctx.n_hosts
            bucket = pad_bucket(ctx.n_tasks)
            # The twin loops over the true T; the scan-form kernels walk
            # the PADDED bucket, so the two sides' cell counts differ —
            # mixing them would put predictions and EMA samples in
            # inconsistent units.  The CPU slim pass (phase2="auto")
            # early-exits at the last valid task, so its work scales with
            # the true T (the round-6 model re-fit).
            if jax.default_backend() == "cpu":
                dev_cells = cells
            else:
                dev_cells = bucket * ctx.n_hosts
            pred_twin = cells * self._cpu_cell_cost
            pred_device = self._device_floor + dev_cells * self._device_cell_cost
            twin_predicted = pred_twin <= self._DEVICE_ADVANTAGE * pred_device
            big = cells >= self._CELL_COST_MIN_SAMPLE
            # Symmetric exploration: each side occasionally serves a big
            # tick the model assigned to the other, so BOTH per-cell EMAs
            # keep receiving samples — otherwise a single bad estimate
            # (either direction) would be self-sealing.
            explore_twin = (
                not twin_predicted
                and big
                # Absolute bound (margin × probed floor), NOT margin ×
                # pred_device: the affine device prediction grows with the
                # batch, and a relative gate would let one exploration
                # sample cost 8× a large device tick.  Past this bound the
                # verdict is clear anyway (the cost ratio approaches the
                # slope ratio).
                and pred_twin <= self._EXPLORE_MARGIN * self._device_floor
                and self._device_routed % self._EXPLORE_EVERY
                == self._EXPLORE_EVERY - 1
            )
            explore_device = (
                twin_predicted
                and big
                # Only warm buckets: an exploration sample must cost
                # ~margin × floor, not a multi-second cold XLA compile.
                # (Cold buckets get warmed by predicted device wins, whose
                # sustained use amortizes the compile.)
                and bucket in self._warm_buckets
                and pred_device <= self._EXPLORE_MARGIN * pred_twin
                and self._twin_routed % self._EXPLORE_EVERY
                == self._EXPLORE_EVERY - 1
            )
            if (twin_predicted and not explore_device) or explore_twin:
                t0 = time.perf_counter()  # graftcheck: ignore[determinism] -- adaptive-routing EMA sample; which side serves a tick is timing-dependent BY DESIGN, and placements are route-invariant (twin bit-parity on the CPU backend)
                out = self._cpu_twin.place(ctx)
                dt = time.perf_counter() - t0  # graftcheck: ignore[determinism] -- same EMA sample window
                if big:
                    self._cpu_cell_cost = 0.5 * (self._cpu_cell_cost + dt / cells)
                if explore_twin:
                    self._device_routed += 1
                else:
                    self._twin_routed += 1
                return out
            t0 = time.perf_counter()  # graftcheck: ignore[determinism] -- adaptive-routing EMA sample (device side); see the twin-side justification above
            if self.degrade_after is not None:
                try:
                    out = self._device_place(ctx)
                except Exception as exc:  # noqa: BLE001 — degradation guard
                    # Twin fallback; no EMA update (the sample measures
                    # neither side's healthy cost).
                    self._note_kernel_failure(exc)
                    return self._cpu_twin.place(ctx)
                self._consecutive_failures = 0
            else:
                out = self._device_place(ctx)
            dt = time.perf_counter() - t0  # graftcheck: ignore[determinism] -- same EMA sample window (device side)
            # Attribute time beyond the probed floor to per-padded-cell
            # work — but never from a bucket's first call, which includes
            # XLA compile.  (The floor itself stays probe-only for the
            # same reason.)
            if big and bucket in self._warm_buckets:
                self._device_cell_cost = 0.5 * (
                    self._device_cell_cost
                    + max(dt - self._device_floor, 0.0) / dev_cells
                )
            self._warm_buckets.add(bucket)
            if explore_device:
                self._twin_routed += 1
            else:
                self._device_routed += 1
            return out
        return self._guarded_device_place(ctx)

    def _device_place(self, ctx: TickContext) -> np.ndarray:
        raise NotImplementedError

    def _padded(self, ctx: TickContext, order: Optional[List[int]] = None):
        """(avail [H,4], demands [B,4], valid [B]) device-ready, task axis
        padded to a bucket; ``order`` optionally permutes tasks."""
        T = ctx.n_tasks
        B = pad_bucket(T)
        demands = ctx.demands if order is None else ctx.demands[order]
        # Stage in the policy dtype — an f32 buffer here would quantize
        # demands and break the f64 cross-backend parity contract.
        dem = np.zeros((B, 4), dtype=np.dtype(self.dtype))
        dem[:T] = demands
        valid = np.zeros(B, dtype=bool)
        valid[:T] = True
        avail = self._stage(self._pad_avail_np(ctx.avail), self.dtype)
        return avail, self._stage(dem, self.dtype), self._stage(valid)

    @staticmethod
    def _unpad(placements, T: int, order: Optional[List[int]] = None) -> np.ndarray:
        out = np.asarray(placements[:T]).astype(np.int64)
        if order is None:
            return out
        unscrambled = np.full(T, -1, dtype=np.int64)
        unscrambled[np.asarray(order)] = out
        return unscrambled

    def _mc_sensitivity(self, ctx, order, batched_place, n_replicas,
                        perturb, seed):
        """Shared Monte-Carlo scaffolding behind every policy's
        ``placement_sensitivity``: replica 0 carries the exact
        availability snapshot (its placements ARE the production
        decision), replicas 1..R−1 draw ±``perturb`` multiplicative
        noise, and ``stability[t]`` is the fraction of replicas agreeing
        with the nominal host for task t.  ``batched_place(avail_r, dem,
        valid) -> [R, B]`` supplies the policy's own batched kernel.
        Returns ``(nominal [T], stability [T], placements [R, T])`` in
        ctx task order."""
        T = ctx.n_tasks
        avail, dem, valid = self._padded(ctx, order)
        rng = np.random.default_rng(seed)
        # Sized off the STAGED avail (== ctx.n_hosts except under the
        # elastic pad extent, where perturbed DEAD_AVAIL rows stay
        # negative and so inert).
        noise = rng.uniform(
            1 - perturb, 1 + perturb,
            size=(n_replicas, int(np.asarray(avail).shape[0]), 1),
        )
        noise[0] = 1.0  # replica 0 = the production decision
        avail_r = jnp.asarray(np.asarray(avail)[None] * noise,
                              dtype=self.dtype)
        p = np.asarray(batched_place(avail_r, dem, valid))  # [R, B]
        placements = np.stack(
            [self._unpad(row, T, order) for row in p]
        )  # [R, T] in ctx order
        nominal = placements[0]
        stability = (placements == nominal[None, :]).mean(axis=0)
        return nominal, stability, placements


class TpuOpportunisticPolicy(_DevicePolicyBase):
    name = "opportunistic_tpu"

    def __init__(self, adaptive: bool = False, phase2="auto",
                 degrade_after=None, risk_weight: float = 0.0,
                 rework_cost: float = 1.0, weights=None):
        super().__init__(adaptive, phase2, degrade_after,
                         risk_weight, rework_cost, weights)
        self._cpu_twin = OpportunisticPolicy(
            mode="numpy", weights=self.weights
        )

    def _span_kw(self, ctx, plan, dem_host, B, K):
        # [K, B] positional Philox rows: tick k of the span consumes
        # ``tick_uniforms(seed, tick_seq + k, ·)`` exactly like the
        # sequential path (prefix property — the per-tick path draws the
        # first T_k of the same counter stream), so span service leaves
        # the stream aligned for any fallback tick.
        seed = ctx.scheduler.seed or 0
        # Policy dtype at source (dtype pass): the f64 Philox draws round
        # once on assignment, exactly like the old cast-at-staging.
        u = np.zeros((K, B), dtype=np.dtype(self.dtype))
        for k in range(plan.n_ticks):
            u[k] = tick_uniforms(seed, ctx.tick_seq + k, B)
        return dict(policy="opportunistic", uniforms=self._stage(u, self.dtype),
                    phase2=self.phase2)

    def _device_place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        avail, dem, valid = self._padded(ctx)
        u = np.zeros(valid.shape[0], dtype=np.dtype(self.dtype))
        u[:T] = tick_uniforms(ctx.scheduler.seed or 0, ctx.tick_seq, T)
        placements, _ = self._kernel_for(
            opportunistic_kernel, opportunistic_kernel_sharded
        )(
            avail, dem, valid, self._stage(u, self.dtype),
            phase2=self.phase2, live=self._live_arg(ctx),
            risk=self._risk_arg(ctx),
        )
        return self._unpad(placements, T)


class TpuFirstFitPolicy(_DevicePolicyBase):
    name = "first_fit_tpu"

    def __init__(self, decreasing: bool = False, adaptive: bool = False,
                 phase2="auto", degrade_after=None,
                 risk_weight: float = 0.0, rework_cost: float = 1.0,
                 weights=None):
        super().__init__(adaptive, phase2, degrade_after,
                         risk_weight, rework_cost, weights)
        self.decreasing = decreasing
        self._cpu_twin = FirstFitPolicy(
            decreasing=decreasing, mode="numpy", weights=self.weights,
        )

    def _span_kw(self, ctx, plan, dem_host, B, K):
        return dict(
            policy="first-fit", strict=False, decreasing=self.decreasing,
            sort_norm=(
                self._span_norms(dem_host, B) if self.decreasing else None
            ),
            totals=self._staged_topology().totals, phase2=self.phase2,
        )

    def _device_place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        order = None
        if self.decreasing:
            order = _sort_decreasing(ctx.demands, list(range(T)))
            ctx.visit_order = order  # ref returns the sorted list (vbp.py:17)
        avail, dem, valid = self._padded(ctx, order)
        placements, _ = self._kernel_for(
            first_fit_kernel, first_fit_kernel_sharded
        )(
            avail, dem, valid, strict=False,
            totals=self._staged_topology().totals,
            phase2=self.phase2, live=self._live_arg(ctx),
            risk=self._risk_arg(ctx),
        )
        return self._unpad(placements, T, order)

    def placement_sensitivity(self, ctx: TickContext, n_replicas: int = 256,
                              perturb: float = 0.05, seed: int = 0):
        """Monte-Carlo robustness of this tick's first-fit decision —
        same contract as :meth:`TpuCostAwarePolicy.placement_sensitivity`
        (replica 0 is the production decision), scoring with this arm's
        own kernel so the sensitivity-gated dispatcher can wrap the VBP
        arm (ref ``scheduler/vbp.py:9-17``)."""
        import jax

        order = None
        if self.decreasing:
            order = _sort_decreasing(ctx.demands, list(range(ctx.n_tasks)))
            ctx.visit_order = order  # ref returns the sorted list (vbp.py:17)
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        risk_arg = None if risk is None else jnp.asarray(risk, self.dtype)
        return self._mc_sensitivity(
            ctx, order,
            lambda avail_r, dem, valid: jax.vmap(
                lambda a: first_fit_kernel(
                    a, dem, valid, strict=False, risk=risk_arg
                )[0]
            )(avail_r),
            n_replicas, perturb, seed,
        )


class TpuBestFitPolicy(_DevicePolicyBase):
    name = "best_fit_tpu"

    def __init__(self, decreasing: bool = False, adaptive: bool = False,
                 phase2="auto", degrade_after=None,
                 risk_weight: float = 0.0, rework_cost: float = 1.0,
                 weights=None):
        super().__init__(adaptive, phase2, degrade_after,
                         risk_weight, rework_cost, weights)
        self.decreasing = decreasing
        self._cpu_twin = BestFitPolicy(
            decreasing=decreasing, mode="numpy", weights=self.weights,
        )

    def _span_kw(self, ctx, plan, dem_host, B, K):
        return dict(
            policy="best-fit", decreasing=self.decreasing,
            sort_norm=(
                self._span_norms(dem_host, B) if self.decreasing else None
            ),
            totals=self._staged_topology().totals, phase2=self.phase2,
        )

    def _device_place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        order = None
        if self.decreasing:
            order = _sort_decreasing(ctx.demands, list(range(T)))
            ctx.visit_order = order  # ref returns the sorted list (vbp.py:42)
        avail, dem, valid = self._padded(ctx, order)
        placements, _ = self._kernel_for(
            best_fit_kernel, best_fit_kernel_sharded
        )(
            avail, dem, valid,
            totals=self._staged_topology().totals,
            phase2=self.phase2, live=self._live_arg(ctx),
            risk=self._risk_arg(ctx),
        )
        return self._unpad(placements, T, order)

    def placement_sensitivity(self, ctx: TickContext, n_replicas: int = 256,
                              perturb: float = 0.05, seed: int = 0):
        """Monte-Carlo robustness of this tick's best-fit decision —
        same contract as :meth:`TpuCostAwarePolicy.placement_sensitivity`
        (replica 0 is the production decision), scoring with this arm's
        own kernel (ref ``scheduler/vbp.py:20-42``)."""
        import jax

        order = None
        if self.decreasing:
            order = _sort_decreasing(ctx.demands, list(range(ctx.n_tasks)))
            ctx.visit_order = order  # ref returns the sorted list (vbp.py:42)
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        risk_arg = None if risk is None else jnp.asarray(risk, self.dtype)
        return self._mc_sensitivity(
            ctx, order,
            lambda avail_r, dem, valid: jax.vmap(
                lambda a: best_fit_kernel(a, dem, valid, risk=risk_arg)[0]
            )(avail_r),
            n_replicas, perturb, seed,
        )


class TpuCostAwarePolicy(_DevicePolicyBase):
    """Cost-aware (PIVOT) placement on the device.

    Anchor grouping stays host-side (it walks the DAG and is memoized per
    task group — see ``CostAwarePolicy.group_tasks``); everything O(T × H)
    runs in the fused kernel.
    """

    name = "cost_aware_tpu"

    def __init__(
        self,
        bin_pack: str = "first-fit",
        sort_tasks: bool = False,
        sort_hosts: bool = False,
        host_decay: bool = False,
        realtime_bw: bool = False,
        use_pallas: Optional[bool] = None,
        adaptive: bool = False,
        phase2="auto",
        degrade_after: Optional[int] = None,
        risk_weight: float = 0.0,
        rework_cost: float = 1.0,
        weights=None,
    ):
        super().__init__(adaptive, phase2, degrade_after,
                         risk_weight, rework_cost, weights)
        assert bin_pack in ("first-fit", "best-fit")
        #: Learned score exponents (w_cost, w_bw, w_norm) or None at the
        #: reference (1, 1, 1) shape — None keeps every existing
        #: compiled program serving bit-identically (the kernels trace
        #: no ``pow``); non-None rides the scan/two-phase/fused-span
        #: kernels as a traced [3] operand, so tuner-promoted weights
        #: (pivot_tpu/mpc) change values with ZERO recompiles.
        self._score_exp = self.weights.score_exponents()
        if self._score_exp is not None and realtime_bw:
            raise ValueError(
                "learned score exponents pow the static phase-1 "
                "bandwidth table; realtime_bw rows bypass that table — "
                "score with the static topology (realtime_bw=False) or "
                "the reference exponents"
            )
        if self._score_exp is not None and use_pallas:
            raise ValueError(
                "the Pallas kernel's tile algebra hard-codes the "
                "reference exponent shape — learned w_cost/w_bw/w_norm "
                "are served by the scan/two-phase kernels; drop "
                "use_pallas=True"
            )
        if realtime_bw and use_pallas:
            raise ValueError(
                "realtime_bw is served by the scan kernel only — the "
                "Pallas kernel has no live-bandwidth input; drop "
                "use_pallas=True"
            )
        self.bin_pack = bin_pack
        self.sort_tasks = sort_tasks
        self.sort_hosts = sort_hosts
        self.host_decay = host_decay
        #: Score with live route-queue bandwidth instead of the static
        #: table: the anchor↔host realtime values are sampled host-side at
        #: the tick instant (the queues live on the event kernel, not the
        #: device) and fed to the kernel as one [H] row per anchor group
        #: plus a per-task row index.
        self.realtime_bw = realtime_bw
        # The Pallas greedy kernel keeps the whole tick in VMEM (~5× the
        # scan kernel per tick on a v5e) but is f32-only; auto-enable on
        # the TPU backend, keep the scan kernel for CPU/f64 parity runs.
        self.use_pallas = use_pallas
        # Grouping logic shared verbatim with the CPU policy; the same
        # object doubles as the adaptive numpy twin (root anchors come
        # from the entity-keyed draw — no stream state — so the twin and
        # the kernel agree no matter which side served earlier ticks)
        # AND as the realtime-bandwidth sampler, so the kernel scores with
        # bit-identical inputs to the twin.
        self._grouper = CostAwarePolicy(
            bin_pack=bin_pack,
            sort_tasks=sort_tasks,
            sort_hosts=sort_hosts,
            host_decay=host_decay,
            realtime_bw=realtime_bw,
            weights=self.weights,
        )
        self._cpu_twin = self._grouper

    def apply_weights(self, weights) -> None:
        """Live promotion with the same guards the constructor enforces:
        a promoted vector whose exponents depart the reference shape is
        rejected on configurations the exponent operand has not been
        threaded through (Pallas / realtime-bw / sharded / 2-D batched)
        — rejecting beats silently serving the old exponents.  At the
        reference shape (``score_exponents() is None``) every
        configuration accepts the promotion."""
        from pivot_tpu.search.weights import PolicyWeights

        w = (
            weights
            if isinstance(weights, PolicyWeights)
            else PolicyWeights.from_array(weights)
        ).validate()
        exps = w.score_exponents()
        if exps is not None:
            if self.realtime_bw:
                raise ValueError(
                    "cannot promote learned score exponents onto a "
                    "realtime_bw policy — the exponents pow the static "
                    "phase-1 bandwidth table"
                )
            if self.use_pallas:
                raise ValueError(
                    "cannot promote learned score exponents onto a "
                    "Pallas-kernel policy — its tile algebra hard-codes "
                    "the reference exponent shape"
                )
            if self._mesh is not None:
                raise ValueError(
                    "cannot promote learned score exponents onto a "
                    "host-sharded policy (ops/shard.py exemption)"
                )
            if (
                self._batch_client is not None
                and getattr(self._batch_client, "mesh", None) is not None
            ):
                raise ValueError(
                    "cannot promote learned score exponents onto a "
                    "2-D-mesh-batched policy (ops/shard.py exemption)"
                )
        super().apply_weights(w)
        self._score_exp = exps

    def enable_batching(self, client) -> None:
        if self.use_pallas:
            raise ValueError(
                "cross-run batching serves ticks through vmap(scan "
                "kernel); the Pallas kernel batches replicas on its own "
                "sublane axis — drop use_pallas=True"
            )
        if (
            self._score_exp is not None
            and getattr(client, "mesh", None) is not None
        ):
            raise ValueError(
                "the 2-D coalesced-flush twins (ops/shard.py) have not "
                "been threaded for learned score exponents — batch "
                "through a mesh-free DispatchBatcher, or keep the "
                "reference exponents"
            )
        super().enable_batching(client)

    def enable_sharding(self, mesh) -> None:
        if self._score_exp is not None:
            raise ValueError(
                "the host-sharded kernels (ops/shard.py) have not been "
                "threaded for learned score exponents (a declared "
                "exemption in analysis/parity.py) — serve learned "
                "exponents single-device, or keep the reference shape"
            )
        super().enable_sharding(mesh)

    def _span_kw(self, ctx, plan, dem_host, B, K):
        if self.realtime_bw:
            return None  # live route-queue samples are per-tick host state
        slots = plan.slots
        # Per-slot anchor identity and zone: anchors are span-constant
        # (a ready group's predecessors are finished with immutable
        # placements; root anchors are entity-keyed draws), so ONE
        # grouping walk covers every tick — the driver re-derives each
        # tick's first-seen bucket order from its own batch order.
        span_ctx = TickContext(ctx.scheduler, list(slots), ctx.tick_seq)
        groups = self._grouper.group_tasks(span_ctx)
        storage = ctx.cluster.storage
        meta = ctx.meta
        az = np.zeros(B, dtype=np.int32)
        bucket = np.zeros(B, dtype=np.int32)
        for bi, (anchor, idxs) in enumerate(groups.items()):
            if not hasattr(anchor, "locality"):  # root group → keyed storage
                anchor = storage[
                    resolve_root_anchor(span_ctx, anchor, len(storage))
                ]
            zone = meta.zone_index[anchor.locality]
            for i in idxs:
                az[i] = zone
                bucket[i] = bi
        topo = self._staged_topology()
        kw = dict(
            policy="cost-aware",
            bin_pack=self.bin_pack,
            sort_tasks=self.sort_tasks,
            sort_hosts=self.sort_hosts,
            host_decay=self.host_decay,
            sort_norm=(
                self._span_norms(dem_host, B) if self.sort_tasks else None
            ),
            anchor_zone=self._stage(az),
            bucket_id=self._stage(bucket),
            cost_zz=topo.cost,
            bw_zz=topo.bw,
            host_zone=self._host_zone_arg(topo),
            base_task_counts=(
                # The resident tier carries the counts device-side — do
                # not stage the [H] buffer it would immediately discard.
                None if self._resident is not None
                else self._stage(
                    self._pad_h(ctx.host_task_counts, 0), jnp.int32
                )
            ),
            totals=topo.totals,
            phase2=self.phase2,
        )
        if self._score_exp is not None:
            # Span-constant learned exponents: a [3] traced operand
            # (RAGGED_INVARIANT), absent entirely at the reference shape
            # so default-weight spans keep their compiled programs.
            kw["score_exp"] = self._stage(
                np.asarray(self._score_exp), self.dtype
            )
        market = getattr(ctx.scheduler, "market", None)
        if market is not None:
            # Time-varying prices: the [P, Z, Z] stack (staged once per
            # market) + this span's [K] segment-index row — tick k scores
            # with cost_stack[cost_seg[k]], the per-tick path's
            # ``cost_matrix_at`` slice exactly.
            if self._market_stack_dev is None:
                self._market_stack_dev = self._stage(
                    market.cost_tensor(ctx.meta), self.dtype
                )
            seg = np.zeros(K, dtype=np.int32)
            seg[: plan.n_ticks] = market.segment_indices(
                plan.grid[: plan.n_ticks]
            )
            kw["cost_stack"] = self._market_stack_dev
            kw["cost_seg"] = self._stage(seg)
        return kw

    def _anchor_stream(self, ctx: TickContext):
        """The kernel's per-task anchor stream: ``(order, az_arr [B] i32,
        ng_arr [B] bool, group_rows, row_idx)`` — grouping walked
        host-side exactly like the numpy twin, tasks laid out
        bucket-major.  Shared by :meth:`_device_place` and
        :meth:`placement_sensitivity` so the two cannot drift."""
        T = ctx.n_tasks
        meta = ctx.meta
        storage = ctx.cluster.storage
        groups = self._grouper.group_tasks(ctx)

        order: List[int] = []
        anchor_zone = []
        new_group = []
        group_rows = [] if self.realtime_bw else None
        row_idx = [] if self.realtime_bw else None
        for anchor, idxs in groups.items():
            if not hasattr(anchor, "locality"):  # root group → keyed storage
                anchor = storage[resolve_root_anchor(ctx, anchor, len(storage))]
            if self.sort_tasks:
                idxs = _sort_decreasing(ctx.demands, idxs)
            az = meta.zone_index[anchor.locality]
            if group_rows is not None:
                # Live anchor↔host round-trip bandwidth at the tick
                # instant, via the SAME sampler the numpy twin scores
                # with (CostAwarePolicy._roundtrip_vectors) — one row per
                # anchor group, indexed per task below.
                _, bw_rt = self._grouper._roundtrip_vectors(ctx, anchor)
                group_rows.append(bw_rt)
            for j, i in enumerate(idxs):
                order.append(i)
                anchor_zone.append(az)
                new_group.append(j == 0)
                if row_idx is not None:
                    row_idx.append(len(group_rows) - 1)

        B = pad_bucket(T)
        az_arr = np.zeros(B, dtype=np.int32)
        az_arr[:T] = anchor_zone
        ng_arr = np.zeros(B, dtype=bool)
        ng_arr[:T] = new_group
        return order, az_arr, ng_arr, group_rows, row_idx

    def placement_sensitivity(
        self,
        ctx: TickContext,
        n_replicas: int = 256,
        perturb: float = 0.05,
        seed: int = 0,
    ):
        """Monte-Carlo robustness of THIS tick's placement decision.

        How sensitive is the greedy cost-aware placement to noise in the
        host-availability snapshot (stale resource telemetry, in-flight
        releases)?  Replica 0 carries the exact snapshot — its placements
        ARE the production decision — and replicas 1..R−1 draw ±``perturb``
        multiplicative availability noise.  Returns ``(nominal [T],
        stability [T], placements [R, T])`` in ctx task order, where
        ``stability[t]`` is the fraction of replicas agreeing with the
        nominal host for task t — tasks near a capacity or score boundary
        score low and are the ones a dispatcher might hold a tick.

        This is the production consumer of the replica-batched Pallas
        kernel at its native shape (one shared task stream × R perturbed
        ``[H, 4]`` snapshots, the whole greedy pass VMEM-resident per
        block — 76–104 M decisions/s on a v5e at the bench shape); on
        non-TPU backends the vmapped scan kernel serves the same
        contract.  Not expressible by the ensemble sweeps: their rows'
        readiness diverges after one tick, breaking the kernel's
        shared-stream premise (see RESULTS.md round 3).
        """
        import jax

        if self.realtime_bw:
            raise ValueError(
                "placement_sensitivity scores on the static topology "
                "tables (the Pallas kernel has no live-bandwidth input)"
            )
        if self.topology is None:
            raise RuntimeError("bind() the policy to a scheduler first")
        order, az_arr, ng_arr, _gr, _ri = self._anchor_stream(ctx)

        def batched(avail_r, dem, valid):
            args = (
                dem,
                valid,
                jnp.asarray(ng_arr),
                jnp.asarray(az_arr),
                # The tick's cost operand — the market-scaled slice when
                # a spot market is attached, so replica 0 stays exactly
                # the production decision.
                jnp.asarray(self._market_cost_arg(ctx)),
                self.topology.bw,
                self.topology.host_zone,
                jnp.asarray(ctx.host_task_counts, dtype=jnp.int32),
            )
            kw = dict(
                bin_pack=self.bin_pack,
                sort_hosts=self.sort_hosts,
                host_decay=self.host_decay,
            )
            risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
            if risk is not None:
                kw["risk"] = jnp.asarray(risk, dtype=self.dtype)
            if self._score_exp is not None:
                kw["score_exp"] = jnp.asarray(
                    self._score_exp, dtype=self.dtype
                )
            # Kernel choice mirrors _device_place exactly: an explicit
            # use_pallas override wins, and the auto default requires the
            # TPU backend AND f32 (the Pallas kernel is f32-only — an f64
            # policy must not have its inputs silently quantized).
            use_pallas = self.use_pallas
            if use_pallas is None:
                use_pallas = (
                    jax.default_backend() == "tpu"
                    and self.dtype == jnp.float32
                    and self._score_exp is None
                )
            if use_pallas:
                return cost_aware_pallas_batched(avail_r, *args, **kw)[0]
            return jax.vmap(
                lambda a: cost_aware_kernel(a, *args, **kw)
            )(avail_r)[0]

        return self._mc_sensitivity(
            ctx, order, batched, n_replicas, perturb, seed
        )

    def _device_place(self, ctx: TickContext) -> np.ndarray:
        T = ctx.n_tasks
        order, az_arr, ng_arr, group_rows, row_idx = self._anchor_stream(ctx)
        avail, dem, valid = self._padded(ctx, order)
        use_pallas = self.use_pallas
        if use_pallas is None:
            import jax

            use_pallas = (
                jax.default_backend() == "tpu"
                and self.dtype == jnp.float32
                # The Pallas kernel has no realtime input; the scan
                # kernel serves that mode on every backend (explicit
                # use_pallas=True + realtime_bw is rejected in __init__).
                and not self.realtime_bw
                # Nor a learned-exponent input (explicit use_pallas=True
                # with non-default exponents likewise rejected).
                and self._score_exp is None
            )
        if self._batch_client is not None or self._mesh is not None:
            # The batcher's program is vmap(scan kernel): the Pallas
            # greedy kernel batches replicas along its own sublane axis
            # (cost_aware_pallas_batched) and cannot ride a run axis too.
            # The sharded tier likewise has no Pallas form (one core's
            # VMEM cannot hold the sharded tick).  Explicit
            # use_pallas=True is rejected at enable_batching /
            # enable_sharding.
            use_pallas = False
        kw = {}
        if group_rows is not None:
            # One [H] row per anchor group + a per-task row index: the
            # per-tick host→device transfer is G × H + B values, not a
            # dense task-replicated [B, H].  The group axis pads to a
            # small bucket so XLA compiles one program per (G-bucket, B,
            # H) shape, not per group count.
            G = pad_bucket(max(len(group_rows), 1))
            rows = np.ones((G, ctx.n_hosts), dtype=np.dtype(self.dtype))
            if group_rows:
                rows[: len(group_rows)] = np.stack(group_rows)
            idx = np.zeros(az_arr.shape[0], dtype=np.int32)
            idx[:T] = row_idx
            kw["rt_bw_rows"] = self._stage(rows, self.dtype)
            kw["rt_bw_idx"] = self._stage(idx)
        if use_pallas:
            call = functools.partial(self._call_kernel, cost_aware_pallas)
        else:
            call = self._kernel_for(cost_aware_kernel, cost_aware_kernel_sharded)
        live_arg = self._live_arg(ctx)
        if live_arg is not None:
            # Both kernel arms accept the quarantine mask; omit it when
            # all-live so the existing compiled programs keep serving.
            kw["live"] = live_arg
        risk_arg = self._risk_arg(ctx)
        if risk_arg is not None:
            # Same pattern for the eviction-risk vector: omitted (None)
            # whenever the term is disengaged (resolve_risk).
            kw["risk"] = risk_arg
        if self._score_exp is not None:
            # Learned exponents as a traced [3] operand — same omit-when-
            # disengaged pattern, so reference-shape policies keep their
            # compiled programs bit for bit.
            kw["score_exp"] = self._stage(
                np.asarray(self._score_exp), self.dtype
            )
        topo = self._staged_topology()
        if not use_pallas:
            # Phase-1 demand-vs-total pre-filter (two-phase kernels only —
            # the Pallas kernel has no totals input).  Speculation-only:
            # it steers the chunked form's fill model and can never
            # change a placement (ops/kernels.py).
            kw["totals"] = topo.totals
            kw["phase2"] = self.phase2
        placements, _ = call(
            avail,
            dem,
            valid,
            self._stage(ng_arr),
            self._stage(az_arr),
            self._market_cost_arg(ctx),
            topo.bw,
            self._host_zone_arg(topo),
            self._stage(self._pad_h(ctx.host_task_counts, 0), jnp.int32),
            bin_pack=self.bin_pack,
            sort_hosts=self.sort_hosts,
            host_decay=self.host_decay,
            **kw,
        )
        return self._unpad(placements, T, order)
