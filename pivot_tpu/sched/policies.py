"""Placement policies: opportunistic, first-fit, best-fit, cost-aware.

Each policy supports two CPU modes sharing the :class:`TickContext` feed:

  * ``mode='naive'`` — reference-faithful per-task/per-host Python loops,
    the measured performance baseline (mirrors ``scheduler/opportunistic.py``,
    ``scheduler/vbp.py``, ``scheduler/cost_aware.py`` in the reference).
  * ``mode='numpy'`` — vectorized over hosts; bit-identical placements to
    the TPU kernels in ``pivot_tpu.ops`` (which consume the same Philox
    uniform stream and the same tie-breaking rules).

Deliberate, documented fixes of reference quirks (SURVEY.md §4):
  * ``decreasing`` is a real boolean (the reference's ``str(False)`` is
    always truthy, ``scheduler/vbp.py:9,35`` — so its first-fit *always*
    sorted; experiments pass ``decreasing=True`` anyway).
  * Best-fit keeps the reference's strict ``>`` fit test
    (``scheduler/vbp.py:45``) and cost-aware first-fit its strict ``>``
    (``scheduler/cost_aware.py:124``) — both preserved since they shape
    behavior; ties in argmin resolve to the lowest host index (the
    reference breaks ties by uuid string order, which is unreproducible).
  * Best-fit + ``host_decay`` works here (the reference's
    ``_best_fit`` dereferences an uninitialized ``None`` counter,
    ``scheduler/cost_aware.py:26,67``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from pivot_tpu.sched import Policy, TickContext
from pivot_tpu.sched.rand import keyed_storage_index, tick_uniforms
from pivot_tpu.search.weights import PolicyWeights


def resolve_root_anchor(ctx: TickContext, app, n_storage: int) -> int:
    """Storage index anchoring ``app``'s root task groups — the keyed
    draw (:func:`pivot_tpu.sched.rand.keyed_storage_index`) shared by
    every policy backend AND the ensemble estimator, keyed on the app's
    submission ordinal.  An app that never went through
    ``GlobalScheduler.submit`` (direct-policy unit harnesses) keys as
    ordinal 0."""
    seed = ctx.scheduler.seed or 0
    ordinal = getattr(app, "_submit_ordinal", 0)
    return int(keyed_storage_index(seed, ordinal, n_storage))

__all__ = [
    "OpportunisticPolicy",
    "FirstFitPolicy",
    "BestFitPolicy",
    "CostAwarePolicy",
    "fold_quarantine",
    "resolve_risk",
    "resolve_weights",
]


def resolve_weights(
    weights: Optional[PolicyWeights],
    risk_weight: float = 0.0,
    rework_cost: float = 1.0,
) -> PolicyWeights:
    """Fold a policy constructor's scoring knobs into the ONE typed
    vector (round 16, ``pivot_tpu/search/weights.py``).

    Every backend now carries ``self.weights`` as the source of truth;
    the legacy ``risk_weight`` / ``rework_cost`` constructor knobs stay
    accepted (they populate the vector's risk dims) but may not be
    combined with an explicit ``weights=`` — two sources for one knob
    is exactly the scatter this refactor removes.  The score exponents
    (``w_cost``/``w_bw``/``w_norm``) parameterize the cost-aware score
    terms; policies whose selections have no such terms (first-fit's
    index order, best-fit's residual norm, the opportunistic draw) are
    exponent-invariant by construction and consume only the risk dims.
    """
    if weights is None:
        return PolicyWeights(
            risk_weight=risk_weight, rework_cost=rework_cost
        ).validate()
    if not isinstance(weights, PolicyWeights):
        weights = PolicyWeights.from_array(np.asarray(weights, dtype=float))
    if (risk_weight, rework_cost) != (0.0, 1.0):
        raise ValueError(
            "pass weights= OR the legacy risk_weight/rework_cost knobs, "
            "not both — the typed vector is the one source of truth"
        )
    return weights.validate()


def resolve_risk(ctx: TickContext, risk_weight: float,
                 rework_cost: float) -> Optional[np.ndarray]:
    """The tick's ``[H]`` risk penalty vector — ``risk_weight × hazard ×
    rework_cost`` per host, where ``hazard`` is the spot-market's per-host
    preemption rate at the tick instant (``TickContext.hazard_vector``)
    and ``rework_cost`` prices the expected loss of a placement on an
    evicted host (lost compute-seconds × restart overhead, a scalar knob).

    Returns ``None`` — the exact-bit-parity path, no risk ops traced or
    evaluated anywhere downstream — when the weight is zero, there is no
    market environment, or every hazard is zero.  One resolver shared by
    the CPU policies and the device wrappers, so the two sides can never
    disagree about when the risk term engages.

    How the vector is consumed (the shared cross-backend rule, mirrored
    exactly by ``ops/kernels.py``):

      * score-based selections (best-fit residual, cost-aware scores)
        add it: ``score += risk``;
      * index-ordered selections (plain first-fit; cost-aware first-fit
        with ``sort_hosts=False``) replace the index order with the
        lexicographic ``(risk, host index)`` order — the masked-argmin
        tie rule gives exactly this for a score of ``risk``;
      * the opportunistic random choice restricts to the minimum-risk
        tier of fitting hosts (same Philox draw, narrower support).
    """
    if not risk_weight:
        return None
    hazard = ctx.hazard_vector
    if hazard is None:
        return None
    risk = risk_weight * rework_cost * hazard
    if not risk.any():
        return None
    return risk


def fold_quarantine(ctx: TickContext) -> None:
    """Fold the tick's quarantine/drain mask into the availability
    working copy: masked hosts get the −1 sentinel — the same mechanism
    that already excludes DOWN hosts from every fit test (demands are
    ≥ 0, so no strict or non-strict comparison can select a −1 row,
    zero-demand tasks included).  Reusing the sentinel keeps every
    naive/numpy inner loop and incremental fast path untouched, and is
    placement-identical to the device kernels' fused ``live`` mask: the
    two produce the same fit masks, and scores of *fitting* (live,
    untouched) hosts are computed from identical rows.  No-op when every
    host is live."""
    live = ctx.live_mask
    if live is not None:
        ctx.avail[~live] = -1.0


def _norms(mat: np.ndarray) -> np.ndarray:
    return np.sqrt(np.sum(mat * mat, axis=-1))


_NO_EXTRA = 0  # broadcast-zero "no placements yet" for frozen decay


def _same_demand(d, prev_d) -> bool:
    """Scalar 4-component equality for the identical-demand run fast paths
    (``prev_d`` may be None).  One definition so the strict/non-strict fit
    helpers below stay visually distinct from it."""
    return prev_d is not None and (
        d[0] == prev_d[0]
        and d[1] == prev_d[1]
        and d[2] == prev_d[2]
        and d[3] == prev_d[3]
    )


def _row_fits(row, d) -> bool:
    """Non-strict scalar fit (FirstFit/Opportunistic mask semantics)."""
    return row[0] >= d[0] and row[1] >= d[1] and row[2] >= d[2] and row[3] >= d[3]


def _row_fits_strict(row, d) -> bool:
    """Strict scalar fit (BestFit/CostAware mask semantics, ref :124/:45)."""
    return row[0] > d[0] and row[1] > d[1] and row[2] > d[2] and row[3] > d[3]




def _sort_decreasing(demands: np.ndarray, idxs: List[int]) -> List[int]:
    """Stable sort of task indices by descending demand L2 norm."""
    norms = _norms(demands[idxs])
    order = np.argsort(-norms, kind="stable")
    return [idxs[i] for i in order]


class OpportunisticPolicy(Policy):
    """Uniformly random choice among fitting hosts (ref opportunistic.py:11-20)."""

    name = "opportunistic"

    def __init__(self, mode: str = "numpy", risk_weight: float = 0.0,
                 rework_cost: float = 1.0,
                 weights: Optional[PolicyWeights] = None):
        assert mode in ("naive", "numpy")
        self.mode = mode
        self.weights = resolve_weights(weights, risk_weight, rework_cost)
        self.risk_weight = self.weights.risk_weight
        self.rework_cost = self.weights.rework_cost

    def place(self, ctx: TickContext) -> np.ndarray:
        fold_quarantine(ctx)
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        placements = np.full(ctx.n_tasks, -1, dtype=np.int64)
        avail, demands = ctx.avail, ctx.demands
        if self.mode == "naive":
            rnd = ctx.scheduler.randomizer
            for i in range(ctx.n_tasks):
                fits = [
                    h for h in range(ctx.n_hosts) if np.all(avail[h] >= demands[i])
                ]
                if fits and risk is not None:
                    # Risk-aware: the random choice narrows to the
                    # minimum-risk tier of fitting hosts (same draw).
                    rmin = min(risk[h] for h in fits)
                    fits = [h for h in fits if risk[h] == rmin]
                if fits:
                    h = int(rnd.choice(fits))
                    avail[h] -= demands[i]
                    placements[i] = h
        else:
            u = tick_uniforms(ctx.scheduler.seed or 0, ctx.tick_seq, ctx.n_tasks)
            # Incremental fit mask over runs of identical demand vectors
            # (instances of one group are adjacent in submission order):
            # placing a task only mutates one host row, so only that mask
            # entry can change for the next identical demand.  The risk
            # tier is applied at SELECTION time against the cached mask,
            # so the incremental update stays exact.
            prev_d = None
            mask = None
            for i in range(ctx.n_tasks):
                d = demands[i]
                if not _same_demand(d, prev_d):
                    mask = np.all(avail >= d, axis=1)
                    prev_d = d
                n_fit = int(mask.sum())
                if n_fit:
                    fits = np.nonzero(mask)[0]
                    if risk is not None:
                        r = risk[fits]
                        fits = fits[r == r.min()]
                        n_fit = len(fits)
                    h = int(fits[min(int(u[i] * n_fit), n_fit - 1)])
                    avail[h] -= d
                    row = avail[h]
                    mask[h] = _row_fits(row, d)
                    placements[i] = h
        return placements


class FirstFitPolicy(Policy):
    """First host in cluster order that fits (ref vbp.py:6-29)."""

    name = "first_fit"

    def __init__(self, decreasing: bool = False, mode: str = "numpy",
                 risk_weight: float = 0.0, rework_cost: float = 1.0,
                 weights: Optional[PolicyWeights] = None):
        assert mode in ("naive", "numpy")
        self.decreasing = decreasing
        self.mode = mode
        self.weights = resolve_weights(weights, risk_weight, rework_cost)
        self.risk_weight = self.weights.risk_weight
        self.rework_cost = self.weights.rework_cost

    def place(self, ctx: TickContext) -> np.ndarray:
        fold_quarantine(ctx)
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        placements = np.full(ctx.n_tasks, -1, dtype=np.int64)
        avail, demands = ctx.avail, ctx.demands
        idxs = list(range(ctx.n_tasks))
        if self.decreasing:
            idxs = _sort_decreasing(demands, idxs)
            ctx.visit_order = idxs  # ref returns the sorted list (vbp.py:17)
        if risk is not None:
            # Risk-aware first fit: the host visit order becomes the
            # lexicographic (risk, index) order — argmin over fits of the
            # risk vector, ties to the lowest index (resolve_risk's
            # shared rule; identical to the kernels' masked argmin).
            for i in idxs:
                d = demands[i]
                mask = np.all(avail >= d, axis=1)
                if not mask.any():
                    continue
                h = int(np.argmin(np.where(mask, risk, np.inf)))
                avail[h] -= d
                placements[i] = h
            return placements
        if self.mode == "naive":
            for i in idxs:
                for h in range(ctx.n_hosts):
                    if np.all(avail[h] >= demands[i]):
                        avail[h] -= demands[i]
                        placements[i] = h
                        break
        else:
            # Scan-resume over runs of identical demands (see CostAware
            # ``_first_fit``): rows before the previous hit were rejected
            # against the same demand and are unmutated.
            prev_d = None
            start = 0
            for i in idxs:
                d = demands[i]
                if not _same_demand(d, prev_d):
                    start = 0
                    prev_d = d
                if start < 0:
                    continue
                row = avail[start]
                if _row_fits(row, d):
                    h = start
                else:
                    mask = np.all(avail[start:] >= d, axis=1)
                    if not mask.any():
                        start = -1
                        continue
                    h = start + int(np.argmax(mask))
                avail[h] -= d
                placements[i] = h
                start = h
        return placements


class BestFitPolicy(Policy):
    """Min residual-L2 host among strict fits (ref vbp.py:32-49)."""

    name = "best_fit"

    def __init__(self, decreasing: bool = False, mode: str = "numpy",
                 risk_weight: float = 0.0, rework_cost: float = 1.0,
                 weights: Optional[PolicyWeights] = None):
        assert mode in ("naive", "numpy")
        self.decreasing = decreasing
        self.mode = mode
        self.weights = resolve_weights(weights, risk_weight, rework_cost)
        self.risk_weight = self.weights.risk_weight
        self.rework_cost = self.weights.rework_cost

    def place(self, ctx: TickContext) -> np.ndarray:
        fold_quarantine(ctx)
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        placements = np.full(ctx.n_tasks, -1, dtype=np.int64)
        avail, demands = ctx.avail, ctx.demands
        idxs = list(range(ctx.n_tasks))
        if self.decreasing:
            idxs = _sort_decreasing(demands, idxs)
            ctx.visit_order = idxs  # ref returns the sorted list (vbp.py:42)
        if self.mode == "naive":
            for i in idxs:
                best, best_score = -1, np.inf
                for h in range(ctx.n_hosts):
                    if np.all(avail[h] > demands[i]):  # strict, ref :45
                        score = float(np.linalg.norm(avail[h] - demands[i]))
                        if risk is not None:
                            score = score + risk[h]
                        if score < best_score:
                            best, best_score = h, score
                if best >= 0:
                    avail[best] -= demands[i]
                    placements[i] = best
        else:
            # Incremental residual vector over runs of identical demands:
            # placing mutates one host row, so one residual entry updates.
            prev_d = None
            residual = None
            for i in idxs:
                d = demands[i]
                if not _same_demand(d, prev_d):
                    mask = np.all(avail > d, axis=1)  # strict, ref :45
                    residual = _norms(avail - d)
                    if risk is not None:
                        residual = residual + risk  # score += risk term
                    residual[~mask] = np.inf
                    prev_d = d
                h = int(np.argmin(residual))  # lowest index on ties
                if residual[h] == np.inf:
                    continue
                avail[h] -= d
                row = avail[h]
                if _row_fits_strict(row, d):
                    r = row - d  # same ops as _norms(avail - d) row-wise
                    residual[h] = np.sqrt(r[0] * r[0] + r[1] * r[1] + r[2] * r[2] + r[3] * r[3])
                    if risk is not None:
                        residual[h] = residual[h] + risk[h]
                else:
                    residual[h] = np.inf
                placements[i] = h
        return placements


class CostAwarePolicy(Policy):
    """Data-locality / egress-cost-aware placement — the PIVOT policy
    (ref cost_aware.py:11-127).

    Tasks are grouped by *anchor*: the zone-local storage at the majority
    predecessor placement locality (``_group_tasks``, ref ``:45-58``); root
    tasks anchor to a random storage per application.  Within a group,
    hosts are scored by round-trip egress cost × crowding decay /
    (residual-capacity norm × round-trip bandwidth) and greedily
    first-fit in score order (or best-fit per task).
    """

    name = "cost_aware"

    def __init__(
        self,
        bin_pack: str = "first-fit",
        sort_tasks: bool = False,
        sort_hosts: bool = False,
        realtime_bw: bool = False,
        host_decay: bool = False,
        mode: str = "numpy",
        risk_weight: float = 0.0,
        rework_cost: float = 1.0,
        weights: Optional[PolicyWeights] = None,
    ):
        assert bin_pack in ("first-fit", "best-fit")
        assert mode in ("naive", "numpy")
        self.bin_pack = bin_pack
        self.sort_tasks = sort_tasks
        self.sort_hosts = sort_hosts
        self.realtime_bw = realtime_bw
        self.host_decay = host_decay
        self.mode = mode
        self.weights = resolve_weights(weights, risk_weight, rework_cost)
        self.risk_weight = self.weights.risk_weight
        self.rework_cost = self.weights.rework_cost
        #: (w_cost, w_bw, w_norm) when any score exponent departs from
        #: the reference shape, else None — the None branch keeps the
        #: exact unparameterized score expressions below (no ``pow``),
        #: which is the default vector's bit-parity contract.
        self._score_exp = self.weights.score_exponents()

    def apply_weights(self, weights) -> None:
        super().apply_weights(weights)
        self._score_exp = self.weights.score_exponents()

    # -- grouping --------------------------------------------------------
    def group_tasks(
        self, ctx: TickContext
    ) -> "OrderedDict[object, List[int]]":
        """Anchor → task indices, in first-seen order (ref ``:45-58``).

        Keys are Storage nodes, or the Application for root task groups
        (resolved to a random storage at placement time).
        """
        cluster = ctx.cluster
        groups: "OrderedDict[object, List[int]]" = OrderedDict()
        for i, task in enumerate(ctx.tasks):
            group = task.group
            # Anchor memo: once a group is ready its predecessors are all
            # finished with immutable placements, so the majority vote is a
            # fixed function — compute it once per group, not per instance
            # per tick (the reference recomputes it for every task, every
            # tick: cost_aware.py:45-58).
            anchor = group.__dict__.get("_anchor_memo")
            if anchor is None:
                app = group.application
                pred_tasks = [
                    t
                    for p in app.get_predecessors(group.id)
                    for t in p.tasks
                    if t.placement is not None
                ]
                if pred_tasks:
                    # Majority placement; ties resolve to first occurrence,
                    # matching Counter insertion order in the reference.
                    counts: "OrderedDict[str, int]" = OrderedDict()
                    for t in pred_tasks:
                        counts[t.placement] = counts.get(t.placement, 0) + 1
                    majority = max(counts.items(), key=lambda kv: kv[1])[0]
                    locality = cluster.get_host(majority).locality
                    anchor = cluster.get_storage_by_locality(locality)
                else:
                    anchor = app
                group.__dict__["_anchor_memo"] = anchor
            groups.setdefault(anchor, []).append(i)
        return groups

    # -- scoring ---------------------------------------------------------
    def _roundtrip_vectors(
        self, ctx: TickContext, anchor
    ) -> Tuple[np.ndarray, np.ndarray]:
        """([H] roundtrip $ cost, [H] roundtrip bw) anchor↔host.

        The cost matrix comes from ``ctx.cost_matrix`` — the market's
        time-varying ``[Z, Z]`` slice when a spot-market environment is
        attached, the static ``meta.cost_matrix`` object itself (same
        ndarray, bit-identical scores) otherwise."""
        meta = ctx.meta
        az = meta.zone_index[anchor.locality]
        hz = ctx.host_zones
        cm = ctx.cost_matrix
        cost_rt = cm[az, hz] + cm[hz, az]
        if self.realtime_bw:
            bw_rt = np.array(
                [
                    ctx.cluster.get_route(anchor.id, h.id).realtime_bw
                    + ctx.cluster.get_route(h.id, anchor.id).realtime_bw
                    for h in ctx.hosts
                ]
            )
        else:
            bw_rt = meta.bw_matrix[az, hz] + meta.bw_matrix[hz, az]
        return cost_rt, bw_rt

    def _decay(self, ctx: TickContext, extra_tasks: np.ndarray) -> np.ndarray:
        """[H] crowding decay factor (ref ``:81,115``)."""
        if not self.host_decay:
            return np.ones(ctx.n_hosts)
        return np.maximum(ctx.host_task_counts + extra_tasks, 1).astype(np.float64)

    # -- placement -------------------------------------------------------
    def place(self, ctx: TickContext) -> np.ndarray:
        fold_quarantine(ctx)
        risk = resolve_risk(ctx, self.risk_weight, self.rework_cost)
        placements = np.full(ctx.n_tasks, -1, dtype=np.int64)
        avail, demands = ctx.avail, ctx.demands
        storage = ctx.cluster.storage
        extra_tasks = np.zeros(ctx.n_hosts, dtype=np.int32)  # placed this tick
        for anchor, idxs in self.group_tasks(ctx).items():
            if not hasattr(anchor, "locality"):  # root group: keyed storage
                anchor = storage[resolve_root_anchor(ctx, anchor, len(storage))]
            if self.sort_tasks:
                idxs = _sort_decreasing(demands, idxs)
            cost_rt, bw_rt = self._roundtrip_vectors(ctx, anchor)
            if self.bin_pack == "first-fit":
                self._first_fit(
                    ctx, idxs, avail, demands, cost_rt, bw_rt, placements,
                    risk,
                )
            else:
                self._best_fit(
                    ctx, idxs, avail, demands, cost_rt, bw_rt, extra_tasks,
                    placements, risk,
                )
        return placements

    def _first_fit(self, ctx, idxs, avail, demands, cost_rt, bw_rt,
                   placements, risk=None) -> None:
        """Hosts sorted once per group by score, then greedy first strict fit
        (ref ``:99-127``; scores use availability at sort time).

        The decay factor is the host task count at *tick start* — the
        reference reads ``len(h.tasks)``, which cannot change during a
        synchronous schedule() call (``cost_aware.py:115``) — unlike
        best-fit's live within-tick counter.
        """
        if self.sort_hosts:
            with np.errstate(divide="ignore"):
                if self._score_exp is None:
                    score = (
                        cost_rt
                        * self._decay(ctx, _NO_EXTRA)
                        / (_norms(avail) * bw_rt)
                    )
                else:
                    # Searchable exponents (PolicyWeights): pow form,
                    # engaged only off the default vector.
                    wc, wb, wn = self._score_exp
                    score = (
                        cost_rt ** wc
                        * self._decay(ctx, _NO_EXTRA)
                        / (_norms(avail) ** wn * bw_rt ** wb)
                    )
            if risk is not None:
                score = score + risk  # the shared score += risk rule
            order = np.argsort(score, kind="stable")
        elif risk is not None:
            # sort_hosts=False is an index-ordered selection: the risk
            # term replaces it with the lexicographic (risk, index) order
            # (resolve_risk's shared rule — the kernels' masked argmin
            # over a score of ``risk`` gives exactly this).
            order = np.argsort(risk, kind="stable")
        else:
            order = np.arange(ctx.n_hosts)
        if self.mode == "naive":
            for i in idxs:
                for h in order:
                    if np.all(avail[h] > demands[i]):  # strict, ref :124
                        avail[h] -= demands[i]
                        placements[i] = h
                        break
        else:
            # Gather once; placing at sorted position p only mutates row p,
            # so the working copy stays exact with one row write per task.
            avail_sorted = avail[order]
            # Start-pointer for runs of identical demand vectors (instances
            # of one task group, adjacent after the stable decreasing
            # sort): rows before the previous hit were rejected against the
            # same demand and have not changed since, so the scan resumes
            # there — bit-identical placements, O(remaining) per task.
            prev_d = None
            start = 0
            for i in idxs:
                d = demands[i]
                if not _same_demand(d, prev_d):
                    start = 0
                    prev_d = d
                if start < 0:  # previous identical demand found no fit
                    continue
                # Constant-time fast path: the run's previous hit row still
                # fits — rows before it were rejected against this same
                # demand and are unmutated, so it IS the first fit.
                row = avail_sorted[start]
                if _row_fits_strict(row, d):
                    p = start
                else:
                    mask = (avail_sorted[start:] > d).all(axis=1)
                    if not mask.any():
                        start = -1
                        continue
                    p = start + int(np.argmax(mask))
                h = int(order[p])
                avail[h] -= d
                avail_sorted[p] = avail[h]
                placements[i] = h
                start = p

    def _best_fit(
        self, ctx, idxs, avail, demands, cost_rt, bw_rt, extra_tasks,
        placements, risk=None,
    ) -> None:
        """Per-task min of cost × residual × decay / bw among non-strict fits
        (ref ``:63-97``); ``+ risk`` per host when the risk term engages."""
        if self.mode == "naive":
            for i in idxs:
                best, best_score = -1, np.inf
                for h in range(ctx.n_hosts):
                    if not np.all(avail[h] >= demands[i]):  # non-strict, ref :87
                        continue
                    r = float(np.linalg.norm(avail[h] - demands[i]))
                    decay = (
                        max(int(ctx.host_task_counts[h]) + int(extra_tasks[h]), 1)
                        if self.host_decay
                        else 1.0
                    )
                    if self._score_exp is None:
                        score = cost_rt[h] * r * decay / bw_rt[h]
                    else:
                        wc, wb, wn = self._score_exp
                        score = (
                            cost_rt[h] ** wc * r ** wn * decay
                            / bw_rt[h] ** wb
                        )
                    if risk is not None:
                        score = score + risk[h]
                    if score < best_score:
                        best, best_score = h, score
                if best >= 0:
                    avail[best] -= demands[i]
                    placements[i] = best
                    extra_tasks[best] += 1
        else:
            for i in idxs:
                mask = np.all(avail >= demands[i], axis=1)  # non-strict, ref :87
                if not mask.any():
                    continue
                residual = _norms(avail - demands[i])
                with np.errstate(divide="ignore", invalid="ignore"):
                    if self._score_exp is None:
                        score = (
                            cost_rt * residual
                            * self._decay(ctx, extra_tasks) / bw_rt
                        )
                    else:
                        wc, wb, wn = self._score_exp
                        score = (
                            cost_rt ** wc * residual ** wn
                            * self._decay(ctx, extra_tasks) / bw_rt ** wb
                        )
                if risk is not None:
                    score = score + risk  # the shared score += risk rule
                score[~mask] = np.inf
                h = int(np.argmin(score))
                avail[h] -= demands[i]
                placements[i] = h
                extra_tasks[h] += 1
