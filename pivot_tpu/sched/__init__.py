"""Two-level scheduler runtime: global tick loop + per-application local schedulers.

Capability parity with the reference's ``scheduler/__init__.py``:

  * ``GlobalScheduler``  — tick loop every ``interval`` sim-seconds: drain
    wait queue (LIFO) and submit queue into the ready batch, snapshot host
    state, invoke the placement policy, route placed tasks to the cluster's
    ``dispatch_q`` and unplaced tasks to the wait queue (ref ``:87-118``);
    completion listener that finishes tasks, releases DAG successors, and
    resubmits failed tasks — the infinite retry loop (ref ``:120-147``).
  * ``LocalScheduler``   — per-app: seeds the ready stack with DAG sources,
    pumps ready tasks (LIFO, matching the reference's OrderedDict.popitem)
    to the global submit queue every ``interval`` ticks (ref ``:150-222``).

The **policy boundary** is redesigned for the TPU backend: instead of the
reference's ``schedule(tasks)`` mutating task objects against a dict
snapshot, a policy receives a :class:`TickContext` — dense ``[T,4]`` demand
and ``[H,4]`` availability arrays plus zone vectors — and returns an ``[T]``
array of host indices (−1 = unplaced).  The same context feeds the naive
Python, vectorized numpy, and fused TPU implementations, which is what makes
placement-parity testing across backends possible.

Documented deviations from the reference (quirks fixed deliberately, see
SURVEY.md §4):
  * The reference caps the number of submit-queue items drained per tick at
    ``len(submit_q) - len(wait_q)`` (``scheduler/__init__.py:96-99``), so a
    non-empty wait queue starves fresh submissions; here the ready batch is
    wait queue + everything currently submitted.
  * Finished applications are actually removed from the local-scheduler
    registry (the reference pops by the wrong key, ``:145``, and rescans
    every app's DAG each tick).

**Retry governance** (round 7, ``sched/retry.py``): the reference's
resubmit-forever loop is now *governed* when the scheduler is built with
a :class:`~pivot_tpu.sched.retry.RetryPolicy` (per-task budgets +
deterministically-jittered exponential backoff; budget exhaustion
dead-letters the task, fails its application, and records the shed
reason) and/or a :class:`~pivot_tpu.sched.retry.HostCircuitBreaker`
(K consecutive failures quarantine a host for a cooldown; the ``[H]``
live mask in :attr:`TickContext.live_mask` — quarantines plus
spot-preemption drain flags — is fused into every placement backend's
fit mask).  Both default to ``None``, which keeps the loop bit-identical
to the reference-parity behavior above.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from pivot_tpu.des import Environment, Store
from pivot_tpu.infra import Cluster, Host
from pivot_tpu.infra.meter import Meter, SloMeter
from pivot_tpu.sched.retry import DeadLetter, HostCircuitBreaker, RetryPolicy
from pivot_tpu.utils import LogMixin
from pivot_tpu.utils.trace import NULL_TRACER, Tracer
from pivot_tpu.workload import Application, Task, TaskState

__all__ = [
    "TickContext",
    "Policy",
    "GlobalScheduler",
    "LocalScheduler",
    "DeadLetter",
    "HostCircuitBreaker",
    "RetryPolicy",
]


class TickContext:
    """Dense batch view of one scheduling tick — the policy/kernel feed.

    Arrays are index-aligned with ``tasks`` (rows) and the cluster host
    order (columns / host indices).
    """

    def __init__(
        self,
        scheduler: "GlobalScheduler",
        tasks: List[Task],
        tick_seq: int,
    ):
        cluster = scheduler.cluster
        self.scheduler = scheduler
        self.cluster = cluster
        self.meta = cluster.meta
        self.env_now = scheduler.env.now
        self.tick_seq = tick_seq
        self.tasks = tasks
        self.hosts: List[Host] = cluster.hosts
        # Mutable working copy: policies decrement as they assign within the
        # tick (greedy sequential semantics, ref scheduler snapshots).
        self.avail = cluster.availability_matrix()
        self.demands = (
            np.stack([t.demand for t in tasks])
            if tasks
            else np.zeros((0, 4), dtype=np.float64)
        )
        self._host_zones: Optional[np.ndarray] = None
        self._host_task_counts: Optional[np.ndarray] = None
        self._live_mask: Optional[np.ndarray] = None
        self._live_mask_set = False
        # Policies that iterate the batch in a different order than given
        # (the VBP decreasing arms) record it here: the reference's tick
        # loop consumes ``schedule(ready_q)``'s RETURN list — the sorted
        # one — so dispatch and wait-queue insertion follow the policy's
        # visit order, not batch order (ref ``scheduler/__init__.py:102-115``,
        # ``vbp.py:17,42``).  ``None`` means batch order (opportunistic
        # returns ``list(tasks)``, cost-aware returns ``tasks`` unsorted —
        # its sort happens per anchor bucket on a copy, ref
        # ``cost_aware.py:28-43``).
        self.visit_order: Optional[List[int]] = None

    @property
    def n_tasks(self) -> int:
        return len(self.tasks)

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    @property
    def host_zones(self) -> np.ndarray:
        if self._host_zones is None:
            self._host_zones = self.cluster.host_zone_vector()
        return self._host_zones

    @property
    def host_task_counts(self) -> np.ndarray:
        """[H] number of tasks currently resident per host (decay factor)."""
        if self._host_task_counts is None:
            self._host_task_counts = np.array(
                [h.n_tasks for h in self.hosts], dtype=np.int32
            )
        return self._host_task_counts

    @property
    def live_mask(self) -> Optional[np.ndarray]:
        """[H] bool quarantine mask for this tick — False marks hosts
        excluded from NEW placements: circuit-breaker quarantines
        (``scheduler.breaker``) and spot-preemption drain flags
        (``Host.draining``).  ``None`` = every host live, the
        allocation-free common case.  Down hosts are *not* represented
        here — the availability snapshot's −1 sentinel already keeps
        every fit mask off them.  CPU policies fold the mask into the
        availability working copy (``policies.fold_quarantine``); device
        policies pass it to the kernels' ``live`` argument — identical
        fit masks either way."""
        if self._live_mask_set:
            return self._live_mask
        self._live_mask_set = True
        breaker = getattr(self.scheduler, "breaker", None)
        now = self.env_now
        mask = None
        for i, h in enumerate(self.hosts):
            if getattr(h, "draining", False) or (
                breaker is not None and breaker.is_quarantined(h.id, now)
            ):
                if mask is None:
                    mask = np.ones(len(self.hosts), dtype=bool)
                mask[i] = False
        self._live_mask = mask
        return mask


class Policy(LogMixin):
    """A placement policy: consumes a TickContext, returns host indices."""

    name = "abstract"

    def place(self, ctx: TickContext) -> np.ndarray:
        """Return [T] int array of host indices; −1 leaves a task unplaced."""
        raise NotImplementedError

    def bind(self, scheduler: "GlobalScheduler") -> None:
        """Called once when attached to a scheduler (override to warm up)."""


class LocalScheduler(LogMixin):
    """Per-application scheduler: DAG readiness tracking + submission pump.

    Pump wake-ups land on the reference's tick grid — ``start_time + k·
    interval`` (ref ``scheduler/__init__.py:185-194``) — but are scheduled
    *on demand*: when the ready stack is empty nothing ticks, removing the
    reference's per-app idle polling without changing submission times.
    """

    def __init__(
        self,
        env: Environment,
        app: Application,
        submit_q: Store,
        interval: float = 5,
    ):
        self.env = env
        self.application = app
        self.submit_q = submit_q
        self.interval = interval
        self._ready_stack: List[Task] = []
        self._start_time = 0.0
        self._wake_armed = False

    def start(self) -> None:
        env, app = self.env, self.application
        app.start_time = env.now
        self._start_time = env.now
        for group in app.get_sources():
            for task in group.materialize_tasks():
                self._ready_stack.append(task)
        # First pump fires immediately (grid point k = 0).
        self._wake_armed = True
        env.schedule_callback(0.0, self._pump)

    def _pump(self) -> None:
        self._wake_armed = False
        submit = self.submit_q.put
        stack = self._ready_stack
        while stack:
            task = stack.pop()  # LIFO, ref popitem()
            if task.is_nascent:
                submit(task)

    def _arm_wake(self) -> None:
        """Schedule the next pump at the first grid point after now."""
        if self._wake_armed or not self._ready_stack:
            return
        elapsed = self.env.now - self._start_time
        k = int(elapsed // self.interval) + 1
        delay = self._start_time + k * self.interval - self.env.now
        self._wake_armed = True
        self.env.schedule_callback(delay, self._pump)

    def notify(self, task: Task) -> None:
        """Called by the global listener when one of our tasks finishes.

        Failed tasks never reach here — the listener resubmits them to the
        global queue directly (the retry loop lives in the global
        scheduler, not here).
        """
        assert task.is_finished
        group = task.group
        if group.is_finished:
            for succ in self.application.get_ready_successors(group.id):
                for t in succ.materialize_tasks():
                    self._ready_stack.append(t)
        self._arm_wake()


class GlobalScheduler(LogMixin):
    """The global tick loop + completion listener around a pluggable policy."""

    def __init__(
        self,
        env: Environment,
        cluster: Cluster,
        policy: Policy,
        interval: float = 5,
        seed: Optional[int] = None,
        meter: Optional[Meter] = None,
        tracer: Optional[Tracer] = None,
        retry: Optional[RetryPolicy] = None,
        breaker: Optional[HostCircuitBreaker] = None,
        slo: Optional[SloMeter] = None,
    ):
        self.env = env
        self.cluster = cluster
        self.policy = policy
        self.interval = interval
        self.seed = seed
        self.meter = meter
        self.tracer = tracer or NULL_TRACER
        #: Retry governance (``sched/retry.py``) — both None by default,
        #: which preserves the reference-parity resubmit-forever loop
        #: bit for bit.  ``slo`` (serving layer) receives shed reasons
        #: for dead-lettered tasks.
        self.retry = retry
        self.breaker = breaker
        self.slo = slo
        #: Terminal dead-letter queue, in dead-lettering order.
        self.dead_letters: List[DeadLetter] = []
        #: Tasks of failed applications dropped before (re)placement.
        self.n_cancelled = 0
        #: Placements that landed on a down or quarantined host — the
        #: invariant auditor asserts this stays empty (infra/audit.py).
        self.placement_violations: List[str] = []
        self._attempts: Dict[Task, int] = {}  # failures per live task
        self._failed_apps: set = set()
        self.randomizer = np.random.RandomState(seed)
        self.submit_q = Store(env)
        self._wait_stack: List[Task] = []
        # First dispatch tick that saw each still-unplaced task — the
        # submit→placement turnover clock (see _dispatch_loop).
        self._pending_since: Dict[Task, float] = {}
        self._local: Dict[str, LocalScheduler] = {}
        self._n_submitted = 0  # monotone; feeds keyed root-anchor ordinals
        self._n_unfinished = 0
        self._stopped = False
        self._tick_seq = 0
        policy.bind(self)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self.env.process(self._dispatch_loop())
        self.env.process(self._listen_loop())

    def stop(self) -> None:
        self._stopped = True

    @property
    def is_active(self) -> bool:
        return not self._stopped or self._n_unfinished > 0

    def submit(self, app: Application) -> None:
        if app.id in self._local:
            self.logger.error("application %s already exists", app.id)
            return
        # Submission ordinal: the stable identity the keyed root-anchor
        # draw uses (policies.resolve_root_anchor); equals the app's row
        # index in EnsembleWorkload, so DES and estimator key identically.
        # Monotone — ``_local`` drops finished apps, so its size recycles.
        app._submit_ordinal = self._n_submitted
        self._n_submitted += 1
        local = LocalScheduler(self.env, app, self.submit_q, self.interval)
        self._local[app.id] = local
        self._n_unfinished += 1
        local.start()

    def get_local(self, app_id: str) -> Optional[LocalScheduler]:
        return self._local.get(app_id)

    # -- the tick loop ---------------------------------------------------
    def _dispatch_loop(self):
        env, cluster = self.env, self.cluster
        while self.is_active:
            ready: List[Task] = []
            while self._wait_stack:
                ready.append(self._wait_stack.pop())  # LIFO, ref popitem()
            ready.extend(self.submit_q.drain())
            if self._failed_apps and ready:
                # A dead-lettered task fails its whole application;
                # sibling tasks still circulating (wait queue, submit
                # queue, late local-scheduler pumps) are cancelled here
                # rather than placed — the conservation auditor accounts
                # them via ``n_cancelled``.
                kept: List[Task] = []
                for task in ready:
                    app = task.application
                    if app is not None and app.id in self._failed_apps:
                        self._cancel_task(task)
                    else:
                        kept.append(task)
                ready = kept
            if ready:
                if self.meter:
                    self.meter.increment_scheduling_ops(len(ready))
                    # Turnover clock starts at the first dispatch tick that
                    # sees a task (≤1 tick after its Store put) and runs
                    # across wait-queue residency; a retry after an
                    # execution failure restarts it (the placement decision
                    # being timed is the new one).
                    now = env.now
                    for task in ready:
                        self._pending_since.setdefault(task, now)
                ctx = TickContext(self, ready, self._tick_seq)
                with self.tracer.span(
                    "scheduler", "tick", env.now, n_ready=len(ready)
                ) as span_args:
                    placements = self.policy.place(ctx)
                    if self.tracer.enabled:
                        span_args["n_placed"] = int(
                            sum(1 for h in placements if h >= 0)
                        )
                self._tick_seq += 1
                # Reference parity: consume placements in the policy's
                # visit order (``schedule()``'s return order) — it sets
                # both the within-tick dispatch sequence and, decisively,
                # the wait-queue insertion order that next tick's LIFO
                # drain reverses (ref ``scheduler/__init__.py:102-115``).
                visit = (
                    ctx.visit_order
                    if ctx.visit_order is not None
                    else range(len(ready))
                )
                live = ctx.live_mask
                for i in visit:
                    task, h_idx = ready[i], placements[i]
                    if not task.is_nascent:
                        self.logger.error("task %s not nascent at dispatch", task.id)
                        continue
                    if h_idx < 0:
                        task.placement = None
                        self._wait_stack.append(task)
                    else:
                        host = ctx.hosts[int(h_idx)]
                        if not host.up or (
                            live is not None and not live[int(h_idx)]
                        ):
                            self.placement_violations.append(
                                f"t={env.now:.3f}: task {task.id} placed on "
                                f"{'down' if not host.up else 'quarantined'} "
                                f"host {host.id}"
                            )
                        task.placement = host.id
                        cluster.dispatch_q.put(task)
                        task.set_submitted()
                        if self.meter:
                            self.meter.add_scheduling_turnover(
                                env.now - self._pending_since.pop(task, env.now)
                            )
            yield env.timeout(self.interval)

    # -- the completion listener -----------------------------------------
    def _listen_loop(self):
        env = self.env
        notify_q = self.cluster.notify_q
        while self.is_active:
            item = yield notify_q.get()
            self._handle_notification(item)
            # Same-instant batching: notifications already queued (e.g. a
            # whole admission-failure batch) are handled in FIFO order
            # without one get-event round-trip each.
            for queued in notify_q.drain():
                self._handle_notification(queued)

    def _handle_notification(self, item):
        env = self.env
        success, task = item
        app = task.application
        if app is None:
            self.logger.error("task %s has no application", task.id)
            return
        local = self._local.get(app.id)
        if local is None:
            if app.id in self._failed_apps:
                # Late notification for a dead-lettered application: an
                # in-flight sibling concluded after the app failed.
                # Account it so the conservation audit still balances.
                if success:
                    task.set_finished()
                else:
                    task.set_nascent()
                    task.placement = None
                    self._cancel_task(task)
                return
            self.logger.error("application %s unknown", app.id)
            return
        if success:
            if self.breaker is not None and task.placement is not None:
                self.breaker.record_success(task.placement)
            if self.retry is not None:
                self._attempts.pop(task, None)
            task.set_finished()
            self.tracer.emit(
                "task", "finished", env.now, id=task.id, host=task.placement
            )
            local.notify(task)
        else:
            failed_host = task.placement
            if self.breaker is not None and failed_host is not None:
                if self.breaker.record_failure(failed_host, env.now):
                    self.tracer.emit(
                        "host", "quarantined", env.now, id=failed_host,
                        until=env.now + self.breaker.cooldown,
                    )
            task.set_nascent()
            task.placement = None
            if self.retry is not None:
                attempts = self._attempts.get(task, 0) + 1
                self._attempts[task] = attempts
                if self.retry.exhausted(attempts):
                    self._dead_letter(task, failed_host, attempts)
                    return
                self.tracer.emit("task", "retry", env.now, id=task.id)
                delay = self.retry.backoff(attempts, task.id)
                if delay > 0.0:
                    # Backed-off resubmission: the task re-enters the
                    # submit queue only after its (deterministically
                    # jittered) delay — de-synchronizing the retry wave
                    # a correlated outage creates.
                    env.schedule_callback(
                        delay, lambda t=task: self.submit_q.put(t)
                    )
                else:
                    self.submit_q.put(task)
            else:
                self.tracer.emit("task", "retry", env.now, id=task.id)
                self.submit_q.put(task)
        if app.is_finished:
            app.end_time = env.now
            self.tracer.emit("app", "finished", env.now, id=app.id)
            self.logger.debug(
                "[%.3f] application %s finished in %.3f s",
                env.now,
                app.id,
                app.end_time - app.start_time,
            )
            self._local.pop(app.id, None)
            self._n_unfinished -= 1

    # -- retry governance (``sched/retry.py``) ----------------------------
    def _cancel_task(self, task: Task) -> None:
        """Drop a task whose application has already failed: it is never
        (re)placed; its pending bookkeeping is released."""
        self.n_cancelled += 1
        self._pending_since.pop(task, None)
        self._attempts.pop(task, None)
        self.tracer.emit("task", "cancelled", self.env.now, id=task.id)

    def _dead_letter(
        self, task: Task, host_id: Optional[str], attempts: int,
        reason: str = "retry_budget",
    ) -> None:
        """Terminal path for a budget-exhausted task: record it, shed the
        reason to the SLO meter, and fail its application (a DAG with a
        permanently lost task can never finish — leaving it live would
        keep the scheduler loop alive forever, the reference's wedge)."""
        task.set_dead()
        self._attempts.pop(task, None)
        self._pending_since.pop(task, None)
        entry = DeadLetter(
            task.id, task.application.id, host_id, reason, self.env.now,
            attempts,
        )
        self.dead_letters.append(entry)
        if self.slo is not None:
            self.slo.record_shed(reason)
        self.tracer.emit(
            "task", "dead_letter", self.env.now, id=task.id, reason=reason,
            attempts=attempts, host=host_id,
        )
        self.logger.warning(
            "[%.3f] task %s dead-lettered after %d attempts (%s)",
            self.env.now, task.id, attempts, reason,
        )
        self._fail_application(task.application)

    def _fail_application(self, app: Application) -> None:
        if app.id in self._failed_apps:
            return
        self._failed_apps.add(app.id)
        app.failed = True
        app.end_time = self.env.now
        self.tracer.emit("app", "failed", self.env.now, id=app.id)
        if self._local.pop(app.id, None) is not None:
            self._n_unfinished -= 1
