"""Two-level scheduler runtime: global tick loop + per-application local schedulers.

Capability parity with the reference's ``scheduler/__init__.py``:

  * ``GlobalScheduler``  — tick loop every ``interval`` sim-seconds: drain
    wait queue (LIFO) and submit queue into the ready batch, snapshot host
    state, invoke the placement policy, route placed tasks to the cluster's
    ``dispatch_q`` and unplaced tasks to the wait queue (ref ``:87-118``);
    completion listener that finishes tasks, releases DAG successors, and
    resubmits failed tasks — the infinite retry loop (ref ``:120-147``).
  * ``LocalScheduler``   — per-app: seeds the ready stack with DAG sources,
    pumps ready tasks (LIFO, matching the reference's OrderedDict.popitem)
    to the global submit queue every ``interval`` ticks (ref ``:150-222``).

The **policy boundary** is redesigned for the TPU backend: instead of the
reference's ``schedule(tasks)`` mutating task objects against a dict
snapshot, a policy receives a :class:`TickContext` — dense ``[T,4]`` demand
and ``[H,4]`` availability arrays plus zone vectors — and returns an ``[T]``
array of host indices (−1 = unplaced).  The same context feeds the naive
Python, vectorized numpy, and fused TPU implementations, which is what makes
placement-parity testing across backends possible.

Documented deviations from the reference (quirks fixed deliberately, see
SURVEY.md §4):
  * The reference caps the number of submit-queue items drained per tick at
    ``len(submit_q) - len(wait_q)`` (``scheduler/__init__.py:96-99``), so a
    non-empty wait queue starves fresh submissions; here the ready batch is
    wait queue + everything currently submitted.
  * Finished applications are actually removed from the local-scheduler
    registry (the reference pops by the wrong key, ``:145``, and rescans
    every app's DAG each tick).

**Retry governance** (round 7, ``sched/retry.py``): the reference's
resubmit-forever loop is now *governed* when the scheduler is built with
a :class:`~pivot_tpu.sched.retry.RetryPolicy` (per-task budgets +
deterministically-jittered exponential backoff; budget exhaustion
dead-letters the task, fails its application, and records the shed
reason) and/or a :class:`~pivot_tpu.sched.retry.HostCircuitBreaker`
(K consecutive failures quarantine a host for a cooldown; the ``[H]``
live mask in :attr:`TickContext.live_mask` — quarantines plus
spot-preemption drain flags — is fused into every placement backend's
fit mask).  Both default to ``None``, which keeps the loop bit-identical
to the reference-parity behavior above.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from pivot_tpu.des import Callback, Environment, Store
from pivot_tpu.infra import Cluster, Host
from pivot_tpu.infra.meter import Meter, SloMeter
from pivot_tpu.sched.retry import DeadLetter, HostCircuitBreaker, RetryPolicy
from pivot_tpu.utils import LogMixin
from pivot_tpu.utils.trace import NULL_TRACER, Tracer
from pivot_tpu.workload import Application, Task, TaskState

__all__ = [
    "TickContext",
    "Policy",
    "GlobalScheduler",
    "LocalScheduler",
    "DeadLetter",
    "HostCircuitBreaker",
    "RetryPolicy",
]


class TickContext:
    """Dense batch view of one scheduling tick — the policy/kernel feed.

    Arrays are index-aligned with ``tasks`` (rows) and the cluster host
    order (columns / host indices).
    """

    def __init__(
        self,
        scheduler: "GlobalScheduler",
        tasks: List[Task],
        tick_seq: int,
    ):
        cluster = scheduler.cluster
        self.scheduler = scheduler
        self.cluster = cluster
        self.meta = cluster.meta
        self.env_now = scheduler.env.now
        self.tick_seq = tick_seq
        self.tasks = tasks
        self.hosts: List[Host] = cluster.hosts
        # Mutable working copy: policies decrement as they assign within the
        # tick (greedy sequential semantics, ref scheduler snapshots).
        self.avail = cluster.availability_matrix()
        self.demands = (
            np.stack([t.demand for t in tasks])
            if tasks
            else np.zeros((0, 4), dtype=np.float64)
        )
        self._host_zones: Optional[np.ndarray] = None
        self._host_task_counts: Optional[np.ndarray] = None
        self._live_mask: Optional[np.ndarray] = None
        self._live_mask_set = False
        self._hazard: Optional[np.ndarray] = None
        self._hazard_set = False
        # Policies that iterate the batch in a different order than given
        # (the VBP decreasing arms) record it here: the reference's tick
        # loop consumes ``schedule(ready_q)``'s RETURN list — the sorted
        # one — so dispatch and wait-queue insertion follow the policy's
        # visit order, not batch order (ref ``scheduler/__init__.py:102-115``,
        # ``vbp.py:17,42``).  ``None`` means batch order (opportunistic
        # returns ``list(tasks)``, cost-aware returns ``tasks`` unsorted —
        # its sort happens per anchor bucket on a copy, ref
        # ``cost_aware.py:28-43``).
        self.visit_order: Optional[List[int]] = None

    @property
    def n_tasks(self) -> int:
        return len(self.tasks)

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    @property
    def host_zones(self) -> np.ndarray:
        if self._host_zones is None:
            self._host_zones = self.cluster.host_zone_vector()
        return self._host_zones

    @property
    def host_task_counts(self) -> np.ndarray:
        """[H] number of tasks currently resident per host (decay factor)."""
        if self._host_task_counts is None:
            self._host_task_counts = np.array(
                [h.n_tasks for h in self.hosts], dtype=np.int32
            )
        return self._host_task_counts

    @property
    def live_mask(self) -> Optional[np.ndarray]:
        """[H] bool quarantine mask for this tick — False marks hosts
        excluded from NEW placements: circuit-breaker quarantines
        (``scheduler.breaker``) and spot-preemption drain flags
        (``Host.draining``).  ``None`` = every host live, the
        allocation-free common case.  Down hosts are *not* represented
        here — the availability snapshot's −1 sentinel already keeps
        every fit mask off them.  CPU policies fold the mask into the
        availability working copy (``policies.fold_quarantine``); device
        policies pass it to the kernels' ``live`` argument — identical
        fit masks either way."""
        if self._live_mask_set:
            return self._live_mask
        self._live_mask_set = True
        breaker = getattr(self.scheduler, "breaker", None)
        now = self.env_now
        mask = None
        for i, h in enumerate(self.hosts):
            if getattr(h, "draining", False) or (
                breaker is not None and breaker.is_quarantined(h.id, now)
            ):
                if mask is None:
                    mask = np.ones(len(self.hosts), dtype=bool)
                mask[i] = False
        self._live_mask = mask
        return mask

    @property
    def hazard_vector(self) -> Optional[np.ndarray]:
        """[H] per-host spot-preemption hazard (events/host/sim-second) at
        this tick's instant, gathered from the scheduler's
        :class:`~pivot_tpu.infra.market.MarketSchedule` through the
        cluster's host→zone map — the feed of the risk-aware scoring term
        (``policies.resolve_risk``).  ``None`` when the scheduler carries
        no market environment: the exact pre-market code path, no hazard
        arrays anywhere downstream."""
        if not self._hazard_set:
            self._hazard_set = True
            market = getattr(self.scheduler, "market", None)
            if market is not None:
                self._hazard = market.hazard_vector(
                    self.env_now, self.host_zones
                )
        return self._hazard

    @property
    def cost_matrix(self) -> np.ndarray:
        """The tick's ``[Z, Z]`` egress-cost matrix: the market-scaled
        slice of the ``[P, Z, Z]`` tensor when a
        :class:`~pivot_tpu.infra.market.MarketSchedule` is attached
        (``MarketSchedule.cost_matrix_at`` — cached per segment, so ticks
        inside one price segment share the identical ndarray), else the
        static ``meta.cost_matrix`` object itself — bit-identical to
        every pre-market caller."""
        market = getattr(self.scheduler, "market", None)
        if market is None:
            return self.meta.cost_matrix
        return market.cost_matrix_at(self.env_now, self.meta)


class Policy(LogMixin):
    """A placement policy: consumes a TickContext, returns host indices."""

    name = "abstract"

    def place(self, ctx: TickContext) -> np.ndarray:
        """Return [T] int array of host indices; −1 leaves a task unplaced."""
        raise NotImplementedError

    def bind(self, scheduler: "GlobalScheduler") -> None:
        """Called once when attached to a scheduler (override to warm up)."""

    def apply_weights(self, weights) -> None:
        """Hot-swap the scoring-weight vector on a LIVE policy.

        The promotion surface of model-predictive serving
        (``pivot_tpu/mpc``): every concrete policy resolves its risk
        term per :meth:`place` call (``policies.resolve_risk``), so
        swapping the attributes here takes effect on the next decision
        without re-binding or recompiling anything.  Subclasses that
        cache derived scoring state (``_score_exp``) or own a CPU twin
        override and extend this.
        """
        from pivot_tpu.search.weights import PolicyWeights

        w = (
            weights
            if isinstance(weights, PolicyWeights)
            else PolicyWeights.from_array(weights)
        ).validate()
        self.weights = w
        self.risk_weight = w.risk_weight
        self.rework_cost = w.rework_cost


class LocalScheduler(LogMixin):
    """Per-application scheduler: DAG readiness tracking + submission pump.

    Pump wake-ups land on the reference's tick grid — ``start_time + k·
    interval`` (ref ``scheduler/__init__.py:185-194``) — but are scheduled
    *on demand*: when the ready stack is empty nothing ticks, removing the
    reference's per-app idle polling without changing submission times.
    """

    def __init__(
        self,
        env: Environment,
        app: Application,
        submit_q: Store,
        interval: float = 5,
        scheduler: Optional["GlobalScheduler"] = None,
    ):
        self.env = env
        self.application = app
        self.submit_q = submit_q
        self.interval = interval
        self.scheduler = scheduler
        self._ready_stack: List[Task] = []
        self._start_time = 0.0
        self._wake_armed = False
        #: The armed pump's heap entry — tagged with ``owner=self`` so the
        #: pure-tick-run extractor (``GlobalScheduler._extract_span``) can
        #: recognize, snapshot, and absorb the delivery into a fused span
        #: (cancelling the entry so it cannot double-deliver).
        self._wake_cb: Optional[Callback] = None

    def start(self) -> None:
        env, app = self.env, self.application
        app.start_time = env.now
        self._start_time = env.now
        for group in app.get_sources():
            for task in group.materialize_tasks():
                self._ready_stack.append(task)
        # First pump fires immediately (grid point k = 0).
        self._wake_armed = True
        self._wake_cb = env.schedule_callback(0.0, self._pump)
        self._wake_cb.owner = self
        if self.scheduler is not None:
            self.scheduler._armed_pumps += 1

    def _pump(self) -> None:
        self._wake_armed = False
        self._wake_cb = None
        if self.scheduler is not None:
            self.scheduler._armed_pumps -= 1
            # Submissions mutate the ready set a fused span speculated
            # over — an un-absorbed pump firing mid-replay must abort the
            # remaining span ticks (``_replay_span``'s epoch check).
            self.scheduler._span_epoch += 1
        submit = self.submit_q.put
        stack = self._ready_stack
        while stack:
            task = stack.pop()  # LIFO, ref popitem()
            if task.is_nascent:
                submit(task)

    def pump_snapshot(self) -> List[Task]:
        """The tasks the armed pump will deliver when it fires, in
        delivery order.  Valid across a pure window: stack membership
        only changes via completions (which abort fused spans before the
        affected tick) and nascency only via placement (stack tasks are
        unplaced until delivered) — so the span extractor can fold this
        as the pump's future delivery without touching the pump itself."""
        return [t for t in reversed(self._ready_stack) if t.is_nascent]

    def _arm_wake(self) -> None:
        """Schedule the next pump at the first grid point after now."""
        if self._wake_armed or not self._ready_stack:
            return
        elapsed = self.env.now - self._start_time
        k = int(elapsed // self.interval) + 1
        delay = self._start_time + k * self.interval - self.env.now
        self._wake_armed = True
        self._wake_cb = self.env.schedule_callback(delay, self._pump)
        self._wake_cb.owner = self
        if self.scheduler is not None:
            self.scheduler._armed_pumps += 1

    def notify(self, task: Task) -> None:
        """Called by the global listener when one of our tasks finishes.

        Failed tasks never reach here — the listener resubmits them to the
        global queue directly (the retry loop lives in the global
        scheduler, not here).
        """
        assert task.is_finished
        group = task.group
        if group.is_finished:
            for succ in self.application.get_ready_successors(group.id):
                for t in succ.materialize_tasks():
                    self._ready_stack.append(t)
        self._arm_wake()


class SpanPlan:
    """One extracted pure tick run, priced and served as a single fused
    device dispatch (``ops/tickloop.py``).

    ``slots`` is the span's task universe: the tick-0 ready batch in
    batch order, then each in-window pump delivery (cohort) in fire
    order; ``arrive[s]`` is the tick index at which slot ``s`` joins the
    ready pool.  ``outcome`` is filled by the policy's ``place_span``
    (slot-indexed per-tick placements).  The plan never mutates DES
    state: the folded pumps stay armed and fire normally during replay —
    the fused program merely *pre-computed* what they will deliver.
    """

    __slots__ = (
        "ctx", "grid", "slots", "arrive", "pump_ticks", "epoch", "disturb",
        "outcome",
    )

    def __init__(self, ctx, grid, slots, arrive, pump_ticks, epoch,
                 disturb=0):
        self.ctx = ctx
        self.grid = grid  # [K] exact tick instants (iterated fl-adds)
        self.slots = slots  # [S] Task — ready batch, then cohorts
        self.arrive = arrive  # [S] int — delivery tick per slot
        self.pump_ticks = pump_ticks  # delivery tick per folded pump
        self.epoch = epoch  # span epoch at extraction
        self.disturb = disturb  # disturbance epoch at extraction
        self.outcome = None

    @property
    def n_ticks(self) -> int:
        return len(self.grid)


class GlobalScheduler(LogMixin):
    """The global tick loop + completion listener around a pluggable policy."""

    def __init__(
        self,
        env: Environment,
        cluster: Cluster,
        policy: Policy,
        interval: float = 5,
        seed: Optional[int] = None,
        meter: Optional[Meter] = None,
        tracer: Optional[Tracer] = None,
        retry: Optional[RetryPolicy] = None,
        breaker: Optional[HostCircuitBreaker] = None,
        slo: Optional[SloMeter] = None,
        fuse_spans: bool = True,
        market=None,
    ):
        self.env = env
        self.cluster = cluster
        self.policy = policy
        self.interval = interval
        self.seed = seed
        self.meter = meter
        self.tracer = tracer or NULL_TRACER
        #: Spot-market environment (``infra/market.py``): per-zone
        #: time-varying price multipliers and preemption hazards.  When
        #: set, every :class:`TickContext` exposes the tick's [H] hazard
        #: vector (risk-aware scoring) and the market-scaled egress-cost
        #: matrix.  ``None`` (default) keeps the static-world code paths
        #: bit-identical to pre-market behavior.
        self.market = market
        if market is not None and getattr(cluster, "meta", None) is not None:
            # Eager catalog check: a schedule generated against a different
            # locality file would otherwise surface deep inside a tick as
            # an IndexError (hazard gather) or, worse, silently score every
            # host with the wrong zone's hazard.
            market.check_zones(cluster.meta)
        #: Retry governance (``sched/retry.py``) — both None by default,
        #: which preserves the reference-parity resubmit-forever loop
        #: bit for bit.  ``slo`` (serving layer) receives shed reasons
        #: for dead-lettered tasks.
        self.retry = retry
        self.breaker = breaker
        self.slo = slo
        #: Terminal dead-letter queue, in dead-lettering order.
        self.dead_letters: List[DeadLetter] = []
        #: Tasks of failed applications dropped before (re)placement.
        self.n_cancelled = 0
        #: Placements that landed on a down or quarantined host — the
        #: invariant auditor asserts this stays empty (infra/audit.py).
        self.placement_violations: List[str] = []
        #: Proactive-survival counters (``on_preempt_warning``): queued
        #: tasks migrated off a draining host before starting, and doomed
        #: running tasks restarted at the warning instead of wasting the
        #: whole lead window.
        self.n_migrated = 0
        self.n_proactive_restarts = 0
        self._attempts: Dict[Task, int] = {}  # failures per live task
        self._failed_apps: set = set()
        self.randomizer = np.random.RandomState(seed)
        self.submit_q = Store(env)
        self._wait_stack: List[Task] = []
        # First dispatch tick that saw each still-unplaced task — the
        # submit→placement turnover clock (see _dispatch_loop).
        self._pending_since: Dict[Task, float] = {}
        self._local: Dict[str, LocalScheduler] = {}
        self._n_submitted = 0  # monotone; feeds keyed root-anchor ordinals
        self._n_unfinished = 0
        self._stopped = False
        self._tick_seq = 0
        #: Pure-tick-run fusion (round 8).  When on, the dispatch loop
        #: (a) fast-forwards across windows of provably no-op ticks
        #: instead of paying one policy dispatch each (availability only
        #: decreases within a pure window, so a tick that leaves tasks
        #: unplaced proves every later in-window tick places nothing),
        #: and (b) hands whole windows WITH in-window pump deliveries to
        #: a span-capable device policy (``place_span``) as ONE fused
        #: device program (``ops/tickloop.py``).  Placements, meters, and
        #: wait-queue order are bit-identical either way — asserted by
        #: ``tests/test_tickloop.py``'s DES parity tests.
        self.fuse_spans = fuse_spans
        #: Monotone counter of scheduler-visible mutations (completions,
        #: submissions, un-absorbed pump fires).  A fused span's replay
        #: commits precomputed ticks only while this stays unchanged; any
        #: bump aborts the remaining span (the committed prefix is exact).
        self._span_epoch = 0
        #: Disturbance sub-counter (round 20): the epoch bumps that are
        #: NOT pure arrivals — completions, withdrawals, preemption
        #: drains.  A span-epoch mismatch with this unchanged means the
        #: only in-window mutations were submissions + pump fires, which
        #: is the mid-span-splice qualifying condition: the new work can
        #: be JOINED into the running span (``_try_splice``) instead of
        #: aborting it.  Any disturbance still aborts exactly as before.
        self._disturb_epoch = 0
        #: Mid-span-splice admission gate: an optional ``task -> bool``
        #: predicate every mid-span arrival must pass before the running
        #: span re-runs with it joined.  The serve driver points this at
        #: its tier policy (tier-0 latency-critical sessions splice;
        #: batch tiers wait for the flush boundary).  ``None`` admits
        #: every arrival (when the policy has splice enabled at all).
        self.splice_gate = None
        #: Serving's SLO-checkpoint span bound (round 17,
        #: ``fuse_spans="slo"``): an optional zero-arg callable returning
        #: a sim-time horizon spans must not cross.  The serve driver
        #: points it at its release frontier, so a fused span never
        #: speculates past the last revealed arrival — each span ends at
        #: an admission checkpoint where the SLO meter records exactly
        #: one decision latency (``serve/session.py``).  ``None`` (the
        #: batch default) leaves span extraction unchanged.
        self.span_horizon = None
        self._ff_evt = None  # pending fast-forward wake (early-wakeable)
        self._ff_cb: Optional[Callback] = None
        self._ff_anchor = 0.0  # tick-grid anchor of the pending wake
        self._ff_rescheduled = False  # a submit pulled the wake earlier
        self._ff_target = float("inf")
        #: Armed local-pump count — the O(1) gate on span extraction
        #: (maintained by LocalScheduler arm/fire).
        self._armed_pumps = 0
        #: Fusion observability: fast-forwarded no-op ticks, fused spans
        #: served / their tick count, replay aborts, declined plans.
        self.span_stats: Dict[str, int] = {
            "ff_ticks": 0,
            "fused_spans": 0,
            "fused_ticks": 0,
            "span_aborts": 0,
            "spans_declined": 0,
            # Span-length observability (round 18): the longest span
            # extracted and the sum of extracted lengths — fragmentation
            # diagnostics for the ragged batcher (extracted length is
            # what the K-bucket ladder quantises; committed ticks are
            # ``fused_ticks``).  Always present, zero under per-tick
            # dispatch, so summary key sets match across serve arms.
            "span_ticks_max": 0,
            "span_ticks_sum": 0,
            # Mid-span splices committed (round 20): arrivals joined into
            # a RUNNING span without waiting for the flush boundary.
            "span_splices": 0,
        }
        policy.bind(self)

    def _stage_task(self, task: Task, name: str, **args) -> None:
        """Causal-trace hook (round 14): link a task-level event into
        its serve job's parent-linked chain when the app carries a
        trace id (stamped by the serve driver at admission).  Call
        sites gate on ``self.tracer.enabled`` so the disabled path
        costs nothing; the payload is sim-time only — the wall side is
        stamped inside ``pivot_tpu/obs`` (the determinism boundary)."""
        trace = getattr(task.application, "_obs_trace", None)
        if trace is not None:
            self.tracer.stage(
                trace, name, sim=self.env.now, task=task.id, **args
            )

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self.env.process(self._dispatch_loop())
        self.env.process(self._listen_loop())

    def stop(self) -> None:
        self._stopped = True

    @property
    def is_active(self) -> bool:
        return not self._stopped or self._n_unfinished > 0

    def submit(self, app: Application) -> None:
        if app.id in self._local:
            self.logger.error("application %s already exists", app.id)
            return
        # Submission ordinal: the stable identity the keyed root-anchor
        # draw uses (policies.resolve_root_anchor); equals the app's row
        # index in EnsembleWorkload, so DES and estimator key identically.
        # Monotone — ``_local`` drops finished apps, so its size recycles.
        app._submit_ordinal = self._n_submitted
        self._n_submitted += 1
        self._span_epoch += 1
        local = LocalScheduler(
            self.env, app, self.submit_q, self.interval, scheduler=self
        )
        self._local[app.id] = local
        self._n_unfinished += 1
        local.start()
        # A submission while the dispatch loop sleeps across a
        # fast-forwarded window (serve-mode thread injection) must pull
        # the wake back to the next grid tick, or the new app would wait
        # out the whole window.
        if self._ff_evt is not None and not self._ff_evt.triggered:
            self._reschedule_ff_wake()

    def get_local(self, app_id: str) -> Optional[LocalScheduler]:
        return self._local.get(app_id)

    def can_withdraw(self, app: Application) -> bool:
        """True iff ``app`` is *admitted-but-unplaced*: it is live here
        and no materialized task has ever left NASCENT (none submitted,
        running, finished — and none ever failed, which would leave a
        backed-off resubmission callback in flight).  Only such apps are
        preemptible: withdrawing them cancels pure bookkeeping, no
        in-flight execution or heap event refers to them afterwards."""
        if self._local.get(app.id) is None:
            return False
        for group in app.groups:
            for task in group.tasks:
                if not task.is_nascent or task in self._attempts:
                    return False
        return True

    def withdraw(self, app: Application) -> bool:
        """In-queue preemption (round 9, the serve driver's victim path):
        remove an admitted-but-unplaced application from the scheduler —
        its armed local pump is cancelled, its tasks are purged from the
        wait stack and submit queue, and the app stops counting toward
        ``_n_unfinished`` — as if it had never been submitted.  Returns
        False (and mutates nothing) when the app is not withdrawable
        (already placed / running / finished / unknown).  Must run on
        the thread that owns this scheduler's event kernel."""
        if not self.can_withdraw(app):
            return False
        local = self._local.pop(app.id)
        if local._wake_armed and local._wake_cb is not None:
            local._wake_cb.cancel()
            local._wake_armed = False
            local._wake_cb = None
            self._armed_pumps -= 1
        mine = {t for g in app.groups for t in g.tasks}
        self._wait_stack = [t for t in self._wait_stack if t not in mine]
        self.submit_q.items[:] = [
            t for t in self.submit_q.items if t not in mine
        ]
        for t in mine:
            self._pending_since.pop(t, None)
        self._n_unfinished -= 1
        # Withdrawal mutates the ready universe a fused span may have
        # speculated over — and wakes the loop's fast-forward sleep path
        # conservatively via the epoch bump at its next check.
        self._span_epoch += 1
        self._disturb_epoch += 1
        self.tracer.emit("app", "withdrawn", self.env.now, id=app.id)
        return True

    # -- proactive spot survival (round 11, ``infra/market.py``) -----------
    def enable_proactive_drain(self, injector) -> None:
        """Register this scheduler's proactive-survival handler on a
        :class:`~pivot_tpu.infra.faults.FaultInjector`: every
        spot-preemption *warning* (after ``Host.draining`` is set, so the
        live mask already excludes the host from new placements) runs
        :meth:`on_preempt_warning`.  Without this call the scheduler is
        purely reactive — the warning drains, the abort kills, the retry
        loop restarts — which is the hazard-blind baseline the
        ``spot_survival`` bench compares against."""
        if getattr(self.cluster, "executor", None) is None:
            # Only the 'fast' executor backend exposes eviction; on the
            # 'process' backend warnings still migrate queued tasks, but
            # doomed residents burn their whole lead window — results
            # diverge from the 'fast' backend.
            self.logger.warning(
                "proactive drain: cluster executor backend has no "
                "eviction support (ClusterConfig.executor != 'fast'); "
                "doomed running tasks will not be restarted early"
            )
        injector.add_warning_hook(self.on_preempt_warning)

    def on_preempt_warning(self, host, lead: float = 0.0) -> None:
        """The drain → migrate → restart half of spot survival (Bamboo /
        SpotServe shape, PAPERS.md), run at the preemption WARNING:

          * **migrate**: tasks already *placed* on the doomed host but not
            yet started (sitting in the cluster's dispatch queue) are
            pulled back to NASCENT and resubmitted for a re-decision next
            tick — they never touch the host, consume no retry attempt,
            and re-place with the drain mask (and the risk term) active;
          * **restart**: running residents that provably cannot conclude
            before the abort (``now + lead``) are evicted NOW — capacity
            refunded (the machine is alive), the execution aborted, and
            the task surfaced as a governed retry — instead of burning
            the whole lead window on doomed compute that the abort would
            waste anyway (the reactive arm's rework bill).

        Residents that CAN finish inside the lead are left to drain out —
        evicting them would turn free completions into retries.  The
        scheduler-visible mutations bump the span epoch, so any fused
        span speculating over this instant aborts exactly."""
        env = self.env
        # Migrate queued-not-yet-started tasks back to a re-decision.
        dispatch_q = self.cluster.dispatch_q
        mine = [
            t for t in dispatch_q.items
            if isinstance(t, Task) and t.placement == host.id
        ]
        if mine:
            dispatch_q.items[:] = [
                t for t in dispatch_q.items if t not in mine
            ]
            for task in mine:
                task.set_nascent()
                task.placement = None
                self.submit_q.put(task)
                self.n_migrated += 1
                self.tracer.emit(
                    "task", "migrated", env.now, id=task.id, host=host.id
                )
            self._span_epoch += 1
            self._disturb_epoch += 1
        # Restart doomed running residents under the retry governor.
        executor = getattr(self.cluster, "executor", None)
        if executor is not None and lead >= 0:
            evicted = executor.evict_doomed(host, env.now + lead)
            if evicted:
                self.n_proactive_restarts += len(evicted)
                for task in evicted:
                    self.tracer.emit(
                        "task", "proactive_restart", env.now,
                        id=task.id, host=host.id,
                    )
                self._span_epoch += 1
                self._disturb_epoch += 1

    # -- the tick loop ---------------------------------------------------
    def _dispatch_loop(self):
        env, cluster = self.env, self.cluster
        while self.is_active:
            at_boundary = False
            ready: List[Task] = []
            while self._wait_stack:
                ready.append(self._wait_stack.pop())  # LIFO, ref popitem()
            ready.extend(self.submit_q.drain())
            if self._failed_apps and ready:
                # A dead-lettered task fails its whole application;
                # sibling tasks still circulating (wait queue, submit
                # queue, late local-scheduler pumps) are cancelled here
                # rather than placed — the conservation auditor accounts
                # them via ``n_cancelled``.
                kept: List[Task] = []
                for task in ready:
                    app = task.application
                    if app is not None and app.id in self._failed_apps:
                        self._cancel_task(task)
                    else:
                        kept.append(task)
                ready = kept
            if ready:
                if self.meter:
                    self.meter.increment_scheduling_ops(len(ready))
                    # Turnover clock starts at the first dispatch tick that
                    # sees a task (≤1 tick after its Store put) and runs
                    # across wait-queue residency; a retry after an
                    # execution failure restarts it (the placement decision
                    # being timed is the new one).
                    now = env.now
                    for task in ready:
                        self._pending_since.setdefault(task, now)
                ctx = TickContext(self, ready, self._tick_seq)
                plan = (
                    self._extract_span(ctx) if self.fuse_spans else None
                )
                if plan is not None:
                    at_boundary = yield from self._serve_span(ctx, plan)
                else:
                    with self.tracer.span(
                        "scheduler", "tick", env.now, n_ready=len(ready)
                    ) as span_args:
                        placements = self.policy.place(ctx)
                        if self.tracer.enabled:
                            span_args["n_placed"] = int(
                                sum(1 for h in placements if h >= 0)
                            )
                    self._tick_seq += 1
                    # Reference parity: consume placements in the
                    # policy's visit order (``schedule()``'s return
                    # order) — it sets both the within-tick dispatch
                    # sequence and, decisively, the wait-queue insertion
                    # order that next tick's LIFO drain reverses (ref
                    # ``scheduler/__init__.py:102-115``).
                    visit = (
                        ctx.visit_order
                        if ctx.visit_order is not None
                        else range(len(ready))
                    )
                    self._dispatch_tick(ctx, ready, placements, visit)
            if at_boundary:
                # A span replay aborted at a fresh, unprocessed tick
                # instant: run that tick now, without sleeping.
                continue
            if self.fuse_spans:
                yield from self._sleep_to_next_tick()
            else:
                yield env.timeout(self.interval)

    def _dispatch_tick(self, ctx, ready, placements, visit) -> None:
        """Consume one tick's placements in visit order: dispatch placed
        tasks, re-stack unplaced ones — the half of the tick the fused
        span replay shares with the per-tick path."""
        env, cluster = self.env, self.cluster
        live = ctx.live_mask
        for i in visit:
            task, h_idx = ready[i], placements[i]
            if not task.is_nascent:
                self.logger.error("task %s not nascent at dispatch", task.id)
                continue
            if h_idx < 0:
                task.placement = None
                self._wait_stack.append(task)
            else:
                host = ctx.hosts[int(h_idx)]
                if not host.up or (
                    live is not None and not live[int(h_idx)]
                ):
                    self.placement_violations.append(
                        f"t={env.now:.3f}: task {task.id} placed on "
                        f"{'down' if not host.up else 'quarantined'} "
                        f"host {host.id}"
                    )
                task.placement = host.id
                cluster.dispatch_q.put(task)
                task.set_submitted()
                if self.tracer.enabled:
                    self._stage_task(task, "placed", host=host.id)
                if self.meter:
                    self.meter.add_scheduling_turnover(
                        env.now - self._pending_since.pop(task, env.now)
                    )

    # -- pure-tick-run fusion (round 8) -----------------------------------
    #
    # A *pure tick run* is a window of upcoming ticks whose scheduler
    # inputs are computable now: the event heap holds nothing before the
    # window's end except local-pump deliveries (whose payloads are
    # snapshot-stable over the window), no quarantine expires inside it,
    # and therefore availability / live mask / ready sets evolve only by
    # this scheduler's own placements.  Two exploits:
    #
    #   * ``_sleep_to_next_tick`` — after ANY tick, the unplaced remainder
    #     provably cannot place until the window ends (availability only
    #     decreases within it, and a task that had no fitting host at its
    #     own step availability — a superset of every later snapshot —
    #     never gains one), so the in-window ticks are exact no-ops: the
    #     loop accounts their meters/wait-queue churn in O(1) kernel
    #     dispatches (zero) and sleeps to the first potentially-productive
    #     tick.  The sleep is early-wakeable by ``submit`` (serve-mode
    #     thread injection).
    #   * ``_extract_span``/``_serve_span`` — when pump deliveries land
    #     INSIDE the window, placements genuinely evolve across ticks;
    #     a span-capable device policy executes the whole window as one
    #     fused device program (``place_span`` → ``ops/tickloop.py``) and
    #     the loop replays the precomputed decisions tick by tick.  The
    #     folded pumps are never touched — they fire normally during the
    #     replay (each bump of ``_span_epoch`` is *expected*); any
    #     UNexpected epoch bump (completion, foreign submission) aborts
    #     the remaining span before the affected tick, which is exact:
    #     committed ticks saw precisely the state the device assumed.

    def _quarantine_bound(self, now: float) -> float:
        if self.breaker is None:
            return float("inf")
        return self.breaker.next_expiry(now)

    def _pump_allow(self):
        """Heap-scan predicate approving OUR locals' armed pump entries."""
        def allow(ev) -> bool:
            owner = getattr(ev, "owner", None)
            return (
                type(ev) is Callback
                and isinstance(owner, LocalScheduler)
                and self._local.get(owner.application.id) is owner
            )
        return allow

    def _extract_span(self, ctx: "TickContext") -> Optional[SpanPlan]:
        """Try to extract (and device-price) a fused span starting at the
        current tick.  Returns a plan with ``outcome`` filled, or None —
        in which case NOTHING was mutated and the per-tick path serves
        the tick.  Spans need a span-capable policy AND at least one
        non-empty in-window pump delivery; windows without deliveries are
        the fast-forward path's business (strictly cheaper)."""
        policy = self.policy
        place_span = getattr(policy, "place_span", None)
        if place_span is None or not policy.span_capable():
            return None
        if self._armed_pumps == 0:
            # O(1) bail before the O(heap) scan: spans exist to fold
            # in-window pump deliveries; with no pump armed there is
            # nothing to fold (fast-forward owns delivery-free windows).
            return None
        env = self.env
        now = env.now
        t_foreign, allowed = env.scan_window(allow=self._pump_allow())
        if not allowed:
            return None
        t_bound = min(t_foreign, self._quarantine_bound(now))
        # Serving's admission-window bound (``fuse_spans="slo"``): never
        # speculate past the stream's revealed frontier.  INCLUSIVE,
        # unlike the foreign/quarantine bounds: a tick landing exactly on
        # the frontier is safe — arrivals at that instant are already
        # revealed (``wait_released`` admits at ``released >= t`` for the
        # same reason), and anything revealed later bumps ``_span_epoch``
        # and aborts the replay before the affected tick.  Exclusive
        # truncation here is what used to clip mixed-horizon groups to
        # their minimum frontier and fragment the ragged batcher's
        # K-buckets.
        t_horizon = (
            self.span_horizon() if self.span_horizon is not None
            else float("inf")
        )
        cap = int(getattr(policy, "span_cap", 32))
        # Exact grid: iterated float adds, the same op sequence the
        # sequential timeout chain performs — anchor + k*interval can
        # differ by an ulp and shift every in-window event comparison.
        grid = [now]
        t = now
        for _ in range(cap - 1):
            t = t + self.interval
            if t >= t_bound or t > t_horizon:
                break
            grid.append(t)
        if len(grid) < 2:
            return None
        k_span = len(grid)
        slots: List[Task] = list(ctx.tasks)
        arrive: List[int] = [0] * len(slots)
        pump_ticks: List[int] = []
        any_delivery = False
        for (t_p, _prio, _seq, cb) in allowed:
            if t_p > grid[-1]:
                continue  # delivers beyond the span; stays armed
            # Delivery tick: first grid instant at-or-after the pump.  A
            # pump landing EXACTLY on a grid instant fires BEFORE that
            # tick — any in-window pump was armed before the span
            # started, so its heap seq precedes the replay timeout
            # scheduled one interval earlier (identical ordering to the
            # sequential chain's per-tick timeouts).
            tick_i = next(i for i in range(1, k_span) if grid[i] >= t_p)
            pump_ticks.append(tick_i)
            snap = cb.owner.pump_snapshot()
            if snap:
                any_delivery = True
            slots.extend(snap)
            arrive.extend([tick_i] * len(snap))
        if not any_delivery:
            return None
        plan = SpanPlan(ctx, grid, slots, arrive, pump_ticks,
                        self._span_epoch, self._disturb_epoch)
        outcome = place_span(ctx, plan)
        if outcome is None:
            self.span_stats["spans_declined"] += 1
            return None
        plan.outcome = outcome
        return plan

    def _serve_span(self, ctx: "TickContext", plan: SpanPlan):
        """Replay a priced span: commit the precomputed placements tick
        by tick, sleeping the normal interval in between so in-window
        events (transfers, the folded pumps themselves) fire exactly as
        they would sequentially.  Yields from ``_dispatch_loop``; returns
        True when the replay aborted at a fresh unprocessed tick instant
        (the caller re-enters its loop body without sleeping)."""
        env = self.env
        outcome = plan.outcome
        placements = outcome.placements  # [K, B] slot-indexed, host numpy
        slots = plan.slots
        slot_of = {task: s for s, task in enumerate(slots)}
        decreasing = bool(getattr(self.policy, "decreasing", False))
        if decreasing:
            dem = np.stack([t.demand for t in slots])
            norms = np.sqrt(np.sum(dem * dem, axis=1))
        self.span_stats["fused_spans"] += 1
        self.span_stats["span_ticks_sum"] += plan.n_ticks
        if plan.n_ticks > self.span_stats["span_ticks_max"]:
            self.span_stats["span_ticks_max"] = plan.n_ticks
        ready_k = list(ctx.tasks)
        for k in range(plan.n_ticks):
            if k > 0:
                yield env.timeout(self.interval)
                expected = plan.epoch + sum(
                    1 for pt in plan.pump_ticks if pt <= k
                )
                if self._span_epoch != expected or not self.is_active:
                    new = self._try_splice(plan, k, slot_of)
                    if new is None:
                        self.span_stats["span_aborts"] += 1
                        return True
                    # Splice committed: the running span's universe now
                    # includes the mid-span arrivals (joined at tick k)
                    # and the outcome matrix was re-run from the resident
                    # checkpoint — adopt both and keep replaying.
                    slots = plan.slots
                    for t in new:
                        slot_of[t] = len(slot_of)
                    placements = plan.outcome.placements
                    if decreasing:
                        dem = np.stack([t.demand for t in slots])
                        norms = np.sqrt(np.sum(dem * dem, axis=1))
                ready_k = []
                while self._wait_stack:
                    ready_k.append(self._wait_stack.pop())
                ready_k.extend(self.submit_q.drain())
                if any(t not in slot_of for t in ready_k):
                    # Defensive: the batch diverged from the speculation
                    # (should be unreachable under the epoch check) —
                    # serve this tick live and end the span.
                    self.span_stats["span_aborts"] += 1
                    sub_ctx = TickContext(self, ready_k, self._tick_seq)
                    with self.tracer.span(
                        "scheduler", "tick", env.now, n_ready=len(ready_k)
                    ) as span_args:
                        live_placements = self.policy.place(sub_ctx)
                        if self.tracer.enabled:
                            span_args["n_placed"] = int(
                                sum(1 for h in live_placements if h >= 0)
                            )
                    self._tick_seq += 1
                    visit = (
                        sub_ctx.visit_order
                        if sub_ctx.visit_order is not None
                        else range(len(ready_k))
                    )
                    self._dispatch_tick(
                        sub_ctx, ready_k, live_placements, visit
                    )
                    return False
                if not ready_k:
                    continue  # pool drained, cohort still ahead
                if self.meter:
                    self.meter.increment_scheduling_ops(len(ready_k))
                    now = env.now
                    for task in ready_k:
                        self._pending_since.setdefault(task, now)
            row = placements[k]
            pl = [int(row[slot_of[t]]) for t in ready_k]
            if self.tracer.enabled:
                with self.tracer.span(
                    "scheduler", "tick", env.now, n_ready=len(ready_k)
                ) as span_args:
                    span_args["n_placed"] = int(
                        sum(1 for h in pl if h >= 0)
                    )
            self._tick_seq += 1
            self.span_stats["fused_ticks"] += 1
            if decreasing:
                bn = norms[[slot_of[t] for t in ready_k]]
                visit = [int(j) for j in np.argsort(-bn, kind="stable")]
            else:
                visit = list(range(len(ready_k)))
            self._dispatch_tick(ctx, ready_k, pl, visit)
        return False

    def _try_splice(self, plan: SpanPlan, k: int, slot_of) -> Optional[list]:
        """Attempt a mid-span splice at replay tick ``k`` (round 20).

        Runs inside ``_serve_span``'s epoch-mismatch branch: the span
        speculated past a scheduler-visible mutation.  When that
        mutation is PURELY new arrivals (submissions + their pump
        fires — the disturbance epoch unchanged), the arrivals can be
        joined into the RUNNING span instead of aborting it: the policy
        re-runs the span from its resident span-entry checkpoint with
        the new slots joined at ``arrive = k``
        (``sched/tpu.py:span_splice``), verifies the committed prefix
        bit-identical, and hands back the extended placements matrix.
        On success the plan's universe/outcome are extended in place,
        the epoch re-anchored, and the replay continues — batch
        membership changed INSIDE the span.  Returns the joined tasks,
        or None to decline (the caller aborts exactly as before; the
        queues are only PEEKED here, never drained, so a decline leaves
        every task where the live tick expects it).

        Declines when: the policy has no splice support or checkpoint,
        any disturbance landed (completions / withdraw / preempt
        drain), a folded cohort also lands at tick ``k`` (its
        submit-queue drain order would interleave with the arrivals,
        while slot order cannot), the gate rejects an arrival, or the
        prefix check fails."""
        policy_splice = getattr(self.policy, "span_splice", None)
        if policy_splice is None or not self.is_active:
            return None
        if self._disturb_epoch != plan.disturb:
            return None
        if any(pt == k for pt in plan.pump_ticks):
            return None
        if any(t not in slot_of for t in self._wait_stack):
            return None  # foreign task in the wait stack — not a pure join
        new = [t for t in self.submit_q.items if t not in slot_of]
        if not new or any(not t.is_nascent for t in new):
            return None
        gate = self.splice_gate
        if gate is not None and not all(gate(t) for t in new):
            return None
        pl = policy_splice(plan.ctx, plan, k, new)
        if pl is None:
            return None
        plan.slots = list(plan.slots) + new
        plan.arrive = list(plan.arrive) + [k] * len(new)
        plan.outcome.placements = pl
        # Re-anchor: future expected-epoch checks count folded pumps
        # STRICTLY AFTER k on top of the epoch as of this commit.
        plan.epoch = self._span_epoch - sum(
            1 for pt in plan.pump_ticks if pt <= k
        )
        self.span_stats["span_splices"] += 1
        self.tracer.emit(
            "scheduler", "span_splice", self.env.now, tick=k,
            joined=len(new),
        )
        return new

    def _reschedule_ff_wake(self) -> None:
        """Pull a pending fast-forward wake back to the first grid tick
        strictly after now (a submission injected work mid-window).  The
        woken loop processes that tick IMMEDIATELY — it is the first tick
        that can see the new work, exactly when the sequential chain
        would have drained it."""
        env = self.env
        t = self._ff_anchor
        while t <= env.now:
            t = t + self.interval
        if self._ff_rescheduled and t >= self._ff_target:
            return  # an earlier submission already pulled the wake ≤ t
        if self._ff_cb is not None:
            self._ff_cb.cancel()
        self._ff_rescheduled = True
        self._ff_target = t
        evt = self._ff_evt
        self._ff_cb = env.schedule_callback_at(
            t, lambda: None if evt.triggered else evt.succeed()
        )

    def _noop_tick_churn(self, stack: List[Task]) -> List[Task]:
        """Wait-stack state after one provably-no-op tick: drain
        (LIFO-reversed), visit in the policy's order, push back.  The
        decreasing VBP arms visit norm-descending (``_sort_decreasing``
        semantics — stable on ties); everything else visits in batch
        order, i.e. the stack simply reverses."""
        ready = list(reversed(stack))
        if getattr(self.policy, "decreasing", False):
            dem = np.stack([t.demand for t in ready])
            norms = np.sqrt(np.sum(dem * dem, axis=1))
            order = np.argsort(-norms, kind="stable")
            return [ready[int(i)] for i in order]
        return ready

    def _sleep_to_next_tick(self):
        """Sleep to the next tick that could possibly make progress,
        accounting the provably-no-op ticks in between without paying a
        policy dispatch for any of them.  The last hop is a plain
        ``timeout(interval)`` issued from the final skipped instant, so
        same-instant event ordering at the productive tick is identical
        to the sequential chain's."""
        env = self.env
        interval = self.interval
        anchor = env.now
        # O(1) bail before the O(heap) scan: an event due before the
        # next tick makes that tick the first potentially-productive one
        # — nothing to skip (the overwhelmingly common case in busy
        # phases, where the heap is at its largest).
        if env.peek() < anchor + interval:
            yield env.timeout(interval)
            return
        t_foreign, _ = env.scan_window()
        t_bound = min(t_foreign, self._quarantine_bound(anchor))
        # First grid tick at-or-after the bound may see input — run it.
        # Everything strictly before is a no-op: empty-ready if the wait
        # stack is empty, a zero-placement re-scan otherwise.
        n_skip = 0
        t = anchor + interval
        if t_bound != float("inf"):
            while t < t_bound and n_skip < 1_000_000:
                n_skip += 1
                t = t + interval
        if n_skip == 0:
            yield env.timeout(interval)
            return
        self._ff_anchor = anchor
        self._ff_rescheduled = False
        self._ff_target = float("inf")
        evt = env.event()
        self._ff_evt = evt
        # Wake at the LAST no-op instant (one interval short of the
        # productive tick); the final timeout below is issued from that
        # instant exactly like the sequential chain's last timeout, so
        # same-instant event ordering at the productive tick matches.
        last_noop = anchor
        for _ in range(n_skip):
            last_noop = last_noop + interval
        self._ff_cb = env.schedule_callback_at(
            last_noop, lambda: None if evt.triggered else evt.succeed()
        )
        yield evt
        self._ff_evt = None
        self._ff_cb = None
        rescheduled = self._ff_rescheduled
        self._ff_rescheduled = False
        # Lazily account what was actually skipped — an early wake via
        # ``submit`` shortens the window, and its wake instant is the
        # first tick that can SEE the submission: it is processed, not
        # skipped.  A normal wake's instant is itself a provable no-op;
        # the trailing timeout then reaches the productive tick.
        now = env.now
        skipped = 0
        t = anchor + interval
        while t < now:
            skipped += 1
            t = t + interval
        if not rescheduled:
            skipped += 1  # the wake instant itself (t == now)
        stack = self._wait_stack
        if skipped > 0:
            self.span_stats["ff_ticks"] += skipped
        if skipped > 0 and stack:
            if self.meter:
                self.meter.increment_scheduling_ops(skipped * len(stack))
            if self.tracer.enabled:
                t = anchor
                for _ in range(skipped):
                    t = t + interval
                    with self.tracer.span(
                        "scheduler", "tick", t, n_ready=len(stack)
                    ) as span_args:
                        span_args["n_placed"] = 0
            self._tick_seq += skipped
            # Stack churn has period 2 after the first tick (a stable
            # sort of a reversed sorted list flips tie runs; flipping
            # again restores them), so two explicit churns cover any m.
            s1 = self._noop_tick_churn(stack)
            if skipped == 1:
                final = s1
            else:
                s2 = self._noop_tick_churn(s1)
                final = s1 if skipped % 2 == 1 else s2
            self._wait_stack = final
        if not rescheduled:
            yield env.timeout(interval)

    # -- the completion listener -----------------------------------------
    def _listen_loop(self):
        env = self.env
        notify_q = self.cluster.notify_q
        while self.is_active:
            item = yield notify_q.get()
            self._handle_notification(item)
            # Same-instant batching: notifications already queued (e.g. a
            # whole admission-failure batch) are handled in FIFO order
            # without one get-event round-trip each.
            for queued in notify_q.drain():
                self._handle_notification(queued)

    def _handle_notification(self, item):
        env = self.env
        self._span_epoch += 1  # completions invalidate speculated spans
        self._disturb_epoch += 1
        success, task = item
        app = task.application
        if app is None:
            self.logger.error("task %s has no application", task.id)
            return
        local = self._local.get(app.id)
        if local is None:
            if app.id in self._failed_apps:
                # Late notification for a dead-lettered application: an
                # in-flight sibling concluded after the app failed.
                # Account it so the conservation audit still balances.
                if success:
                    task.set_finished()
                else:
                    task.set_nascent()
                    task.placement = None
                    self._cancel_task(task)
                return
            self.logger.error("application %s unknown", app.id)
            return
        if success:
            if self.breaker is not None and task.placement is not None:
                self.breaker.record_success(task.placement)
            if self.retry is not None:
                self._attempts.pop(task, None)
            task.set_finished()
            self.tracer.emit(
                "task", "finished", env.now, id=task.id, host=task.placement
            )
            if self.tracer.enabled:
                self._stage_task(task, "task_finished")
            local.notify(task)
        else:
            failed_host = task.placement
            if self.breaker is not None and failed_host is not None:
                if self.breaker.record_failure(failed_host, env.now):
                    self.tracer.emit(
                        "host", "quarantined", env.now, id=failed_host,
                        until=env.now + self.breaker.cooldown,
                    )
            task.set_nascent()
            task.placement = None
            if self.retry is not None:
                attempts = self._attempts.get(task, 0) + 1
                self._attempts[task] = attempts
                # Tier-aware budget: multi-tenant serving stamps the
                # app's priority tier at injection; batch apps default
                # to tier 0, which resolves to the classic budget.
                tier = int(getattr(app, "_serve_tier", 0))
                if self.retry.exhausted(attempts, tier):
                    self._dead_letter(task, failed_host, attempts)
                    return
                self.tracer.emit("task", "retry", env.now, id=task.id)
                if self.tracer.enabled:
                    self._stage_task(task, "retry", attempt=attempts)
                delay = self.retry.backoff(attempts, task.id)
                if delay > 0.0:
                    # Backed-off resubmission: the task re-enters the
                    # submit queue only after its (deterministically
                    # jittered) delay — de-synchronizing the retry wave
                    # a correlated outage creates.
                    env.schedule_callback(
                        delay, lambda t=task: self.submit_q.put(t)
                    )
                else:
                    self.submit_q.put(task)
            else:
                self.tracer.emit("task", "retry", env.now, id=task.id)
                if self.tracer.enabled:
                    self._stage_task(task, "retry")
                self.submit_q.put(task)
        if app.is_finished:
            app.end_time = env.now
            self.tracer.emit("app", "finished", env.now, id=app.id)
            self.logger.debug(
                "[%.3f] application %s finished in %.3f s",
                env.now,
                app.id,
                app.end_time - app.start_time,
            )
            self._local.pop(app.id, None)
            self._n_unfinished -= 1

    # -- retry governance (``sched/retry.py``) ----------------------------
    def _cancel_task(self, task: Task) -> None:
        """Drop a task whose application has already failed: it is never
        (re)placed; its pending bookkeeping is released."""
        self.n_cancelled += 1
        self._pending_since.pop(task, None)
        self._attempts.pop(task, None)
        self.tracer.emit("task", "cancelled", self.env.now, id=task.id)

    def _dead_letter(
        self, task: Task, host_id: Optional[str], attempts: int,
        reason: str = "retry_budget",
    ) -> None:
        """Terminal path for a budget-exhausted task: record it, shed the
        reason to the SLO meter, and fail its application (a DAG with a
        permanently lost task can never finish — leaving it live would
        keep the scheduler loop alive forever, the reference's wedge)."""
        task.set_dead()
        self._attempts.pop(task, None)
        self._pending_since.pop(task, None)
        entry = DeadLetter(
            task.id, task.application.id, host_id, reason, self.env.now,
            attempts, tier=int(getattr(task.application, "_serve_tier", 0)),
        )
        self.dead_letters.append(entry)
        if self.slo is not None:
            self.slo.record_shed(reason)
        self.tracer.emit(
            "task", "dead_letter", self.env.now, id=task.id, reason=reason,
            attempts=attempts, host=host_id,
        )
        if self.tracer.enabled:
            self._stage_task(
                task, "dead_letter", reason=reason, attempts=attempts
            )
        self.logger.warning(
            "[%.3f] task %s dead-lettered after %d attempts (%s)",
            self.env.now, task.id, attempts, reason,
        )
        self._fail_application(task.application)

    def _fail_application(self, app: Application) -> None:
        if app.id in self._failed_apps:
            return
        self._failed_apps.add(app.id)
        app.failed = True
        app.end_time = self.env.now
        self.tracer.emit("app", "failed", self.env.now, id=app.id)
        if self._local.pop(app.id, None) is not None:
            self._n_unfinished -= 1
