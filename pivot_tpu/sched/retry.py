"""Retry governance: bounded backoff retries, dead-lettering, host quarantine.

The reference's only failure semantics are *resubmit forever*: a failed
task is reset to NASCENT and re-queued unconditionally
(``scheduler/__init__.py:136-139``), every tick, for as long as the
simulation runs.  That is the textbook retry-storm shape — a workload
that cannot ever fit (or a host that kills everything placed on it)
consumes scheduler ticks and placement bandwidth forever, and a single
poisoned task wedges its application into an unfinishable state that
keeps the whole run alive.  Production schedulers bound exactly this
machinery (Borg's per-task retry limits and machine quarantine,
PAPERS.md); this module supplies the three governance pieces the
scheduler loop wires in (``sched/__init__.py``):

  * :class:`RetryPolicy` — per-task retry budgets and exponential
    backoff with **deterministic jitter**: the jitter draw is a pure
    hash of ``(seed, task id, attempt)``, so two runs of the same seeded
    simulation back off identically (no hidden RNG stream, no
    cross-contamination with workload/cluster draws).
  * :class:`DeadLetter` / the scheduler's dead-letter queue — a task
    that exhausts its budget terminates *exactly once* as dead-lettered
    (new terminal ``TaskState.DEAD``), its application is marked failed,
    and the shed reason reaches the serving SLO meter.  The invariant
    auditor (``infra/audit.py``) checks the conservation law this
    creates: admitted ⇒ completed | dead-lettered | cancelled-with-app.
  * :class:`HostCircuitBreaker` — K *consecutive* task failures on one
    host quarantine it for a cooldown.  Quarantine is advisory state on
    the scheduler (the host object is untouched — it may be perfectly
    healthy and is still running already-resident tasks): it surfaces as
    the ``[H]`` live mask every placement backend fuses into its fit
    mask (``TickContext.live_mask`` → ``sched/policies.fold_quarantine``
    / the kernels' ``live`` argument), so no NEW placement lands on a
    quarantined host while the cooldown runs.
  * :class:`RetryGate` — a process-wide cap on *concurrent* retries
    (round 21, the serve recovery plane): backoff spreads a retry wave
    in time, the gate bounds its width, so a degraded device cannot
    amplify one slow dispatch into a metastable retry storm.

All of these are inert by default — ``GlobalScheduler(retry=None,
breaker=None)`` keeps the reference-parity resubmit-forever loop
bit-identical to before this module existed.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = ["DeadLetter", "HostCircuitBreaker", "RetryGate", "RetryPolicy"]


def _unit_hash(*parts) -> float:
    """Deterministic uniform in [0, 1) from a tuple of hashable parts —
    the jitter source.  blake2b, not ``hash()``: Python string hashing
    is salted per process and would break run-to-run reproducibility."""
    digest = hashlib.blake2b(
        ":".join(str(p) for p in parts).encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2.0**64


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded, backed-off retries for failed task executions.

    ``max_retries`` is the per-task retry budget: a task may fail at most
    ``max_retries`` times and still be resubmitted; failure number
    ``max_retries + 1`` dead-letters it (``None`` = unbounded, the
    reference's semantics, but with backoff still applied).  Backoff for
    failure ``attempt`` (1-based) is ``min(base · factor^(attempt−1),
    cap)`` sim-seconds, multiplied by ``1 ± jitter·u`` where ``u`` is the
    deterministic per-(task, attempt) hash draw — de-synchronizing the
    retry wave a correlated outage creates (every task aborted by a zone
    failure would otherwise land on the same future tick, the classic
    retry-storm resonance) without sacrificing reproducibility.
    """

    max_retries: Optional[int] = 3
    base: float = 0.0
    factor: float = 2.0
    cap: float = 300.0
    jitter: float = 0.1
    seed: int = 0
    #: Optional per-tier retry budgets for multi-tenant serving
    #: (``pivot_tpu.serve``): index = priority tier (0 = most important,
    #: tiers beyond the tuple use its last entry), value = that tier's
    #: ``max_retries`` (``None`` = unbounded).  Production cells spend
    #: far more retry budget on serving work than on best-effort batch
    #: (Borg-NG, PAPERS.md); this is that knob.  ``None`` (default) uses
    #: ``max_retries`` for every tier — bit-identical to pre-tier runs.
    tier_max_retries: Optional[tuple] = None

    def __post_init__(self):
        if self.max_retries is not None and self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.base < 0 or self.cap < 0:
            raise ValueError("backoff base/cap must be >= 0")
        if self.factor < 1.0:
            raise ValueError(f"backoff factor must be >= 1, got {self.factor}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")
        if self.tier_max_retries is not None:
            t = tuple(self.tier_max_retries)
            if not t or any(b is not None and b < 0 for b in t):
                raise ValueError(
                    f"tier_max_retries must be a non-empty tuple of "
                    f"budgets >= 0 (or None), got {self.tier_max_retries!r}"
                )
            object.__setattr__(self, "tier_max_retries", t)

    def budget(self, tier: int = 0) -> Optional[int]:
        """Effective retry budget for ``tier`` (``None`` = unbounded)."""
        if self.tier_max_retries is None:
            return self.max_retries
        return self.tier_max_retries[
            min(tier, len(self.tier_max_retries) - 1)
        ]

    def exhausted(self, attempts: int, tier: int = 0) -> bool:
        """True once ``attempts`` failures have overdrawn ``tier``'s
        budget (tier 0 with no per-tier table = the classic budget)."""
        budget = self.budget(tier)
        return budget is not None and attempts > budget

    def max_attempts(self, tier: int = 0) -> Optional[int]:
        """Explicit total-attempt bound for ``tier``: the initial try
        plus its retry budget (``None`` = unbounded).  The recovery
        plane's dispatch watchdog sizes its loop off THIS, not off the
        raw retry budget, so "how many times may this run at all" is a
        stated number rather than an off-by-one folklore."""
        budget = self.budget(tier)
        return None if budget is None else budget + 1

    def backoff(self, attempt: int, key: str) -> float:
        """Sim-seconds to wait before resubmitting failure ``attempt`` of
        the task identified by ``key`` (its id).  Deterministic: the
        jitter draw is the seeded ``_unit_hash(seed, key, attempt)`` —
        never an ambient RNG — so a journaled replay backs off
        identically to the run it replays."""
        if self.base <= 0.0:
            return 0.0
        delay = min(self.base * self.factor ** (attempt - 1), self.cap)
        if self.jitter > 0.0:
            u = _unit_hash(self.seed, key, attempt)
            delay *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return delay


class RetryGate:
    """Cap on CONCURRENT retries — the metastable-failure guard.

    Backoff de-synchronizes a retry wave in *time*; this gate bounds it
    in *width*.  Bronson et al. ("Metastable Failures", PAPERS.md): a
    degraded device that slows every dispatch turns unbounded retry
    concurrency into a sustaining feedback loop — retries of slow work
    make the work slower, which makes more of it retry.  Admission to a
    retry therefore goes through this gate: at most ``max_concurrent``
    retries may be in flight across the process at once, and a caller
    that cannot get a slot within its patience *sheds* (fails fast)
    rather than queueing more load onto a plane that is already
    drowning.

    Thread-safe; shared by every dispatch path of one recovery plane.
    ``peak`` records the high-water mark (the soak test's cap
    assertion), ``shed`` the fast-failed acquisitions.
    """

    def __init__(self, max_concurrent: int = 2):
        if max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {max_concurrent}"
            )
        self.max_concurrent = int(max_concurrent)
        self._cv = threading.Condition()
        self._in_flight = 0
        self.peak = 0
        self.shed = 0

    def acquire(self, timeout: Optional[float] = 0.0) -> bool:
        """Take a retry slot; False (a shed) when none frees up within
        ``timeout`` wall seconds (0 = fail fast, None = wait forever)."""
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self._in_flight < self.max_concurrent,
                timeout=timeout,
            )
            if not ok:
                self.shed += 1
                return False
            self._in_flight += 1
            self.peak = max(self.peak, self._in_flight)
            return True

    def release(self) -> None:
        with self._cv:
            if self._in_flight <= 0:
                raise RuntimeError("RetryGate.release without acquire")
            self._in_flight -= 1
            self._cv.notify()

    @property
    def in_flight(self) -> int:
        with self._cv:
            return self._in_flight


@dataclass(frozen=True)
class DeadLetter:
    """One dead-lettered task: the terminal record the audit reconciles."""

    task_id: str
    app_id: str
    host_id: Optional[str]  # last placement that failed (None: never placed)
    reason: str  # "retry_budget" | "app_failed"
    at: float  # sim time of dead-lettering
    attempts: int  # failures consumed (== budget(tier) + 1 on exhaustion)
    tier: int = 0  # the app's serving tier (0 outside multi-tenant serving)


class HostCircuitBreaker:
    """Quarantine a host after K consecutive task failures on it.

    Failure streaks count *consecutive* failures — any successful
    completion on the host resets its streak, so a transient blip never
    trips the breaker.  Tripping quarantines the host for ``cooldown``
    sim-seconds and resets the streak (the host re-enters placement
    clean when the cooldown expires; if it keeps killing tasks it trips
    again — repeated trips are visible in :attr:`trips`).

    Purely scheduler-side state: consult :meth:`is_quarantined` /
    :meth:`live_mask` at decision time.  Not thread-safe; each scheduler
    (session) owns its own breaker.
    """

    def __init__(self, k: int = 3, cooldown: float = 60.0):
        if k < 1:
            raise ValueError(f"breaker threshold k must be >= 1, got {k}")
        if cooldown <= 0:
            raise ValueError(f"cooldown must be > 0, got {cooldown}")
        self.k = k
        self.cooldown = cooldown
        self._streak: Dict[str, int] = {}
        self._until: Dict[str, float] = {}
        #: (sim time, host id, quarantined-until) per trip, in trip order.
        self.trips: List[Tuple[float, str, float]] = []

    def record_failure(self, host_id: str, now: float) -> bool:
        """One task failure attributed to ``host_id``; returns True when
        this failure trips the breaker (host newly quarantined)."""
        streak = self._streak.get(host_id, 0) + 1
        if streak >= self.k:
            self._streak[host_id] = 0
            self._until[host_id] = now + self.cooldown
            self.trips.append((now, host_id, now + self.cooldown))
            return True
        self._streak[host_id] = streak
        return False

    def record_success(self, host_id: str) -> None:
        """A task completed on ``host_id`` — its failure streak resets.
        An existing quarantine runs its cooldown out regardless (the
        success is an already-resident task finishing, not evidence the
        next placement is safe)."""
        if self._streak.get(host_id):
            self._streak[host_id] = 0

    def is_quarantined(self, host_id: str, now: float) -> bool:
        until = self._until.get(host_id)
        if until is None:
            return False
        if now >= until:
            del self._until[host_id]  # expired: prune so the dict stays small
            return False
        return True

    @property
    def n_quarantined(self) -> int:
        """Hosts with a (possibly expired, not yet pruned) quarantine."""
        return len(self._until)

    def next_expiry(self, now: float) -> float:
        """Earliest instant a live quarantine expires, or ``+inf``.

        Quarantine expiry is the one scheduler-visible state change that
        happens by CLOCK rather than by event (``is_quarantined`` just
        compares ``now``), so the pure-tick-run extractor must bound its
        fused windows by it: a tick at or past an expiry sees a larger
        live mask and is no longer a provable no-op."""
        live = [u for u in self._until.values() if u > now]
        return min(live) if live else float("inf")
