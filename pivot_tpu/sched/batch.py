"""Cross-run dispatch batching: one device call for many runs' ticks.

The hot path of the DES grid driver is no longer compute-bound but
*dispatch-bound*: a remote accelerator has a fixed per-call latency floor
(76–86 ms over this image's tunnel, ``sched/tpu.py``) that dwarfs the
per-tick kernel compute, and the reference's only answer to many
concurrent experiments is one OS process per run
(``alibaba/sim.py:187-195``) — every process pays the full floor alone.
This module amortizes the floor across runs: G concurrently-stepped DES
experiment runs submit their per-tick placement-kernel calls to a
:class:`DispatchBatcher`, which coalesces co-pending calls of identical
shape into a single ``[G, ...]``-vmapped device dispatch and hands each
run back its own row.

Correctness contract (the bar the grid driver is held to,
``tests/test_batch_dispatch.py``): a run's placements are **bit-identical**
whether its tick was served alone or inside any batch.  This holds
because the kernels are pure functions of their per-tick inputs — the
RNG the opportunistic arm consumes is the stateless per-tick Philox
stream (``sched/rand.py``), keyed on (seed, tick, task), so per-run
streams stay aligned with the numpy twins no matter how ticks are
grouped — and ``vmap`` of the placement kernels evaluates each row with
the same op sequence as the unbatched program.  Batch *composition* may
vary run-to-run with thread timing; results cannot.

The round-6 two-phase kernels (``ops/kernels.py``) keep this contract in
every phase-2 mode: their ``lax.while_loop`` passes stop at each row's
own last valid task, and under ``vmap`` rows that finish early go inert
(out-of-range writes drop, fit masks force no-ops) while longer rows
keep stepping — asserted by ``tests/test_two_phase.py::
test_two_phase_vmap_mixed_valid_lengths`` with rows of different task
counts sharing one dispatch, exactly the mixed-T batches this module
coalesces.  The ``totals`` pre-filter operand rides as a normal stacked
array column; the static ``phase2`` selector rides in ``static_kw`` like
every other kernel config flag.

Compilation discipline: the group axis pads to a bucket
(:func:`group_bucket`, the G-analog of ``sched.tpu.pad_bucket``), so XLA
compiles one program per (G-bucket, T-bucket, H) triple, never per group
size.  Pad rows replicate request 0 (no NaNs, no shape churn) and their
outputs are discarded.

**Whole spans, not just single ticks (round 8).**  The request model is
kernel-agnostic — a request is (callable, same-shaped arrays, static
config) — so the fused tick driver (``ops/tickloop.py``) rides the same
machinery: ``sched.tpu.place_span`` routes through ``_call_kernel``
exactly like a per-tick kernel call, and co-pending same-shape spans of
G lock-step runs coalesce into one vmapped dispatch covering G×K
simulator ticks.  Span *lengths* may differ per row (``n_ticks_dyn`` is
a stacked operand): the driver's loop body is per-row inert once a
row's horizon ends, asserted by ``tests/test_tickloop.py::
test_fused_span_batched_rows_stay_inert``.  This is also what
simplified the request model's economics at G=1: a lone live slot now
takes a synchronous same-thread fast path (``single_fast_path`` stat)
instead of paying the queue hand-off and coordinator hop for a batch of
one.

Two layers:

  * :func:`batch_execute` — the pure core: take N same-shaped kernel
    requests, run one vmapped dispatch, return per-request outputs
    (host-fetched in ONE transfer — the other half of the
    amortization).  ``bench.py``'s ``grid_batched`` row times exactly
    this program against N sequential dispatches.
  * :class:`DispatchBatcher` — the concurrency layer for the lock-step
    grid driver (``experiments.runner.run_grid_lockstep``): each DES run
    advances in its own thread, a blocked :meth:`BatchClient.dispatch`
    parks the run at its tick boundary, and the coordinator flushes
    whenever every live run is parked — tick-synchronous lock-step
    without rewriting the event kernel.  Runs that desynchronize
    (different tick boundaries, no co-pending partner of the same
    shape) fall back to a plain sequential kernel call, bit-identical
    by the contract above.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax

__all__ = [
    "BatchClient",
    "DispatchBatcher",
    "batch_execute",
    "group_bucket",
]

#: Small-G buckets below the task-axis bucket ladder: grid batches are
#: typically a handful of runs, where padding 3 → 8 would double the
#: dispatch's compute for nothing.
_G_BUCKETS = (2, 4, 8, 16)


def group_bucket(g: int) -> int:
    """Smallest batch bucket ≥ g (caps XLA program count per tick shape).

    1 is its own bucket — a lone request runs the *unbatched* kernel
    program (the sequential-fallback path), which both skips a useless
    vmap wrapper and keeps the single-run program the only one compiled
    for non-coalescing workloads.
    """
    if g <= 1:
        return 1
    for b in _G_BUCKETS:
        if g <= b:
            return b
    from pivot_tpu.sched.tpu import pad_bucket

    return pad_bucket(g)


@functools.lru_cache(maxsize=256)
def _batched_fn(kernel, static_items: tuple, n_args: int, kw_keys: tuple,
                mesh=None, host_ok: bool = False):
    """jit(vmap(kernel)) closed over the static config — cached per
    (kernel, static kwargs, array-kwarg names, mesh); jit's own cache
    keys the shapes, so this is one entry per kernel configuration, one
    XLA program per (G-bucket, input-shape) combination.  The signature
    is flat positional leaves (arguments first, array-kwargs in
    ``kw_keys`` order after) — nested container pytrees cost measurably
    more per dispatch, and per-dispatch overhead is this module's whole
    subject.

    With a replica-only ``mesh``, every stacked operand's leading [G]
    axis is sharded over the mesh's ``replica`` axis (``in_shardings``),
    so XLA partitions the vmapped program row-wise: co-pending runs
    execute on DISTINCT devices instead of queueing on one.  Rows never
    communicate (the kernels are per-row pure), so partitioning cannot
    change a row's op sequence — bit-identical outputs, asserted by
    ``tests/test_shard.py``.

    With a 2-D ``replica × host`` mesh (round 17 — batching × sharding
    composed), kernels with a registered sharded family resolve to the
    ``shard_map(vmap(per-shard body))`` program instead
    (``ops.shard.batched_sharded_call``): the [G] run axis shards over
    ``replica`` AND each row's host axis shards over ``host`` — one
    dispatch, G runs × S host shards.  Unregistered kernels keep the
    plain vmap program (bit-identical either way)."""
    static_kw = dict(static_items)
    if mesh is not None and host_ok:
        # ``host_ok`` is the caller's shape check: the kernel has a
        # registered sharded family AND the stacked host axis divides
        # the mesh's host shards (batch_execute computes it — shapes
        # aren't visible here).
        from pivot_tpu.ops.shard import batched_sharded_call

        fn = batched_sharded_call(mesh, kernel, static_kw, n_args, kw_keys)
        if fn is not None:
            return fn

    def call(*cols):
        return kernel(
            *cols[:n_args],
            **dict(zip(kw_keys, cols[n_args:])),
            **static_kw,
        )

    if mesh is None:
        return jax.jit(jax.vmap(call))
    from jax.sharding import NamedSharding, PartitionSpec

    shard = NamedSharding(mesh, PartitionSpec("replica"))
    return jax.jit(jax.vmap(call), in_shardings=shard, out_shardings=shard)


def _replica_mesh_for(mesh, gb: int):
    """The mesh to shard a ``gb``-row batch over, or None: the replica
    axis must divide the padded group bucket (contiguous row blocks per
    device), and a 1-row batch has nothing to spread.  A None return on
    a real mesh is a *fallback to the single-device program* — silent
    here (bit-identical by contract), but metered by the batcher
    (``mesh_fallbacks``) so a 2-D deployment can't quietly degrade to
    single-device dispatches.  (On a 2-D mesh, :func:`_plan_mesh` pads
    shardable groups up to the replica axis FIRST, so this fallback is
    the replica-only mesh's and unshardable kernels' path.)"""
    if mesh is None or gb <= 1:
        return None
    return mesh if gb % int(mesh.shape["replica"]) == 0 else None


def _plan_mesh(mesh, kernel, g: int, args0: tuple, arr_kw_keys=()):
    """One coalesced group's (padded bucket, mesh, 2-D eligibility) —
    the ONE routing decision ``batch_execute`` executes and the
    batcher's stats mirror, so the meter can never disagree with the
    program.

    On a 2-D ``replica × host`` mesh, a group of a kernel with a
    registered sharded family whose host axis divides the host shards
    gets its ``[G]`` bucket set to the SMALLEST multiple of the replica
    axis ≥ the group size: padding a 2-row group to 4 costs redundant
    pad rows (their outputs are discarded) but keeps the flush on the
    mesh — without it, every small coalesced group (the common serving
    case) would silently run single-device, which is exactly what the
    ``mesh_fallbacks`` meter exists to catch.  The smallest dividing
    bucket (not the power-of-two ladder rounded up) cuts the wasted
    rows — a 9-row group on a replica-4 axis pads to 12, not 16 — and
    the compile cache stays bounded: distinct [G] sizes are multiples
    of the replica axis capped by the pool size."""
    gb = group_bucket(g)
    host_ok = False
    if mesh is not None and g > 1:
        from pivot_tpu.ops.shard import mesh_is_2d, sharded_twin_of
        from pivot_tpu.parallel.mesh import host_axis_size

        if (
            mesh_is_2d(mesh)
            and sharded_twin_of(kernel, arr_kw_keys) is not None
            and args0 and hasattr(args0[0], "shape")
            and args0[0].shape[0] % host_axis_size(mesh) == 0
        ):
            r = int(mesh.shape["replica"])
            gb = ((g + r - 1) // r) * r
            host_ok = True
    fn_mesh = _replica_mesh_for(mesh, gb)
    host_ok = host_ok and fn_mesh is not None
    return gb, fn_mesh, host_ok


def _to_host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def batch_execute(
    kernel,
    requests: Sequence[Tuple[tuple, dict]],
    static_kw: Optional[dict] = None,
    mesh=None,
) -> list:
    """Serve N same-shaped kernel requests as one vmapped device dispatch.

    ``requests`` is a sequence of ``(args, arr_kw)`` pairs — positional
    array arguments plus array keyword arguments — whose shapes and
    dtypes must match pairwise (the caller groups by
    :func:`_request_key`).  Returns one output pytree per request, in
    order, with every leaf already fetched to host numpy: the batch pays
    ONE host→device staging and ONE device→host fetch where N sequential
    dispatches pay N of each.

    A single request takes the unbatched kernel program — the sequential
    fallback, bit-identical by the vmap-parity contract.

    Run-invariant operands (topology tables) are stacked G-wide like
    everything else rather than closed over with ``in_axes=None``: a
    broadcast concept would forbid grouping runs whose topologies differ
    (heterogeneous-cluster grids) or force value-hashing every dispatch,
    and the redundant bytes ride INSIDE the one batched call — a few KB
    of [Z, Z] tables against the ~78 ms per-call floor being amortized,
    no extra round-trip.

    ``mesh`` shards the stacked [G] axis over the mesh's ``replica``
    axis (``parallel.mesh.replica_mesh``), so the G rows execute on
    distinct devices — the multi-chip rung above same-device vmap.
    Falls back to the unsharded program when the padded group bucket
    does not divide the replica axis (row blocks must be contiguous)
    or the batch is a single request; bit-identical either way (rows
    never communicate).
    """
    static_kw = static_kw or {}
    g = len(requests)
    if g == 0:
        return []
    # Resident-carry firewall (round 20): the batcher re-stages every
    # operand from host numpy at the flush boundary (``stack`` below) —
    # a device-persistent ResidentCarry riding through here would be
    # silently fetched, copied, and severed from its donation chain,
    # defeating residency while APPEARING to work.  The policy layer
    # rejects the combination at enable time (``sched/tpu.py``); this
    # structural check is the belt-and-braces for direct callers.
    from pivot_tpu.ops.tickloop import ResidentCarry

    for req_args, req_kw in requests:
        if any(isinstance(a, ResidentCarry) for a in req_args) or any(
            isinstance(v, ResidentCarry) for v in req_kw.values()
        ):
            raise TypeError(
                "batch_execute cannot serve a resident-carry dispatch: "
                "the flush boundary re-stages operands from host numpy, "
                "which would sever the carry's device-donation chain — "
                "use ops.tickloop.resident_span_run directly (the "
                "resident tier and the cross-run batcher are mutually "
                "exclusive)"
            )
    if g == 1:
        args, arr_kw = requests[0]
        if mesh is not None:
            from pivot_tpu.ops.shard import mesh_is_2d, sharded_twin_of
            from pivot_tpu.parallel.mesh import host_axis_size

            twin = (
                sharded_twin_of(kernel, arr_kw) if mesh_is_2d(mesh)
                else None
            )
            if (
                twin is not None
                and args and hasattr(args[0], "shape")
                and args[0].shape[0] % host_axis_size(mesh) == 0
            ):
                # A lone dispatch on a 2-D mesh still runs HOST-sharded
                # through the family's 1-D twin (replica columns compute
                # replicas of the same program) — on a pod-scale cluster
                # the unsharded single-device program is exactly what
                # sharding exists to avoid.  Bit-identical by the twin
                # parity contract.
                return [_to_host(twin(mesh, *args, **arr_kw, **static_kw))]
        return [_to_host(kernel(*args, **arr_kw, **static_kw))]
    gb, fn_mesh, host_ok = _plan_mesh(
        mesh, kernel, g, requests[0][0], requests[0][1]
    )

    def stack(col):
        arrs = [np.asarray(a) for a in col]
        if gb > g:
            # Pad rows replicate row 0: same shapes, finite values, and
            # their output rows are sliced off below.
            arrs = arrs + [arrs[0]] * (gb - g)
        # Host numpy, NOT jnp.asarray: the jitted call converts its
        # arguments on its fast C++ path; an explicit per-column
        # device_put costs ~3× as much in Python dispatch (measured) —
        # exactly the overhead this module exists to amortize.
        return np.stack(arrs)

    args_cols = tuple(stack(col) for col in zip(*(r[0] for r in requests)))
    kw_keys = tuple(sorted(requests[0][1]))
    kw_cols = tuple(stack([r[1][k] for r in requests]) for k in kw_keys)
    fn = _batched_fn(
        kernel, tuple(sorted(static_kw.items())), len(args_cols), kw_keys,
        fn_mesh, host_ok,
    )
    out = _to_host(fn(*args_cols, *kw_cols))
    return [
        jax.tree_util.tree_map(lambda x: x[r], out) for r in range(g)
    ]


def _request_key(kernel, args, arr_kw, static_kw) -> tuple:
    """Requests with equal keys may share one vmapped dispatch."""
    return (
        kernel,
        tuple(sorted(static_kw.items())),
        tuple((tuple(a.shape), str(a.dtype)) for a in args),
        tuple(
            (k, tuple(v.shape), str(v.dtype))
            for k, v in sorted(arr_kw.items())
        ),
    )


class _Request:
    __slots__ = ("slot", "kernel", "args", "arr_kw", "static_kw", "key",
                 "done", "result", "error", "trim")

    def __init__(self, slot, kernel, args, arr_kw, static_kw):
        self.slot = slot
        self.kernel = kernel
        self.args = args
        self.arr_kw = arr_kw
        self.static_kw = static_kw
        self.key = _request_key(kernel, args, arr_kw, static_kw)
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        #: (K, B) buckets this span request was staged at, set by the
        #: ragged repack when the request rides a merged (K′, B′)
        #: dispatch — the demux slices the result back to these.
        self.trim: Optional[Tuple[int, int]] = None


class BatchClient:
    """One run's handle on the batcher: ``dispatch`` blocks the run's
    thread at its tick boundary until the coordinator serves the batch."""

    def __init__(self, batcher: "DispatchBatcher", slot: int):
        self._batcher = batcher
        self.slot = slot
        self._closed = False
        self._idle = False

    @property
    def mesh(self):
        """The owning batcher's mesh (None, replica-only, or 2-D) —
        what ``sched.tpu`` validates host-sharding compatibility
        against when composing batching with sharding."""
        return self._batcher._mesh

    def dispatch(self, kernel, args, arr_kw=None, static_kw=None):
        if self._closed:
            # An abandoned (stall-supervised) session thread waking up
            # after its slot was reclaimed must not re-enter the barrier:
            # its request would inflate the quiescence count forever.
            raise RuntimeError("batch client is closed")
        batcher = self._batcher
        with batcher._cond:
            # Single-live-slot fast path: a G=1 grid (or the last
            # surviving run of a larger one) has nobody to coalesce
            # with, so the queue hand-off and the coordinator-thread hop
            # buy nothing — serve the call synchronously on this thread.
            # Safe under the lock snapshot: we ARE the one open slot (a
            # closed client raised above), nothing is pending to group
            # with, and we never enter ``_pending``, so the coordinator
            # stays parked on its wait predicate.  Bit-identical by the
            # same contract as a one-request flush (``batch_execute``
            # serves both through the unbatched kernel program).
            solo = batcher._open == 1 and not batcher._pending
            if solo:
                batcher.stats["dispatches"] += 1
                batcher.stats["device_calls"] += 1
                batcher.stats["single_fast_path"] += 1
        if solo:
            if batcher._journal is not None:
                # Write-ahead parity with _flush: a solo dispatch is a
                # one-request flush and journals as one before the
                # device call commits it.
                batcher._journal.append("flush", groups=1, reqs=1)
            # The batcher's mesh rides along so a lone slot on a 2-D
            # mesh still dispatches host-sharded (batch_execute's g=1
            # twin path); on a replica-only mesh g=1 has nothing to
            # spread and runs the plain program as before.
            return batch_execute(
                kernel, [(tuple(args), dict(arr_kw or {}))],
                dict(static_kw or {}), mesh=batcher._mesh,
            )[0]
        req = _Request(
            self.slot, kernel, tuple(args), dict(arr_kw or {}),
            dict(static_kw or {}),
        )
        self._batcher._submit(req)
        req.done.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def set_idle(self, idle: bool) -> None:
        """Declare this slot idle (no work pending, not about to dispatch)
        or busy again.  An idle slot is excluded from the quiescence count,
        so a serving session blocked on its job inbox cannot park the
        other sessions' co-pending dispatches forever.  Idempotent; a
        closed client ignores the call."""
        if self._closed or idle == self._idle:
            return
        self._idle = idle
        self._batcher._set_idle(1 if idle else -1)

    def close(self) -> None:
        """Mark this run finished (idempotent) — the coordinator stops
        waiting for it.  MUST be called (``finally``) or the barrier
        deadlocks."""
        if not self._closed:
            self._closed = True
            self._batcher._close_slot(was_idle=self._idle)


class DispatchBatcher:
    """Tick-synchronous barrier + coalescer for G concurrent DES runs.

    Each run executes in its own thread; a placement dispatch parks the
    thread.  The coordinator (:meth:`serve`, run on the driver thread)
    waits for *quiescence* — every not-yet-finished run parked on a
    request — then flushes: co-pending requests with identical
    (kernel, shape, static-config) keys become one vmapped device call,
    stragglers run the plain single-run program.  Deadlock-free by
    construction: run threads only ever block inside ``dispatch``, and
    the coordinator only waits on the quiescence predicate, which thread
    exits (``BatchClient.close``) also satisfy.

    Two serving extensions over the batch-mode barrier (both inert by
    default, used by ``pivot_tpu.serve``):

      * **idle slots** — :meth:`BatchClient.set_idle` excludes a slot
        from the quiescence count while its session waits for work, so
        an empty session cannot park a busy one;
      * **deadline flush** (``flush_after`` seconds) — once at least one
        request is pending, the coordinator waits at most that long for
        full quiescence before flushing the partial batch, so a
        straggler session cannot stall co-pending dispatches
        indefinitely.  ``None`` (the batch-mode default) keeps the
        quiescence-only flush.

    ``stats`` after :meth:`serve` (documented contract — asserted by
    ``tests/test_batch_dispatch.py`` and ``docs/ARCHITECTURE.md``):
    ``runs`` (slots), ``dispatches`` (kernel calls requested),
    ``device_calls`` (actual dispatches issued), ``coalesced`` (requests
    served inside a >1 batch), ``max_group`` (largest batch),
    ``deadline_flushes`` (partial flushes forced by ``flush_after``),
    ``single_fast_path`` (calls served synchronously on the owning
    thread because theirs was the only live slot — no queue hand-off,
    no coordinator hop), ``mesh_dispatches`` (device calls whose [G]
    axis sharded over the replica mesh — multi-chip coalesced
    flushes), ``mesh_fallbacks`` (dispatches on a mesh that ran the
    single-device program when a mesh program was on the table: a
    coalesced flush whose padded group bucket does not divide the
    replica axis, or a fragment of a flush whose kernel appeared under
    multiple shape keys — bit-identical either way, but a deployment
    seeing this climb is quietly degrading; the first occurrence is
    also logged), its root-cause split ``mesh_fallback_unshardable``
    (the kernel has no sharded family or carries operands the sharded
    forms reject), ``mesh_fallback_mixed_shapes`` (the flush held the
    same kernel under ≥ 2 shape keys — the fragmentation the ragged
    repack exists to remove), ``mesh_fallback_indivisible`` (the
    bucket does not divide the replica axis; the causes partition
    ``mesh_fallbacks`` exactly), the ragged-repack trio
    ``ragged_merges`` (mixed-horizon span groups merged into one
    (K′, B′) bucket), ``ragged_rows`` (requests that rode a merged
    dispatch), ``ragged_pad_cells`` (K×B device cells executed beyond
    the members' own buckets — the padding waste the profiler
    attributes ragged losses to), and the
    pool-resize pair ``respawns`` (slots
    opened beyond the construction-time count: supervisor restarts and
    autoscaler growth) / ``retired_slots`` (slots closed for good:
    finished runs, drained-and-retired or crashed sessions).  At any
    instant ``live_slots == runs − retired_slots``.

    ``ragged=True`` (the default) turns on continuous span batching:
    co-pending ``fused_tick_run`` requests that differ ONLY in their
    span-length bucket K and slot-bucket width B are repacked to one
    merged (K′, B′) bucket and ride one device program, each result
    sliced back to its own buckets on demux (``ops/tickloop.py``
    ragged helpers; bit-identical by the inert-tail contract).  Rows
    join and leave the device batch at span boundaries — a tier-0
    2-tick span and a tier-2 16-tick span share one dispatch instead
    of fragmenting the flush.  ``ragged=False`` keeps the PR-15
    exact-shape coalescing (the bench A/B arm).
    """

    def __init__(self, n_slots: int, flush_after: Optional[float] = None,
                 mesh: Optional[object] = None, tracer=None,
                 profiler=None, ragged: bool = True, journal=None):
        if n_slots < 1:
            raise ValueError("DispatchBatcher needs at least one slot")
        if flush_after is not None and flush_after <= 0:
            raise ValueError("flush_after must be positive (or None)")
        #: Observability hook (round 14): each flush lands on the trace
        #: timeline as a wall-duration ``dispatch``/``flush`` span with
        #: its group size — the wall capture happens inside the tracer
        #: (``pivot_tpu/obs``), never here (sched/ is determinism-
        #: scoped).  ``None`` = the zero-cost NULL tracer.
        if tracer is None:
            from pivot_tpu.obs.tracer import NULL_TRACER

            tracer = NULL_TRACER
        self.tracer = tracer
        #: Sampled dispatch profiler (round 15, ``obs/profiler.py``):
        #: the flush boundary is where batched kernel calls actually
        #: hit the device, so the profiler brackets ``batch_execute``
        #: HERE — the per-policy ``_call_kernel`` hook deliberately
        #: stands down when a batch client is attached (it would time
        #: slot park time, not the dispatch).  The wall capture and the
        #: sampling decision both live inside the profiler (this module
        #: is determinism-scoped).  ``None`` = zero cost.
        self.profiler = profiler
        #: Write-ahead journal (``pivot_tpu.recover.Journal``): when the
        #: serve driver runs a recovery plane, every flush appends a
        #: record BEFORE any of its device calls execute, so a replay
        #: knows which co-pending sets the killed run committed to.
        #: ``None`` (default) = no recovery plane, zero cost.
        self._journal = journal
        self._cond = threading.Condition()
        self._n_slots = n_slots
        self._open = n_slots
        self._idle = 0
        self._flush_after = flush_after
        #: Replica-axis mesh (``parallel.mesh.replica_mesh``): coalesced
        #: flushes shard their stacked [G] axis over it so co-pending
        #: runs land on distinct devices (see :func:`batch_execute`).
        #: ``None`` (default) keeps the single-device vmap program.
        self._mesh = mesh
        self._pending: List[_Request] = []
        self._clients = 0
        self.stats: Dict[str, int] = {
            "runs": n_slots,
            "dispatches": 0,
            "device_calls": 0,
            "coalesced": 0,
            "max_group": 0,
            "deadline_flushes": 0,
            "single_fast_path": 0,
            #: Device calls whose [G] axis actually sharded over the
            #: replica mesh (mesh set AND the bucket divided the axis).
            "mesh_dispatches": 0,
            #: Mesh-eligible dispatches that ran the single-device
            #: program instead — fallbacks a 2-D deployment must watch
            #: (docstring above; logged once).  The three cause
            #: counters below partition this total exactly.
            "mesh_fallbacks": 0,
            "mesh_fallback_unshardable": 0,
            "mesh_fallback_mixed_shapes": 0,
            "mesh_fallback_indivisible": 0,
            #: Ragged continuous batching (docstring above): merged
            #: mixed-horizon span groups / requests riding them / K×B
            #: pad cells executed beyond the members' own buckets.
            "ragged_merges": 0,
            "ragged_rows": 0,
            "ragged_pad_cells": 0,
        }
        #: Continuous span batching (mixed-horizon ``fused_tick_run``
        #: repack) — see the class docstring.
        self._ragged = bool(ragged)
        self._mesh_fallback_logged = False
        #: Pool-resize accounting (serving autoscaler + supervisor):
        #: slots opened beyond the construction-time count and slots
        #: retired (closed for good — drained sessions, crashed runs).
        #: ``live_slots`` is the open count the autoscaler sizes against.
        self.stats["respawns"] = 0
        self.stats["retired_slots"] = 0

    def client(self) -> BatchClient:
        with self._cond:
            if self._clients >= self._n_slots:
                raise ValueError(
                    f"all {self._n_slots} batcher slots already claimed"
                )
            slot = self._clients
            self._clients += 1
        return BatchClient(self, slot)

    def respawn_client(self) -> BatchClient:
        """Open a FRESH slot beyond the construction-time count — the
        serving supervisor's restart path and the autoscaler's growth
        path (``serve/driver.py`` / ``serve/autoscale.py``): a crashed
        session's slot is closed by its dying thread, and a replacement
        or scale-up session must not inherit any old slot's state, so it
        gets a new one.  The quiescence predicate tracks ``_open``
        (closed slots don't count), so the slot population growing and
        shrinking over restarts/resizes never parks the coordinator."""
        with self._cond:
            slot = self._clients
            self._clients += 1
            self._n_slots += 1
            self._open += 1
            self.stats["runs"] = self._n_slots
            self.stats["respawns"] += 1
            self._cond.notify_all()
        return BatchClient(self, slot)

    @property
    def live_slots(self) -> int:
        """Open (not yet retired) slots — what the serving autoscaler
        sizes the pool against."""
        with self._cond:
            return self._open

    # -- run-thread side --------------------------------------------------
    def _submit(self, req: _Request) -> None:
        with self._cond:
            self._pending.append(req)
            self._cond.notify_all()

    def _close_slot(self, was_idle: bool = False) -> None:
        with self._cond:
            self._open -= 1
            self.stats["retired_slots"] += 1
            if was_idle:
                self._idle -= 1
            self._cond.notify_all()

    def _set_idle(self, delta: int) -> None:
        with self._cond:
            self._idle += delta
            self._cond.notify_all()

    # -- coordinator side -------------------------------------------------
    def _quiescent(self) -> bool:
        # Every live, non-idle run is parked on a request (each run has at
        # most one outstanding dispatch — its thread is blocked on it).
        if self._open == 0:
            return True
        if not self._pending:
            return False
        return len(self._pending) >= max(self._open - self._idle, 0)

    def serve(self) -> None:
        """Coordinator loop: flush batches until every run finished."""
        while True:
            with self._cond:
                # Phase 1: sleep until there is anything to do at all — a
                # pending request to (eventually) flush, or shutdown.
                self._cond.wait_for(
                    lambda: self._pending or self._open == 0
                )
                if self._open == 0 and not self._pending:
                    return
                # Phase 2: wait for quiescence, bounded by the flush
                # deadline.  ``wait_for`` returns False on timeout.
                quiesced = self._cond.wait_for(
                    self._quiescent, timeout=self._flush_after
                )
                if not self._pending:
                    continue
                if not quiesced:
                    self.stats["deadline_flushes"] += 1
                batch, self._pending = self._pending, []
            self._flush(batch)

    def _execute(self, reqs: List["_Request"]):
        """One coalesced device call for a same-key request group —
        through the sampled profiler when one is attached (its span
        carries ``in_flush`` so ``obs_report --check`` can assert the
        device span nests inside the surrounding flush span)."""
        call = lambda: batch_execute(  # noqa: E731 — thunk for the profiler
            reqs[0].kernel,
            [(r.args, r.arr_kw) for r in reqs],
            reqs[0].static_kw,
            mesh=self._mesh,
        )
        prof = self.profiler
        if prof is None or not prof.enabled:
            return call()
        from pivot_tpu.obs.profiler import family_of

        shape = {"g": len(reqs)}
        args0 = reqs[0].args
        if args0 and hasattr(args0[0], "shape") and len(
            args0[0].shape
        ) == 2:
            shape["h"] = int(args0[0].shape[0])
        if len(args0) > 1 and hasattr(args0[1], "shape"):
            shape["b"] = int(args0[1].shape[0])
        n_ticks = reqs[0].static_kw.get("n_ticks")
        if n_ticks is not None:
            shape["k"] = int(n_ticks)
        # Ragged attribution: the K×B cells this merged dispatch
        # executes beyond its members' own buckets — where the ragged
        # path loses against the same-shape ideal (pure padding waste;
        # zero on exact-shape groups).
        pad = sum(
            int(n_ticks) * shape.get("b", 0) - t[0] * t[1]
            for t in (r.trim for r in reqs) if t is not None
        )
        if pad:
            shape["ragged_pad_cells"] = pad
        # Staged-operand bytes (round 20): every member's args + array
        # kwargs re-enter the device from host numpy at this flush —
        # the re-staged arm's per-span transfer bill, the number the
        # ``serve_resident`` bench row compares against the resident
        # tier's delta shipping.
        h2d = sum(
            int(getattr(a, "nbytes", 0))
            for r in reqs
            for a in (*r.args, *r.arr_kw.values())
        )
        return prof.profile(
            family_of(reqs[0].kernel), call, shape=shape, flush=True,
            h2d_bytes=h2d,
        )

    def _fallback_cause(self, req: "_Request", fragmented: bool) -> str:
        """Root cause of one mesh fallback — the three causes partition
        ``mesh_fallbacks`` exactly: ``unshardable`` (no sharded family
        or operands the sharded forms reject), ``mixed_shapes`` (the
        flush held this kernel under ≥ 2 shape keys — fragmentation),
        ``indivisible`` (the padded bucket does not divide the replica
        axis)."""
        from pivot_tpu.ops.shard import mesh_is_2d, sharded_twin_of

        if mesh_is_2d(self._mesh) and sharded_twin_of(
            req.kernel, req.arr_kw
        ) is None:
            return "unshardable"
        if fragmented:
            return "mixed_shapes"
        return "indivisible"

    def _ragged_regroup(self, batch: List[_Request]) -> None:
        """Continuous span batching: merge co-pending ``fused_tick_run``
        requests that differ only in their (K, B) buckets into one
        (K′, B′) = (max K, max B) bucket so they share one device
        program (keys rewritten in place — the exact-key grouping below
        then coalesces them naturally).  Bit-identical per request by
        the inert-tail contract (``ops/tickloop.py``); the demux slices
        each result back via ``req.trim``."""
        from pivot_tpu.ops.tickloop import (
            fused_tick_run,
            ragged_span_pad,
            ragged_span_signature,
        )

        cand: Dict[tuple, List[_Request]] = {}
        for req in batch:
            if req.kernel is not fused_tick_run:
                continue
            sig = ragged_span_signature(
                req.args, req.arr_kw, req.static_kw
            )
            if sig is not None:
                cand.setdefault(sig, []).append(req)
        for reqs in cand.values():
            if len(reqs) < 2 or len({r.key for r in reqs}) < 2:
                continue  # solo or already same-shape — nothing to merge
            k2 = max(int(r.static_kw["n_ticks"]) for r in reqs)
            b2 = max(int(r.args[1].shape[0]) for r in reqs)
            pad_cells = 0
            for r in reqs:
                k, b = int(r.static_kw["n_ticks"]), int(r.args[1].shape[0])
                r.args, r.arr_kw = ragged_span_pad(r.args, r.arr_kw, k2, b2)
                r.static_kw = dict(r.static_kw, n_ticks=k2)
                r.trim = (k, b)
                r.key = _request_key(
                    r.kernel, r.args, r.arr_kw, r.static_kw
                )
                pad_cells += k2 * b2 - k * b
            with self._cond:
                self.stats["ragged_merges"] += 1
                self.stats["ragged_rows"] += len(reqs)
                self.stats["ragged_pad_cells"] += pad_cells

    def _flush(self, batch: List[_Request]) -> None:
        # Deterministic composition given a fixed co-pending set: groups
        # in first-key-seen order, rows in slot order.  (Results are
        # composition-independent anyway — the vmap-parity contract.)
        try:
            if self._ragged:
                self._ragged_regroup(batch)
            groups: Dict[tuple, List[_Request]] = {}
            for req in batch:
                groups.setdefault(req.key, []).append(req)
            # Per-kernel shape-key multiplicity across THIS flush: a
            # group that lost the mesh while its kernel rode other keys
            # fragmented — the mixed-shapes fallback cause the ragged
            # repack exists to remove.
            kernel_keys: Dict[object, set] = {}
            for key in groups:
                kernel_keys.setdefault(key[0], set()).add(key)
            if self._journal is not None and batch:
                # Write-ahead: the flush's composition hits the journal
                # before its first device call, so a crash mid-flush
                # leaves a record of what was committed.
                self._journal.append(
                    "flush", groups=len(groups), reqs=len(batch),
                )
            for reqs in groups.values():
                reqs.sort(key=lambda r: r.slot)
                # Under the cond: the single-live-slot fast path bumps
                # these same counters on the owning run's thread (found
                # by graftcheck's thread-guard pass — unlocked "+=" here
                # could lose an increment against a concurrent solo
                # dispatch after a respawn reopens the pool).
                log_fallback = False
                fragmented = len(kernel_keys[reqs[0].kernel]) > 1
                # The SAME routing decision batch_execute will make for
                # this group — stats and program cannot disagree.
                _gb, grp_mesh, _ok = _plan_mesh(
                    self._mesh, reqs[0].kernel, len(reqs), reqs[0].args,
                    reqs[0].arr_kw,
                )
                with self._cond:
                    self.stats["dispatches"] += len(reqs)
                    self.stats["device_calls"] += 1
                    self.stats["max_group"] = max(
                        self.stats["max_group"], len(reqs)
                    )
                    if len(reqs) > 1:
                        self.stats["coalesced"] += len(reqs)
                    if grp_mesh is not None:
                        self.stats["mesh_dispatches"] += 1
                    elif self._mesh is not None and (
                        len(reqs) > 1 or fragmented
                    ):
                        # The group LOST its mesh (coalesced but the
                        # bucket does not divide the replica axis, the
                        # kernel has no sharded form, or the flush
                        # fragmented into shape-keyed slivers) — this
                        # dispatch runs the single-device program.
                        # Metered by cause + logged once so a 2-D
                        # deployment can't quietly degrade.
                        self.stats["mesh_fallbacks"] += 1
                        self.stats[
                            "mesh_fallback_" + self._fallback_cause(
                                reqs[0], fragmented
                            )
                        ] += 1
                        if not self._mesh_fallback_logged:
                            self._mesh_fallback_logged = True
                            log_fallback = True
                if log_fallback:
                    import logging

                    logging.getLogger(__name__).warning(
                        "DispatchBatcher: %d-request flush (bucket %d) "
                        "cannot ride the mesh (%s) — "
                        "serving on a single device; further fallbacks "
                        "counted in stats['mesh_fallbacks'] and the "
                        "per-cause mesh_fallback_* counters",
                        len(reqs), _gb,
                        self._fallback_cause(reqs[0], fragmented),
                    )
                try:
                    with self.tracer.wall_span(
                        "dispatch", "flush", group=len(reqs),
                        slots=[r.slot for r in reqs],
                    ):
                        outs = self._execute(reqs)
                except BaseException as exc:  # noqa: BLE001 — deliver, don't hang
                    for r in reqs:
                        r.error = exc
                        r.done.set()
                    continue
                from pivot_tpu.ops.tickloop import ragged_span_trim

                for r, out in zip(reqs, outs):
                    r.result = (
                        ragged_span_trim(out, *r.trim)
                        if r.trim is not None else out
                    )
                    r.done.set()
        except BaseException as exc:  # noqa: BLE001 — coordinator crash-safety
            # A failure OUTSIDE the per-group kernel call (malformed
            # request, stats bookkeeping, result demux) must still reach
            # every owning slot: an undelivered request would leave its
            # run thread parked forever and the whole grid deadlocked.
            # The exception propagates through each owner's ``dispatch``;
            # the coordinator itself keeps serving the other slots.
            for r in batch:
                if not r.done.is_set():
                    r.error = exc
                    r.done.set()
