"""Sensitivity-gated dispatch: hold placements that are not noise-robust.

``TpuCostAwarePolicy.placement_sensitivity`` scores each tick's greedy
cost-aware decision (the hot loop of ref ``scheduler/cost_aware.py:99-127``)
against ±perturb noise in the host-availability snapshot — replica 0 is
the exact production decision, replicas 1..R−1 re-run the whole batched
kernel under multiplicative noise, and ``stability[t]`` is the fraction
agreeing with the nominal host.  This module gives that signal a
dispatcher: a policy wrapper that HOLDS (leaves unplaced for one tick)
any task whose nominal placement is below a stability threshold, on the
hypothesis that decisions made at a capacity/score boundary under stale
telemetry are the ones worth deferring.

The experiment around it (``cli.py sensitivity``) pairs this arm against
the identical un-gated policy on the same (trace, cluster, seed) and
reports the egress / runtime / makespan deltas across seeds — a measured
answer (positive or negative) to "does holding low-stability placements
help?", which is the production-consumer question VERDICT r03 item 6
left open.  The reference cannot ask it: scoring one tick under R noise
replicas IS the replica-batched kernel workload.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pivot_tpu.sched import Policy, TickContext

__all__ = ["SensitivityGatedCostAware", "evaluate_candidates"]


def evaluate_candidates(weights, env, **kw) -> np.ndarray:
    """Score a ``[B]`` population of scoring-weight vectors under a
    seeded market environment: ``scores[b]`` is candidate b's mean
    cost-per-completed-task over the environment's R paired Monte-Carlo
    rollouts (lower is better).

    This is the round-16 refactor of this module's batched-arm market
    evaluator into a reusable library function: the machinery that
    batches arms under a live market used to be private to the
    gated-policy class below (``placement_sensitivity`` replica batches
    scored through the policy's own kernel, replica 0 the production
    decision) — now the batched evaluation over *candidate weight
    vectors* is a first-class call the search loop
    (``pivot_tpu.search.es`` / ``pivot_tpu.search.cem``) drives
    directly.  ``weights`` is a ``[B, 5]`` matrix
    (:meth:`pivot_tpu.search.PolicyWeights.stack`) or a sequence of
    :class:`~pivot_tpu.search.PolicyWeights`; ``env`` a
    :class:`~pivot_tpu.search.fitness.SearchEnv`.  Keyword arguments
    (``key``, ``backend``, ``mesh``, ``tick_order``) pass through to
    :func:`pivot_tpu.search.fitness.evaluate_rows` — the population is
    one fused device dispatch, host-shardable over a replica mesh.

    Imported lazily so this module (a ``sched`` citizen the CLI loads
    eagerly) never drags the search/ensemble stack in by itself.
    """
    from pivot_tpu.search.fitness import evaluate_rows

    scores, _details = evaluate_rows(weights, env, **kw)
    return scores


class SensitivityGatedCostAware(Policy):
    """Placement with low-stability decisions held one tick.

    Wraps any device policy exposing ``placement_sensitivity`` (the
    cost-aware arm by default; pass ``inner=TpuFirstFitPolicy(
    decreasing=True)`` for the VBP arm — VERDICT r04 item 2); each tick
    runs ONE batched sensitivity call (replica 0 of which is the
    production decision, so gating adds no second placement pass) and
    overrides to −1 any placed task with ``stability < threshold`` that
    has not already been held ``max_holds`` times.  Held tasks re-enter
    through the scheduler's wait queue and are re-scored — with fresh
    noise — next tick; after ``max_holds`` holds the nominal decision
    goes through regardless, so a permanently-marginal task cannot
    starve.
    """

    name = "cost_aware_sensitivity_gated"  # refined per-inner in __init__

    def __init__(
        self,
        threshold: float = 0.7,
        n_replicas: int = 256,
        perturb: float = 0.05,
        max_holds: int = 1,
        noise_seed: int = 0,
        inner: Optional[object] = None,
        **inner_kwargs,
    ):
        from pivot_tpu.sched.tpu import TpuCostAwarePolicy

        if inner is not None and inner_kwargs:
            raise ValueError("pass inner or inner_kwargs, not both")
        self.inner = inner or TpuCostAwarePolicy(**inner_kwargs)
        if not hasattr(self.inner, "placement_sensitivity"):
            raise TypeError(
                f"{type(self.inner).__name__} has no placement_sensitivity"
                " — the gate needs the batched noise-replica kernel"
            )
        inner_name = getattr(self.inner, "name", type(self.inner).__name__)
        self.name = f"{inner_name}_sensitivity_gated"
        self.threshold = threshold
        self.n_replicas = n_replicas
        self.perturb = perturb
        self.max_holds = max_holds
        self.noise_seed = noise_seed
        self._holds: dict = {}
        self.stats = {
            "ticks": 0,
            "decisions": 0,
            "placed_nominal": 0,
            "held": 0,
            "forced_through": 0,  # low-stability but hold budget exhausted
            "stability_sum": 0.0,
            "min_stability": 1.0,
            # Wall seconds spent inside the batched sensitivity calls —
            # the gate's own price (VERDICT r04: "the gate's per-tick
            # wall cost is unmeasured anywhere").
            "sensitivity_wall_s": 0.0,
        }

    def bind(self, scheduler) -> None:
        self.inner.bind(scheduler)

    def place(self, ctx: TickContext) -> np.ndarray:
        import time

        # Fresh noise per tick (seed keyed on the tick ordinal): a held
        # task is re-judged against new draws, not the sample that
        # flagged it.
        t0 = time.perf_counter()  # graftcheck: ignore[determinism] -- wall-clock feeds only the sensitivity_wall_s meter; placements derive from the seeded noise draws alone
        nominal, stability, _ = self.inner.placement_sensitivity(
            ctx,
            n_replicas=self.n_replicas,
            perturb=self.perturb,
            seed=self.noise_seed + ctx.tick_seq,
        )
        self.stats["sensitivity_wall_s"] += time.perf_counter() - t0  # graftcheck: ignore[determinism] -- meter bookkeeping only (same window as the t0 read above)
        placements = np.asarray(nominal, dtype=np.int64).copy()
        st = self.stats
        st["ticks"] += 1
        st["decisions"] += ctx.n_tasks
        for i, task in enumerate(ctx.tasks):
            if placements[i] < 0:
                continue
            st["placed_nominal"] += 1
            s = float(stability[i])
            st["stability_sum"] += s
            if s < st["min_stability"]:
                st["min_stability"] = s
            if s < self.threshold:
                held = self._holds.get(task, 0)
                if held < self.max_holds:
                    self._holds[task] = held + 1
                    placements[i] = -1
                    st["held"] += 1
                else:
                    st["forced_through"] += 1
            if placements[i] >= 0:
                self._holds.pop(task, None)  # placed: forget hold history
        return placements

    def summary(self) -> dict:
        st = dict(self.stats)
        st["mean_stability"] = (
            st.pop("stability_sum") / st["placed_nominal"]
            if st["placed_nominal"]
            else None
        )
        st["sensitivity_wall_s"] = round(st["sensitivity_wall_s"], 3)
        st["sensitivity_wall_per_tick_s"] = (
            round(st["sensitivity_wall_s"] / st["ticks"], 4)
            if st["ticks"] else None
        )
        return st
