"""Deterministic counter-based random streams shared across policy backends.

The vectorized-numpy and TPU policy modes must make *identical* random
choices so placement parity is exact.  Philox is counter-based: the stream
for tick ``t`` is fully determined by ``(seed, t)`` with no sequential
state, so the CPU runtime can generate the tick's uniforms once and feed
the same array to either backend (the TPU kernel takes them as an input —
no on-device RNG divergence to worry about).
"""

from __future__ import annotations

import numpy as np

__all__ = ["tick_uniforms", "keyed_storage_index"]


def tick_uniforms(seed: int, tick_seq: int, n: int) -> np.ndarray:
    """[n] float64 uniforms in [0, 1) for one scheduling tick."""
    bitgen = np.random.Philox(key=seed, counter=[0, 0, 0, tick_seq])
    return np.random.Generator(bitgen).random(n)


# murmur3-style 32-bit finalizer constants; uint32 math only so the JAX
# twin (ensemble._keyed_storage_index_jax) runs on TPU, which has no u64.
_MIX_A = np.uint32(0x9E3779B9)
_MIX_B = np.uint32(0x85EBCA6B)
_MIX_C = np.uint32(0xC2B2AE35)


def keyed_storage_index(seed: int, app_ordinal, n_storage: int, salt: int = 0):
    """Root-anchor storage index for one application — an *entity-keyed*
    draw (pure function of ``(seed, app, salt)``), identical between the
    DES policies and the ensemble estimator.

    The reference redraws a root group's random storage anchor on every
    ``schedule()`` call (``scheduler/cost_aware.py:38-39``), i.e. the
    draw depends on stream *position* — unreproducible by an estimator
    with a different call pattern, which round 1 measured as the dominant
    cost-aware egress divergence.  Keying the draw on stable identity
    makes both engines agree exactly (and the retry path deterministic)
    while staying uniform over storages.  ``salt`` folds in the
    Monte-Carlo replica id (0 = the nominal draw the DES uses).

    ``app_ordinal`` may be a numpy int array (vectorized).
    """
    with np.errstate(over="ignore"):
        x = np.uint32(seed) * _MIX_A + np.uint32(salt)
        x ^= x >> np.uint32(16)
        x = x * _MIX_B + np.asarray(app_ordinal, np.uint32) * _MIX_A
        x ^= x >> np.uint32(13)
        x = x * _MIX_C
        x ^= x >> np.uint32(16)
    return (x % np.uint32(n_storage)).astype(np.int64)
