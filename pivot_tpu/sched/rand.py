"""Deterministic counter-based random streams shared across policy backends.

The vectorized-numpy and TPU policy modes must make *identical* random
choices so placement parity is exact.  Philox is counter-based: the stream
for tick ``t`` is fully determined by ``(seed, t)`` with no sequential
state, so the CPU runtime can generate the tick's uniforms once and feed
the same array to either backend (the TPU kernel takes them as an input —
no on-device RNG divergence to worry about).
"""

from __future__ import annotations

import numpy as np

__all__ = ["tick_uniforms"]


def tick_uniforms(seed: int, tick_seq: int, n: int) -> np.ndarray:
    """[n] float64 uniforms in [0, 1) for one scheduling tick."""
    bitgen = np.random.Philox(key=seed, counter=[0, 0, 0, tick_seq])
    return np.random.Generator(bitgen).random(n)
