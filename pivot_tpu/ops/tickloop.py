"""Device-resident multi-tick scheduler driver — the fused DES step.

Roofline accounting (round 6, ``infra/roofline.py``) proved the placement
hot path is *dispatch/serialization*-bound: every scheduling tick pays a
fixed host→device→host round trip (probe-measured 76–86 ms over the TPU
tunnel, ~0.1–1 ms even on the in-process CPU backend) that dwarfs the
per-tick kernel compute at realistic tick sizes.  ``DispatchBatcher``
(round 5) amortizes that floor *across* concurrent runs; this module
amortizes it *along the time axis*: K consecutive scheduling ticks of one
run execute as ONE device program, with the ``[H, 4]`` availability
carry, the within-span wait-queue permutation, the resident-task decay
counters, and the decision meters all staying device-resident between
ticks.

**The pure-tick-run contract.**  A span of K ticks may be fused only when
its inputs are computable up front — the DES side
(``GlobalScheduler._dispatch_loop``) extracts *pure tick runs*: maximal
windows in which the event heap holds nothing that could mutate
scheduler-visible state (no completions, no fault/chaos callbacks, no
retry resubmissions, no quarantine expiries), except local-scheduler pump
deliveries, whose payloads are snapshotted and folded in as *cohorts* —
``arrive[b]`` below is the tick index at which slot ``b`` joins the ready
pool.  Within such a window the ready set evolves only by this driver's
own placements: unplaced tasks re-enter the wait stack in visit order and
re-drain LIFO next tick (the reference's ``popitem`` semantics), which
the loop carry reproduces exactly.  Everything else — anchors, demands,
the live/quarantine mask, Philox draws — is constant or precomputable
over the window.  See ``docs/ARCHITECTURE.md`` ("pure tick runs").

**Bit-parity.**  Each simulated tick invokes the same unjitted two-phase
kernel core (``ops/kernels.py`` ``*_impl``) the per-tick path jits, on an
identically ordered task stream, so a fused span is bit-identical —
placements, availability carry, and meter counts — to K sequential
single-tick dispatches in every ``phase2`` mode (scan oracle, slim,
speculative chunk commit).  :func:`reference_tick_run` is the in-module
sequential referee: an independent host-side implementation of the same
span semantics driving one public kernel call per tick, which the parity
suite (``tests/test_tickloop.py``) holds :func:`fused_tick_run` to.

**Early exit.**  Two provable no-op conditions end the loop before the
horizon: the pool drained with no future cohorts (subsequent ticks have
an empty ready batch), and a zero-placement tick with no future cohorts —
availability only ever *decreases* within a span, so a task batch with no
fitting host this tick can never fit later in the span; all remaining
ticks are exact no-ops the host accounts for without device work.  The
returned ``ticks_run``/``n_stack_final`` let the caller extrapolate the
skipped ticks' meters exactly.

Host-sync discipline: no ``block_until_ready`` / host fetch / ``.item()``
may appear inside the loop body — enforced statically by
``tools/hotpath_lint.py`` (tier-1 wired).

Backend forms (the parity manifest's span family): this driver, the
sequential :func:`reference_tick_run` referee, the host-sharded twin
(``ops/shard.py::sharded_fused_tick_run``), and — round 17 — the
``[G]``-batched 2-D form (``sharded_batched_tick_run``), which serves G
coalesced spans on a ``replica × host`` mesh; the cross-run batcher
resolves :func:`fused_tick_run` requests to it when its mesh carries a
host axis (``sched/batch.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pivot_tpu.ops.kernels import (
    _apply_live,
    best_fit_impl,
    best_fit_kernel,
    cost_aware_impl,
    cost_aware_kernel,
    first_fit_impl,
    first_fit_kernel,
    opportunistic_impl,
    opportunistic_kernel,
)

__all__ = [
    "RAGGED_AXES",
    "RAGGED_INVARIANT",
    "ResidentCarry",
    "SpanResult",
    "edit_bucket",
    "fused_tick_run",
    "ragged_span_pad",
    "ragged_span_signature",
    "ragged_span_trim",
    "reference_tick_run",
    "resident_carry_clone",
    "resident_carry_export",
    "resident_carry_init",
    "resident_carry_restore",
    "resident_span_run",
    "span_bucket",
]

#: Static span-length buckets: one XLA program per (bucket, B, H, config),
#: never per span length — ``n_ticks_dyn`` trims the actual horizon.
_K_BUCKETS = (1, 2, 4, 8, 16, 32)


def span_bucket(k: int) -> int:
    """Smallest span bucket ≥ k (caps XLA program count per shape)."""
    for b in _K_BUCKETS:
        if k <= b:
            return b
    return ((k + 31) // 32) * 32


# ---------------------------------------------------------------------------
# Span slot-axis algebra — shared by the single-device driver below and the
# host-sharded driver (``ops/shard.py``).  These operate only on replicated
# [B] slot-axis state, never on the [H] host axis, so the sharded driver
# reuses them verbatim (every device computes identical values) and the two
# drivers cannot drift.  All three are hotpath-lint targets.
# ---------------------------------------------------------------------------


def _span_ready_batch(arrive, k, stackpos, n_stack, big):
    """Tick ``k``'s ready batch: LIFO re-drain of the wait stack (reverse
    stack order), then the tick's arriving cohort in delivery order —
    exactly the dispatch loop's drain sequence.  Returns ``(batch_pos
    [B] i32, in_batch [B] bool, t_k scalar i32, arriving [B] bool)``."""
    arriving = arrive == k
    arr_rank = jnp.cumsum(arriving.astype(jnp.int32)) - 1
    in_stack = stackpos >= 0
    batch_pos = jnp.where(
        in_stack,
        n_stack - 1 - stackpos,
        jnp.where(arriving, n_stack + arr_rank, big),
    ).astype(jnp.int32)
    in_batch = in_stack | arriving
    t_k = (n_stack + jnp.sum(arriving.astype(jnp.int32))).astype(jnp.int32)
    return batch_pos, in_batch, t_k, arriving


def _span_stream_order(policy, decreasing, sort_tasks, in_batch, batch_pos,
                       sort_norm, bucket_id, iota_b, big):
    """Kernel-stream order (ties resolved by batch position, which is
    unique — every sort is total, no stability needed):
      * batch-order arms: the batch order itself;
      * decreasing VBP arms: demand-norm-descending over the batch
        (``sort_norm`` is the HOST-computed f64 norm, the same values
        ``_sort_decreasing`` keys on — recomputing norms device-side
        could round a tie differently);
      * cost-aware: anchor buckets in first-seen batch order
        (``bucket_id`` is the host-resolved anchor identity — buckets
        have unique first-seen positions, so groups are contiguous
        after the sort), batch-ordered or norm-descending within a
        bucket."""
    B = iota_b.shape[0]
    inactive = (~in_batch).astype(jnp.int32)
    if policy == "cost-aware":
        bf_bucket = jax.ops.segment_min(
            jnp.where(in_batch, batch_pos, big),
            bucket_id,
            num_segments=B,
        )
        bfirst = bf_bucket[bucket_id]
        key3 = -sort_norm if sort_tasks else batch_pos
        return lax.sort(
            (inactive, bfirst, key3, batch_pos, iota_b), num_keys=4
        )[-1]
    if decreasing:
        return lax.sort(
            (inactive, -sort_norm, batch_pos, iota_b), num_keys=3
        )[-1]
    return lax.sort((inactive, batch_pos, iota_b), num_keys=2)[-1]


def _span_group_entries(bucket_id, order, iota_b):
    """Per-position group-entry flags of the permuted cost-aware stream
    (buckets are contiguous after :func:`_span_stream_order`)."""
    b_p = bucket_id[order]
    return jnp.where(iota_b == 0, True, b_p != jnp.roll(b_p, 1))


def _span_requeue(decreasing, in_batch, placed, batch_pos, order, iota_b,
                  big):
    """Wait-stack rebuild: unplaced batch members re-enter in VISIT order
    — the kernel-stream order for the decreasing VBP arms (the reference
    consumes ``schedule()``'s sorted return list), the batch order for
    everything else (cost-aware's bucket sort happens on a copy; its
    return order is the batch).  Returns ``(new_stackpos [B] i32,
    new_n_stack scalar i32)``."""
    B = iota_b.shape[0]
    if decreasing:
        visit_pos = jnp.zeros((B,), jnp.int32).at[order].set(iota_b)
    else:
        visit_pos = batch_pos
    unplaced = in_batch & ~placed
    srt = lax.sort(
        (jnp.where(unplaced, visit_pos, big), iota_b), num_keys=1
    )[1]
    ranks = jnp.zeros((B,), jnp.int32).at[srt].set(iota_b)
    new_stackpos = jnp.where(unplaced, ranks, -1)
    new_n_stack = jnp.sum(unplaced.astype(jnp.int32)).astype(jnp.int32)
    return new_stackpos, new_n_stack


class SpanResult(NamedTuple):
    """One fused span's outputs (axes: K = tick bucket, B = slot bucket).

    ``placements`` rows are indexed by *slot* (the span's task identity:
    tick-0 ready batch first in batch order, then cohorts in delivery
    order); −1 = unplaced that tick / not in that tick's batch.  Rows at
    index ≥ ``ticks_run`` are provable no-ops (all −1): if
    ``n_stack_final`` > 0 the span stalled (those ticks still present
    ``n_stack_final`` ready tasks to the meter and place none), otherwise
    the pool drained (those ticks have an empty ready batch and touch no
    meter).
    """

    placements: jax.Array  # [K, B] i32 host index per slot, −1 unplaced
    n_ready: jax.Array  # [K] i32 ready-batch size per executed tick
    n_placed: jax.Array  # [K] i32 placements per executed tick
    ticks_run: jax.Array  # scalar i32 — ticks actually executed
    n_stack_final: jax.Array  # scalar i32 — wait-stack size at exit
    stackpos: jax.Array  # [B] i32 final wait-stack position, −1 = out
    avail: jax.Array  # [H, 4] availability carry at exit


def _fused_tick_run_impl(
    avail,
    demands,
    arrive,
    n_ticks_dyn,
    uniforms,
    sort_norm,
    anchor_zone,
    bucket_id,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    totals,
    live,
    risk_rows,
    cost_stack,
    cost_seg,
    score_exp,
    *,
    policy: str,
    n_ticks: int,
    strict: bool,
    decreasing: bool,
    bin_pack: str,
    sort_tasks: bool,
    sort_hosts: bool,
    host_decay: bool,
    phase2,
):
    B = demands.shape[0]
    H = avail.shape[0]
    K = n_ticks
    avail, restore = _apply_live(avail, live)
    iota_b = jnp.arange(B, dtype=jnp.int32)
    big = jnp.asarray(2 * B + 2, jnp.int32)  # > any real batch position

    def cond(st):
        k, done = st[0], st[1]
        return (k < n_ticks_dyn) & ~done

    def body(st):
        k, done, stackpos, n_stack, avail, cum, p_out, nr_out, np_out = st
        # A dead row under vmap (the cross-run batcher coalesces whole
        # spans) must be inert: every state write below gates on alive.
        alive = (k < n_ticks_dyn) & ~done

        # 1. This tick's ready batch (shared algebra, ``_span_ready_batch``).
        batch_pos, in_batch, t_k, _arriving = _span_ready_batch(
            arrive, k, stackpos, n_stack, big
        )

        # 2. Kernel-stream order (shared algebra, ``_span_stream_order``).
        order = _span_stream_order(
            policy, decreasing, sort_tasks, in_batch, batch_pos,
            sort_norm, bucket_id, iota_b, big,
        )
        dem_p = demands[order]
        valid_p = in_batch[order]
        # Per-tick market state (round 11, ``infra/market.py``): the
        # tick's [H] risk row and — for cost-aware — the tick's [Z, Z]
        # slice of the [P, Z, Z] price-scaled cost tensor, indexed by the
        # per-span [K] time-index row (the same pattern as the Philox
        # uniform rows).  Both None in market-free worlds: the traced
        # program is unchanged bit for bit.
        risk_k = None if risk_rows is None else risk_rows[k]
        cost_k = cost_zz if cost_stack is None else cost_stack[cost_seg[k]]

        # 3. One two-phase kernel core — the same ops the per-tick jitted
        #    path runs, so placements are bit-identical to a single-tick
        #    dispatch with these inputs.
        if policy == "opportunistic":
            # Positional Philox draws: row k is ``tick_uniforms(seed,
            # tick_seq + k, B)`` and position j's draw serves batch
            # position j — identical to the sequential path's per-tick
            # stream (prefix property of the counter-based generator).
            p_ord, new_avail = opportunistic_impl(
                avail, dem_p, valid_p, uniforms[k], phase2=phase2,
                risk=risk_k,
            )
        elif policy == "first-fit":
            p_ord, new_avail = first_fit_impl(
                avail, dem_p, valid_p, strict=strict, totals=totals,
                phase2=phase2, risk=risk_k,
            )
        elif policy == "best-fit":
            p_ord, new_avail = best_fit_impl(
                avail, dem_p, valid_p, totals=totals, phase2=phase2,
                risk=risk_k,
            )
        else:  # cost-aware
            ng_p = _span_group_entries(bucket_id, order, iota_b)
            p_ord, new_avail = cost_aware_impl(
                avail,
                dem_p,
                valid_p,
                ng_p,
                anchor_zone[order],
                cost_k,
                bw_zz,
                host_zone,
                base_task_counts + cum,
                bin_pack=bin_pack,
                sort_hosts=sort_hosts,
                host_decay=host_decay,
                totals=totals,
                phase2=phase2,
                risk=risk_k,
                score_exp=score_exp,
            )
        row = jnp.full((B,), -1, jnp.int32).at[order].set(
            p_ord.astype(jnp.int32)
        )
        placed = row >= 0
        n_placed = jnp.sum(placed.astype(jnp.int32)).astype(jnp.int32)

        # 4. Wait-stack rebuild (shared algebra, ``_span_requeue``).
        new_stackpos, new_n_stack = _span_requeue(
            decreasing, in_batch, placed, batch_pos, order, iota_b, big
        )

        # 5. Span-cumulative resident-task counts (the host-decay base
        #    grows by one per placement, mirroring Host.n_tasks at
        #    admission).
        cum_new = cum.at[jnp.where(placed, row, H)].add(
            placed.astype(jnp.int32), mode="drop"
        )

        # 6. Provable-no-op early exit (see module docstring).
        future = jnp.any((arrive > k) & (arrive < n_ticks_dyn))
        done_new = ~future & ((new_n_stack == 0) | (n_placed == 0))

        kk = jnp.where(alive, k, K)  # dead rows write out of bounds → drop
        return (
            k + 1,
            jnp.where(alive, done_new, done),
            jnp.where(alive, new_stackpos, stackpos),
            jnp.where(alive, new_n_stack, n_stack),
            jnp.where(alive, new_avail, avail),
            jnp.where(alive, cum_new, cum),
            p_out.at[kk].set(jnp.where(alive, row, -1), mode="drop"),
            nr_out.at[kk].set(t_k, mode="drop"),
            np_out.at[kk].set(n_placed, mode="drop"),
        )

    st0 = (
        jnp.asarray(0, jnp.int32),
        jnp.asarray(False),
        jnp.full((B,), -1, jnp.int32),  # tick-0 stack is empty: the base
        jnp.asarray(0, jnp.int32),      # batch arrives as cohort 0
        avail,
        jnp.zeros((H,), jnp.int32),
        jnp.full((K, B), -1, jnp.int32),
        jnp.zeros((K,), jnp.int32),
        jnp.zeros((K,), jnp.int32),
    )
    k, _done, stackpos, n_stack, avail, _cum, p_out, nr_out, np_out = (
        lax.while_loop(cond, body, st0)
    )
    return SpanResult(
        p_out, nr_out, np_out, k, n_stack, stackpos, restore(avail)
    )


_fused_tick_run = jax.jit(
    _fused_tick_run_impl,
    static_argnames=(
        "policy",
        "n_ticks",
        "strict",
        "decreasing",
        "bin_pack",
        "sort_tasks",
        "sort_hosts",
        "host_decay",
        "phase2",
    ),
    # DELIBERATELY NOT donated (a negative entry in the analysis
    # donation manifest, ``pivot_tpu/analysis/donation.py``): the span
    # operands are staged straight from host numpy at the call boundary
    # (``place_span``/tests/bench), and on the CPU backend
    # ``jnp.asarray(host_array)`` is ZERO-COPY for large aligned arrays
    # — a donated carry would let XLA reuse memory the caller still
    # owns (measured: silent corruption of the DES availability
    # snapshot the sequential referee reads).  The donation pass
    # enforces this decision in BOTH directions: adding donate_argnums
    # here is a finding until the manifest entry flips.
    #
    # The DONATING form of this driver is ``_resident_span_run`` below:
    # its carry is always a previous jit OUTPUT (device-owned by
    # construction — ``resident_carry_init`` materializes an explicit
    # device copy before the first donation), so the zero-copy hazard
    # structurally cannot occur there.  Callers that want buffer reuse
    # go resident; this entry point stays the safe re-staged form.
)


def fused_tick_run(
    avail,
    demands,
    arrive,
    n_ticks_dyn,
    *,
    policy: str,
    n_ticks: int,
    uniforms=None,
    sort_norm=None,
    anchor_zone=None,
    bucket_id=None,
    cost_zz=None,
    bw_zz=None,
    host_zone=None,
    base_task_counts=None,
    totals=None,
    live=None,
    risk_rows=None,
    cost_stack=None,
    cost_seg=None,
    score_exp=None,
    strict: bool = False,
    decreasing: bool = False,
    bin_pack: str = "first-fit",
    sort_tasks: bool = False,
    sort_hosts: bool = True,
    host_decay: bool = False,
    phase2="auto",
) -> SpanResult:
    """Execute up to ``n_ticks_dyn`` scheduling ticks as one device program.

    Inputs (B = slot bucket, K = ``n_ticks`` span bucket, H hosts):
      avail            [H, 4]  availability carry at span start
      demands          [B, 4]  per-slot demand (slot layout: tick-0 ready
                               batch in batch order, then cohorts in
                               delivery order; pad slots get
                               ``arrive >= n_ticks``)
      arrive           [B] i32 tick index at which each slot joins the pool
      n_ticks_dyn      scalar  actual span horizon (≤ the static bucket)
      uniforms         [K, B]  positional Philox draws (opportunistic)
      sort_norm        [B]     host-computed demand L2 norms (the
                               ``_sort_decreasing`` keys; decreasing /
                               ``sort_tasks`` arms)
      anchor_zone      [B] i32 per-slot anchor zone (cost-aware)
      bucket_id        [B] i32 per-slot anchor-bucket identity < B
                               (cost-aware; anchors are span-constant)
      cost_zz/bw_zz/host_zone/base_task_counts/totals — the cost-aware
                               topology operands (``DeviceTopology``)
      live             [H]     span-constant quarantine mask (or None)
      risk_rows        [K, H]  per-tick eviction-risk rows (the market's
                               hazard × risk_weight × rework_cost at each
                               span instant — one row per tick, like the
                               Philox uniform rows; or None)
      cost_stack       [P, Z, Z] price-scaled egress-cost tensor
                               (``MarketSchedule.cost_tensor``; or None —
                               ``cost_zz`` then serves every tick)
      cost_seg         [K] i32 per-tick segment index into ``cost_stack``
                               (``MarketSchedule.segment_indices`` of the
                               span grid — the per-span time-index row)
      score_exp        [3]     span-constant learned score exponents
                               ``(w_cost, w_bw, w_norm)`` for cost-aware
                               (``PolicyWeights.score_exponents()``; or
                               None — the reference (1, 1, 1) shape,
                               traced program unchanged bit for bit)

    Static config mirrors the per-tick kernels (``strict``/``decreasing``
    for the VBP arms, ``bin_pack``/``sort_tasks``/``sort_hosts``/
    ``host_decay`` for cost-aware, ``phase2`` selecting the sequential
    pass).  Returns a :class:`SpanResult` (see its docstring for the
    no-op-tail contract).  Bit-identical to :func:`reference_tick_run`
    on the same inputs — the fused-parity suite's contract.
    """
    return _fused_tick_run(
        avail,
        demands,
        arrive,
        n_ticks_dyn,
        uniforms,
        sort_norm,
        anchor_zone,
        bucket_id,
        cost_zz,
        bw_zz,
        host_zone,
        base_task_counts,
        totals,
        live,
        risk_rows,
        cost_stack,
        cost_seg,
        score_exp,
        policy=policy,
        n_ticks=n_ticks,
        strict=strict,
        decreasing=decreasing,
        bin_pack=bin_pack,
        sort_tasks=sort_tasks,
        sort_hosts=sort_hosts,
        host_decay=host_decay,
        phase2=phase2,
    )


# ---------------------------------------------------------------------------
# Resident span carries — device-persistent serve state (round 20).
#
# ``fused_tick_run`` re-stages the full operand set from host numpy every
# span and ships the availability carry back after every program; at serve
# scale (H up to 100k hosts) the staging bytes, not the decisions, dominate
# the span cost.  The resident entry point below keeps the span carry —
# availability, per-host task counts, live mask — ON DEVICE between
# consecutive spans and accepts only a small host-built *delta* per span:
#
#   * sparse host-row EDITS (chaos/live-mask flips, completion releases,
#     any host divergence the caller's mirror-diff detects), padded to an
#     edit bucket and scattered with ``mode="drop"`` inert padding;
#   * the per-span slot operands (demands/arrive/norms/anchors), which are
#     genuinely new each span and stay host-staged;
#   * a market-segment GATHER: instead of rendering ``risk_rows`` [K, H]
#     on the host per span (O(K*H) bytes), the caller stages the full
#     per-segment risk table [P, H] ONCE and sends a [K] i32 segment row
#     per span — the device gathers its own rows.
#
# The carry argument is DONATED (the manifest-declared positive entry in
# ``analysis/donation.py`` — contrast the re-staged driver's negative
# entry above): every carry a caller can hold is a previous jit OUTPUT
# (``resident_carry_init``/``resident_carry_clone`` are themselves jitted
# ``jnp.copy`` programs, so even the first carry is a device-owned copy,
# never a zero-copy view of caller numpy).  The PR-11 hazard therefore
# structurally cannot occur: XLA reuses only buffers the caller received
# from XLA.  The caller-side discipline — never touch a carry after
# passing it — is enforced by the donation pass's use-after-donate check
# (``resident_span_run`` is a registered donating call).
#
# Mid-span splice rides the same machinery: the scheduler keeps a cloned
# checkpoint of the span-entry carry, and a qualifying mid-span arrival
# re-dispatches the WHOLE span from the checkpoint with the new slot
# joined at ``arrive = k``.  The inert-join contract (a slot with
# ``arrive > tick`` sorts last and places −1, exactly how pump cohorts
# already enter mid-span) makes ticks [0, k) of the re-run bit-identical
# to the committed prefix, so splice admission is bit-identical to the
# flush-boundary referee replayed sequentially — the in-flight program's
# result is simply discarded.
# ---------------------------------------------------------------------------

#: Static edit-row buckets: one XLA program per (edit bucket, B, K, H,
#: config).  0 = the steady-state no-edit program (no scatter traced).
_EDIT_BUCKETS = (0, 8, 32, 128, 512)


def edit_bucket(n: int) -> int:
    """Smallest edit-row bucket ≥ n (caps XLA program count per shape)."""
    for b in _EDIT_BUCKETS:
        if n <= b:
            return b
    return ((n + 511) // 512) * 512


class ResidentCarry(NamedTuple):
    """Device-resident serve state carried between consecutive spans.

    Opaque to the host: callers obtain one from
    :func:`resident_carry_init` / :func:`resident_carry_clone` /
    :func:`resident_span_run` and must treat a carry passed to
    :func:`resident_span_run` as CONSUMED (the buffers are donated).
    ``live`` is always materialized — an all-True mask is bitwise
    identity through ``_apply_live`` (``jnp.where(True, x, y) == x``),
    so the no-quarantine case costs nothing and the traced program stays
    shape-stable when quarantines come and go.
    """

    avail: jax.Array  # [H, 4] availability
    counts: jax.Array  # [H] i32 resident-task counts (cost-aware decay)
    live: jax.Array  # [H] bool quarantine mask (all-True when unused)


def _resident_carry_init_impl(avail, counts, live):
    # ``jnp.copy`` inside jit forces fresh DEVICE-OWNED output buffers:
    # on the CPU backend a bare identity jit would alias the caller's
    # numpy (the zero-copy hazard), and ``x + 0`` is not bitwise for
    # -0.0.  These copies are what licenses donation downstream.
    return ResidentCarry(jnp.copy(avail), jnp.copy(counts), jnp.copy(live))


_resident_carry_init = jax.jit(_resident_carry_init_impl)


def resident_carry_init(avail, counts=None, live=None) -> ResidentCarry:
    """Materialize a device-owned :class:`ResidentCarry` from host state.

    ``counts`` defaults to zeros, ``live`` to all-True.  This is the one
    full [H]-sized staging the resident path pays; every subsequent span
    ships only deltas.  The returned carry's buffers are explicit device
    copies — safe to donate even though the inputs were host numpy.
    """
    avail = jnp.asarray(avail)
    H = avail.shape[0]
    if counts is None:
        counts = np.zeros((H,), np.int32)
    if live is None:
        live = np.ones((H,), bool)
    return _resident_carry_init(
        avail,
        jnp.asarray(counts, jnp.int32),
        jnp.asarray(live, bool),
    )


def _resident_carry_clone_impl(carry):
    avail, counts, live = carry
    return ResidentCarry(jnp.copy(avail), jnp.copy(counts), jnp.copy(live))


_resident_carry_clone = jax.jit(_resident_carry_clone_impl)


def resident_carry_clone(carry: ResidentCarry) -> ResidentCarry:
    """Independent device copy of ``carry`` (splice checkpoints).

    The clone and the original are separately donate-able; cloning before
    a speculative dispatch is how the scheduler keeps a rollback point
    without violating the consumed-on-call contract.
    """
    return _resident_carry_clone(carry)


def resident_carry_export(carry: ResidentCarry) -> dict:
    """Host numpy copies of a carry's buffers (the snapshot D2H fetch).

    Donation safety: call this ONLY on a clone or on a PENDING carry (a
    jit output not yet passed to the next donating dispatch — the same
    window the resident mirror-diff reads in).  Reading a carry after
    it was donated is the exact hazard the extended
    ``analysis/donation.py`` host-read-after-donate check flags.
    """
    return {
        "avail": np.asarray(carry.avail),
        "counts": np.asarray(carry.counts),
        "live": np.asarray(carry.live),
    }


def resident_carry_restore(avail, counts, live) -> ResidentCarry:
    """Re-materialize a device-owned carry from snapshot host arrays.

    The warm-resume half of the recovery plane: a carry exported (or
    snapshotted) at span ``n`` restores here, and continuing the span
    chain from it is bit-identical to never having stopped
    (``tests/test_recovery.py`` kernel-level referee).  Same explicit
    device-copy contract as :func:`resident_carry_init` — the restored
    buffers are safe to donate immediately.
    """
    return resident_carry_init(avail, counts=counts, live=live)


def _resident_span_run_impl(
    carry,
    edit_idx,
    edit_avail,
    edit_counts,
    edit_live,
    demands,
    arrive,
    n_ticks_dyn,
    uniforms,
    sort_norm,
    anchor_zone,
    bucket_id,
    cost_zz,
    bw_zz,
    host_zone,
    totals,
    risk_table,
    risk_seg,
    cost_stack,
    cost_seg,
    score_exp,
    *,
    policy,
    n_ticks,
    strict,
    decreasing,
    bin_pack,
    sort_tasks,
    sort_hosts,
    host_decay,
    phase2,
):
    avail, counts, live = carry
    H = avail.shape[0]
    if edit_idx is not None:
        # Sparse host-row repairs; pad rows carry index H → dropped.
        avail = avail.at[edit_idx].set(edit_avail, mode="drop")
        counts = counts.at[edit_idx].set(edit_counts, mode="drop")
        live = live.at[edit_idx].set(edit_live, mode="drop")
    # Market rows gathered on device from the once-staged segment table —
    # bitwise the host-rendered ``risk_rows[k] = table[seg[k]]`` rows the
    # re-staged arm ships, because both sides index the same f-dtype rows.
    risk_rows = None if risk_seg is None else risk_table[risk_seg]
    res = _fused_tick_run_impl(
        avail,
        demands,
        arrive,
        n_ticks_dyn,
        uniforms,
        sort_norm,
        anchor_zone,
        bucket_id,
        cost_zz,
        bw_zz,
        host_zone,
        counts,
        totals,
        live,
        risk_rows,
        cost_stack,
        cost_seg,
        score_exp,
        policy=policy,
        n_ticks=n_ticks,
        strict=strict,
        decreasing=decreasing,
        bin_pack=bin_pack,
        sort_tasks=sort_tasks,
        sort_hosts=sort_hosts,
        host_decay=host_decay,
        phase2=phase2,
    )
    # Fold the span's own placements into the resident count state so the
    # steady state (no completions between spans) needs zero edit rows;
    # the caller's mirror-diff repairs completion decrements.
    placed = res.placements >= 0
    tgt = jnp.where(placed, res.placements, H)
    hist = jnp.zeros((H,), jnp.int32).at[tgt.reshape(-1)].add(
        placed.reshape(-1).astype(jnp.int32), mode="drop"
    )
    return res, ResidentCarry(res.avail, counts + hist, live)


_resident_span_run = jax.jit(
    _resident_span_run_impl,
    static_argnames=(
        "policy",
        "n_ticks",
        "strict",
        "decreasing",
        "bin_pack",
        "sort_tasks",
        "sort_hosts",
        "host_decay",
        "phase2",
    ),
    # The carry IS donated — the declared positive manifest entry in
    # ``analysis/donation.py`` (resident-span-carry).  Safe because the
    # carry pytree is always jit output (see the section comment above);
    # the use-after-donate caller check polices the host side.
    donate_argnums=(0,),
)


def resident_span_run(
    carry: ResidentCarry,
    demands,
    arrive,
    n_ticks_dyn,
    *,
    policy: str,
    n_ticks: int,
    edit_idx=None,
    edit_avail=None,
    edit_counts=None,
    edit_live=None,
    uniforms=None,
    sort_norm=None,
    anchor_zone=None,
    bucket_id=None,
    cost_zz=None,
    bw_zz=None,
    host_zone=None,
    totals=None,
    risk_table=None,
    risk_seg=None,
    cost_stack=None,
    cost_seg=None,
    score_exp=None,
    strict: bool = False,
    decreasing: bool = False,
    bin_pack: str = "first-fit",
    sort_tasks: bool = False,
    sort_hosts: bool = True,
    host_decay: bool = False,
    phase2="auto",
):
    """Run one fused span against a device-resident carry.

    The delta contract (vs :func:`fused_tick_run`'s full re-staging):

      carry        ResidentCarry — CONSUMED (donated); use the returned
                   carry for the next span.  Never re-read after the call.
      edit_idx     [E] i32 host-row indices to repair before the span
                   (pad entries = H, dropped), or None for the
                   steady-state no-edit program
      edit_avail   [E, 4] replacement availability rows
      edit_counts  [E] i32 replacement resident-task counts
      edit_live    [E] bool replacement quarantine-mask entries
      risk_table   [P, H] per-market-segment eviction-risk rows, staged
                   once per market epoch (or None)
      risk_seg     [K] i32 per-tick segment index into ``risk_table``
                   (or None → no risk shaping this span)

    Per-span slot operands (``demands``/``arrive``/``uniforms``/
    ``sort_norm``/``anchor_zone``/``bucket_id``) and the static config
    match :func:`fused_tick_run` exactly; ``base_task_counts`` and
    ``live`` come from the carry instead of keywords.  Returns
    ``(SpanResult, ResidentCarry)`` where the result is bit-identical to
    ``fused_tick_run`` on the post-edit host state — the resident parity
    suite's contract (``tests/test_resident.py``).
    """
    return _resident_span_run(
        carry,
        edit_idx,
        edit_avail,
        edit_counts,
        edit_live,
        demands,
        arrive,
        n_ticks_dyn,
        uniforms,
        sort_norm,
        anchor_zone,
        bucket_id,
        cost_zz,
        bw_zz,
        host_zone,
        totals,
        risk_table,
        risk_seg,
        cost_stack,
        cost_seg,
        score_exp,
        policy=policy,
        n_ticks=n_ticks,
        strict=strict,
        decreasing=decreasing,
        bin_pack=bin_pack,
        sort_tasks=sort_tasks,
        sort_hosts=sort_hosts,
        host_decay=host_decay,
        phase2=phase2,
    )


def reference_tick_run(
    avail,
    demands,
    arrive,
    n_ticks: int,
    *,
    policy: str,
    uniforms=None,
    sort_norm=None,
    anchor_zone=None,
    bucket_id=None,
    cost_zz=None,
    bw_zz=None,
    host_zone=None,
    base_task_counts=None,
    totals=None,
    live=None,
    risk_rows=None,
    cost_stack=None,
    cost_seg=None,
    score_exp=None,
    strict: bool = False,
    decreasing: bool = False,
    bin_pack: str = "first-fit",
    sort_tasks: bool = False,
    sort_hosts: bool = True,
    host_decay: bool = False,
    phase2="auto",
):
    """Sequential referee for :func:`fused_tick_run`: the same span
    semantics driven tick by tick with ONE public (jitted) kernel call
    per tick and the wait-stack algebra in plain Python — i.e. exactly
    what the per-tick dispatch path pays, which is also what ``bench.py``
    ``fused_tick`` times it against.  The market operands
    (``risk_rows``/``cost_stack``/``cost_seg``) follow the driver's
    contract: tick ``k`` scores with ``risk_rows[k]`` and — cost-aware —
    ``cost_stack[cost_seg[k]]``.  Returns ``(placements [K, B] i64,
    n_ready [K], n_placed [K], avail [H, 4])`` as host numpy, with the
    no-op tail rows materialized (so outputs compare 1:1 against a
    :class:`SpanResult` whose tail the device loop skipped).
    """
    B = demands.shape[0]
    avail = jnp.asarray(avail)
    arrive = np.asarray(arrive)
    placements = np.full((n_ticks, B), -1, dtype=np.int64)
    n_ready = np.zeros(n_ticks, dtype=np.int64)
    n_placed = np.zeros(n_ticks, dtype=np.int64)
    dem_host = np.asarray(demands)
    cum = np.zeros(np.asarray(avail).shape[0], dtype=np.int32)
    stack: list = []
    for k in range(n_ticks):
        batch = list(reversed(stack)) + [
            int(b) for b in np.flatnonzero(arrive == k)
        ]
        if not batch:
            continue
        n_ready[k] = len(batch)
        if policy == "cost-aware":
            first_seen: dict = {}
            for pos, b in enumerate(batch):
                first_seen.setdefault(int(bucket_id[b]), pos)
            if sort_tasks:
                order = sorted(
                    range(len(batch)),
                    key=lambda pos: (
                        first_seen[int(bucket_id[batch[pos]])],
                        -float(sort_norm[batch[pos]]),
                        pos,
                    ),
                )
            else:
                order = sorted(
                    range(len(batch)),
                    key=lambda pos: (
                        first_seen[int(bucket_id[batch[pos]])],
                        pos,
                    ),
                )
            order = [batch[pos] for pos in order]
        elif decreasing:
            order = sorted(
                batch, key=lambda b: -float(sort_norm[b])
            )  # python sort is stable: ties keep batch order
        else:
            order = batch
        dem_p = np.zeros_like(dem_host)
        dem_p[: len(order)] = dem_host[order]
        valid_p = np.zeros(B, dtype=bool)
        valid_p[: len(order)] = True
        kw = dict(phase2=phase2, live=live)
        if risk_rows is not None:
            kw["risk"] = jnp.asarray(np.asarray(risk_rows)[k])
        cost_k = cost_zz
        if cost_stack is not None:
            cost_k = jnp.asarray(cost_stack)[int(np.asarray(cost_seg)[k])]
        if policy == "opportunistic":
            p_ord, avail = opportunistic_kernel(
                avail, jnp.asarray(dem_p), jnp.asarray(valid_p),
                uniforms[k], **kw,
            )
        elif policy == "first-fit":
            p_ord, avail = first_fit_kernel(
                avail, jnp.asarray(dem_p), jnp.asarray(valid_p),
                strict=strict, totals=totals, **kw,
            )
        elif policy == "best-fit":
            p_ord, avail = best_fit_kernel(
                avail, jnp.asarray(dem_p), jnp.asarray(valid_p),
                totals=totals, **kw,
            )
        else:
            az_p = np.zeros(B, dtype=np.int32)
            az_p[: len(order)] = np.asarray(anchor_zone)[order]
            ng_p = np.zeros(B, dtype=bool)
            prev = None
            for j, b in enumerate(order):
                ng_p[j] = prev is None or int(bucket_id[b]) != prev
                prev = int(bucket_id[b])
            p_ord, avail = cost_aware_kernel(
                avail,
                jnp.asarray(dem_p),
                jnp.asarray(valid_p),
                jnp.asarray(ng_p),
                jnp.asarray(az_p),
                cost_k,
                bw_zz,
                host_zone,
                base_task_counts + jnp.asarray(cum),
                bin_pack=bin_pack,
                sort_hosts=sort_hosts,
                host_decay=host_decay,
                totals=totals,
                score_exp=score_exp,
                **kw,
            )
        p_host = np.asarray(p_ord)
        for j, b in enumerate(order):
            placements[k, b] = p_host[j]
        visit = order if decreasing else batch
        stack = [b for b in visit if placements[k, b] < 0]
        placed_hosts = [
            int(placements[k, b]) for b in order if placements[k, b] >= 0
        ]
        np.add.at(cum, placed_hosts, 1)
        n_placed[k] = len(placed_hosts)
    return placements, n_ready, n_placed, np.asarray(avail)


# ---------------------------------------------------------------------------
# Ragged span repack — the continuous-batching contract
# ---------------------------------------------------------------------------
# Mixed-horizon spans (different K tick buckets and/or B slot buckets)
# can ride ONE coalesced device program because the padded tails are
# provably inert:
#
#   * K tail (ticks in [n_ticks_dyn, K′)): the while-loop condition is
#     ``(k < n_ticks_dyn) & ~done`` and the batched while rule
#     select-masks each row's carry, so a finished row's state (its
#     ``k`` included) freezes at its own exit value — ``ticks_run``
#     stays per-row exact and rows ≥ ``ticks_run`` of ``placements``/
#     ``n_ready``/``n_placed`` keep their −1/0 init.  The per-tick
#     gathers (``uniforms[k]``, ``risk_rows[k]``, ``cost_seg[k]``)
#     never index past the row's own live range, so zero-padding those
#     tails cannot reach any live tick.
#   * B tail (slots in [B, B′)): a pad slot arrives at K′ ≥ n_ticks_dyn,
#     so it never joins a ready batch (``_span_ready_batch``), sorts
#     after every active slot (the ``inactive`` sort key), contributes
#     the ``big`` sentinel to the cost-aware ``segment_min``, and the
#     kernels return −1 for its invalid position — no live slot's
#     stream position, score, or placement moves.
#
# The batcher (``sched/batch.py``) uses these three helpers to merge
# co-pending ``fused_tick_run`` requests whose shapes differ only in
# (K, B) into one (K′, B′) = (max K, max B) bucket, then slices each
# result back — bit-identical to the request's own solo dispatch, the
# ragged-parity suite's contract (``tests/test_ragged.py``).  They are
# HOST-side staging utilities (numpy in, numpy out, never jitted), so
# they deliberately do NOT match the ``_span_*`` hostsync-discovery
# patterns that lint device bodies.

#: Array-kwarg name → (K axis, B axis) — which axes of each span operand
#: the ragged repack must pad (None = operand lacks that axis).  The
#: parity pass (``analysis/parity.py``) asserts this table plus
#: :data:`RAGGED_INVARIANT` covers every array knob of the span family.
RAGGED_AXES = {
    "uniforms": (0, 1),
    "risk_rows": (0, None),
    "cost_seg": (0, None),
    "sort_norm": (None, 0),
    "anchor_zone": (None, 0),
    "bucket_id": (None, 0),
}

#: Span operands with no K or B axis: stacked per-row by the batcher
#: like everything else, untouched by the repack.
RAGGED_INVARIANT = frozenset({
    "cost_zz", "bw_zz", "host_zone", "base_task_counts", "totals",
    "live", "cost_stack", "score_exp",
})


def ragged_span_signature(args, arr_kw, static_kw):
    """Coalescing key for mixed-horizon span requests: the request key
    with the span-length bucket K (the ``n_ticks`` static) and the
    slot-bucket width B normalized OUT, so requests that differ only in
    their (K, B) pads may merge into one device program.  Returns a
    hashable tuple, or None when the operands do not match the span
    family's layout (defensive — the batcher then leaves the request on
    the exact-key path)."""
    if len(args) != 4:
        return None
    avail, demands, arrive, _n_dyn = args
    if (
        getattr(avail, "ndim", None) != 2
        or getattr(demands, "ndim", None) != 2
        or getattr(arrive, "ndim", None) != 1
        or "n_ticks" not in static_kw
    ):
        return None
    for name in arr_kw:
        if name not in RAGGED_AXES and name not in RAGGED_INVARIANT:
            return None
    statics = tuple(sorted(
        (k, v) for k, v in static_kw.items() if k != "n_ticks"
    ))
    names = tuple(sorted(arr_kw))
    dtypes = tuple(str(arr_kw[n].dtype) for n in names)
    invariant_shapes = tuple(
        tuple(arr_kw[n].shape) for n in names if n in RAGGED_INVARIANT
    )
    return (
        tuple(avail.shape), str(avail.dtype), str(demands.dtype),
        str(arrive.dtype), names, dtypes, invariant_shapes, statics,
    )


def _ragged_pad_to(arr, shape):
    out = np.zeros(shape, arr.dtype)
    out[tuple(slice(0, s) for s in arr.shape)] = arr
    return out


def ragged_span_pad(args, arr_kw, k2: int, b2: int):
    """Pad one staged span request from its own (K, B) buckets up to the
    merged (K′, B′) = ``(k2, b2)`` — new pad slots arrive at ``k2`` (so
    they can never join a batch) and every K/B tail is zero-filled (the
    inert-tail contract above).  Returns ``(args, arr_kw)`` rebuilt;
    operands already at the target shape pass through untouched."""
    avail, demands, arrive, n_ticks_dyn = args
    b = demands.shape[0]
    if b != b2:
        demands = _ragged_pad_to(demands, (b2,) + demands.shape[1:])
        arr2 = np.full((b2,), k2, arrive.dtype)
        arr2[:b] = arrive
        arrive = arr2
    out_kw = {}
    for name, v in arr_kw.items():
        k_ax, b_ax = RAGGED_AXES.get(name, (None, None))
        shape = list(v.shape)
        if k_ax is not None:
            shape[k_ax] = k2
        if b_ax is not None:
            shape[b_ax] = b2
        shape = tuple(shape)
        out_kw[name] = v if shape == v.shape else _ragged_pad_to(v, shape)
    return (avail, demands, arrive, n_ticks_dyn), out_kw


def ragged_span_trim(res: SpanResult, k: int, b: int) -> SpanResult:
    """Slice a merged-bucket :class:`SpanResult` back to the request's
    own (K, B) buckets — the demux half of the ragged contract.  The
    scalar fields (``ticks_run``, ``n_stack_final``) and the [H, 4]
    carry are per-row exact already (inert-tail contract)."""
    return SpanResult(
        placements=res.placements[:k, :b],
        n_ready=res.n_ready[:k],
        n_placed=res.n_placed[:k],
        ticks_run=res.ticks_run,
        n_stack_final=res.n_stack_final,
        stackpos=res.stackpos[:b],
        avail=res.avail,
    )
