"""Fused fit / score / argmin placement kernels — two-phase form.

This is the TPU decision backend demanded by the north star (BASELINE.md):
each scheduling tick evaluates all ready-task × host placements in a single
device call.  The greedy *sequential* semantics of the reference policies
(each placement decrements availability seen by the next task —
``scheduler/vbp.py``, ``scheduler/cost_aware.py:99-127``) are preserved
exactly; how much of each step actually RUNS sequentially is this module's
subject.

Round-6 restructure ("break the task-axis serial chain"): the historical
kernels were a ``lax.scan`` over the task axis that recomputed the full
O(H) fit/score row — topology gathers, demand broadcasts, group-score
norms, masked argmin — *inside* every sequential step, even though only
the ``[H, 4]`` availability carry has a cross-task dependency.  Every
kernel now comes in a **two-phase form**:

  * **phase 1** hoists everything that does not depend on the availability
    carry out of the sequential pass: the ``[Z, H]`` round-trip topology
    tables, the host-decay prescale of the cost table (``cost_rt * decay``
    multiplies the same two operands as the in-step form, so the product
    is bit-identical per element), the realtime-bandwidth row indexing,
    and the demand-vs-total static pre-filter;
  * **phase 2** is the residual sequential pass, selected by the static
    ``phase2`` argument:

      - ``"scan"`` — the reference-shaped ``lax.scan`` (one full fit +
        score + argmin row per step).  This is also what the retained
        ``*_kernel_ref`` oracles run.
      - ``"slim"`` — a ``lax.while_loop`` that (a) stops at the last
        valid task instead of scanning the whole padded bucket (a
        T=600 tick in the 2048 bucket stops paying 2048 steps), and
        (b) computes the cost-aware group score only at group-entry
        steps via ``lax.cond`` — in the common unbatched dispatch the
        O(H) sqrt-heavy score row, profiled as the dominant per-step
        cost, runs ~#groups times instead of T times.  (Under ``vmap``
        XLA lowers the cond to a select and the skip degrades to the
        scan form's cost — batched callers on TPU should prefer
        ``"scan"``/Pallas, see below.)
      - ``int C`` — **speculative chunk commit**: place a chunk of C
        tasks in parallel against chunk-entry availability using a
        capacity-aware *fill model* (how many copies of a demand each
        host holds, filled in frozen-score order), replay the exact
        ``[H, 4]`` carry fold over the speculated placements (the only
        irreducibly sequential work, ~4 scalar writes per step on CPU),
        then re-decide every chunk task against its exact prefix
        availability in one vectorized pass and commit through the
        first disagreement.  Placements and the availability output are
        **bit-identical to the scan by construction**: a committed task's
        decision is always the vectorized re-decision under the exact
        fold — speculation quality only moves the commit boundary, never
        the result.  See ``_speculate_commit`` for the induction.
      - ``"auto"`` (default) — ``"slim"`` on the CPU backend, ``"scan"``
        elsewhere.  Measured on the CPU backend at the acceptance shape
        (T=600 real tasks in the 2048 bucket, H=1024, f64): slim ≈ 3.4×
        the scan oracle single-dispatch.  The chunked form commits whole
        chunks at realistic contention (fill speculation: ~10 outer
        iterations for 600 tasks at C=64) but XLA-CPU per-op dispatch
        overhead in the outer loop body (~0.5–3 ms/iteration measured)
        exceeds the serial chain it replaces, so it is opt-in — it is
        the shape intended for backends where the per-step latency
        floor, not per-op throughput, dominates (the VERDICT round-5
        "per-tick device compute" gap; see docs/ARCHITECTURE.md).

The ``totals`` argument (full host capacity, ``DeviceTopology.totals``)
feeds the phase-1 demand-vs-total pre-filter.  It steers only the
*speculation* (a host whose total capacity cannot hold a demand gets fill
capacity 0), never the exact re-decision — so a stale or wrong ``totals``
can cost commit width but can never change a placement.

Design notes (TPU-first), unchanged from the scan era:
  * **No data-dependent shapes**: the task axis is padded to a bucket size
    by the caller (``pivot_tpu.sched.tpu``) with ``valid=False`` rows; the
    kernel is compiled once per (bucket, H) pair.
  * **No on-device RNG**: the opportunistic policy's random choice consumes
    a Philox uniform stream generated host-side (``sched/rand.py``), so CPU
    and TPU backends make bit-identical choices.
  * **First-fit over a sorted host list ≡ masked argmin**: for a host order
    sorted by a per-group score (stable), the first fitting host is exactly
    the fitting host minimizing ``(score, host_index)`` — the kernels never
    materialize a sort; the group's score vector freezes at group entry.
  * ``argmin``/``argmax`` tie-breaking to the lowest index is the shared
    tie rule across the numpy policies and these kernels.

Dtype: float32 on TPU.  Exact cross-backend placement parity is validated
on CPU with x64 enabled; on TPU, f32 rounding can flip near-boundary fits
— accepted, since the acceptance criterion is identical makespan/cost
*rankings* (BASELINE.md).  The two-phase forms are additionally held
bit-identical to the ``*_kernel_ref`` scan oracles — placements AND the
availability output — by ``tests/test_two_phase.py`` across every policy,
phase-2 mode, and chunk size.
"""

from __future__ import annotations

import functools
from typing import NamedTuple


import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "DeviceTopology",
    "opportunistic_kernel",
    "first_fit_kernel",
    "best_fit_kernel",
    "cost_aware_kernel",
    "opportunistic_kernel_ref",
    "first_fit_kernel_ref",
    "best_fit_kernel_ref",
    "cost_aware_kernel_ref",
    "opportunistic_impl",
    "first_fit_impl",
    "best_fit_impl",
    "cost_aware_impl",
]


class DeviceTopology(NamedTuple):
    """Device-resident cluster topology, pushed once per experiment.

    The reference re-derives per-pair route bandwidth from Python dicts on
    every score evaluation (``scheduler/cost_aware.py:73-79``); here the
    ``[Z, Z]`` matrices live on the accelerator and are gathered by zone
    index inside the kernel.
    """

    cost: jax.Array  # [Z, Z] egress $ / GB
    bw: jax.Array  # [Z, Z] Mbps
    host_zone: jax.Array  # [H] i32
    totals: jax.Array  # [H, 4]

    @classmethod
    def from_cluster(cls, cluster, dtype=jnp.float32) -> "DeviceTopology":
        meta = cluster.meta
        return cls(
            cost=jnp.asarray(meta.cost_matrix, dtype=dtype),
            bw=jnp.asarray(meta.bw_matrix, dtype=dtype),
            host_zone=jnp.asarray(cluster.host_zone_vector(), dtype=jnp.int32),
            totals=jnp.asarray(cluster.totals_matrix(), dtype=dtype),
        )

    @property
    def n_hosts(self) -> int:
        return self.host_zone.shape[0]


def _fits(avail: jax.Array, demand: jax.Array, strict: bool) -> jax.Array:
    """[H] fit mask: every dimension satisfies avail (>|>=) demand."""
    if strict:
        return jnp.all(avail > demand, axis=1)
    return jnp.all(avail >= demand, axis=1)


def _apply_live(avail, live):
    """Fuse an optional [H] quarantine mask (``live``; False = host
    excluded from placement — circuit-breaker quarantine or preemption
    drain, ``sched/retry.py``) into every downstream fit test by giving
    masked rows the −1 sentinel the availability snapshot already uses
    for DOWN hosts: demands are ≥ 0, so neither the strict nor the
    non-strict comparison can ever select a −1 row (zero-demand tasks
    included), and the chunked fill model prices masked hosts at zero
    capacity.  Returns ``(masked avail, restore)`` where ``restore``
    rewrites the untouched original rows into the availability output —
    a masked host's capacity is unchanged by a tick that cannot place on
    it, and the restore is what keeps every phase-2 mode's availability
    output bit-identical to the scan oracle's under any mask.

    ``live=None`` (the default everywhere) is the identity: the traced
    program is unchanged, so all-live callers keep today's compiled
    kernels and today's outputs bit for bit.
    """
    if live is None:
        return avail, lambda out: out
    orig = avail
    masked = jnp.where(live[:, None], avail, jnp.asarray(-1.0, avail.dtype))
    return masked, lambda out: jnp.where(live[:, None], out, orig)


def _norms(mat: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.sum(mat * mat, axis=-1))


# -- the risk term (round 11, ``infra/market.py``) ---------------------------
#
# ``risk`` is the optional [H] eviction-risk penalty vector
# (``risk_weight × hazard × rework_cost``, resolved host-side by
# ``sched.policies.resolve_risk``).  It is fused into phase-1 scoring by
# the SHARED cross-backend rule the CPU policies implement:
#
#   * score-based selections (best-fit residual, cost-aware scores) add
#     it: ``score += risk``;
#   * index-ordered selections (plain first-fit; cost-aware first-fit
#     with ``sort_hosts=False``) replace the index order with the
#     lexicographic ``(risk, host index)`` order — the masked argmin over
#     a score of ``risk`` gives exactly this (ties → lowest index);
#   * the opportunistic random choice restricts to the minimum-risk tier
#     of fitting hosts (same Philox draw, narrower support).
#
# ``risk=None`` (the default everywhere) is the identity: no risk op is
# traced, so all existing callers keep today's compiled programs — and
# today's outputs — bit for bit.  The helpers below are the single
# definition of each rule, shared by scan / slim / chunk forms and (via
# import) the host-sharded kernels, so no two backends can drift.


def _risk_restrict(fit, risk):
    """Opportunistic rule: narrow ``fit`` ([H] or [C, H]) to its
    minimum-risk tier (no-op when nothing fits: the masked min is +inf,
    which no finite risk equals)."""
    if risk is None:
        return fit
    rmin = jnp.min(_risk_key(fit, risk), axis=-1, keepdims=True)
    return fit & (risk == rmin)


def _risk_score(score, risk):
    """Score rule: ``score += risk`` (broadcasts over a [C, H] block)."""
    if risk is None:
        return score
    return score + risk


def _risk_key(fit, risk):
    """Index-order rule: the masked-argmin key for lexicographic
    (risk, index) selection — +inf where nothing fits, so any argmin's
    lowest-index tie-break yields exactly (risk, index) order over the
    fitting set.  Shared by the flat scans, slim/chunk phase 2, and the
    sharded two-stage reduces."""
    return jnp.where(fit, risk, jnp.asarray(jnp.inf, risk.dtype))


def _place(avail, demand, h, ok):
    """Decrement row ``h`` by ``demand`` when ``ok`` (no-op otherwise).

    Two lowerings, chosen by backend at trace time (jit caches per
    backend), both exact — x − d·1 ≡ x + (−d), x − d·0 ≡ x — and
    placement-bit-equal to each other:

      * accelerator: one-hot arithmetic, not ``avail.at[h].add`` —
        under ``vmap`` (the Monte-Carlo replica axis) the indexed form
        lowers to a batched scatter whose per-replica index vector
        lands in TPU scalar memory and serializes on the scalar core
        (see ARCHITECTURE.md, "the scalar-core lesson");
      * cpu: the indexed scatter — the one-hot form writes O(H·4)
        values per scan step where the scatter writes 4.  Measured at
        the bench shape (T=2048, H=512, R=1024): the round-2 one-hot
        rewrite cost the CPU path 391.8k → 336.4k decisions/s (−14%);
        this split restores it (VERDICT r03 item 6).
    """
    if jax.default_backend() == "cpu":
        delta = jnp.where(ok, demand, jnp.zeros_like(demand))
        return avail.at[h].add(-delta)
    hit = (jnp.arange(avail.shape[0]) == h)[:, None] & ok
    return avail - jnp.where(hit, demand[None, :], jnp.zeros((), avail.dtype))


def _bump_count(counts, h, ok):
    """Increment ``counts[h]`` by 1 when ``ok`` — the best-fit live-decay
    counter update, backend-split exactly like :func:`_place`."""
    if jax.default_backend() == "cpu":
        return counts.at[h].add(jnp.where(ok, 1, 0))
    return counts + (
        (jnp.arange(counts.shape[0]) == h) & ok
    ).astype(counts.dtype)


# ---------------------------------------------------------------------------
# Reference scan kernels — the in-tree parity oracles.
#
# These are the pre-round-6 kernels verbatim (one full fit/score/argmin row
# per lax.scan step).  The two-phase kernels below are held bit-identical
# to them on every backend/mode by tests/test_two_phase.py; ``phase2=
# "scan"`` on the public kernels runs these same bodies.
# ---------------------------------------------------------------------------


def _opportunistic_scan(avail, demands, valid, uniforms, risk=None):
    def body(avail, x):
        demand, valid_i, u = x
        fit = _fits(avail, demand, strict=False) & valid_i
        fit = _risk_restrict(fit, risk)
        n_fit = jnp.sum(fit)
        k = jnp.minimum((u * n_fit).astype(jnp.int32), n_fit - 1)
        rank = jnp.cumsum(fit)  # 1-based rank among fitting hosts
        h = jnp.argmax(fit & (rank == k + 1))
        ok = n_fit > 0
        return _place(avail, demand, h, ok), jnp.where(ok, h, -1).astype(jnp.int32)

    return _scan_swap(body, avail, (demands, valid, uniforms))


@jax.jit
def opportunistic_kernel_ref(avail, demands, valid, uniforms, live=None,
                             risk=None):
    """Uniformly random fitting host per task (ref opportunistic.py:11-20).

    The k-th fitting host (k = ⌊u·n_fit⌋) is selected via a cumulative-sum
    rank match — no host list materialization.  ``live`` is the optional
    [H] quarantine mask (:func:`_apply_live`); ``risk`` the optional [H]
    eviction-risk vector (minimum-risk-tier rule, module comment above).
    Returns ([T] int32 placements, [H,4] new availability).
    """
    avail, restore = _apply_live(avail, live)
    p, a = _opportunistic_scan(avail, demands, valid, uniforms, risk)
    return p, restore(a)


def _first_fit_scan(avail, demands, valid, strict, risk=None):
    def body(avail, x):
        demand, valid_i = x
        fit = _fits(avail, demand, strict) & valid_i
        if risk is None:
            h = jnp.argmax(fit)
        else:
            # Risk-aware first fit: lexicographic (risk, index) order.
            h = jnp.argmin(_risk_key(fit, risk))
        ok = jnp.any(fit)
        return _place(avail, demand, h, ok), jnp.where(ok, h, -1).astype(jnp.int32)

    return _scan_swap(body, avail, (demands, valid))


@functools.partial(jax.jit, static_argnames=("strict",))
def first_fit_kernel_ref(avail, demands, valid, strict=False, live=None,
                         risk=None):
    """Lowest-index fitting host per task (ref vbp.py:6-29)."""
    avail, restore = _apply_live(avail, live)
    p, a = _first_fit_scan(avail, demands, valid, strict, risk)
    return p, restore(a)


def _best_fit_scan(avail, demands, valid, risk=None):
    big = jnp.asarray(jnp.inf, avail.dtype)

    def body(avail, x):
        demand, valid_i = x
        fit = _fits(avail, demand, strict=True) & valid_i
        residual = _risk_score(_norms(avail - demand), risk)
        h = jnp.argmin(jnp.where(fit, residual, big))
        ok = jnp.any(fit)
        return _place(avail, demand, h, ok), jnp.where(ok, h, -1).astype(jnp.int32)

    return _scan_swap(body, avail, (demands, valid))


@jax.jit
def best_fit_kernel_ref(avail, demands, valid, live=None, risk=None):
    """Min residual-L2 host among strict fits (ref vbp.py:32-49)."""
    avail, restore = _apply_live(avail, live)
    p, a = _best_fit_scan(avail, demands, valid, risk)
    return p, restore(a)


def _cost_aware_scan(
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    bin_pack,
    sort_hosts,
    host_decay,
    rt_bw_rows,
    rt_bw_idx,
    risk=None,
    score_exp=None,
):
    if score_exp is not None and rt_bw_rows is not None:
        raise ValueError(
            "learned score exponents pow the static phase-1 bandwidth "
            "table; realtime_bw rows bypass that table — the combination "
            "is rejected at the policy layer (sched/tpu.py)"
        )
    H = avail.shape[0]
    big = jnp.asarray(jnp.inf, avail.dtype)
    first_fit = bin_pack == "first-fit"
    base_counts = base_task_counts.astype(avail.dtype)
    w_norm = None if score_exp is None else score_exp[2]
    # [Z, H] round-trip tables: anchor-zone z ↔ each host.
    cost_rt, bw_rt, _ = _ca_phase1(
        cost_zz, bw_zz, host_zone, base_counts, prescale_decay=False,
        score_exp=score_exp,
    )

    def group_score(avail, cost_row, bw_row):
        if not sort_hosts:
            if risk is not None:
                # Index-ordered selection → lexicographic (risk, index).
                return risk
            return jnp.arange(H, dtype=avail.dtype)  # identity host order
        decay = jnp.maximum(base_counts, 1.0) if host_decay else 1.0
        norms = _norms(avail)
        if w_norm is not None:
            norms = norms ** w_norm
        return _risk_score(cost_row * decay / (norms * bw_row), risk)

    def body(carry, x):
        avail, frozen_score, extra = carry
        if rt_bw_rows is None:
            demand, valid_i, new_g, az = x
            bw_row = bw_rt[az]
        else:
            demand, valid_i, new_g, az, row_idx = x
            bw_row = rt_bw_rows[row_idx]
        cost_row = cost_rt[az]
        if first_fit:
            score = jnp.where(
                new_g, group_score(avail, cost_row, bw_row), frozen_score
            )
            fit = _fits(avail, demand, strict=True) & valid_i
            h = jnp.argmin(jnp.where(fit, score, big))
        else:
            score = frozen_score  # unused carry for best-fit
            residual = _norms(avail - demand)
            if w_norm is not None:
                residual = residual ** w_norm
            decay = (
                jnp.maximum(base_counts + extra.astype(avail.dtype), 1.0)
                if host_decay
                else 1.0
            )
            per_task = _risk_score(cost_row * residual * decay / bw_row, risk)
            fit = _fits(avail, demand, strict=False) & valid_i
            h = jnp.argmin(jnp.where(fit, per_task, big))
        ok = jnp.any(fit)
        avail = _place(avail, demand, h, ok)
        if not first_fit:
            # Only best-fit's live decay reads the within-tick counter
            # (first-fit decay is frozen at tick start, ref :115).
            extra = _bump_count(extra, h, ok)
        return (avail, score, extra), jnp.where(ok, h, -1).astype(jnp.int32)

    init = (
        avail,
        jnp.zeros(H, dtype=avail.dtype),
        jnp.zeros(H, dtype=jnp.int32),
    )
    xs = (demands, valid, new_group, anchor_zone)
    if rt_bw_rows is not None:
        xs = xs + (rt_bw_idx,)
    (avail, _, _), placements = lax.scan(body, init, xs)
    return placements, avail


@functools.partial(
    jax.jit,
    static_argnames=("bin_pack", "sort_hosts", "host_decay"),
)
def cost_aware_kernel_ref(
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    rt_bw_rows=None,
    rt_bw_idx=None,
    live=None,
    risk=None,
    score_exp=None,
):
    """The PIVOT cost-aware placement (ref cost_aware.py:28-127), fused —
    the reference-shaped scan, retained as the parity oracle.

    Inputs (task axis T padded, host axis H, zone axis Z):
      demands          [T, 4]  — tasks pre-ordered by the caller: groups in
                                 first-seen order, optionally sorted
                                 descending by demand norm within a group
      valid            [T]     — padding mask
      new_group        [T]     — True where task i starts a new anchor group
      anchor_zone      [T] i32 — zone index of each task's anchor storage
      cost_zz, bw_zz   [Z, Z]  — device-resident egress-cost / bandwidth
                                 matrices (from :class:`DeviceTopology`)
      host_zone        [H] i32
      base_task_counts [H]     — tasks resident per host at tick start

    ``rt_bw_rows`` ([G, H]) + ``rt_bw_idx`` ([T] i32, row per task)
    together override the static bandwidth table with caller-supplied
    round-trip bandwidths — the ``realtime_bw`` scoring mode
    (``infra.network.Route.realtime_bw``, ref ``resources/network.py:
    70-73``), sampled host-side at the tick instant.

    First-fit: the group's host score ``cost·decay / (‖avail‖·bw)`` is
    frozen when the scan enters the group (matching the reference's
    sort-at-group-start); placement is a masked argmin with strict fits.
    Best-fit: per-task score ``cost·‖avail−d‖·decay / bw`` over non-strict
    fits, with a live placement counter in the decay.  ``live`` is the
    optional [H] quarantine mask (:func:`_apply_live`).
    """
    avail, restore = _apply_live(avail, live)
    p, a = _cost_aware_scan(
        avail, demands, valid, new_group, anchor_zone, cost_zz, bw_zz,
        host_zone, base_task_counts, bin_pack, sort_hosts, host_decay,
        rt_bw_rows, rt_bw_idx, risk, score_exp,
    )
    return p, restore(a)


def _scan_swap(body, avail, xs):
    new_avail, placements = lax.scan(body, avail, xs)
    return placements, new_avail


# ---------------------------------------------------------------------------
# Two-phase machinery
# ---------------------------------------------------------------------------


def _ca_phase1(cost_zz, bw_zz, host_zone, base_counts, prescale_decay,
               score_exp=None):
    """Cost-aware phase-1 tables for a host block: the ``[Z, H]``
    round-trip topology tables and the (optional) exact host-decay
    prescale of the cost table.  ``host_zone``/``base_counts`` may be the
    full ``[H]`` vectors or one shard's contiguous block — every output
    element depends only on its own host column, so the sharded kernels
    (``ops/shard.py``) call this on their local block and get the exact
    same elements the single-device kernels compute, bit for bit.

    ``score_exp`` is the optional traced [3] exponent vector
    ``(w_cost, w_bw, w_norm)`` of :class:`~pivot_tpu.search.weights.
    PolicyWeights` — the cost/bw tables are powed HERE, once per
    dispatch, so the per-step score sites stay pow-free; ``w_norm``
    applies at the score sites (the norm is availability-dependent).
    ``None`` keeps the traced program unchanged bit for bit (the
    reference (1, 1, 1) shape never pays a ``pow``)."""
    cost_rt = cost_zz[:, host_zone] + cost_zz[host_zone, :].T
    bw_rt = bw_zz[:, host_zone] + bw_zz[host_zone, :].T
    if score_exp is not None:
        cost_rt = cost_rt ** score_exp[0]
        bw_rt = bw_rt ** score_exp[1]
    if prescale_decay:
        num_rt = cost_rt * jnp.maximum(base_counts, 1.0)[None, :]
    else:
        num_rt = cost_rt
    return cost_rt, bw_rt, num_rt


def _ca_group_score(num_row, avail, bw_row, w_norm=None):
    """The cost-aware first-fit group score row ``num / (‖avail‖^wₙ·bw)``
    over a host block — shared verbatim by the slim phase-2 body and the
    sharded kernels so the two can never round differently.  ``w_norm``
    None = the reference shape (no ``pow`` traced)."""
    norms = _norms(avail)
    if w_norm is not None:
        norms = norms ** w_norm
    return num_row / (norms * bw_row)


def _ca_best_fit_score(cost_row, avail, demand, decay, bw_row,
                       w_norm=None):
    """The cost-aware best-fit per-task score
    ``cost·‖avail−d‖^wₙ·decay/bw`` over a host block — shared like
    :func:`_ca_group_score`."""
    residual = _norms(avail - demand)
    if w_norm is not None:
        residual = residual ** w_norm
    return cost_row * residual * decay / bw_row


def _resolve_phase2(phase2):
    """``"auto"`` → slim sequential pass on CPU (measured 3.4× the scan at
    the acceptance shape), reference scan elsewhere (batched TPU callers
    keep the scan's gather-free step structure — the scalar-core lesson)."""
    if phase2 == "auto":
        return "slim" if jax.default_backend() == "cpu" else "scan"
    if phase2 in ("scan", "slim"):
        return phase2
    if isinstance(phase2, int) and phase2 >= 1:
        return phase2
    raise ValueError(
        f"phase2 must be 'auto', 'scan', 'slim', or a chunk size >= 1; "
        f"got {phase2!r}"
    )


def _effective_len(valid):
    """Index one past the last valid task — the slim/chunked passes stop
    here instead of walking the full padded bucket (the scan cannot)."""
    B = valid.shape[0]
    idx = jnp.where(valid, jnp.arange(B, dtype=jnp.int32), -1)
    return (jnp.max(idx, initial=-1) + 1).astype(jnp.int32)


def _static_viable(totals, demand, strict):
    """Phase-1 demand-vs-total pre-filter row [H]: hosts whose FULL
    capacity cannot hold ``demand`` can never fit it at any availability.
    Speculation-only — feeds fill capacities, never the exact re-decision,
    so it cannot affect placements (only commit width)."""
    if totals is None:
        return None
    if strict:
        return jnp.all(totals > demand[None, :], axis=1)
    return jnp.all(totals >= demand[None, :], axis=1)


def _fill_capacity(avail, demand, strict, viable):
    """[H] fill model: how many back-to-back copies of ``demand`` each
    host's current availability holds.  Division-based, so it can be off
    by one against the exact sequential fold at ulp boundaries —
    speculation only, the re-decision pass referees."""
    q = jnp.min(
        jnp.where(demand[None, :] > 0, avail / demand[None, :], jnp.inf),
        axis=1,
    )
    q = jnp.where(jnp.isfinite(q), q, jnp.asarray(2.0**31, q.dtype))
    n = jnp.ceil(q) - 1 if strict else jnp.floor(q)
    n = jnp.clip(n, 0, 1 << 30).astype(jnp.int32)
    if viable is not None:
        n = jnp.where(viable, n, 0)
    return n


def _fill_pick(score_row, caps, ranks):
    """Predict placements for ``ranks`` [C] of identical-demand tasks
    filling hosts in ``score_row`` order (stable — ties to the lowest
    host index, like the masked argmin).  Returns (h [C], ok [C]);
    negative ranks are inert."""
    H = score_row.shape[0]
    iota = jnp.arange(H, dtype=jnp.int32)
    _, caps_s, hid_s = lax.sort(
        (score_row, caps, iota), num_keys=1, is_stable=True
    )
    cum = jnp.cumsum(caps_s)
    j = jnp.sum(cum[None, :] <= ranks[:, None], axis=1).astype(jnp.int32)
    ok = (j < H) & (ranks >= 0)
    h = jnp.where(ok, hid_s[jnp.minimum(j, H - 1)], 0)
    return h, ok


def _fill_pick_by_index(caps, ranks):
    """:func:`_fill_pick` for score == host index (plain first-fit): the
    sorted order is the index order, so the sort is skipped."""
    H = caps.shape[0]
    cum = jnp.cumsum(caps)
    j = jnp.sum(cum[None, :] <= ranks[:, None], axis=1).astype(jnp.int32)
    ok = (j < H) & (ranks >= 0)
    h = jnp.where(ok, jnp.minimum(j, H - 1), 0)
    return h, ok


def _speculate_commit(avail, extra, track_extra, dem_c, h_s, ok_s, recheck):
    """The exact core of speculative chunk commit.

    Given speculated placements ``(h_s, ok_s)`` for a chunk, replays the
    exact ``[H, 4]`` carry fold over them (``_place`` per step — the same
    op sequence as the scan oracle, so every prefix availability is
    bit-identical to the sequential pass), then calls ``recheck(a_pre,
    ex_pre)`` to re-decide every chunk task against its exact prefix
    state in one vectorized pass.

    Commit induction: let fc be the first position where the re-decision
    differs from the speculation.  For k < fc the speculated decrements
    ARE the true ones, so ``a_pre[k]`` is the true sequential
    availability for every k ≤ fc — which makes the re-decisions for all
    k ≤ fc the true sequential decisions (including fc itself).  The
    caller may therefore commit any prefix of length ≤ fc + 1; positions
    beyond the commit are rewritten by later iterations.

    Returns ``(p_c, h_c, ok_c, fc, a_pre, ex_pre, commit_avail_fn)``
    where ``commit_avail_fn(n_commit)`` produces the exact availability
    (and extra counter) after committing ``n_commit`` tasks.
    """
    def substep(carry, x):
        a, ex = carry
        h, ok, d = x
        a2 = _place(a, d, h, ok)
        ex2 = _bump_count(ex, h, ok) if track_extra else ex
        return (a2, ex2), (a, ex)

    (_, _), (a_pre, ex_pre) = lax.scan(
        substep, (avail, extra), (h_s, ok_s, dem_c)
    )
    h_c, ok_c = recheck(a_pre, ex_pre)
    p_c = jnp.where(ok_c, h_c, -1).astype(jnp.int32)
    p_s = jnp.where(ok_s, h_s, -1).astype(jnp.int32)
    C = dem_c.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    fc = jnp.min(jnp.where(p_c != p_s, idx, C))

    def commit_state(n_commit):
        # Positions < n_commit − 1 are spec == check, so a_pre[cm] is the
        # exact fold; one more exact _place with cm's true decision
        # finishes it (cm = last committed position; n_commit >= 1).
        cm = jnp.minimum(n_commit - 1, C - 1)
        new_avail = _place(a_pre[cm], dem_c[cm], h_c[cm], ok_c[cm])
        new_extra = (
            _bump_count(ex_pre[cm], h_c[cm], ok_c[cm]) if track_extra
            else extra
        )
        return new_avail, new_extra

    return p_c, h_c, ok_c, fc, a_pre, ex_pre, commit_state


def _pad_chunk(x, C):
    """Pad the task axis by C so ``dynamic_slice`` windows at any position
    < B stay in bounds; the pad rows are ``valid=False`` no-ops."""
    return jnp.pad(x, ((0, C),) + ((0, 0),) * (x.ndim - 1))


def _slim_drive(avail, demands, n_eff, decide_row):
    """Shared slim phase-2 driver for the carry-free kernels.

    ``decide_row(avail, j, demand) -> (h, ok)`` is the per-task decision
    (the same ops as the scan oracle's step).  The driver owns the
    protocol the batcher contract depends on: early exit at ``n_eff``,
    and under ``vmap`` rows past their own ``n_eff`` go inert — ``ok``
    is forced False (no decrement) and the placement write targets an
    out-of-range index that drops.
    """
    B = demands.shape[0]

    def body(st):
        j, placements, avail = st
        demand = demands[j]
        h, ok = decide_row(avail, j, demand)
        ok = ok & (j < n_eff)
        avail = _place(avail, demand, h, ok)
        jj = jnp.where(j < n_eff, j, B)
        placements = placements.at[jj].set(
            jnp.where(ok, h, -1).astype(jnp.int32), mode="drop"
        )
        return j + 1, placements, avail

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B,), -1, jnp.int32), avail)
    _, placements, avail = lax.while_loop(lambda st: st[0] < n_eff, body, st0)
    return placements, avail


def _chunk_drive(avail, demands, valid, n_eff, C, speculate, recheck):
    """Shared chunked phase-2 driver for the carry-free kernels.

    ``speculate(avail, dem_c, valid_c, pos) -> (h_s, ok_s)`` proposes a
    chunk's placements from chunk-entry state (any quality — exactness
    comes from the re-decision); ``recheck(a_pre, dem_c, valid_c, pos)
    -> (h_c, ok_c)`` re-decides every position against its exact prefix
    availability with the oracle's ops (``pos`` lets a kernel slice its
    own per-task streams, e.g. the opportunistic uniforms).  The driver
    owns the commit protocol (see :func:`_speculate_commit`): positions
    beyond the commit are rewritten by later iterations, finished vmap
    rows spin inertly in the +C pad region.
    """
    B = demands.shape[0]
    demP, validP = _pad_chunk(demands, C), _pad_chunk(valid, C)

    def body(st):
        pos, placements, avail = st
        dem_c = lax.dynamic_slice_in_dim(demP, pos, C)
        valid_c = lax.dynamic_slice_in_dim(validP, pos, C)
        h_s, ok_s = speculate(avail, dem_c, valid_c, pos)
        ok_s = ok_s & valid_c
        h_s = jnp.where(ok_s, h_s, 0)
        p_c, h_c, ok_c, fc, _a, _e, commit_state = _speculate_commit(
            avail, None, False, dem_c, h_s, ok_s,
            lambda a_pre, _ex: recheck(a_pre, dem_c, valid_c, pos),
        )
        n_commit = jnp.minimum(fc + 1, C)
        placements = lax.dynamic_update_slice_in_dim(placements, p_c, pos, 0)
        new_avail, _ = commit_state(n_commit)
        return pos + n_commit, placements, new_avail

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B + C,), -1, jnp.int32),
           avail)
    _, placements, avail = lax.while_loop(lambda st: st[0] < n_eff, body, st0)
    return placements[:B], avail


# ---------------------------------------------------------------------------
# Public two-phase kernels
#
# Each kernel's body lives in an UNJITTED ``*_impl`` core; the public name
# is its jitted wrapper.  The cores are the reuse surface of the fused
# tick driver (``ops/tickloop.py``), which invokes one core per simulated
# tick INSIDE its own jitted ``lax.while_loop`` — re-entering a ``jax.jit``
# there would be a trace-time no-op at best, and the driver must be able
# to fold the per-tick availability output straight into its loop carry.
# The cores are also the hotpath-lint targets (``tools/hotpath_lint.py``):
# no host-sync call may appear in them.
# ---------------------------------------------------------------------------


def opportunistic_impl(avail, demands, valid, uniforms, phase2="auto",
                       live=None, risk=None):
    """Uniformly random fitting host per task (ref opportunistic.py:11-20),
    two-phase form — see the module docstring for the ``phase2`` modes.
    Bit-identical to :func:`opportunistic_kernel_ref` in every mode.
    No ``totals`` pre-filter input: the random choice has no fill model
    to steer, so the operand would be dead weight on the dispatch path.
    ``live`` is the optional [H] quarantine mask (:func:`_apply_live`);
    ``risk`` the optional [H] eviction-risk vector (minimum-risk-tier
    rule — same Philox draw, narrower support).
    Returns ([T] int32 placements, [H,4] new availability)."""
    mode = _resolve_phase2(phase2)
    avail, restore = _apply_live(avail, live)
    if mode == "scan":
        p, a = _opportunistic_scan(avail, demands, valid, uniforms, risk)
        return p, restore(a)
    B = demands.shape[0]
    if B == 0:
        return jnp.zeros((0,), jnp.int32), restore(avail)
    n_eff = _effective_len(valid)

    if mode == "slim":
        def decide_row(avail, j, demand):
            fit = _fits(avail, demand, strict=False) & valid[j]
            fit = _risk_restrict(fit, risk)
            n_fit = jnp.sum(fit)
            k = jnp.minimum((uniforms[j] * n_fit).astype(jnp.int32), n_fit - 1)
            rank = jnp.cumsum(fit)
            h = jnp.argmax(fit & (rank == k + 1))
            return h, n_fit > 0

        p, a = _slim_drive(avail, demands, n_eff, decide_row)
        return p, restore(a)

    C = min(mode, B)
    uP = _pad_chunk(uniforms, C)

    def decide(avail_c, dem_c, valid_c, pos):
        u_c = lax.dynamic_slice_in_dim(uP, pos, C)
        fit = jnp.all(avail_c >= dem_c[:, None, :], axis=2)
        fit = fit & valid_c[:, None]
        fit = _risk_restrict(fit, risk)
        n_fit = jnp.sum(fit, axis=1)
        k = jnp.minimum((u_c * n_fit).astype(jnp.int32), n_fit - 1)
        rank = jnp.cumsum(fit, axis=1)
        h = jnp.argmax(fit & (rank == (k + 1)[:, None]), axis=1)
        return h.astype(jnp.int32), n_fit > 0

    # Random choices do not pile on, so fit masks rarely move within a
    # chunk: plain chunk-entry speculation (the decision itself, run
    # against A0) commits wide here.
    p, a = _chunk_drive(
        avail, demands, valid, n_eff, C,
        lambda avail, dem_c, valid_c, pos: decide(
            avail[None], dem_c, valid_c, pos
        ),
        decide,
    )
    return p, restore(a)


opportunistic_kernel = jax.jit(
    opportunistic_impl, static_argnames=("phase2",)
)


def first_fit_impl(avail, demands, valid, strict=False, totals=None,
                   phase2="auto", live=None, risk=None):
    """Lowest-index fitting host per task (ref vbp.py:6-29), two-phase
    form.  Bit-identical to :func:`first_fit_kernel_ref` in every mode.
    ``live`` is the optional [H] quarantine mask (:func:`_apply_live`);
    ``risk`` the optional [H] eviction-risk vector — the index order
    becomes the lexicographic (risk, index) order (module comment)."""
    mode = _resolve_phase2(phase2)
    avail, restore = _apply_live(avail, live)
    if mode == "scan":
        p, a = _first_fit_scan(avail, demands, valid, strict, risk)
        return p, restore(a)
    B = demands.shape[0]
    if B == 0:
        return jnp.zeros((0,), jnp.int32), restore(avail)
    n_eff = _effective_len(valid)

    if mode == "slim":
        def decide_row(avail, j, demand):
            fit = _fits(avail, demand, strict) & valid[j]
            if risk is None:
                return jnp.argmax(fit), jnp.any(fit)
            return jnp.argmin(_risk_key(fit, risk)), jnp.any(fit)

        p, a = _slim_drive(avail, demands, n_eff, decide_row)
        return p, restore(a)

    def speculate(avail, dem_c, valid_c, pos):
        # Fill speculation in host-index order (first-fit's score IS the
        # index — or the risk vector when the risk term engages); capacity
        # from the leading demand — identical-demand runs (task-group
        # instances) commit whole chunks.
        C = dem_c.shape[0]
        viable = _static_viable(totals, dem_c[0], strict)
        caps = _fill_capacity(avail, dem_c[0], strict, viable)
        ranks = jnp.arange(C, dtype=jnp.int32)
        if risk is None:
            return _fill_pick_by_index(caps, ranks)
        return _fill_pick(risk, caps, ranks)

    def recheck(a_pre, dem_c, valid_c, pos):
        fit = (
            jnp.all(a_pre > dem_c[:, None, :], axis=2) if strict
            else jnp.all(a_pre >= dem_c[:, None, :], axis=2)
        )
        fit = fit & valid_c[:, None]
        if risk is None:
            h = jnp.argmax(fit, axis=1)
        else:
            h = jnp.argmin(_risk_key(fit, risk), axis=1)
        return h.astype(jnp.int32), jnp.any(fit, axis=1)

    p, a = _chunk_drive(
        avail, demands, valid, n_eff, min(mode, B), speculate, recheck
    )
    return p, restore(a)


first_fit_kernel = jax.jit(
    first_fit_impl, static_argnames=("strict", "phase2")
)


def best_fit_impl(avail, demands, valid, totals=None, phase2="auto",
                  live=None, risk=None):
    """Min residual-L2 host among strict fits (ref vbp.py:32-49), two-phase
    form.  Bit-identical to :func:`best_fit_kernel_ref` in every mode.
    ``live`` is the optional [H] quarantine mask (:func:`_apply_live`);
    ``risk`` the optional [H] eviction-risk vector (``score += risk``)."""
    mode = _resolve_phase2(phase2)
    avail, restore = _apply_live(avail, live)
    if mode == "scan":
        p, a = _best_fit_scan(avail, demands, valid, risk)
        return p, restore(a)
    B = demands.shape[0]
    if B == 0:
        return jnp.zeros((0,), jnp.int32), restore(avail)
    big = jnp.asarray(jnp.inf, avail.dtype)
    n_eff = _effective_len(valid)

    if mode == "slim":
        def decide_row(avail, j, demand):
            fit = _fits(avail, demand, strict=True) & valid[j]
            residual = _risk_score(_norms(avail - demand), risk)
            return jnp.argmin(jnp.where(fit, residual, big)), jnp.any(fit)

        p, a = _slim_drive(avail, demands, n_eff, decide_row)
        return p, restore(a)

    def speculate(avail, dem_c, valid_c, pos):
        # Best-fit piles onto its argmin host (placing there shrinks the
        # residual further) until the fit fails, then moves to the next
        # host in CHUNK-ENTRY residual order — untouched hosts' residuals
        # don't move.  The fill model captures exactly that.
        C = dem_c.shape[0]
        viable = _static_viable(totals, dem_c[0], strict=True)
        caps = _fill_capacity(avail, dem_c[0], strict=True, viable=viable)
        resid0 = _risk_score(_norms(avail - dem_c[0][None, :]), risk)
        return _fill_pick(resid0, caps, jnp.arange(C, dtype=jnp.int32))

    def recheck(a_pre, dem_c, valid_c, pos):
        fit = jnp.all(a_pre > dem_c[:, None, :], axis=2) & valid_c[:, None]
        residual = _risk_score(_norms(a_pre - dem_c[:, None, :]), risk)
        h = jnp.argmin(jnp.where(fit, residual, big), axis=1)
        return h.astype(jnp.int32), jnp.any(fit, axis=1)

    p, a = _chunk_drive(
        avail, demands, valid, n_eff, min(mode, B), speculate, recheck
    )
    return p, restore(a)


best_fit_kernel = jax.jit(best_fit_impl, static_argnames=("phase2",))


def cost_aware_impl(
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    rt_bw_rows=None,
    rt_bw_idx=None,
    totals=None,
    phase2="auto",
    live=None,
    risk=None,
    score_exp=None,
):
    """The PIVOT cost-aware placement (ref cost_aware.py:28-127), two-phase
    form — argument contract as :func:`cost_aware_kernel_ref`, plus the
    phase-1 ``totals`` pre-filter, the static ``phase2`` mode selector
    (module docstring), the optional [H] quarantine mask ``live``
    (:func:`_apply_live`), and the optional [H] eviction-risk vector
    ``risk`` (``score += risk``; the ``sort_hosts=False`` index order
    becomes lexicographic (risk, index)).  Bit-identical to the oracle
    in every mode.

    ``score_exp`` — optional traced [3] ``(w_cost, w_bw, w_norm)``
    exponent vector (``PolicyWeights.score_exponents()``): cost/bw pow
    at phase 1 (:func:`_ca_phase1`), the norm/residual pow at the score
    sites, matching ``sched/policies.py::CostAwarePolicy``'s learned
    shape ``cost^w_c·decay / (‖·‖^w_n·bw^w_b)`` (first-fit) and
    ``cost^w_c·‖·‖^w_n·decay / bw^w_b`` (best-fit).  ``None`` (the
    reference (1, 1, 1) shape) traces the exact pre-existing program —
    the bit-parity default.  Traced, not static: tuner-promoted weights
    change values with zero recompiles.  Rejected with ``rt_bw_rows``
    (realtime rows bypass the powed table).

    Phase-1 hoists here: the ``[Z, H]`` round-trip tables (already
    pre-scan), the host-decay prescale of the cost table (exact: the same
    two operands multiply), and the per-task realtime-bandwidth row
    indexing.  The group score ``(cost_row·decay) / (‖avail‖·bw_row)``
    keeps the oracle's operand association, so hoisting cannot move a
    rounding.  A full ``[T, H]`` score materialization was measured and
    rejected for the CPU phase 2 — at (B=2048, H=1024, f64) the 16 MB/
    table writes cost more than the whole slim pass; the Pallas TPU
    kernel is where the dense [T, H] phase-1 tiles pay
    (``ops/pallas_kernels.py``).
    """
    mode = _resolve_phase2(phase2)
    if score_exp is not None and rt_bw_rows is not None:
        raise ValueError(
            "learned score exponents pow the static phase-1 bandwidth "
            "table; realtime_bw rows bypass that table — the combination "
            "is rejected at the policy layer (sched/tpu.py)"
        )
    avail, restore = _apply_live(avail, live)
    if mode == "scan":
        p, a = _cost_aware_scan(
            avail, demands, valid, new_group, anchor_zone, cost_zz, bw_zz,
            host_zone, base_task_counts, bin_pack, sort_hosts, host_decay,
            rt_bw_rows, rt_bw_idx, risk, score_exp,
        )
        return p, restore(a)
    B, H = demands.shape[0], avail.shape[0]
    if B == 0:
        return jnp.zeros((0,), jnp.int32), restore(avail)
    first_fit = bin_pack == "first-fit"
    big = jnp.asarray(jnp.inf, avail.dtype)
    dtype = avail.dtype
    base_counts = base_task_counts.astype(dtype)
    track_extra = (not first_fit) and host_decay
    w_norm = None if score_exp is None else score_exp[2]

    # ---- phase 1 ----
    # Exact hoist of the group score's (cost_row * decay) product:
    # prescaling the table rows multiplies the same two operands.
    cost_rt, bw_rt, num_rt = _ca_phase1(
        cost_zz, bw_zz, host_zone, base_counts,
        first_fit and sort_hosts and host_decay,
        score_exp=score_exp,
    )
    iota_h = jnp.arange(H, dtype=dtype)
    n_eff = _effective_len(valid)

    def bw_row_at(az_j, ri_j):
        return bw_rt[az_j] if rt_bw_rows is None else rt_bw_rows[ri_j]

    ri = rt_bw_idx if rt_bw_rows is not None else anchor_zone

    if mode == "slim":
        def body(st):
            j, placements, avail, frozen, extra = st
            demand = demands[j]
            valid_j = valid[j] & (j < n_eff)
            if first_fit:
                if sort_hosts:
                    # lax.cond skips the O(H) sqrt-heavy score row on
                    # non-entry steps in the unbatched dispatch (~T/#groups
                    # of all steps); under vmap it lowers to a select and
                    # costs like the scan form.
                    frozen = lax.cond(
                        new_group[j],
                        lambda a: _risk_score(_ca_group_score(
                            num_rt[anchor_zone[j]], a,
                            bw_row_at(anchor_zone[j], ri[j]), w_norm,
                        ), risk),
                        lambda a: frozen,
                        avail,
                    )
                else:
                    frozen = jnp.where(
                        new_group[j],
                        iota_h if risk is None else risk,
                        frozen,
                    )
                fit = _fits(avail, demand, strict=True) & valid_j
                h = jnp.argmin(jnp.where(fit, frozen, big))
            else:
                decay = (
                    jnp.maximum(base_counts + extra.astype(dtype), 1.0)
                    if host_decay else 1.0
                )
                per_task = _risk_score(_ca_best_fit_score(
                    cost_rt[anchor_zone[j]], avail, demand, decay,
                    bw_row_at(anchor_zone[j], ri[j]), w_norm,
                ), risk)
                fit = _fits(avail, demand, strict=False) & valid_j
                h = jnp.argmin(jnp.where(fit, per_task, big))
            ok = jnp.any(fit)
            avail = _place(avail, demand, h, ok)
            if track_extra:
                extra = _bump_count(extra, h, ok)
            jj = jnp.where(j < n_eff, j, B)
            placements = placements.at[jj].set(
                jnp.where(ok, h, -1).astype(jnp.int32), mode="drop"
            )
            return j + 1, placements, avail, frozen, extra

        st0 = (jnp.asarray(0, jnp.int32), jnp.full((B,), -1, jnp.int32),
               avail, jnp.zeros(H, dtype), jnp.zeros(H, jnp.int32))
        _, placements, avail, _, _ = lax.while_loop(
            lambda st: st[0] < n_eff, body, st0
        )
        return placements, restore(avail)

    C = min(mode, B)
    demP, validP, ngP = (_pad_chunk(x, C) for x in (demands, valid, new_group))
    azP, riP = _pad_chunk(anchor_zone, C), _pad_chunk(ri, C)

    def body(st):
        pos, placements, avail, frozen, extra = st
        dem_c = lax.dynamic_slice_in_dim(demP, pos, C)
        valid_c = lax.dynamic_slice_in_dim(validP, pos, C)
        ng_c = lax.dynamic_slice_in_dim(ngP, pos, C)
        az_c = lax.dynamic_slice_in_dim(azP, pos, C)
        ri_c = lax.dynamic_slice_in_dim(riP, pos, C)
        idx = jnp.arange(C, dtype=jnp.int32)

        if first_fit:
            # Segment-scored chunk: positions before the chunk's first
            # group entry e1 keep the carried frozen score; [e1, e2) get
            # the score frozen at e1 (computed from the EXACT prefix
            # availability in the recheck).  The commit is capped at the
            # second entry e2 — one O(H) score row per iteration instead
            # of per chunk position.
            e1 = jnp.min(jnp.where(ng_c, idx, C))
            e2 = jnp.min(jnp.where(ng_c & (idx > e1), idx, C))
            e1c = jnp.minimum(e1, C - 1)
            az_e1, ri_e1 = az_c[e1c], ri_c[e1c]

            if sort_hosts:
                row_spec = _risk_score(
                    _ca_group_score(
                        num_rt[az_e1], avail, bw_row_at(az_e1, ri_e1),
                        w_norm,
                    ),
                    risk,
                )
            elif risk is not None:
                row_spec = risk
            else:
                row_spec = iota_h
            viableA = _static_viable(totals, dem_c[0], strict=True)
            viableB = _static_viable(totals, dem_c[e1c], strict=True)
            capsA = _fill_capacity(avail, dem_c[0], True, viableA)
            capsB = _fill_capacity(avail, dem_c[e1c], True, viableB)
            hA, okA = _fill_pick(
                frozen, capsA, jnp.where(idx < e1, idx, -1)
            )
            hB, okB = _fill_pick(
                row_spec, capsB,
                jnp.where((idx >= e1) & (idx < e2), idx - e1, -1),
            )
            h_s = jnp.where(idx < e1, hA, hB)
            ok_s = jnp.where(idx < e1, okA, okB) & valid_c
            h_s = jnp.where(ok_s, h_s, 0)
            commit_cap = e2

            def recheck(a_pre, _ex):
                if sort_hosts:
                    row_check = _risk_score(
                        _ca_group_score(
                            num_rt[az_e1], a_pre[e1c],
                            bw_row_at(az_e1, ri_e1), w_norm,
                        ),
                        risk,
                    )
                elif risk is not None:
                    row_check = risk
                else:
                    row_check = iota_h
                score_rows = jnp.where(
                    (idx >= e1)[:, None], row_check[None], frozen[None]
                )
                fit = jnp.all(a_pre > dem_c[:, None, :], axis=2)
                fit = fit & valid_c[:, None]
                h = jnp.argmin(jnp.where(fit, score_rows, big), axis=1)
                recheck.row_check = row_check
                return h.astype(jnp.int32), jnp.any(fit, axis=1)
        else:
            cost_rows = cost_rt[az_c]                       # [C, H]
            bw_rows = bw_rt[az_c] if rt_bw_rows is None else rt_bw_rows[ri_c]
            resid0 = _norms(avail - dem_c[0][None, :])
            if w_norm is not None:
                resid0 = resid0 ** w_norm
            dec0 = jnp.maximum(base_counts + extra.astype(dtype), 1.0) \
                if host_decay else 1.0
            row_spec = _risk_score(
                cost_rows[0] * resid0 * dec0 / bw_rows[0], risk
            )
            viable0 = _static_viable(totals, dem_c[0], strict=False)
            caps = _fill_capacity(avail, dem_c[0], False, viable0)
            h_s, ok_s = _fill_pick(row_spec, caps, idx)
            ok_s = ok_s & valid_c
            h_s = jnp.where(ok_s, h_s, 0)
            commit_cap = jnp.asarray(C, jnp.int32)

            def recheck(a_pre, ex_pre):
                fit = jnp.all(a_pre >= dem_c[:, None, :], axis=2)
                fit = fit & valid_c[:, None]
                residual = _norms(a_pre - dem_c[:, None, :])
                if w_norm is not None:
                    residual = residual ** w_norm
                decay = (
                    jnp.maximum(base_counts[None] + ex_pre.astype(dtype), 1.0)
                    if host_decay else 1.0
                )
                cand = _risk_score(
                    cost_rows * residual * decay / bw_rows, risk
                )
                h = jnp.argmin(jnp.where(fit, cand, big), axis=1)
                return h.astype(jnp.int32), jnp.any(fit, axis=1)

        p_c, h_c, ok_c, fc, a_pre, ex_pre, commit_state = _speculate_commit(
            avail, extra, track_extra, dem_c, h_s, ok_s, recheck
        )
        n_commit = jnp.minimum(jnp.minimum(fc + 1, commit_cap), C)
        n_commit = jnp.maximum(n_commit, 1)
        placements = lax.dynamic_update_slice_in_dim(placements, p_c, pos, 0)
        new_avail, new_extra = commit_state(n_commit)
        if first_fit:
            new_frozen = jnp.where(e1 < n_commit, recheck.row_check, frozen)
        else:
            new_frozen = frozen
        return pos + n_commit, placements, new_avail, new_frozen, new_extra

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B + C,), -1, jnp.int32),
           avail, jnp.zeros(H, dtype), jnp.zeros(H, jnp.int32))
    _, placements, avail, _, _ = lax.while_loop(
        lambda st: st[0] < n_eff, body, st0
    )
    return placements[:B], restore(avail)


cost_aware_kernel = jax.jit(
    cost_aware_impl,
    static_argnames=("bin_pack", "sort_hosts", "host_decay", "phase2"),
)
