"""Fused fit / score / argmin placement kernels.

This is the TPU decision backend demanded by the north star (BASELINE.md):
each scheduling tick evaluates all ready-task × host placements in a single
device call.  The greedy *sequential* semantics of the reference policies
(each placement decrements availability seen by the next task —
``scheduler/vbp.py``, ``scheduler/cost_aware.py:99-127``) are preserved by a
``lax.scan`` over the task axis carrying the ``[H, 4]`` availability matrix;
everything per-step is a fused mask + argmin over hosts.

Design notes (TPU-first):
  * **No data-dependent shapes**: the task axis is padded to a bucket size
    by the caller (``pivot_tpu.sched.tpu``) with ``valid=False`` rows; the
    kernel is compiled once per (bucket, H) pair.
  * **No on-device RNG**: the opportunistic policy's random choice consumes
    a Philox uniform stream generated host-side (``sched/rand.py``), so CPU
    and TPU backends make bit-identical choices.
  * **First-fit over a sorted host list ≡ masked argmin**: for a host order
    sorted by a per-group score (stable), the first fitting host is exactly
    the fitting host minimizing ``(score, host_index)`` — so the kernel
    never materializes a sort; it freezes the group's score vector when the
    scan enters a new group and takes a masked argmin per task
    (ties → lowest index, matching a stable sort).
  * ``argmin``/``argmax`` tie-breaking to the lowest index is the shared
    tie rule across the numpy policies and these kernels.

Dtype: float32 on TPU.  Exact cross-backend placement parity is validated
on CPU with x64 enabled; on TPU, f32 rounding can flip near-boundary fits
— accepted, since the acceptance criterion is identical makespan/cost
*rankings* (BASELINE.md).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "DeviceTopology",
    "opportunistic_kernel",
    "first_fit_kernel",
    "best_fit_kernel",
    "cost_aware_kernel",
]


class DeviceTopology(NamedTuple):
    """Device-resident cluster topology, pushed once per experiment.

    The reference re-derives per-pair route bandwidth from Python dicts on
    every score evaluation (``scheduler/cost_aware.py:73-79``); here the
    ``[Z, Z]`` matrices live on the accelerator and are gathered by zone
    index inside the kernel.
    """

    cost: jax.Array  # [Z, Z] egress $ / GB
    bw: jax.Array  # [Z, Z] Mbps
    host_zone: jax.Array  # [H] i32
    totals: jax.Array  # [H, 4]

    @classmethod
    def from_cluster(cls, cluster, dtype=jnp.float32) -> "DeviceTopology":
        meta = cluster.meta
        return cls(
            cost=jnp.asarray(meta.cost_matrix, dtype=dtype),
            bw=jnp.asarray(meta.bw_matrix, dtype=dtype),
            host_zone=jnp.asarray(cluster.host_zone_vector(), dtype=jnp.int32),
            totals=jnp.asarray(cluster.totals_matrix(), dtype=dtype),
        )

    @property
    def n_hosts(self) -> int:
        return self.host_zone.shape[0]


def _fits(avail: jax.Array, demand: jax.Array, strict: bool) -> jax.Array:
    """[H] fit mask: every dimension satisfies avail (>|>=) demand."""
    if strict:
        return jnp.all(avail > demand, axis=1)
    return jnp.all(avail >= demand, axis=1)


def _norms(mat: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.sum(mat * mat, axis=-1))


def _place(avail, demand, h, ok):
    """Decrement row ``h`` by ``demand`` when ``ok`` (no-op otherwise).

    Two lowerings, chosen by backend at trace time (jit caches per
    backend), both exact — x − d·1 ≡ x + (−d), x − d·0 ≡ x — and
    placement-bit-equal to each other:

      * accelerator: one-hot arithmetic, not ``avail.at[h].add`` —
        under ``vmap`` (the Monte-Carlo replica axis) the indexed form
        lowers to a batched scatter whose per-replica index vector
        lands in TPU scalar memory and serializes on the scalar core
        (see ARCHITECTURE.md, "the scalar-core lesson");
      * cpu: the indexed scatter — the one-hot form writes O(H·4)
        values per scan step where the scatter writes 4.  Measured at
        the bench shape (T=2048, H=512, R=1024): the round-2 one-hot
        rewrite cost the CPU path 391.8k → 336.4k decisions/s (−14%);
        this split restores it (VERDICT r03 item 6).
    """
    if jax.default_backend() == "cpu":
        delta = jnp.where(ok, demand, jnp.zeros_like(demand))
        return avail.at[h].add(-delta)
    hit = (jnp.arange(avail.shape[0]) == h)[:, None] & ok
    return avail - jnp.where(hit, demand[None, :], jnp.zeros((), avail.dtype))


@jax.jit
def opportunistic_kernel(avail, demands, valid, uniforms):
    """Uniformly random fitting host per task (ref opportunistic.py:11-20).

    The k-th fitting host (k = ⌊u·n_fit⌋) is selected via a cumulative-sum
    rank match — no host list materialization.
    Returns ([T] int32 placements, [H,4] new availability).
    """

    def body(avail, x):
        demand, valid_i, u = x
        fit = _fits(avail, demand, strict=False) & valid_i
        n_fit = jnp.sum(fit)
        k = jnp.minimum((u * n_fit).astype(jnp.int32), n_fit - 1)
        rank = jnp.cumsum(fit)  # 1-based rank among fitting hosts
        h = jnp.argmax(fit & (rank == k + 1))
        ok = n_fit > 0
        return _place(avail, demand, h, ok), jnp.where(ok, h, -1).astype(jnp.int32)

    return _scan_swap(body, avail, (demands, valid, uniforms))


@functools.partial(jax.jit, static_argnames=("strict",))
def first_fit_kernel(avail, demands, valid, strict=False):
    """Lowest-index fitting host per task (ref vbp.py:6-29)."""

    def body(avail, x):
        demand, valid_i = x
        fit = _fits(avail, demand, strict) & valid_i
        h = jnp.argmax(fit)
        ok = jnp.any(fit)
        return _place(avail, demand, h, ok), jnp.where(ok, h, -1).astype(jnp.int32)

    return _scan_swap(body, avail, (demands, valid))


@jax.jit
def best_fit_kernel(avail, demands, valid):
    """Min residual-L2 host among strict fits (ref vbp.py:32-49)."""
    big = jnp.asarray(jnp.inf, avail.dtype)

    def body(avail, x):
        demand, valid_i = x
        fit = _fits(avail, demand, strict=True) & valid_i
        residual = _norms(avail - demand)
        h = jnp.argmin(jnp.where(fit, residual, big))
        ok = jnp.any(fit)
        return _place(avail, demand, h, ok), jnp.where(ok, h, -1).astype(jnp.int32)

    return _scan_swap(body, avail, (demands, valid))


@functools.partial(
    jax.jit,
    static_argnames=("bin_pack", "sort_hosts", "host_decay"),
)
def cost_aware_kernel(
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    rt_bw_rows=None,
    rt_bw_idx=None,
):
    """The PIVOT cost-aware placement (ref cost_aware.py:28-127), fused.

    Inputs (task axis T padded, host axis H, zone axis Z):
      demands          [T, 4]  — tasks pre-ordered by the caller: groups in
                                 first-seen order, optionally sorted
                                 descending by demand norm within a group
      valid            [T]     — padding mask
      new_group        [T]     — True where task i starts a new anchor group
      anchor_zone      [T] i32 — zone index of each task's anchor storage
      cost_zz, bw_zz   [Z, Z]  — device-resident egress-cost / bandwidth
                                 matrices (from :class:`DeviceTopology`)
      host_zone        [H] i32
      base_task_counts [H]     — tasks resident per host at tick start

    Round-trip cost/bandwidth per (anchor-zone, host) are precomputed once
    as ``[Z, H]`` tables outside the scan, so per tick only the ``[T]``
    anchor-zone vector crosses host→device.

    ``rt_bw_rows`` ([G, H]) + ``rt_bw_idx`` ([T] i32, row per task)
    together override the static bandwidth table with caller-supplied
    round-trip bandwidths — the ``realtime_bw`` scoring mode, where the
    anchor↔host values come from live route queue state
    (``infra.network.Route.realtime_bw``, ref ``resources/network.py:
    70-73``) sampled host-side at the tick instant.  One row per anchor
    GROUP plus a per-task index keeps the per-tick host→device transfer
    at G × H + T values instead of a dense task-replicated [T, H].

    First-fit: the group's host score ``cost·decay / (‖avail‖·bw)`` is
    frozen when the scan enters the group (matching the reference's
    sort-at-group-start, which sees availability mutated by *earlier*
    groups in the same tick); placement is a masked argmin with strict
    fits (first-fit over a stably-sorted list ≡ masked argmin).  Best-fit:
    per-task score ``cost·‖avail−d‖·decay / bw`` over non-strict fits,
    with a live placement counter in the decay.
    """
    H = avail.shape[0]
    big = jnp.asarray(jnp.inf, avail.dtype)
    first_fit = bin_pack == "first-fit"
    base_counts = base_task_counts.astype(avail.dtype)
    # [Z, H] round-trip tables: anchor-zone z ↔ each host.
    cost_rt = cost_zz[:, host_zone] + cost_zz[host_zone, :].T
    bw_rt = bw_zz[:, host_zone] + bw_zz[host_zone, :].T

    def group_score(avail, cost_row, bw_row):
        if not sort_hosts:
            return jnp.arange(H, dtype=avail.dtype)  # identity host order
        decay = jnp.maximum(base_counts, 1.0) if host_decay else 1.0
        return cost_row * decay / (_norms(avail) * bw_row)

    def body(carry, x):
        avail, frozen_score, extra = carry
        if rt_bw_rows is None:
            demand, valid_i, new_g, az = x
            bw_row = bw_rt[az]
        else:
            demand, valid_i, new_g, az, row_idx = x
            bw_row = rt_bw_rows[row_idx]
        cost_row = cost_rt[az]
        if first_fit:
            score = jnp.where(
                new_g, group_score(avail, cost_row, bw_row), frozen_score
            )
            fit = _fits(avail, demand, strict=True) & valid_i
            h = jnp.argmin(jnp.where(fit, score, big))
        else:
            score = frozen_score  # unused carry for best-fit
            residual = _norms(avail - demand)
            decay = (
                jnp.maximum(base_counts + extra.astype(avail.dtype), 1.0)
                if host_decay
                else 1.0
            )
            per_task = cost_row * residual * decay / bw_row
            fit = _fits(avail, demand, strict=False) & valid_i
            h = jnp.argmin(jnp.where(fit, per_task, big))
        ok = jnp.any(fit)
        avail = _place(avail, demand, h, ok)
        if not first_fit:
            # Only best-fit's live decay reads the within-tick counter
            # (first-fit decay is frozen at tick start, ref :115) —
            # backend-split like _place: one-hot off-CPU for the
            # scalar-core reason, indexed scatter on CPU for speed.
            if jax.default_backend() == "cpu":
                extra = extra.at[h].add(jnp.where(ok, 1, 0))
            else:
                extra = extra + (
                    (jnp.arange(extra.shape[0]) == h) & ok
                ).astype(extra.dtype)
        return (avail, score, extra), jnp.where(ok, h, -1).astype(jnp.int32)

    init = (
        avail,
        jnp.zeros(H, dtype=avail.dtype),
        jnp.zeros(H, dtype=jnp.int32),
    )
    xs = (demands, valid, new_group, anchor_zone)
    if rt_bw_rows is not None:
        xs = xs + (rt_bw_idx,)
    (avail, _, _), placements = lax.scan(body, init, xs)
    return placements, avail


def _scan_swap(body, avail, xs):
    new_avail, placements = lax.scan(body, avail, xs)
    return placements, new_avail
