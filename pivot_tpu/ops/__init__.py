"""Fused placement kernels (JAX) — the TPU decision backend.

Two families (``ops.kernels``): the two-phase production kernels and the
``*_kernel_ref`` scan oracles they are held bit-identical to.  On top of
them, ``ops.tickloop`` fuses whole *pure tick runs* — K scheduler ticks
whose inputs are computable up front — into one device program, with the
availability carry, wait-queue permutation, and meters device-resident
between ticks (round 8; see ``docs/ARCHITECTURE.md``).
"""

from pivot_tpu.ops.tickloop import (  # noqa: F401
    SpanResult,
    fused_tick_run,
    reference_tick_run,
    span_bucket,
)

from pivot_tpu.ops.kernels import (  # noqa: F401
    DeviceTopology,
    best_fit_kernel,
    best_fit_kernel_ref,
    cost_aware_kernel,
    cost_aware_kernel_ref,
    first_fit_kernel,
    first_fit_kernel_ref,
    opportunistic_kernel,
    opportunistic_kernel_ref,
)
