"""Fused placement kernels (JAX) — the TPU decision backend.

Two families (``ops.kernels``): the two-phase production kernels and the
``*_kernel_ref`` scan oracles they are held bit-identical to.
"""

from pivot_tpu.ops.kernels import (  # noqa: F401
    DeviceTopology,
    best_fit_kernel,
    best_fit_kernel_ref,
    cost_aware_kernel,
    cost_aware_kernel_ref,
    first_fit_kernel,
    first_fit_kernel_ref,
    opportunistic_kernel,
    opportunistic_kernel_ref,
)
