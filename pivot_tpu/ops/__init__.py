"""Fused placement kernels (JAX) — the TPU decision backend."""

from pivot_tpu.ops.kernels import (  # noqa: F401
    DeviceTopology,
    best_fit_kernel,
    cost_aware_kernel,
    first_fit_kernel,
    opportunistic_kernel,
)
