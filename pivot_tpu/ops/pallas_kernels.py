"""Pallas TPU kernel for the fused greedy placement pass.

The ``lax.scan`` kernels in :mod:`pivot_tpu.ops.kernels` express the
greedy within-tick semantics as T sequential HLO loop iterations, each a
masked argmin over hosts.  This module collapses the *entire* tick into a
single Pallas program: the ``[4, H]`` availability matrix, the frozen
group-score vector, and the best-fit decay counter stay resident in VMEM
scratch for the whole pass, per-task scalars (demands, anchor zone, flags)
stream through SMEM in chunks, and each step is a handful of VPU ops over
the lane (=host) axis — no per-iteration HBM traffic at all.

Semantics are identical to :func:`pivot_tpu.ops.kernels.cost_aware_kernel`
(the PIVOT cost-aware policy, ref ``scheduler/cost_aware.py:28-127``):
  * first-fit: strict fits, group score ``cost·decay/(‖avail‖·bw)`` frozen
    at group entry, masked argmin with ties → lowest host index;
  * best-fit: non-strict fits, live per-task score
    ``cost·‖avail−d‖·decay/bw`` with a within-tick placement counter.

Layout (TPU-first):
  * hosts on the **lane** axis, padded to a multiple of 128; padding hosts
    carry ``avail = -1e30`` so no fit test can ever select them;
  * the four resource dimensions are unrolled (four ``[1, Hp]`` rows), so
    fit masks and norms are plain VPU vector ops — no cross-lane work
    except the final min-reductions;
  * ``[Z, H]`` round-trip cost/bw tables are precomputed outside and read
    per task by a dynamic-sublane gather on the anchor zone.

Batching: ``jax.vmap`` over the wrapper maps to an extra grid dimension
(one greedy pass per replica per program instance) — this is how the
Monte-Carlo ensemble (``pivot_tpu.parallel.ensemble``) runs R replicas'
ticks concurrently on one chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["cost_aware_pallas"]

_BIG = 1e30
_NEG = -1e30


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _greedy_body(
    first_fit: bool,
    sort_hosts: bool,
    host_decay: bool,
    chunk: int,
    Hp: int,
):
    """Kernel body factory; all mode flags are Python-static."""

    def kernel(
        demands_s,  # [4, chunk] f32 SMEM (task axis on lanes — SMEM blocks
        valid_s,  # [1, chunk] i32 SMEM    are lane-padded to 128, so the
        ng_s,  # [1, chunk] i32 SMEM       narrow axis must be the leading one)
        az_s,  # [1, chunk] i32 SMEM
        cost_rt,  # [Zp, Hp] f32 VMEM
        bw_rt,  # [Zp, Hp] f32 VMEM
        base_row,  # [1, Hp] f32 VMEM  (host task counts at tick start)
        avail_in,  # [8, Hp] f32 VMEM  (rows 0-3 = avail.T)
        place_out,  # [1, chunk] i32 SMEM out
        avail_out,  # [8, Hp] f32 VMEM out (revisited across grid steps)
        score_ref,  # [1, Hp] f32 VMEM scratch (frozen group score)
        extra_ref,  # [1, Hp] f32 VMEM scratch (best-fit live counter)
    ):
        c = pl.program_id(0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, Hp), 1)
        lane_f = lane.astype(jnp.float32)

        @pl.when(c == 0)
        def _():
            avail_out[:] = avail_in[:]
            score_ref[:] = jnp.zeros_like(score_ref)
            extra_ref[:] = jnp.zeros_like(extra_ref)

        def step(i, _):
            valid_i = valid_s[0, i] > 0
            az = az_s[0, i]
            d = [demands_s[r, i] for r in range(4)]
            a = [avail_out[r : r + 1, :] for r in range(4)]
            cost_row = cost_rt[pl.ds(az, 1), :]
            bw_row = bw_rt[pl.ds(az, 1), :]

            if first_fit:
                # Freeze the group's host score on group entry (the
                # reference sorts hosts once per anchor group).
                @pl.when(ng_s[0, i] > 0)
                def _():
                    if sort_hosts:
                        norms = jnp.sqrt(
                            a[0] * a[0] + a[1] * a[1] + a[2] * a[2] + a[3] * a[3]
                        )
                        decay = (
                            jnp.maximum(base_row[:], 1.0) if host_decay else 1.0
                        )
                        score_ref[:] = cost_row * decay / (norms * bw_row)
                    else:
                        score_ref[:] = lane_f
                fit = (a[0] > d[0]) & (a[1] > d[1]) & (a[2] > d[2]) & (a[3] > d[3])
                cand = jnp.where(fit & valid_i, score_ref[:], _BIG)
            else:
                r_ = [a[r] - d[r] for r in range(4)]
                residual = jnp.sqrt(
                    r_[0] * r_[0] + r_[1] * r_[1] + r_[2] * r_[2] + r_[3] * r_[3]
                )
                decay = (
                    jnp.maximum(base_row[:] + extra_ref[:], 1.0)
                    if host_decay
                    else 1.0
                )
                per_task = cost_row * residual * decay / bw_row
                fit = (
                    (a[0] >= d[0]) & (a[1] >= d[1]) & (a[2] >= d[2]) & (a[3] >= d[3])
                )
                cand = jnp.where(fit & valid_i, per_task, _BIG)

            m = jnp.min(cand)
            ok = m < _BIG
            h = jnp.min(jnp.where(cand == m, lane, Hp))  # ties → lowest index
            onehot = ((lane == h) & ok).astype(jnp.float32)
            for r in range(4):
                avail_out[r : r + 1, :] = a[r] - d[r] * onehot
            if not first_fit:
                extra_ref[:] = extra_ref[:] + onehot
            place_out[0, i] = jnp.where(ok, h, -1)
            return 0

        jax.lax.fori_loop(0, chunk, step, 0)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("bin_pack", "sort_hosts", "host_decay", "interpret"),
)
def cost_aware_pallas(
    avail,  # [H, 4]
    demands,  # [T, 4]
    valid,  # [T] bool
    new_group,  # [T] bool
    anchor_zone,  # [T] i32
    cost_zz,  # [Z, Z]
    bw_zz,  # [Z, Z]
    host_zone,  # [H] i32
    base_task_counts,  # [H] i32
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    interpret: bool = False,
):
    """Drop-in Pallas replacement for ``kernels.cost_aware_kernel``.

    Returns ``([T] int32 placements, [H, 4] new availability)`` with the
    same greedy semantics; ``interpret=True`` runs the Mosaic interpreter
    (CPU parity tests).
    """
    H, T = avail.shape[0], demands.shape[0]
    if T == 0:  # empty tick — the scan kernel's length-0 scan equivalent
        return jnp.zeros((0,), jnp.int32), avail
    Hp = _round_up(max(H, 128), 128)
    chunk = min(256, _round_up(T, 8))
    Tp = _round_up(T, chunk)
    f32 = jnp.float32

    # [8, Hp] transposed availability; padding hosts can never fit.
    availT = jnp.transpose(avail.astype(f32))  # [4, H]
    avail8 = jnp.concatenate([availT, jnp.ones((4, H), f32)], axis=0)
    avail8 = jnp.pad(avail8, ((0, 0), (0, Hp - H)), constant_values=_NEG)

    def pad_t(x, fill, dt):
        x = x.astype(dt).reshape(T, -1).T  # [w, T] — task axis on lanes
        return jnp.pad(x, ((0, 0), (0, Tp - T)), constant_values=fill)

    dem = pad_t(demands, 0.0, f32)  # [4, Tp]
    val = pad_t(valid, 0, jnp.int32)
    ng = pad_t(new_group, 0, jnp.int32)
    az = pad_t(anchor_zone, 0, jnp.int32)

    # Round-trip anchor-zone ↔ host tables, host-lane padded (bw pad = 1
    # avoids div-by-zero; those lanes are unreachable via the fit mask).
    hz = host_zone.astype(jnp.int32)
    cost_rt = (cost_zz[:, hz] + cost_zz[hz, :].T).astype(f32)
    bw_rt = (bw_zz[:, hz] + bw_zz[hz, :].T).astype(f32)
    Z = cost_rt.shape[0]
    Zp = _round_up(Z, 8)
    cost_rt = jnp.pad(cost_rt, ((0, Zp - Z), (0, Hp - H)))
    bw_rt = jnp.pad(bw_rt, ((0, Zp - Z), (0, Hp - H)), constant_values=1.0)
    base_row = jnp.pad(
        base_task_counts.astype(f32).reshape(1, H), ((0, 0), (0, Hp - H))
    )

    grid = (Tp // chunk,)
    smem_chunk = lambda w: pl.BlockSpec(  # noqa: E731
        (w, chunk), lambda c: (0, c), memory_space=pltpu.SMEM
    )
    whole = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda c: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    placements, avail_out = pl.pallas_call(
        _greedy_body(
            first_fit=bin_pack == "first-fit",
            sort_hosts=sort_hosts,
            host_decay=host_decay,
            chunk=chunk,
            Hp=Hp,
        ),
        grid=grid,
        in_specs=[
            smem_chunk(4),  # demands
            smem_chunk(1),  # valid
            smem_chunk(1),  # new_group
            smem_chunk(1),  # anchor zone
            whole((Zp, Hp)),  # cost_rt
            whole((Zp, Hp)),  # bw_rt
            whole((1, Hp)),  # base counts
            whole((8, Hp)),  # avail in
        ],
        out_specs=(
            smem_chunk(1),
            whole((8, Hp)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((1, Tp), jnp.int32),
            jax.ShapeDtypeStruct((8, Hp), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((1, Hp), f32),  # frozen group score
            pltpu.VMEM((1, Hp), f32),  # best-fit live counter
        ],
        interpret=interpret,
    )(dem, val, ng, az, cost_rt, bw_rt, base_row, avail8)

    return (
        placements[0, :T],
        jnp.transpose(avail_out[:4, :H]).astype(avail.dtype),
    )
