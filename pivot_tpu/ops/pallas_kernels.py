"""Pallas TPU kernel for the fused greedy placement pass.

The ``lax.scan`` kernels in :mod:`pivot_tpu.ops.kernels` express the
greedy within-tick semantics as T sequential HLO loop iterations, each a
masked argmin over hosts.  This module collapses the *entire* tick into a
single Pallas program: the ``[4, H]`` availability matrix, the frozen
group-score vector, and the best-fit decay counter stay resident in VMEM
scratch for the whole pass, per-task scalars (demands, anchor zone, flags)
stream through SMEM in chunks, and each step is a handful of VPU ops over
the lane (=host) axis — no per-iteration HBM traffic at all.

Semantics are identical to :func:`pivot_tpu.ops.kernels.cost_aware_kernel`
(the PIVOT cost-aware policy, ref ``scheduler/cost_aware.py:28-127``):
  * first-fit: strict fits, group score ``cost·decay/(‖avail‖·bw)`` frozen
    at group entry, masked argmin with ties → lowest host index;
  * best-fit: non-strict fits, live per-task score
    ``cost·‖avail−d‖·decay/bw`` with a within-tick placement counter.

Layout (TPU-first):
  * hosts on the **lane** axis, padded to a multiple of 128; padding hosts
    carry ``avail = -1e30`` so no fit test can ever select them;
  * Monte-Carlo replicas on the **sublane** axis, ``block_replicas`` per
    grid block: the four resource dimensions are unrolled into four
    ``[RB, Hp]`` slabs, so every fit mask / norm / argmin issue advances
    RB replicas at once — no cross-lane work except the per-replica
    min-reductions;
  * **phase-1 score tiles** (round 6): the per-task ``[T, H]`` round-trip
    cost/bandwidth rows are materialized OUTSIDE the kernel in one fused
    batched gather (``cost_rt[anchor_zone]`` — the two-phase kernels'
    phase 1, ``ops/kernels.py``) and streamed through the existing
    Mosaic pipeline as ``[chunk, Hp]`` VMEM tiles alongside the task
    scalars.  This replaces the previous in-kernel per-step
    dynamic-sublane gather on the anchor zone from whole-VMEM ``[Z, H]``
    tables — the anchor-zone SMEM stream disappears and each step reads
    its row by loop index from the prefetched tile.  The values are the
    same gathered rows, so placements are bit-identical.

One greedy body serves every form: :func:`cost_aware_pallas_batched`
takes the whole ``[R, H, 4]`` replica ensemble (task stream shared — the
ensemble/bench shape), and :func:`cost_aware_pallas` is its RB=1
single-replica case.  Measured on the v5e at (T=2048, H=512, R=1024)
the batched form is ~2.7× the vmapped ``lax.scan`` kernel and ~13× the
one-replica-per-grid-step form (see RESULTS.md).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pivot_tpu.infra.roofline import (
    PALLAS_VMEM_BUDGET_BYTES,
    V5E_SCOPED_VMEM_BYTES,
)

__all__ = ["cost_aware_pallas", "cost_aware_pallas_batched"]

_BIG = 1e30
_NEG = -1e30
# Largest hardware-proven replica block: RB=1024 at Hp=512 outgrows VMEM
# (Mosaic compile failure); 512 compiles and is the fastest measured.
_MAX_BLOCK_REPLICAS = 512


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@functools.partial(
    jax.jit,
    static_argnames=("bin_pack", "sort_hosts", "host_decay", "interpret"),
)
def cost_aware_pallas(
    avail,  # [H, 4]
    demands,  # [T, 4]
    valid,  # [T] bool
    new_group,  # [T] bool
    anchor_zone,  # [T] i32
    cost_zz,  # [Z, Z]
    bw_zz,  # [Z, Z]
    host_zone,  # [H] i32
    base_task_counts,  # [H] i32
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    interpret: bool = False,
    live=None,
    risk=None,
):
    """Drop-in Pallas replacement for ``kernels.cost_aware_kernel``.

    Returns ``([T] int32 placements, [H, 4] new availability)`` with the
    same greedy semantics; ``interpret=True`` runs the Mosaic interpreter
    (CPU parity tests).  ``live`` is the optional [H] quarantine mask
    (False = host excluded from placement — same contract as the scan
    kernels' ``live``); ``risk`` the optional [H] eviction-risk vector
    fused into the phase-1 scores by the shared rule (``score += risk``;
    the ``sort_hosts=False`` lane order becomes lexicographic
    (risk, lane) — same contract as the scan kernels' ``risk``).  The
    single-replica case of :func:`cost_aware_pallas_batched` — one
    greedy body serves both, so the policy semantics (fit predicates,
    score formulas, tie rule) cannot drift between the batched and
    unbatched forms.
    """
    placements, avail_out = cost_aware_pallas_batched(
        avail[None],
        demands,
        valid,
        new_group,
        anchor_zone,
        cost_zz,
        bw_zz,
        host_zone,
        base_task_counts,
        bin_pack=bin_pack,
        sort_hosts=sort_hosts,
        host_decay=host_decay,
        block_replicas=1,
        interpret=interpret,
        live=live,
        risk=risk,
    )
    return placements[0], avail_out[0]


def _greedy_body_batched(
    first_fit: bool,
    sort_hosts: bool,
    host_decay: bool,
    chunk: int,
    RB: int,
    Hp: int,
    has_risk: bool = False,
):
    """Replica-batched kernel body: ``RB`` replicas ride the sublane axis.

    :func:`cost_aware_pallas` under ``vmap`` runs one replica per grid
    step — each step's vectors are ``[1, Hp]`` (one sublane of the 8×128
    VPU), so 7/8 of every vector ALU issue is wasted and the replica axis
    serializes on the single TensorCore.  Here each grid step advances
    ``RB`` replicas at once on full ``[RB, Hp]`` registers: same
    instruction stream, ``RB×`` the decisions per issue.  Per-task
    scalars (demands/valid/group/anchor) are SHARED across replicas —
    exactly the Monte-Carlo ensemble shape, where only availability is
    perturbed per replica (``bench.py`` ``_bench_device``).
    """

    def kernel(
        demands_s,  # [4, chunk] f32 SMEM (shared task stream)
        valid_s,  # [1, chunk] i32 SMEM
        ng_s,  # [1, chunk] i32 SMEM
        cost_rows,  # [chunk, Hp] f32 VMEM (phase-1 per-task cost rows)
        bw_rows,  # [chunk, Hp] f32 VMEM (phase-1 per-task bw rows)
        base_row,  # [1, Hp] f32 VMEM
        *refs,  # [risk_row [1, Hp] f32 VMEM (has_risk only)], avail_in,
        #         place_out, avail_out, score_ref, extra_ref
    ):
        if has_risk:
            (risk_row, avail_in, place_out, avail_out,
             score_ref, extra_ref) = refs
        else:
            avail_in, place_out, avail_out, score_ref, extra_ref = refs
            risk_row = None
        tc = pl.program_id(1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (RB, Hp), 1)
        lane_f = lane.astype(jnp.float32)
        cl = jax.lax.broadcasted_iota(jnp.int32, (RB, chunk), 1)

        @pl.when(tc == 0)
        def _():
            avail_out[:] = avail_in[:]
            score_ref[:] = jnp.zeros_like(score_ref)
            extra_ref[:] = jnp.zeros_like(extra_ref)

        def step(i, _):
            valid_i = valid_s[0, i] > 0
            d = [demands_s[r, i] for r in range(4)]
            a = [avail_out[0, r * RB : (r + 1) * RB, :] for r in range(4)]
            # Phase-1 tile rows by loop index (no zone gather in-kernel).
            cost_row = cost_rows[pl.ds(i, 1), :]  # [1, Hp] → broadcasts
            bw_row = bw_rows[pl.ds(i, 1), :]

            if first_fit:

                @pl.when(ng_s[0, i] > 0)
                def _():
                    if sort_hosts:
                        norms = jnp.sqrt(
                            a[0] * a[0] + a[1] * a[1] + a[2] * a[2] + a[3] * a[3]
                        )
                        decay = (
                            jnp.maximum(base_row[:], 1.0) if host_decay else 1.0
                        )
                        score = cost_row * decay / (norms * bw_row)
                        if has_risk:
                            # Shared risk rule: score += risk (the risk
                            # term is availability-independent, so adding
                            # at freeze time == adding at selection time).
                            score = score + risk_row[:]
                        score_ref[:] = score
                    elif has_risk:
                        # Index-ordered selection → lexicographic
                        # (risk, lane): the min-lane tie-break below
                        # supplies the second key.
                        score_ref[:] = jnp.broadcast_to(
                            risk_row[:], (RB, Hp)
                        )
                    else:
                        score_ref[:] = lane_f

                fit = (a[0] > d[0]) & (a[1] > d[1]) & (a[2] > d[2]) & (a[3] > d[3])
                cand = jnp.where(fit & valid_i, score_ref[:], _BIG)
            else:
                r_ = [a[r] - d[r] for r in range(4)]
                residual = jnp.sqrt(
                    r_[0] * r_[0] + r_[1] * r_[1] + r_[2] * r_[2] + r_[3] * r_[3]
                )
                decay = (
                    jnp.maximum(base_row[:] + extra_ref[:], 1.0)
                    if host_decay
                    else 1.0
                )
                per_task = cost_row * residual * decay / bw_row
                if has_risk:
                    per_task = per_task + risk_row[:]
                fit = (
                    (a[0] >= d[0]) & (a[1] >= d[1]) & (a[2] >= d[2]) & (a[3] >= d[3])
                )
                cand = jnp.where(fit & valid_i, per_task, _BIG)

            m = jnp.min(cand, axis=1, keepdims=True)  # [RB, 1] per replica
            ok = m < _BIG
            h = jnp.min(
                jnp.where(cand == m, lane, Hp), axis=1, keepdims=True
            )  # ties → lowest host index, per replica
            onehot = ((lane == h) & ok).astype(jnp.float32)
            for r in range(4):
                avail_out[0, r * RB : (r + 1) * RB, :] = a[r] - d[r] * onehot
            if not first_fit:
                extra_ref[:] = extra_ref[:] + onehot
            # Lane-select write of this step's [RB] placement column (a
            # dynamic-lane store would serialize; a [RB, chunk] select is
            # one VPU op).
            hcol = jnp.where(ok, h, -1)  # [RB, 1] i32
            place_out[0, :, :] = jnp.where(cl == i, hcol, place_out[0, :, :])
            return 0

        place_out[0, :, :] = jnp.full((RB, chunk), -1, jnp.int32)
        jax.lax.fori_loop(0, chunk, step, 0)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "bin_pack", "sort_hosts", "host_decay", "block_replicas", "interpret",
    ),
)
def cost_aware_pallas_batched(
    avail_r,  # [R, H, 4] per-replica availability
    demands,  # [T, 4] shared task stream
    valid,  # [T] bool
    new_group,  # [T] bool
    anchor_zone,  # [T] i32
    cost_zz,  # [Z, Z]
    bw_zz,  # [Z, Z]
    host_zone,  # [H] i32
    base_task_counts,  # [H] i32
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    block_replicas: Optional[int] = None,
    interpret: bool = False,
    live=None,
    risk=None,
):
    """Replica-batched greedy pass: ``R`` Monte-Carlo replicas, one kernel.

    Equivalent to ``vmap(cost_aware_pallas)`` over the replica axis of
    ``avail_r`` with the task stream shared — the ensemble-bench shape —
    but advancing ``block_replicas`` replicas per VPU issue instead of
    one (see :func:`_greedy_body_batched`).  Returns ``([R, T] i32
    placements, [R, H, 4] new availability)``.

    ``block_replicas`` trades VPU utilization against VMEM: measured on
    the v5e at (T=2048, H=512, R=1024), throughput rises monotonically
    8→512 (13.8 → 31 M decisions/s; the vmapped scan kernel: 11 M) and
    1024 fails Mosaic compilation (the ``[4·RB, Hp]`` working set plus
    scratch outgrows VMEM).  The default (``None``) picks the largest
    known-good block for ``R`` — ``min(512, R rounded up to a sublane
    multiple)``; placements are bit-identical to the scan kernel at
    every block size (hardware-verified 64/128/256/512, both bin-pack
    modes).

    Sharp edge for callers at large RB: keep BOTH returned arrays live
    through ``jit``.  If the availability output is dead code, XLA
    allocates the unused pallas result on the scoped-VMEM stack instead
    of HBM — measured +4 MB at (RB=512, Hp=512), pushing the 16 MB
    scoped limit over and failing the compile, while the both-outputs
    form compiles and runs (see tools/tpu_validate.py).
    """
    R, H = avail_r.shape[0], avail_r.shape[1]
    T = demands.shape[0]
    if T == 0 or R == 0:
        return jnp.zeros((R, T), jnp.int32), avail_r
    avail_in = avail_r
    if live is not None:
        # Quarantine mask ([H] bool, False = excluded): masked hosts get
        # the same -1e30 sentinel as PADDING lanes, so no fit test in
        # the kernel body can select them — the Pallas analog of the
        # scan kernels' fused ``live`` mask.  Their true availability is
        # restored on the output below (a tick that cannot place on a
        # host cannot change its capacity), keeping the availability
        # result bit-identical to ``cost_aware_kernel(..., live=...)``.
        avail_r = jnp.where(live[None, :, None], avail_r, _NEG)
    Hp = _round_up(max(H, 128), 128)
    chunk = min(256, _round_up(T, 8))
    # Per-replica VMEM bytes of the block's working set: two [4·RB, Hp]
    # avail blocks + two [RB, Hp] scratches (40·Hp) and the [RB, chunk]
    # placement block (8·chunk, both copies); budgeted against
    # ``infra.roofline.PALLAS_VMEM_BUDGET_BYTES`` (deliberate headroom
    # under the ``V5E_SCOPED_VMEM_BYTES`` Mosaic limit).  The phase-1
    # score tiles are replica-independent fixed overhead: two
    # [chunk, Hp] streamed inputs, double-buffered by the pipeline
    # (16·chunk·Hp bytes), subtracted from the budget before the
    # replica split.  The byte formulas here are recomputed from the
    # BlockSpec shapes by the ``pallas-budget`` static pass — editing
    # the specs without these formulas fails ``make lint``.
    rb_bytes = 40 * Hp + 8 * chunk
    tile_bytes = 16 * chunk * Hp
    assert PALLAS_VMEM_BUDGET_BYTES < V5E_SCOPED_VMEM_BYTES
    vmem_budget = max(PALLAS_VMEM_BUDGET_BYTES - tile_bytes, rb_bytes * 8)
    if block_replicas is None:
        # VMEM budget first: cap RB so the working set stays within
        # budget at ANY host count (the fixed 512 cap is only proven at
        # Hp ≤ 512).
        vmem_cap = vmem_budget // rb_bytes
        rb_max = max(8, min(_MAX_BLOCK_REPLICAS, vmem_cap // 8 * 8))
        # Then fewest blocks, sized to split R evenly: picking the max
        # block outright would round R up to a multiple of it (e.g.
        # R=520 → Rp=1024, ~2× padded work); even splitting keeps
        # replica padding under one sublane tile per block.
        n_blocks = -(-R // rb_max)
        block_replicas = _round_up(-(-R // n_blocks), 8)
    elif block_replicas < 1:
        raise ValueError(f"block_replicas must be >= 1, got {block_replicas}")
    elif not interpret:
        # An explicit block size on the REAL Mosaic path must satisfy the
        # same constraints the auto default guarantees, or it fails
        # compilation with an opaque Mosaic error far from the cause.
        # RB ≤ 8 is left as-is (sublane-padded; RB=1 is the
        # hardware-proven cost_aware_pallas wrapper case) — larger
        # non-multiples of 8 are rounded up to a sublane multiple, which
        # cannot change results (placements are bit-identical across
        # block sizes by construction; padding replicas are sliced off).
        if block_replicas > 8:
            block_replicas = _round_up(block_replicas, 8)
        # One sublane tile (RB ≤ 8) is exempt, exactly like the auto
        # path's max(8, ...) floor: there is no smaller block to fall
        # back to, so the budget is best-effort at extreme host counts.
        if block_replicas > 8 and block_replicas * rb_bytes > vmem_budget:
            raise ValueError(
                f"block_replicas={block_replicas} needs "
                f"~{block_replicas * rb_bytes / 1e6:.1f} MB of scoped VMEM at "
                f"Hp={Hp} (budget {vmem_budget / 1e6:.1f} MB of the "
                f"{V5E_SCOPED_VMEM_BYTES / 1e6:.0f} MB limit after the "
                "phase-1 score tiles) and would fail Mosaic compilation; "
                "pass block_replicas=None for the largest known-good block"
            )
    RB = block_replicas
    Tp = _round_up(T, chunk)
    Rp = _round_up(R, RB)
    Rb = Rp // RB
    f32 = jnp.float32

    # [Rb, 4*RB, Hp] resource-major replica slabs; replica and host
    # padding lanes carry avail = -1e30 so no fit test can select them.
    a = jnp.transpose(avail_r.astype(f32), (0, 2, 1))  # [R, 4, H]
    a = jnp.pad(a, ((0, Rp - R), (0, 0), (0, Hp - H)), constant_values=_NEG)
    a = jnp.transpose(a.reshape(Rb, RB, 4, Hp), (0, 2, 1, 3)).reshape(
        Rb, 4 * RB, Hp
    )

    def pad_t(x, fill, dt):
        x = x.astype(dt).reshape(T, -1).T
        return jnp.pad(x, ((0, 0), (0, Tp - T)), constant_values=fill)

    dem = pad_t(demands, 0.0, f32)
    val = pad_t(valid, 0, jnp.int32)
    ng = pad_t(new_group, 0, jnp.int32)

    # Phase 1 (shared with ops/kernels.py): [Z, H] round-trip tables, then
    # ONE fused batched gather to per-task [T, H] score rows — hoisted out
    # of the greedy pass entirely and streamed as tiles.
    hz = host_zone.astype(jnp.int32)
    cost_rt = (cost_zz[:, hz] + cost_zz[hz, :].T).astype(f32)
    bw_rt = (bw_zz[:, hz] + bw_zz[hz, :].T).astype(f32)
    az = anchor_zone.astype(jnp.int32)
    cost_rows = jnp.pad(
        cost_rt[az], ((0, Tp - T), (0, Hp - H))
    )  # [Tp, Hp]; pad tasks are invalid, pad hosts unselectable
    bw_rows = jnp.pad(
        bw_rt[az], ((0, Tp - T), (0, Hp - H)), constant_values=1.0
    )
    base_row = jnp.pad(
        base_task_counts.astype(f32).reshape(1, H), ((0, 0), (0, Hp - H))
    )
    has_risk = risk is not None
    if has_risk:
        # [1, Hp] risk row; padding lanes get 0 — they are unselectable
        # anyway (avail = -1e30 fails every fit test).
        risk_row = jnp.pad(
            risk.astype(f32).reshape(1, H), ((0, 0), (0, Hp - H))
        )

    grid = (Rb, Tp // chunk)
    smem_chunk = lambda w: pl.BlockSpec(  # noqa: E731
        (w, chunk), lambda rb, tc: (0, tc), memory_space=pltpu.SMEM
    )
    whole = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda rb, tc: tuple(0 for _ in shape), memory_space=pltpu.VMEM
    )
    placements, avail_out = pl.pallas_call(
        _greedy_body_batched(
            first_fit=bin_pack == "first-fit",
            sort_hosts=sort_hosts,
            host_decay=host_decay,
            chunk=chunk,
            RB=RB,
            Hp=Hp,
            has_risk=has_risk,
        ),
        grid=grid,
        in_specs=[
            smem_chunk(4),  # demands
            smem_chunk(1),  # valid
            smem_chunk(1),  # new_group
            pl.BlockSpec(  # phase-1 cost-row tiles, streamed by chunk
                (chunk, Hp), lambda rb, tc: (tc, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(  # phase-1 bw-row tiles
                (chunk, Hp), lambda rb, tc: (tc, 0),
                memory_space=pltpu.VMEM,
            ),
            whole((1, Hp)),  # base counts
        ] + ([whole((1, Hp))] if has_risk else []) + [  # risk row
            pl.BlockSpec(
                (1, 4 * RB, Hp), lambda rb, tc: (rb, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, RB, chunk), lambda rb, tc: (rb, 0, tc),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 4 * RB, Hp), lambda rb, tc: (rb, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((Rb, RB, Tp), jnp.int32),
            jax.ShapeDtypeStruct((Rb, 4 * RB, Hp), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((RB, Hp), f32),  # frozen group scores
            pltpu.VMEM((RB, Hp), f32),  # best-fit live counters
        ],
        interpret=interpret,
    )(
        dem, val, ng, cost_rows, bw_rows, base_row,
        *((risk_row,) if has_risk else ()), a,
    )

    placements = placements.reshape(Rp, Tp)[:R, :T]
    avail_out = jnp.transpose(
        avail_out.reshape(Rb, 4, RB, Hp), (0, 2, 1, 3)
    ).reshape(Rp, 4, Hp)[:R, :, :H]
    avail_out = jnp.transpose(avail_out, (0, 2, 1)).astype(avail_in.dtype)
    if live is not None:
        avail_out = jnp.where(live[None, :, None], avail_out, avail_in)
    return placements, avail_out
