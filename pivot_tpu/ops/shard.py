"""Host-axis-sharded placement kernels — pod-scale clusters over a mesh.

Everything before this module fits one chip: H ≤ 1024 hosts, every state
array device-resident on a single device.  Borg-scale cells (Verma et
al., PAPERS.md) are 10k–100k hosts — working sets no single chip
comfortably holds, and per-step host-axis compute a single core pays
alone.  This module partitions the **host axis** of the placement hot
path over the ``host`` axis of a ``jax.sharding.Mesh``
(``parallel/mesh.py``): the ``[H, 4]`` availability carry, the
live/quarantine mask, the phase-1 score rows, and the host-decay
counters all live shard-resident (``[H/S, ...]`` per device), and each
sequential placement step runs its O(H) fit/score work shard-parallel
with a tiny O(S) collective to pick the winner.

**The two-stage argmin.**  The single-device kernels select a host with
``jnp.argmin(where(fit, score, inf))`` — minimum score, ties to the
LOWEST host index (the shared tie rule across numpy policies and
kernels).  Sharded, the selection runs in two stages:

  1. every shard takes a **local argmin** over its block (ties → lowest
     local index) and forms the pair ``(score_min, local_argmin +
     shard_offset)``;
  2. an ``all_gather`` of the S pairs + an argmin over the gathered
     scores (ties → lowest shard index) picks the winner.

Because the mesh shards the host axis into *contiguous index blocks*
(shard s owns hosts ``[s·H/S, (s+1)·H/S)``), lower shard ⇒ strictly
lower global indices, so stage 2's first-occurrence tie-break composes
with stage 1's into exactly "minimum ``(score, global_host_index)``" —
the flat argmin's rule, preserved bit for bit.  The score elements
themselves are computed per host by the SAME shared helpers the
single-device kernels use (``ops/kernels.py`` ``_ca_phase1`` /
``_ca_group_score`` / ``_ca_best_fit_score`` / ``_fits`` / ``_norms``),
each depending only on its own host column, so sharding cannot move a
rounding.  The opportunistic arm's k-th-fitting-host rank is an integer
cumsum, decomposed as local cumsum + exclusive prefix of shard totals —
exact.  ``first-fit``'s lowest-index-fit is a ``pmin`` over per-shard
first-fit candidates.  See docs/ARCHITECTURE.md ("Sharded placement")
for the full tie-break argument.

**Phase-2 modes.**  ``phase2 in ("auto", "scan", "slim")`` all resolve
to the per-step pass: the slim-style early-exit loop (stop at the last
valid task) with one two-stage reduce per task.  ``phase2 = int C``
selects the **sharded speculative chunk commit**: the per-step pass's
collective rendezvous is the whole per-step cost once the local blocks
are small, so the chunked pass amortizes it to O(1) batched reduces per
C-task chunk — speculate every position against chunk-entry state,
replay the exact carry fold shard-locally, re-decide all C positions
against their exact prefixes in one gathered reduce, commit through the
first disagreement (``kernels._speculate_commit``'s induction, so
placements and availability cannot differ from the per-step pass).
Every mode is bit-identical to every single-device mode;
``tests/test_shard.py`` sweeps the parity against each.

**Fused spans.**  :func:`sharded_fused_tick_run` is the host-sharded
twin of ``ops.tickloop.fused_tick_run``: K simulator ticks as one
device program with the sharded ``[H/S, 4]`` availability carry (and the
sharded host-decay counters) staying device-resident between ticks.
The slot-axis algebra — ready-batch assembly, kernel-stream ordering,
wait-stack rebuild — is imported from ``ops.tickloop`` verbatim and
computed redundantly on every shard (it is O(B), replicated state), so
the two drivers cannot drift.

**2-D mesh: batching × sharding composed (round 17).**  Every sharded
form also has a ``[G]``-batched twin (``*_kernel_sharded_batched``,
:func:`sharded_batched_tick_run`) serving G coalesced dispatches on a
``replica × host`` mesh: stacked operands shard their leading [G] run
axis over ``replica`` and their host axis over ``host`` (e.g. stacked
availability ``[G, H, 4]`` is ``P("replica", "host", None)``; stacked
span risk rows ``[G, K, H]`` are ``P("replica", None, "host")``), and
the program is ``shard_map(vmap(per-shard body))`` — the SAME per-shard
bodies the 1-D twins run, vmapped over the local [G/R] rows.  Rows
never communicate over ``replica`` (each is an independent run), and
the ``host``-axis collectives batch per row, so the existing two-stage
tie-break and chunk-commit proofs compose under vmap unchanged: each
row's op sequence is the 1-D sharded program's, which is the flat
program's.  ``DispatchBatcher`` (``sched/batch.py``) builds these
through :func:`batched_sharded_call` whenever its mesh carries a
non-trivial host axis — this is what lifts the old batching/sharding
mutual exclusion in ``sched/tpu.py``.

Layout contract: ``H`` must divide evenly by the mesh's host-axis size
(pad the cluster with DOWN-sentinel hosts otherwise — a ``-1``
availability row can never be selected).  All kernels are cached per
(mesh, static config) and are bit-identical to the single-device
oracles on every backend — the bar ``tests/test_shard.py`` holds them
to at H=1024 on the forced 8-device CPU mesh.

Host-sync discipline: no host fetch may appear in any sharded pass or
the sharded span driver — enforced by ``tools/hotpath_lint.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

try:  # jax >= 0.6 top-level export
    from jax import shard_map as _shard_map
except ImportError:  # the 0.4.x layout this image ships
    from jax.experimental.shard_map import shard_map as _shard_map
from jax.sharding import PartitionSpec as P

from pivot_tpu.ops.kernels import (
    _apply_live,
    _bump_count,
    _ca_best_fit_score,
    _ca_group_score,
    _ca_phase1,
    _effective_len,
    _fits,
    _norms,
    _pad_chunk,
    _place,
    _resolve_phase2,
    _risk_key,
    _risk_score,
)
from pivot_tpu.ops.kernels import (
    best_fit_kernel,
    cost_aware_kernel,
    first_fit_kernel,
    opportunistic_kernel,
)
from pivot_tpu.ops.tickloop import (
    ResidentCarry,
    SpanResult,
    _resident_carry_init_impl,
    _span_group_entries,
    _span_ready_batch,
    _span_requeue,
    _span_stream_order,
    fused_tick_run,
    resident_carry_export,
    resident_carry_init,
)
from pivot_tpu.parallel.mesh import host_axis_size

__all__ = [
    "DEAD_AVAIL",
    "HOST_AXIS",
    "REPLICA_AXIS",
    "batched_sharded_call",
    "best_fit_kernel_sharded",
    "best_fit_kernel_sharded_batched",
    "check_row_divisibility",
    "cost_aware_kernel_sharded",
    "cost_aware_kernel_sharded_batched",
    "elastic_fold_carry",
    "elastic_host_extent",
    "elastic_pad_rows",
    "elastic_pad_state",
    "elastic_trim_rows",
    "first_fit_kernel_sharded",
    "first_fit_kernel_sharded_batched",
    "mesh_is_2d",
    "mesh_shape_ladder",
    "next_ladder_shape",
    "opportunistic_kernel_sharded",
    "opportunistic_kernel_sharded_batched",
    "row_sharding",
    "sharded_batched_tick_run",
    "sharded_fused_tick_run",
    "sharded_resident_carry_init",
    "sharded_resident_span_run",
    "sharded_twin_of",
]

#: Mesh axis the host dimension shards over (``parallel.mesh.build_mesh``
#: axis_names convention).
HOST_AXIS = "host"

#: Mesh axis row/replica batches shard over (``parallel.mesh.replica_mesh``
#: convention — ``sharded_rollout``, the sweep shardings, and the policy-
#: search fitness rows all partition their leading batch axis here).
REPLICA_AXIS = "replica"


def row_sharding(mesh):
    """``NamedSharding`` partitioning a leading row/batch axis over the
    mesh's :data:`REPLICA_AXIS` — the one definition shared by the
    ensemble row consumers (``search/fitness.py``'s candidate rows; the
    same spec `sharded_rollout` and ``sweep_out_shardings`` spell out
    longhand), so "how rows shard" cannot drift between them."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, P(REPLICA_AXIS))


def check_row_divisibility(mesh, n_rows: int) -> None:
    """Raise unless ``n_rows`` splits into equal contiguous blocks over
    the mesh's replica axis (``NamedSharding`` partitions the leading
    axis that way; a ragged split fails deep inside XLA otherwise)."""
    n = int(mesh.shape[REPLICA_AXIS])
    if n < 1:
        raise ValueError("mesh has an empty replica axis")
    if n_rows % n:
        raise ValueError(
            f"{n_rows} rows do not divide over the mesh's {n} replica "
            f"shards — round the population/replica product up to a "
            f"multiple of {n}"
        )

#: Integer sentinel above any host index — the "no candidate" rung of the
#: pmin reduces (1 << 30 like the kernels' fill-capacity clip).
_NO_HOST = 1 << 30


def _check_host_axis(H: int, mesh) -> int:
    n = host_axis_size(mesh)
    if H % n:
        raise ValueError(
            f"host axis H={H} does not divide over the mesh's {n} host "
            f"shards — pad the cluster with DOWN-sentinel hosts (a -1 "
            f"availability row is never selected) to a multiple of {n}"
        )
    return n


def mesh_is_2d(mesh) -> bool:
    """True when ``mesh`` composes both program axes: a non-trivial
    ``host`` axis next to the ``replica`` axis (the ``build_hybrid_mesh``
    / ``build_mesh(host_parallel=…)`` layout).  The batcher consults
    this to decide between the plain ``vmap`` program (replica-only
    mesh) and the 2-D ``shard_map(vmap(...))`` program."""
    return (
        HOST_AXIS in mesh.shape and REPLICA_AXIS in mesh.shape
        and int(mesh.shape[HOST_AXIS]) > 1
    )


def _check_g_axis(mesh, G: int) -> None:
    n = int(mesh.shape[REPLICA_AXIS])
    if G % n:
        raise ValueError(
            f"batch axis G={G} does not divide over the mesh's {n} "
            f"replica shards — the batcher's group bucket must be a "
            f"multiple of the replica axis (sched.batch.group_bucket)"
        )


def _g_spec(spec: P) -> P:
    """Prepend the replica axis to an operand spec: the stacked [G]
    leading axis shards over ``replica``, everything after keeps the
    1-D form's layout."""
    return P(REPLICA_AXIS, *tuple(spec))


def _sharded_mode(phase2):
    """Resolve ``phase2`` for the sharded passes: the scan/slim family
    collapses to the per-step pass ("step"); an int chunk size selects
    the sharded chunk commit (module docstring)."""
    mode = _resolve_phase2(phase2)
    return mode if isinstance(mode, int) else "step"


# ---------------------------------------------------------------------------
# Two-stage reduces (the collective core — every helper here runs INSIDE a
# shard_map region and is a hotpath-lint target)
# ---------------------------------------------------------------------------


def _shard_offset(h_local: int):
    """This shard's first global host index (contiguous block layout)."""
    return (lax.axis_index(HOST_AXIS) * h_local).astype(jnp.int32)


def _two_stage_argmin(masked, any_fit, offset):
    """Exact decomposition of ``jnp.argmin(masked_global)`` + ``ok``.

    Stage 1: local argmin over this shard's block (ties → lowest local
    index).  Stage 2: all-gather the S ``(min_score, global_index)``
    pairs and argmin over the scores — first occurrence wins, i.e. the
    lowest shard, whose candidate has the lowest global index among the
    tied shard minima (contiguous blocks).  Composition = minimum
    ``(score, global_host_index)``, the flat argmin's tie rule, exactly.
    ``ok`` is the global fit flag (any shard saw a fit); ``h`` is 0 when
    nothing fits, mirroring ``argmin`` of an all-inf row.
    """
    li = jnp.argmin(masked).astype(jnp.int32)
    lmin = masked[li]
    # ONE packed gather per step, not three: on a sequential chain the
    # collective's cost is per-rendezvous latency, not bytes, so the
    # (score, index, any-fit) triple rides one [3] vector.  The index
    # converts through the score dtype exactly (f32 holds integers to
    # 2^24 — far beyond any host count this repo targets; f64 beyond
    # 2^53), asserted by the parity suite.
    packed = jnp.stack([
        lmin,
        (li + offset).astype(masked.dtype),
        any_fit.astype(masked.dtype),
    ])
    g = lax.all_gather(packed, HOST_AXIS)       # [S, 3]
    s = jnp.argmin(g[:, 0])
    ok = jnp.any(g[:, 2] > 0)
    return jnp.where(ok, g[s, 1].astype(jnp.int32), 0), ok


def _first_index_of(fit, offset):
    """Lowest GLOBAL index with ``fit`` True — the sharded form of
    ``argmax(fit)`` + ``any(fit)`` (first-fit's selection): per-shard
    first fit, then a ``pmin`` over the global candidates."""
    lh = jnp.argmax(fit).astype(jnp.int32)
    cand = jnp.where(jnp.any(fit), lh + offset,
                     jnp.asarray(_NO_HOST, jnp.int32))
    h = lax.pmin(cand, HOST_AXIS)
    ok = h < _NO_HOST
    return jnp.where(ok, h, 0), ok


def _opportunistic_pick(fit, u_j, offset, n_shards):
    """The k-th fitting host (k = ⌊u·n_fit⌋) under sharding: global
    ``n_fit`` and the 1-based cumulative rank decompose as local integer
    cumsums plus the exclusive prefix of shard totals — exact.  The
    (unique) matching host reduces by pmin like first-fit."""
    c = jnp.sum(fit.astype(jnp.int32))
    counts = lax.all_gather(c, HOST_AXIS)       # [S]
    n_fit = jnp.sum(counts)
    my = lax.axis_index(HOST_AXIS)
    prefix = jnp.sum(
        jnp.where(jnp.arange(n_shards) < my, counts, 0)
    )
    k = jnp.minimum((u_j * n_fit).astype(jnp.int32), n_fit - 1)
    rank = jnp.cumsum(fit.astype(jnp.int32)) + prefix
    match = fit & (rank == k + 1)
    lh = jnp.argmax(match).astype(jnp.int32)
    cand = jnp.where(jnp.any(match), lh + offset,
                     jnp.asarray(_NO_HOST, jnp.int32))
    h = lax.pmin(cand, HOST_AXIS)
    ok = n_fit > 0
    return jnp.where(ok, h, 0), ok


def _risk_restrict_sharded(fit, risk):
    """Sharded opportunistic risk rule (round 11, ``infra/market.py``):
    narrow ``fit`` to the GLOBAL minimum-risk tier of fitting hosts.
    ``risk`` is this shard's [H/S] block; one ``pmin`` finds the global
    tier bound.  No-op when nothing fits anywhere — every shard's masked
    min stays +inf, which no finite risk equals.  Mirrors the
    single-device ``kernels._risk_restrict`` exactly (equality against
    the same float value, computed by the same min tree shape per
    shard)."""
    if risk is None:
        return fit
    local = jnp.min(_risk_key(fit, risk))
    rmin = lax.pmin(local, HOST_AXIS)
    return fit & (risk == rmin)


def _risk_restrict_sharded_rows(fit_rows, risk):
    """Batched :func:`_risk_restrict_sharded`: C rows, one [C] pmin."""
    if risk is None:
        return fit_rows
    local = jnp.min(_risk_key(fit_rows, risk[None]), axis=1)
    rmin = lax.pmin(local, HOST_AXIS)
    return fit_rows & (risk[None] == rmin[:, None])


def _place_local(avail, demand, h, ok, offset):
    """One shard's slice of the global ``_place``: decrement the winning
    row only on the shard that owns it — the same arithmetic on the same
    element the flat update performs; every other shard is a no-op."""
    h_local = h - offset
    local = ok & (h_local >= 0) & (h_local < avail.shape[0])
    return _place(avail, demand, jnp.where(local, h_local, 0), local)


def _bump_local(counts, h, ok, offset):
    """Shard-local slice of ``_bump_count`` (best-fit live decay)."""
    h_local = h - offset
    local = ok & (h_local >= 0) & (h_local < counts.shape[0])
    return _bump_count(counts, jnp.where(local, h_local, 0), local)


def _two_stage_argmin_rows(masked_rows, any_rows, offset):
    """Batched :func:`_two_stage_argmin`: C independent argmin rows
    reduced in ONE packed gather ([S, C, 3]) — the collective backbone
    of the sharded chunk commit, where per-task rendezvous would eat the
    whole weak-scaling budget.  Exact per row by the same tie-break
    composition."""
    C = masked_rows.shape[0]
    li = jnp.argmin(masked_rows, axis=1).astype(jnp.int32)      # [C]
    lmin = jnp.take_along_axis(masked_rows, li[:, None], axis=1)[:, 0]
    packed = jnp.stack([
        lmin,
        (li + offset).astype(masked_rows.dtype),
        any_rows.astype(masked_rows.dtype),
    ], axis=1)                                                  # [C, 3]
    g = lax.all_gather(packed, HOST_AXIS)                       # [S, C, 3]
    s = jnp.argmin(g[:, :, 0], axis=0)                          # [C]
    ok = jnp.any(g[:, :, 2] > 0, axis=0)
    h = g[s, jnp.arange(C), 1].astype(jnp.int32)
    return jnp.where(ok, h, 0), ok


def _first_index_of_rows(fit_rows, offset):
    """Batched :func:`_first_index_of`: C first-fit rows in one pmin."""
    lh = jnp.argmax(fit_rows, axis=1).astype(jnp.int32)
    cand = jnp.where(jnp.any(fit_rows, axis=1), lh + offset,
                     jnp.asarray(_NO_HOST, jnp.int32))
    h = lax.pmin(cand, HOST_AXIS)
    ok = h < _NO_HOST
    return jnp.where(ok, h, 0), ok


def _opportunistic_pick_rows(fit_rows, u_c, offset, n_shards):
    """Batched :func:`_opportunistic_pick`: one [C]-row gather for the
    shard fit totals + one pmin for the winners."""
    C = fit_rows.shape[0]
    c = jnp.sum(fit_rows.astype(jnp.int32), axis=1)             # [C]
    counts = lax.all_gather(c, HOST_AXIS)                       # [S, C]
    n_fit = jnp.sum(counts, axis=0)
    my = lax.axis_index(HOST_AXIS)
    prefix = jnp.sum(
        jnp.where((jnp.arange(n_shards) < my)[:, None], counts, 0), axis=0
    )
    k = jnp.minimum((u_c * n_fit).astype(jnp.int32), n_fit - 1)
    rank = jnp.cumsum(fit_rows.astype(jnp.int32), axis=1) + prefix[:, None]
    match = fit_rows & (rank == (k + 1)[:, None])
    lh = jnp.argmax(match, axis=1).astype(jnp.int32)
    cand = jnp.where(jnp.any(match, axis=1), lh + offset,
                     jnp.asarray(_NO_HOST, jnp.int32))
    h = lax.pmin(cand, HOST_AXIS)
    ok = n_fit > 0
    return jnp.where(ok, h, 0), ok


# ---------------------------------------------------------------------------
# Sharded sequential passes (run INSIDE shard_map; avail is the local block)
# ---------------------------------------------------------------------------


def _carry_free_sharded_pass(avail, demands, valid, n_eff, decide):
    """Sharded analog of ``kernels._slim_drive``: early-exit sequential
    loop over tasks, ``decide(avail, j, demand) -> (h_global, ok)``
    already globally reduced; the placement write and the availability
    fold follow the slim driver's protocol exactly."""
    B = demands.shape[0]
    offset = _shard_offset(avail.shape[0])

    def body(st):
        j, placements, avail = st
        demand = demands[j]
        h, ok = decide(avail, j, demand)
        ok = ok & (j < n_eff)
        avail = _place_local(avail, demand, h, ok, offset)
        jj = jnp.where(j < n_eff, j, B)
        placements = placements.at[jj].set(
            jnp.where(ok, h, -1).astype(jnp.int32), mode="drop"
        )
        return j + 1, placements, avail

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B,), -1, jnp.int32), avail)
    _, placements, avail = lax.while_loop(lambda st: st[0] < n_eff, body, st0)
    return placements, avail


def _opportunistic_sharded_pass(avail, demands, valid, uniforms, n_eff,
                                n_shards, risk=None):
    offset = _shard_offset(avail.shape[0])

    def decide(avail, j, demand):
        fit = _fits(avail, demand, strict=False) & valid[j]
        fit = _risk_restrict_sharded(fit, risk)
        return _opportunistic_pick(fit, uniforms[j], offset, n_shards)

    return _carry_free_sharded_pass(avail, demands, valid, n_eff, decide)


def _first_fit_sharded_pass(avail, demands, valid, n_eff, strict, risk=None):
    offset = _shard_offset(avail.shape[0])

    def decide(avail, j, demand):
        fit = _fits(avail, demand, strict) & valid[j]
        if risk is None:
            return _first_index_of(fit, offset)
        # Risk-aware first fit: lexicographic (risk, global index) — the
        # two-stage argmin's composed tie rule gives it exactly (module
        # docstring), mirroring the flat kernels' masked argmin of risk.
        return _two_stage_argmin(_risk_key(fit, risk), jnp.any(fit), offset)

    return _carry_free_sharded_pass(avail, demands, valid, n_eff, decide)


def _best_fit_sharded_pass(avail, demands, valid, n_eff, risk=None):
    offset = _shard_offset(avail.shape[0])
    big = jnp.asarray(jnp.inf, avail.dtype)

    def decide(avail, j, demand):
        fit = _fits(avail, demand, strict=True) & valid[j]
        residual = _risk_score(_norms(avail - demand), risk)
        return _two_stage_argmin(
            jnp.where(fit, residual, big), jnp.any(fit), offset
        )

    return _carry_free_sharded_pass(avail, demands, valid, n_eff, decide)


# ---------------------------------------------------------------------------
# Sharded speculative chunk commit (phase2 = int C)
#
# The per-step passes above pay one collective rendezvous PER TASK — exact,
# but on a sequential chain the rendezvous latency is the whole per-step
# cost at scale.  The chunked pass amortizes it to O(1) collectives per
# C-task chunk using the SAME exactness induction as the single-device
# speculative chunk commit (``kernels._speculate_commit``):
#
#   1. speculate every chunk position against CHUNK-ENTRY state (one
#      batched two-stage reduce — speculation quality only moves the
#      commit boundary, never a placement);
#   2. replay the exact [H/S, 4] carry fold over the speculated
#      placements SHARD-LOCALLY (each shard folds only its own rows — the
#      same ``_place`` ops as the flat fold, zero collectives);
#   3. re-decide every position against its exact prefix state in ONE
#      batched two-stage reduce;
#   4. commit through the first speculation/re-decision disagreement.
#
# A committed position's decision is always the re-decision under the
# exact prefix fold, so placements and availability are bit-identical to
# the per-step pass (and the flat oracles) by the same induction.
# ---------------------------------------------------------------------------


def _sharded_chunk_drive(avail, demands, valid, n_eff, C, decide_rows,
                         offset):
    """Sharded analog of ``kernels._chunk_drive`` for the carry-free
    policies.  ``decide_rows(a_rows [C, H/S, 4], dem_c, valid_c, pos)
    -> (h [C] global, ok [C])`` must be the exact batched per-position
    decision (one collective inside); speculation calls it on
    chunk-entry rows, the recheck on the exact prefix rows."""
    B = demands.shape[0]
    demP, validP = _pad_chunk(demands, C), _pad_chunk(valid, C)
    idx = jnp.arange(C, dtype=jnp.int32)

    def body(st):
        pos, placements, avail = st
        dem_c = lax.dynamic_slice_in_dim(demP, pos, C)
        valid_c = lax.dynamic_slice_in_dim(validP, pos, C)
        h_s, ok_s = decide_rows(
            jnp.broadcast_to(avail, (C,) + avail.shape), dem_c, valid_c, pos
        )
        ok_s = ok_s & valid_c
        h_s = jnp.where(ok_s, h_s, 0)

        def substep(a, x):
            h, ok, d = x
            return _place_local(a, d, h, ok, offset), a

        _, a_pre = lax.scan(substep, avail, (h_s, ok_s, dem_c))
        h_c, ok_c = decide_rows(a_pre, dem_c, valid_c, pos)
        ok_c = ok_c & valid_c
        p_c = jnp.where(ok_c, h_c, -1).astype(jnp.int32)
        p_s = jnp.where(ok_s, h_s, -1).astype(jnp.int32)
        fc = jnp.min(jnp.where(p_c != p_s, idx, C))
        n_commit = jnp.minimum(fc + 1, C)
        placements = lax.dynamic_update_slice_in_dim(placements, p_c, pos, 0)
        cm = jnp.minimum(n_commit - 1, C - 1)
        new_avail = _place_local(
            a_pre[cm], dem_c[cm], h_c[cm], ok_c[cm], offset
        )
        return pos + n_commit, placements, new_avail

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B + C,), -1, jnp.int32),
           avail)
    _, placements, avail = lax.while_loop(lambda st: st[0] < n_eff, body, st0)
    return placements[:B], avail


def _opportunistic_sharded_chunk(avail, demands, valid, uniforms, n_eff, C,
                                 n_shards, risk=None):
    offset = _shard_offset(avail.shape[0])
    uP = _pad_chunk(uniforms, C)

    def decide_rows(a_rows, dem_c, valid_c, pos):
        u_c = lax.dynamic_slice_in_dim(uP, pos, C)
        fit = jnp.all(a_rows >= dem_c[:, None, :], axis=2) & valid_c[:, None]
        fit = _risk_restrict_sharded_rows(fit, risk)
        return _opportunistic_pick_rows(fit, u_c, offset, n_shards)

    return _sharded_chunk_drive(
        avail, demands, valid, n_eff, C, decide_rows, offset
    )


def _first_fit_sharded_chunk(avail, demands, valid, n_eff, C, strict,
                             risk=None):
    offset = _shard_offset(avail.shape[0])

    def decide_rows(a_rows, dem_c, valid_c, pos):
        fit = (
            jnp.all(a_rows > dem_c[:, None, :], axis=2) if strict
            else jnp.all(a_rows >= dem_c[:, None, :], axis=2)
        )
        fit = fit & valid_c[:, None]
        if risk is None:
            return _first_index_of_rows(fit, offset)
        return _two_stage_argmin_rows(
            _risk_key(fit, risk[None]), jnp.any(fit, axis=1), offset
        )

    return _sharded_chunk_drive(
        avail, demands, valid, n_eff, C, decide_rows, offset
    )


def _best_fit_sharded_chunk(avail, demands, valid, n_eff, C, risk=None):
    offset = _shard_offset(avail.shape[0])
    big = jnp.asarray(jnp.inf, avail.dtype)

    def decide_rows(a_rows, dem_c, valid_c, pos):
        fit = jnp.all(a_rows > dem_c[:, None, :], axis=2) & valid_c[:, None]
        residual = _risk_score(_norms(a_rows - dem_c[:, None, :]), risk)
        return _two_stage_argmin_rows(
            jnp.where(fit, residual, big), jnp.any(fit, axis=1), offset
        )

    return _sharded_chunk_drive(
        avail, demands, valid, n_eff, C, decide_rows, offset
    )


def _cost_aware_sharded_pass(
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    n_eff,
    bin_pack,
    sort_hosts,
    host_decay,
    risk=None,
):
    """Sharded cost-aware sequential pass — the slim body of
    ``kernels.cost_aware_impl`` with every host-row expression evaluated
    on the local block through the SHARED phase-1/score helpers and the
    argmin swapped for the two-stage reduce.  ``host_zone``,
    ``base_task_counts``, and the optional ``risk`` vector are this
    shard's blocks (the shared risk rules: ``score += risk``; the
    ``sort_hosts=False`` index order becomes lexicographic
    (risk, global index) via the two-stage argmin)."""
    B = demands.shape[0]
    Hl = avail.shape[0]
    offset = _shard_offset(Hl)
    first_fit = bin_pack == "first-fit"
    big = jnp.asarray(jnp.inf, avail.dtype)
    dtype = avail.dtype
    base_counts = base_task_counts.astype(dtype)
    track_extra = (not first_fit) and host_decay

    cost_rt, bw_rt, num_rt = _ca_phase1(
        cost_zz, bw_zz, host_zone, base_counts,
        first_fit and sort_hosts and host_decay,
    )
    # Identity host order = the GLOBAL index as a float (exact for any
    # plausible H) — the sort_hosts=False score row, shard's slice.
    iota_h = jnp.arange(Hl, dtype=dtype) + offset.astype(dtype)

    def body(st):
        j, placements, avail, frozen, extra = st
        demand = demands[j]
        valid_j = valid[j] & (j < n_eff)
        if first_fit:
            if sort_hosts:
                frozen = lax.cond(
                    new_group[j],
                    lambda a: _risk_score(_ca_group_score(
                        num_rt[anchor_zone[j]], a, bw_rt[anchor_zone[j]]
                    ), risk),
                    lambda a: frozen,
                    avail,
                )
            else:
                frozen = jnp.where(
                    new_group[j],
                    iota_h if risk is None else risk,
                    frozen,
                )
            fit = _fits(avail, demand, strict=True) & valid_j
            h, ok = _two_stage_argmin(
                jnp.where(fit, frozen, big), jnp.any(fit), offset
            )
        else:
            decay = (
                jnp.maximum(base_counts + extra.astype(dtype), 1.0)
                if host_decay else 1.0
            )
            per_task = _risk_score(_ca_best_fit_score(
                cost_rt[anchor_zone[j]], avail, demand, decay,
                bw_rt[anchor_zone[j]],
            ), risk)
            fit = _fits(avail, demand, strict=False) & valid_j
            h, ok = _two_stage_argmin(
                jnp.where(fit, per_task, big), jnp.any(fit), offset
            )
        avail = _place_local(avail, demand, h, ok, offset)
        if track_extra:
            extra = _bump_local(extra, h, ok, offset)
        jj = jnp.where(j < n_eff, j, B)
        placements = placements.at[jj].set(
            jnp.where(ok, h, -1).astype(jnp.int32), mode="drop"
        )
        return j + 1, placements, avail, frozen, extra

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B,), -1, jnp.int32),
           avail, jnp.zeros(Hl, dtype), jnp.zeros(Hl, jnp.int32))
    _, placements, avail, _, _ = lax.while_loop(
        lambda st: st[0] < n_eff, body, st0
    )
    return placements, avail


def _cost_aware_sharded_chunk_pass(
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    n_eff,
    C,
    bin_pack,
    sort_hosts,
    host_decay,
    risk=None,
):
    """Sharded cost-aware chunk commit — the chunk body of
    ``kernels.cost_aware_impl`` with shard-local score/fold arithmetic,
    the batched two-stage reduce for both the speculation and the exact
    re-decision, and decide-against-chunk-entry speculation (the fill
    model's job is commit width only; the re-decision referees either
    way).  First-fit keeps the single-device form's segment capping: the
    commit never crosses the chunk's SECOND group entry, and the exact
    entry score row is recomputed from the exact prefix state."""
    B = demands.shape[0]
    Hl = avail.shape[0]
    offset = _shard_offset(Hl)
    first_fit = bin_pack == "first-fit"
    big = jnp.asarray(jnp.inf, avail.dtype)
    dtype = avail.dtype
    base_counts = base_task_counts.astype(dtype)
    track_extra = (not first_fit) and host_decay

    cost_rt, bw_rt, num_rt = _ca_phase1(
        cost_zz, bw_zz, host_zone, base_counts,
        first_fit and sort_hosts and host_decay,
    )
    iota_h = jnp.arange(Hl, dtype=dtype) + offset.astype(dtype)
    demP, validP, ngP = (_pad_chunk(x, C) for x in (demands, valid, new_group))
    azP = _pad_chunk(anchor_zone, C)
    idx = jnp.arange(C, dtype=jnp.int32)

    def body(st):
        pos, placements, avail, frozen, extra = st
        dem_c = lax.dynamic_slice_in_dim(demP, pos, C)
        valid_c = lax.dynamic_slice_in_dim(validP, pos, C)
        ng_c = lax.dynamic_slice_in_dim(ngP, pos, C)
        az_c = lax.dynamic_slice_in_dim(azP, pos, C)

        if first_fit:
            e1 = jnp.min(jnp.where(ng_c, idx, C))
            e2 = jnp.min(jnp.where(ng_c & (idx > e1), idx, C))
            e1c = jnp.minimum(e1, C - 1)
            az_e1 = az_c[e1c]
            seg = (idx >= e1)[:, None]

            def score_rows_for(entry_avail):
                if sort_hosts:
                    row = _risk_score(_ca_group_score(
                        num_rt[az_e1], entry_avail, bw_rt[az_e1]
                    ), risk)
                elif risk is not None:
                    row = risk
                else:
                    row = iota_h
                return jnp.where(seg, row[None], frozen[None]), row

            def decide(a_rows, score_rows):
                fit = jnp.all(a_rows > dem_c[:, None, :], axis=2)
                fit = fit & valid_c[:, None]
                return _two_stage_argmin_rows(
                    jnp.where(fit, score_rows, big),
                    jnp.any(fit, axis=1), offset,
                )

            spec_rows, _ = score_rows_for(avail)
            h_s, ok_s = decide(
                jnp.broadcast_to(avail, (C, Hl, 4)), spec_rows
            )
            commit_cap = e2
        else:
            cost_rows = cost_rt[az_c]                   # [C, H/S]
            bw_rows = bw_rt[az_c]

            def decide_bf(a_rows, ex_rows):
                fit = jnp.all(a_rows >= dem_c[:, None, :], axis=2)
                fit = fit & valid_c[:, None]
                residual = _norms(a_rows - dem_c[:, None, :])
                decay = (
                    jnp.maximum(base_counts[None] + ex_rows.astype(dtype),
                                1.0)
                    if host_decay else 1.0
                )
                cand = _risk_score(
                    cost_rows * residual * decay / bw_rows, risk
                )
                return _two_stage_argmin_rows(
                    jnp.where(fit, cand, big), jnp.any(fit, axis=1), offset
                )

            h_s, ok_s = decide_bf(
                jnp.broadcast_to(avail, (C, Hl, 4)),
                jnp.broadcast_to(extra, (C, Hl)),
            )
            commit_cap = jnp.asarray(C, jnp.int32)
        ok_s = ok_s & valid_c
        h_s = jnp.where(ok_s, h_s, 0)

        # Exact shard-local replay of the carry fold (and the best-fit
        # decay counter) over the speculated placements — PRE-states.
        def substep(carry, x):
            a, ex = carry
            h, ok, d = x
            a2 = _place_local(a, d, h, ok, offset)
            ex2 = _bump_local(ex, h, ok, offset) if track_extra else ex
            return (a2, ex2), (a, ex)

        (_, _), (a_pre, ex_pre) = lax.scan(
            substep, (avail, extra), (h_s, ok_s, dem_c)
        )
        if first_fit:
            check_rows, row_check = score_rows_for(a_pre[e1c])
            h_c, ok_c = decide(a_pre, check_rows)
        else:
            h_c, ok_c = decide_bf(a_pre, ex_pre)
        ok_c = ok_c & valid_c
        p_c = jnp.where(ok_c, h_c, -1).astype(jnp.int32)
        p_s = jnp.where(ok_s, h_s, -1).astype(jnp.int32)
        fc = jnp.min(jnp.where(p_c != p_s, idx, C))
        n_commit = jnp.minimum(jnp.minimum(fc + 1, commit_cap), C)
        n_commit = jnp.maximum(n_commit, 1)
        placements = lax.dynamic_update_slice_in_dim(placements, p_c, pos, 0)
        cm = jnp.minimum(n_commit - 1, C - 1)
        new_avail = _place_local(
            a_pre[cm], dem_c[cm], h_c[cm], ok_c[cm], offset
        )
        new_extra = (
            _bump_local(ex_pre[cm], h_c[cm], ok_c[cm], offset)
            if track_extra else extra
        )
        if first_fit:
            new_frozen = jnp.where(e1 < n_commit, row_check, frozen)
        else:
            new_frozen = frozen
        return pos + n_commit, placements, new_avail, new_frozen, new_extra

    st0 = (jnp.asarray(0, jnp.int32), jnp.full((B + C,), -1, jnp.int32),
           avail, jnp.zeros(Hl, dtype), jnp.zeros(Hl, jnp.int32))
    _, placements, avail, _, _ = lax.while_loop(
        lambda st: st[0] < n_eff, body, st0
    )
    return placements[:B], avail


# ---------------------------------------------------------------------------
# Public sharded kernels (cached jitted shard_map per (mesh, config))
# ---------------------------------------------------------------------------

_HOST_VEC = P(HOST_AXIS)          # [H] arrays: live mask, host_zone, counts
_HOST_MAT = P(HOST_AXIS, None)    # [H, 4] availability
_REP = P(None)                    # replicated task-axis operands


def _opt_specs(has_live, has_risk):
    """Trailing in_specs for the optional [H] operands, in the fixed
    (live, risk) order the wrappers append them."""
    return (_HOST_VEC,) * (int(has_live) + int(has_risk))


def _opt_args(live, risk):
    """The optional [H] operands, appended in (live, risk) order."""
    return tuple(a for a in (live, risk) if a is not None)


def _opt_unpack(rest, has_live, has_risk):
    """Unpack ``*rest`` back into (live, risk)."""
    it = iter(rest)
    live = next(it) if has_live else None
    risk = next(it) if has_risk else None
    return live, risk


def _opportunistic_sharded_body(mode, n_shards, has_live, has_risk):
    """Per-shard opportunistic body — shared by the 1-D jit factory and
    the [G]-batched 2-D factory (``shard_map(vmap(body))``), so the two
    programs cannot drift."""

    def fn(avail, demands, valid, uniforms, *rest):
        live, risk = _opt_unpack(rest, has_live, has_risk)
        avail, restore = _apply_live(avail, live)
        n_eff = _effective_len(valid)
        if mode == "step":
            p, a = _opportunistic_sharded_pass(
                avail, demands, valid, uniforms, n_eff, n_shards, risk
            )
        else:
            p, a = _opportunistic_sharded_chunk(
                avail, demands, valid, uniforms, n_eff,
                min(mode, demands.shape[0]), n_shards, risk,
            )
        return p, restore(a)

    return fn


_OPP_SPECS = (_HOST_MAT, P(None, None), _REP, _REP)


@functools.lru_cache(maxsize=None)
def _opportunistic_sharded_fn(mesh, mode, has_live, has_risk):
    fn = _opportunistic_sharded_body(
        mode, host_axis_size(mesh), has_live, has_risk
    )
    return jax.jit(_shard_map(
        fn, mesh=mesh,
        in_specs=_OPP_SPECS + _opt_specs(has_live, has_risk),
        out_specs=(_REP, _HOST_MAT),
        check_rep=False,
    ))


@functools.lru_cache(maxsize=None)
def _opportunistic_sharded_batched_fn(mesh, mode, has_live, has_risk):
    fn = _opportunistic_sharded_body(
        mode, host_axis_size(mesh), has_live, has_risk
    )
    specs = _OPP_SPECS + _opt_specs(has_live, has_risk)
    return jax.jit(_shard_map(
        jax.vmap(fn), mesh=mesh,
        in_specs=tuple(_g_spec(s) for s in specs),
        out_specs=(_g_spec(_REP), _g_spec(_HOST_MAT)),
        check_rep=False,
    ))


def opportunistic_kernel_sharded(mesh, avail, demands, valid, uniforms,
                                 phase2="auto", live=None, risk=None):
    """Host-sharded :func:`kernels.opportunistic_impl` — bit-identical to
    the single-device kernel in every ``phase2`` mode (the sharded pass
    is mode-collapsed; see the module docstring).  ``risk`` (optional
    [H] eviction-risk vector, round 11) narrows the random choice to the
    global minimum-risk tier — same Philox draw, narrower support."""
    mode = _sharded_mode(phase2)
    _check_host_axis(avail.shape[0], mesh)
    if demands.shape[0] == 0:
        return jnp.zeros((0,), jnp.int32), avail
    args = (avail, demands, valid, uniforms) + _opt_args(live, risk)
    return _opportunistic_sharded_fn(
        mesh, mode, live is not None, risk is not None
    )(*args)


def opportunistic_kernel_sharded_batched(mesh, avail, demands, valid,
                                         uniforms, phase2="auto",
                                         live=None, risk=None):
    """[G]-batched :func:`opportunistic_kernel_sharded`: every operand
    carries a leading run axis sharded over the mesh's ``replica`` axis
    while the host axis stays sharded over ``host`` — G coalesced
    dispatches as ONE 2-D program, each row bit-identical to the 1-D
    twin (the same per-shard body under vmap)."""
    mode = _sharded_mode(phase2)
    _check_host_axis(avail.shape[1], mesh)
    _check_g_axis(mesh, avail.shape[0])
    if demands.shape[1] == 0:
        return jnp.zeros(demands.shape[:2], jnp.int32), avail
    args = (avail, demands, valid, uniforms) + _opt_args(live, risk)
    return _opportunistic_sharded_batched_fn(
        mesh, mode, live is not None, risk is not None
    )(*args)


def _first_fit_sharded_body(mode, strict, has_live, has_risk):
    """Per-shard first-fit body shared by the 1-D and batched factories."""

    def fn(avail, demands, valid, *rest):
        live, risk = _opt_unpack(rest, has_live, has_risk)
        avail, restore = _apply_live(avail, live)
        n_eff = _effective_len(valid)
        if mode == "step":
            p, a = _first_fit_sharded_pass(
                avail, demands, valid, n_eff, strict, risk
            )
        else:
            p, a = _first_fit_sharded_chunk(
                avail, demands, valid, n_eff,
                min(mode, demands.shape[0]), strict, risk,
            )
        return p, restore(a)

    return fn


_FF_SPECS = (_HOST_MAT, P(None, None), _REP)


@functools.lru_cache(maxsize=None)
def _first_fit_sharded_fn(mesh, mode, strict, has_live, has_risk):
    fn = _first_fit_sharded_body(mode, strict, has_live, has_risk)
    return jax.jit(_shard_map(
        fn, mesh=mesh,
        in_specs=_FF_SPECS + _opt_specs(has_live, has_risk),
        out_specs=(_REP, _HOST_MAT),
        check_rep=False,
    ))


@functools.lru_cache(maxsize=None)
def _first_fit_sharded_batched_fn(mesh, mode, strict, has_live, has_risk):
    fn = _first_fit_sharded_body(mode, strict, has_live, has_risk)
    specs = _FF_SPECS + _opt_specs(has_live, has_risk)
    return jax.jit(_shard_map(
        jax.vmap(fn), mesh=mesh,
        in_specs=tuple(_g_spec(s) for s in specs),
        out_specs=(_g_spec(_REP), _g_spec(_HOST_MAT)),
        check_rep=False,
    ))


def first_fit_kernel_sharded(mesh, avail, demands, valid, strict=False,
                             totals=None, phase2="auto", live=None,
                             risk=None):
    """Host-sharded :func:`kernels.first_fit_impl`.  ``totals`` (the
    chunked form's speculation pre-filter) is accepted and ignored — the
    sharded pass has no speculation to steer, and the pre-filter can
    never change a placement by contract.  ``risk`` swaps the index
    order for the lexicographic (risk, global index) order."""
    mode = _sharded_mode(phase2)
    _check_host_axis(avail.shape[0], mesh)
    if demands.shape[0] == 0:
        return jnp.zeros((0,), jnp.int32), avail
    args = (avail, demands, valid) + _opt_args(live, risk)
    return _first_fit_sharded_fn(
        mesh, mode, bool(strict), live is not None, risk is not None
    )(*args)


def first_fit_kernel_sharded_batched(mesh, avail, demands, valid,
                                     strict=False, totals=None,
                                     phase2="auto", live=None, risk=None):
    """[G]-batched :func:`first_fit_kernel_sharded` (2-D replica × host
    program; ``totals`` accepted and ignored like the 1-D twin)."""
    mode = _sharded_mode(phase2)
    _check_host_axis(avail.shape[1], mesh)
    _check_g_axis(mesh, avail.shape[0])
    if demands.shape[1] == 0:
        return jnp.zeros(demands.shape[:2], jnp.int32), avail
    args = (avail, demands, valid) + _opt_args(live, risk)
    return _first_fit_sharded_batched_fn(
        mesh, mode, bool(strict), live is not None, risk is not None
    )(*args)


def _best_fit_sharded_body(mode, has_live, has_risk):
    """Per-shard best-fit body shared by the 1-D and batched factories."""

    def fn(avail, demands, valid, *rest):
        live, risk = _opt_unpack(rest, has_live, has_risk)
        avail, restore = _apply_live(avail, live)
        n_eff = _effective_len(valid)
        if mode == "step":
            p, a = _best_fit_sharded_pass(
                avail, demands, valid, n_eff, risk
            )
        else:
            p, a = _best_fit_sharded_chunk(
                avail, demands, valid, n_eff,
                min(mode, demands.shape[0]), risk,
            )
        return p, restore(a)

    return fn


@functools.lru_cache(maxsize=None)
def _best_fit_sharded_fn(mesh, mode, has_live, has_risk):
    fn = _best_fit_sharded_body(mode, has_live, has_risk)
    return jax.jit(_shard_map(
        fn, mesh=mesh,
        in_specs=_FF_SPECS + _opt_specs(has_live, has_risk),
        out_specs=(_REP, _HOST_MAT),
        check_rep=False,
    ))


@functools.lru_cache(maxsize=None)
def _best_fit_sharded_batched_fn(mesh, mode, has_live, has_risk):
    fn = _best_fit_sharded_body(mode, has_live, has_risk)
    specs = _FF_SPECS + _opt_specs(has_live, has_risk)
    return jax.jit(_shard_map(
        jax.vmap(fn), mesh=mesh,
        in_specs=tuple(_g_spec(s) for s in specs),
        out_specs=(_g_spec(_REP), _g_spec(_HOST_MAT)),
        check_rep=False,
    ))


def best_fit_kernel_sharded(mesh, avail, demands, valid, totals=None,
                            phase2="auto", live=None, risk=None):
    """Host-sharded :func:`kernels.best_fit_impl` (``totals`` accepted
    and ignored like :func:`first_fit_kernel_sharded`; ``risk`` adds the
    shared ``score += risk`` term)."""
    mode = _sharded_mode(phase2)
    _check_host_axis(avail.shape[0], mesh)
    if demands.shape[0] == 0:
        return jnp.zeros((0,), jnp.int32), avail
    args = (avail, demands, valid) + _opt_args(live, risk)
    return _best_fit_sharded_fn(
        mesh, mode, live is not None, risk is not None
    )(*args)


def best_fit_kernel_sharded_batched(mesh, avail, demands, valid,
                                    totals=None, phase2="auto", live=None,
                                    risk=None):
    """[G]-batched :func:`best_fit_kernel_sharded` (2-D replica × host
    program; ``totals`` accepted and ignored like the 1-D twin)."""
    mode = _sharded_mode(phase2)
    _check_host_axis(avail.shape[1], mesh)
    _check_g_axis(mesh, avail.shape[0])
    if demands.shape[1] == 0:
        return jnp.zeros(demands.shape[:2], jnp.int32), avail
    args = (avail, demands, valid) + _opt_args(live, risk)
    return _best_fit_sharded_batched_fn(
        mesh, mode, live is not None, risk is not None
    )(*args)


def _cost_aware_sharded_body(mode, bin_pack, sort_hosts, host_decay,
                             has_live, has_risk):
    """Per-shard cost-aware body shared by the 1-D and batched factories."""

    def fn(avail, demands, valid, new_group, anchor_zone, cost_zz, bw_zz,
           host_zone, base_task_counts, *rest):
        live, risk = _opt_unpack(rest, has_live, has_risk)
        avail, restore = _apply_live(avail, live)
        n_eff = _effective_len(valid)
        if mode == "step":
            p, a = _cost_aware_sharded_pass(
                avail, demands, valid, new_group, anchor_zone, cost_zz,
                bw_zz, host_zone, base_task_counts, n_eff,
                bin_pack, sort_hosts, host_decay, risk,
            )
        else:
            p, a = _cost_aware_sharded_chunk_pass(
                avail, demands, valid, new_group, anchor_zone, cost_zz,
                bw_zz, host_zone, base_task_counts, n_eff,
                min(mode, demands.shape[0]), bin_pack, sort_hosts,
                host_decay, risk,
            )
        return p, restore(a)

    return fn


_CA_SPECS = (
    _HOST_MAT, P(None, None), _REP, _REP, _REP,
    P(None, None), P(None, None), _HOST_VEC, _HOST_VEC,
)


@functools.lru_cache(maxsize=None)
def _cost_aware_sharded_fn(mesh, mode, bin_pack, sort_hosts, host_decay,
                           has_live, has_risk):
    fn = _cost_aware_sharded_body(
        mode, bin_pack, sort_hosts, host_decay, has_live, has_risk
    )
    return jax.jit(_shard_map(
        fn, mesh=mesh,
        in_specs=_CA_SPECS + _opt_specs(has_live, has_risk),
        out_specs=(_REP, _HOST_MAT),
        check_rep=False,
    ))


@functools.lru_cache(maxsize=None)
def _cost_aware_sharded_batched_fn(mesh, mode, bin_pack, sort_hosts,
                                   host_decay, has_live, has_risk):
    fn = _cost_aware_sharded_body(
        mode, bin_pack, sort_hosts, host_decay, has_live, has_risk
    )
    specs = _CA_SPECS + _opt_specs(has_live, has_risk)
    return jax.jit(_shard_map(
        jax.vmap(fn), mesh=mesh,
        in_specs=tuple(_g_spec(s) for s in specs),
        out_specs=(_g_spec(_REP), _g_spec(_HOST_MAT)),
        check_rep=False,
    ))


def cost_aware_kernel_sharded(
    mesh,
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    rt_bw_rows=None,
    rt_bw_idx=None,
    totals=None,
    phase2="auto",
    live=None,
    risk=None,
):
    """Host-sharded :func:`kernels.cost_aware_impl` — same argument
    contract minus the realtime-bandwidth rows (live route-queue samples
    are per-tick host state the mesh cannot hold; the device policy
    declines sharding for ``realtime_bw`` like it declines spans).
    ``risk`` is this PR's optional [H] eviction-risk vector, applied by
    the shared rules (``score += risk``; ``sort_hosts=False`` order →
    lexicographic (risk, global index))."""
    mode = _sharded_mode(phase2)
    if rt_bw_rows is not None or rt_bw_idx is not None:
        raise ValueError(
            "realtime_bw has no sharded form — the per-tick sampled "
            "[G, H] rows would reshard every dispatch; use the "
            "single-device kernel for realtime scoring"
        )
    _check_host_axis(avail.shape[0], mesh)
    if demands.shape[0] == 0:
        return jnp.zeros((0,), jnp.int32), avail
    args = (avail, demands, valid, new_group, anchor_zone, cost_zz, bw_zz,
            host_zone, base_task_counts) + _opt_args(live, risk)
    return _cost_aware_sharded_fn(
        mesh, mode, bin_pack, bool(sort_hosts), bool(host_decay),
        live is not None, risk is not None,
    )(*args)


def cost_aware_kernel_sharded_batched(
    mesh,
    avail,
    demands,
    valid,
    new_group,
    anchor_zone,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    bin_pack: str = "first-fit",
    sort_hosts: bool = True,
    host_decay: bool = False,
    rt_bw_rows=None,
    rt_bw_idx=None,
    totals=None,
    phase2="auto",
    live=None,
    risk=None,
):
    """[G]-batched :func:`cost_aware_kernel_sharded` (2-D replica × host
    program; same realtime-bw exclusion, same ignored ``totals``)."""
    mode = _sharded_mode(phase2)
    if rt_bw_rows is not None or rt_bw_idx is not None:
        raise ValueError(
            "realtime_bw has no sharded form — the per-tick sampled "
            "[G, H] rows would reshard every dispatch; use the "
            "single-device kernel for realtime scoring"
        )
    _check_host_axis(avail.shape[1], mesh)
    _check_g_axis(mesh, avail.shape[0])
    if demands.shape[1] == 0:
        return jnp.zeros(demands.shape[:2], jnp.int32), avail
    args = (avail, demands, valid, new_group, anchor_zone, cost_zz, bw_zz,
            host_zone, base_task_counts) + _opt_args(live, risk)
    return _cost_aware_sharded_batched_fn(
        mesh, mode, bin_pack, bool(sort_hosts), bool(host_decay),
        live is not None, risk is not None,
    )(*args)


# ---------------------------------------------------------------------------
# Sharded fused span driver (the tickloop twin)
# ---------------------------------------------------------------------------


def _sharded_span_body(
    avail,
    demands,
    arrive,
    n_ticks_dyn,
    uniforms,
    sort_norm,
    anchor_zone,
    bucket_id,
    cost_zz,
    bw_zz,
    host_zone,
    base_task_counts,
    live,
    risk_rows,
    cost_stack,
    cost_seg,
    *,
    policy: str,
    n_ticks: int,
    n_shards: int,
    strict: bool,
    decreasing: bool,
    bin_pack: str,
    sort_tasks: bool,
    sort_hosts: bool,
    host_decay: bool,
):
    """Per-shard body of :func:`sharded_fused_tick_run` — the tick loop
    of ``tickloop._fused_tick_run_impl`` with the kernel step served by
    the sharded passes and the ``[H]`` carries ([H/S, 4] availability,
    [H/S] span-cumulative decay counts) shard-local.  All [B] slot-axis
    state is replicated and computed via the SHARED span algebra
    helpers, identically on every shard.  The market operands follow the
    tickloop contract: ``risk_rows`` is the [K, H] per-tick risk stack
    (host axis sharded → this shard sees its [K, H/S] block),
    ``cost_stack``/``cost_seg`` the replicated [P, Z, Z] price-scaled
    cost tensor and its per-tick [K] segment-index row."""
    B = demands.shape[0]
    Hl = avail.shape[0]
    K = n_ticks
    avail, restore = _apply_live(avail, live)
    offset = _shard_offset(Hl)
    iota_b = jnp.arange(B, dtype=jnp.int32)
    big = jnp.asarray(2 * B + 2, jnp.int32)

    def cond(st):
        k, done = st[0], st[1]
        return (k < n_ticks_dyn) & ~done

    def body(st):
        k, done, stackpos, n_stack, avail, cum, p_out, nr_out, np_out = st
        alive = (k < n_ticks_dyn) & ~done

        batch_pos, in_batch, t_k, _arriving = _span_ready_batch(
            arrive, k, stackpos, n_stack, big
        )
        order = _span_stream_order(
            policy, decreasing, sort_tasks, in_batch, batch_pos,
            sort_norm, bucket_id, iota_b, big,
        )
        dem_p = demands[order]
        valid_p = in_batch[order]
        n_eff = _effective_len(valid_p)
        # Per-tick market state (tickloop contract): this tick's [H/S]
        # risk block and — cost-aware — its [Z, Z] price slice.  Both
        # None in market-free worlds: the traced program is unchanged.
        risk_k = None if risk_rows is None else risk_rows[k]
        cost_k = cost_zz if cost_stack is None else cost_stack[cost_seg[k]]

        if policy == "opportunistic":
            p_ord, new_avail = _opportunistic_sharded_pass(
                avail, dem_p, valid_p, uniforms[k], n_eff, n_shards,
                risk_k,
            )
        elif policy == "first-fit":
            p_ord, new_avail = _first_fit_sharded_pass(
                avail, dem_p, valid_p, n_eff, strict, risk_k
            )
        elif policy == "best-fit":
            p_ord, new_avail = _best_fit_sharded_pass(
                avail, dem_p, valid_p, n_eff, risk_k
            )
        else:  # cost-aware
            ng_p = _span_group_entries(bucket_id, order, iota_b)
            p_ord, new_avail = _cost_aware_sharded_pass(
                avail, dem_p, valid_p, ng_p, anchor_zone[order],
                cost_k, bw_zz, host_zone, base_task_counts + cum,
                n_eff, bin_pack, sort_hosts, host_decay, risk_k,
            )
        row = jnp.full((B,), -1, jnp.int32).at[order].set(
            p_ord.astype(jnp.int32)
        )
        placed = row >= 0
        n_placed = jnp.sum(placed.astype(jnp.int32)).astype(jnp.int32)

        new_stackpos, new_n_stack = _span_requeue(
            decreasing, in_batch, placed, batch_pos, order, iota_b, big
        )

        # Span-cumulative resident-task counts, this shard's slice: a
        # placement on host h bumps only its owner's block.
        row_local = row - offset
        mine = placed & (row_local >= 0) & (row_local < Hl)
        cum_new = cum.at[jnp.where(mine, row_local, Hl)].add(
            mine.astype(jnp.int32), mode="drop"
        )

        future = jnp.any((arrive > k) & (arrive < n_ticks_dyn))
        done_new = ~future & ((new_n_stack == 0) | (n_placed == 0))

        kk = jnp.where(alive, k, K)
        return (
            k + 1,
            jnp.where(alive, done_new, done),
            jnp.where(alive, new_stackpos, stackpos),
            jnp.where(alive, new_n_stack, n_stack),
            jnp.where(alive, new_avail, avail),
            jnp.where(alive, cum_new, cum),
            p_out.at[kk].set(jnp.where(alive, row, -1), mode="drop"),
            nr_out.at[kk].set(t_k, mode="drop"),
            np_out.at[kk].set(n_placed, mode="drop"),
        )

    st0 = (
        jnp.asarray(0, jnp.int32),
        jnp.asarray(False),
        jnp.full((B,), -1, jnp.int32),
        jnp.asarray(0, jnp.int32),
        avail,
        jnp.zeros((Hl,), jnp.int32),
        jnp.full((K, B), -1, jnp.int32),
        jnp.zeros((K,), jnp.int32),
        jnp.zeros((K,), jnp.int32),
    )
    k, _done, stackpos, n_stack, avail, _cum, p_out, nr_out, np_out = (
        lax.while_loop(cond, body, st0)
    )
    return SpanResult(
        p_out, nr_out, np_out, k, n_stack, stackpos, restore(avail)
    )


_SPAN_IN_SPECS = (
    _HOST_MAT,        # avail
    P(None, None),    # demands
    _REP,             # arrive
    P(),              # n_ticks_dyn
    P(None, None),    # uniforms (or None)
    _REP,             # sort_norm (or None)
    _REP,             # anchor_zone (or None)
    _REP,             # bucket_id (or None)
    P(None, None),    # cost_zz (or None)
    P(None, None),    # bw_zz (or None)
    _HOST_VEC,        # host_zone (or None)
    _HOST_VEC,        # base_task_counts (or None)
    _HOST_VEC,        # live (or None)
    P(None, HOST_AXIS),   # risk_rows [K, H] (or None)
    P(None, None, None),  # cost_stack [P, Z, Z] (or None)
    _REP,                 # cost_seg [K] (or None)
)

_SPAN_OUT_SPECS = SpanResult(
    placements=P(None, None),
    n_ready=_REP,
    n_placed=_REP,
    ticks_run=P(),
    n_stack_final=P(),
    stackpos=_REP,
    avail=_HOST_MAT,
)


def _span_fn_body(mesh, policy, n_ticks, strict, decreasing, bin_pack,
                  sort_tasks, sort_hosts, host_decay):
    n = host_axis_size(mesh)

    def fn(avail, demands, arrive, n_ticks_dyn, uniforms, sort_norm,
           anchor_zone, bucket_id, cost_zz, bw_zz, host_zone,
           base_task_counts, live, risk_rows, cost_stack, cost_seg):
        return _sharded_span_body(
            avail, demands, arrive, n_ticks_dyn, uniforms, sort_norm,
            anchor_zone, bucket_id, cost_zz, bw_zz, host_zone,
            base_task_counts, live, risk_rows, cost_stack, cost_seg,
            policy=policy, n_ticks=n_ticks, n_shards=n, strict=strict,
            decreasing=decreasing, bin_pack=bin_pack,
            sort_tasks=sort_tasks, sort_hosts=sort_hosts,
            host_decay=host_decay,
        )

    return fn


@functools.lru_cache(maxsize=None)
def _sharded_span_fn(mesh, policy, n_ticks, strict, decreasing, bin_pack,
                     sort_tasks, sort_hosts, host_decay):
    fn = _span_fn_body(mesh, policy, n_ticks, strict, decreasing,
                       bin_pack, sort_tasks, sort_hosts, host_decay)
    return jax.jit(_shard_map(
        fn, mesh=mesh,
        in_specs=_SPAN_IN_SPECS,
        out_specs=_SPAN_OUT_SPECS,
        check_rep=False,
        # DELIBERATELY NOT donated — the sharded twin of the tickloop
        # span carry's negative manifest entry (pivot_tpu/analysis/
        # donation.py): span operands are staged from host numpy at the
        # call boundary, and CPU-backend ``jnp.asarray`` is zero-copy
        # for large aligned arrays, so a donated carry would scribble
        # on caller-owned memory.  The donation pass enforces the
        # decision both ways.
    ))


@functools.lru_cache(maxsize=None)
def _sharded_span_batched_fn(mesh, policy, n_ticks, strict, decreasing,
                             bin_pack, sort_tasks, sort_hosts, host_decay):
    fn = _span_fn_body(mesh, policy, n_ticks, strict, decreasing,
                       bin_pack, sort_tasks, sort_hosts, host_decay)
    return jax.jit(_shard_map(
        # The same per-shard span body under vmap: each [G] row is one
        # run's whole span, rows go inert independently (the body's
        # ``alive`` gating — the same property the plain vmapped driver
        # relies on), and the host-axis collectives batch per row.
        jax.vmap(fn), mesh=mesh,
        in_specs=tuple(
            _g_spec(s) for s in _SPAN_IN_SPECS
        ),
        out_specs=SpanResult(
            *(_g_spec(s) for s in _SPAN_OUT_SPECS)
        ),
        check_rep=False,
        # NOT donated — same zero-copy hazard as the 1-D twin above.
    ))


def sharded_fused_tick_run(
    mesh,
    avail,
    demands,
    arrive,
    n_ticks_dyn,
    *,
    policy: str,
    n_ticks: int,
    uniforms=None,
    sort_norm=None,
    anchor_zone=None,
    bucket_id=None,
    cost_zz=None,
    bw_zz=None,
    host_zone=None,
    base_task_counts=None,
    totals=None,
    live=None,
    risk_rows=None,
    cost_stack=None,
    cost_seg=None,
    strict: bool = False,
    decreasing: bool = False,
    bin_pack: str = "first-fit",
    sort_tasks: bool = False,
    sort_hosts: bool = True,
    host_decay: bool = False,
    phase2="auto",
) -> SpanResult:
    """Host-sharded :func:`tickloop.fused_tick_run` — same contract,
    same :class:`SpanResult`, the ``[H, 4]`` carry kept shard-resident
    between ticks.  Bit-identical to the single-device driver (and so to
    :func:`tickloop.reference_tick_run`) on every input the parity suite
    sweeps.  ``totals``/``phase2`` accepted for signature compatibility
    (speculation-free pass; every mode is bit-identical).  The market
    operands (``risk_rows`` [K, H], ``cost_stack`` [P, Z, Z],
    ``cost_seg`` [K]) follow :func:`tickloop.fused_tick_run`'s contract;
    ``risk_rows`` rides the host axis like ``live``."""
    _resolve_phase2(phase2)
    _check_host_axis(avail.shape[0], mesh)
    return _sharded_span_fn(
        mesh, policy, n_ticks, bool(strict), bool(decreasing), bin_pack,
        bool(sort_tasks), bool(sort_hosts), bool(host_decay),
    )(
        avail, demands, arrive, n_ticks_dyn, uniforms, sort_norm,
        anchor_zone, bucket_id, cost_zz, bw_zz, host_zone,
        base_task_counts, live, risk_rows, cost_stack, cost_seg,
    )


def sharded_batched_tick_run(
    mesh,
    avail,
    demands,
    arrive,
    n_ticks_dyn,
    *,
    policy: str,
    n_ticks: int,
    uniforms=None,
    sort_norm=None,
    anchor_zone=None,
    bucket_id=None,
    cost_zz=None,
    bw_zz=None,
    host_zone=None,
    base_task_counts=None,
    totals=None,
    live=None,
    risk_rows=None,
    cost_stack=None,
    cost_seg=None,
    strict: bool = False,
    decreasing: bool = False,
    bin_pack: str = "first-fit",
    sort_tasks: bool = False,
    sort_hosts: bool = True,
    host_decay: bool = False,
    phase2="auto",
) -> SpanResult:
    """[G]-batched :func:`sharded_fused_tick_run`: G coalesced fused
    spans on the 2-D ``replica × host`` mesh — G×K simulator ticks as
    ONE device program, the [G, H/S, 4] availability carries
    shard-resident between ticks.  Every operand carries a leading [G]
    run axis sharded over ``replica``; the host axis keeps the 1-D
    twin's layout (stacked ``risk_rows`` [G, K, H] shard as
    ``P("replica", None, "host")``).  Each row is bit-identical to the
    1-D sharded driver — the same per-shard body under vmap, with the
    same per-row inertness the plain vmapped driver relies on.

    Ragged contract (round 18): rows need NOT share a true horizon —
    ``n_ticks_dyn`` is a [G] operand and each row's while-loop carry
    freezes (select-masked by vmap) once that row exits, so a short
    row's ``ticks_run``/meters stay exact while longer rows keep
    stepping.  The batcher exploits this by padding mixed-horizon
    ``fused_tick_run`` requests to a shared (K-bucket, B-bucket) before
    stacking the [G] axis (``ops.tickloop.ragged_span_pad``); the
    static ``n_ticks`` here is the shared K-bucket, and padded K/B
    extents are inert by the zero-fill-safety of every span operand
    (see ``ragged_span_signature`` for which axes pad where)."""
    _resolve_phase2(phase2)
    _check_host_axis(avail.shape[1], mesh)
    _check_g_axis(mesh, avail.shape[0])
    return _sharded_span_batched_fn(
        mesh, policy, n_ticks, bool(strict), bool(decreasing), bin_pack,
        bool(sort_tasks), bool(sort_hosts), bool(host_decay),
    )(
        avail, demands, arrive, n_ticks_dyn, uniforms, sort_norm,
        anchor_zone, bucket_id, cost_zz, bw_zz, host_zone,
        base_task_counts, live, risk_rows, cost_stack, cost_seg,
    )


# ---------------------------------------------------------------------------
# Sharded resident span driver (the ``tickloop.resident_span_run`` twin)
#
# Same delta contract as the single-device resident driver — the carry
# (shard-local availability, counts, live mask) stays device-resident
# between spans, edits arrive as GLOBAL host indices each shard projects
# into its own block (foreign rows drop), and the market risk rows are
# gathered shard-locally from a once-staged [P, H] segment table.  The
# carry is DONATED: like the 1-D resident driver (and unlike the
# re-staged sharded span twin above), every carry a caller can hold is a
# previous jit output, so the zero-copy hazard cannot occur.
# ---------------------------------------------------------------------------

_RESIDENT_CARRY_SPECS = ResidentCarry(
    avail=_HOST_MAT, counts=_HOST_VEC, live=_HOST_VEC
)

_RESIDENT_IN_SPECS = (
    _RESIDENT_CARRY_SPECS,  # carry
    _REP,             # edit_idx [E] global host indices (or None)
    P(None, None),    # edit_avail [E, 4] (or None)
    _REP,             # edit_counts [E] (or None)
    _REP,             # edit_live [E] (or None)
    P(None, None),    # demands
    _REP,             # arrive
    P(),              # n_ticks_dyn
    P(None, None),    # uniforms (or None)
    _REP,             # sort_norm (or None)
    _REP,             # anchor_zone (or None)
    _REP,             # bucket_id (or None)
    P(None, None),    # cost_zz (or None)
    P(None, None),    # bw_zz (or None)
    _HOST_VEC,        # host_zone (or None)
    P(None, HOST_AXIS),   # risk_table [P, H] (or None)
    _REP,                 # risk_seg [K] (or None)
    P(None, None, None),  # cost_stack [P, Z, Z] (or None)
    _REP,                 # cost_seg [K] (or None)
)

_RESIDENT_OUT_SPECS = (_SPAN_OUT_SPECS, _RESIDENT_CARRY_SPECS)


def _resident_span_fn_body(mesh, policy, n_ticks, strict, decreasing,
                           bin_pack, sort_tasks, sort_hosts, host_decay):
    n = host_axis_size(mesh)

    def fn(carry, edit_idx, edit_avail, edit_counts, edit_live, demands,
           arrive, n_ticks_dyn, uniforms, sort_norm, anchor_zone,
           bucket_id, cost_zz, bw_zz, host_zone, risk_table, risk_seg,
           cost_stack, cost_seg):
        avail, counts, live = carry
        Hl = avail.shape[0]
        offset = _shard_offset(Hl)
        if edit_idx is not None:
            # Global→local projection: rows owned elsewhere (and the
            # pad rows, global index H) land outside [0, Hl) → dropped.
            li = edit_idx - offset
            li = jnp.where((li >= 0) & (li < Hl), li, Hl)
            avail = avail.at[li].set(edit_avail, mode="drop")
            counts = counts.at[li].set(edit_counts, mode="drop")
            live = live.at[li].set(edit_live, mode="drop")
        risk_rows = None if risk_seg is None else risk_table[risk_seg]
        res = _sharded_span_body(
            avail, demands, arrive, n_ticks_dyn, uniforms, sort_norm,
            anchor_zone, bucket_id, cost_zz, bw_zz, host_zone,
            counts, live, risk_rows, cost_stack, cost_seg,
            policy=policy, n_ticks=n_ticks, n_shards=n, strict=strict,
            decreasing=decreasing, bin_pack=bin_pack,
            sort_tasks=sort_tasks, sort_hosts=sort_hosts,
            host_decay=host_decay,
        )
        # Fold this span's placements into the shard-local count state
        # (mirrors the tickloop resident driver's histogram fold).
        placed = res.placements >= 0
        local = res.placements - offset
        mine = placed & (local >= 0) & (local < Hl)
        tgt = jnp.where(mine, local, Hl)
        hist = jnp.zeros((Hl,), jnp.int32).at[tgt.reshape(-1)].add(
            mine.reshape(-1).astype(jnp.int32), mode="drop"
        )
        return res, ResidentCarry(res.avail, counts + hist, live)

    return fn


@functools.lru_cache(maxsize=None)
def _sharded_resident_span_fn(mesh, policy, n_ticks, strict, decreasing,
                              bin_pack, sort_tasks, sort_hosts,
                              host_decay):
    fn = _resident_span_fn_body(mesh, policy, n_ticks, strict, decreasing,
                                bin_pack, sort_tasks, sort_hosts,
                                host_decay)
    return jax.jit(
        _shard_map(
            fn, mesh=mesh,
            in_specs=_RESIDENT_IN_SPECS,
            out_specs=_RESIDENT_OUT_SPECS,
            check_rep=False,
        ),
        # The carry IS donated — the sharded leg of the positive
        # resident-carry manifest entry (analysis/donation.py): its
        # leaves are always previous jit outputs, never zero-copy views
        # of caller numpy.  Contrast ``_sharded_span_fn`` above.
        donate_argnums=(0,),
    )


@functools.lru_cache(maxsize=None)
def _sharded_resident_init_fn(mesh):
    sh = functools.partial(jax.sharding.NamedSharding, mesh)
    return jax.jit(
        _resident_carry_init_impl,
        out_shardings=ResidentCarry(
            avail=sh(_HOST_MAT), counts=sh(_HOST_VEC), live=sh(_HOST_VEC)
        ),
    )


def sharded_resident_carry_init(mesh, avail, counts=None, live=None):
    """Materialize a host-sharded :class:`ResidentCarry` from host state
    — the one full [H]-sized staging of the sharded resident path.  The
    outputs are device-owned copies laid out on ``mesh``'s host axis;
    :func:`tickloop.resident_carry_clone` preserves that layout for
    splice checkpoints."""
    avail = jnp.asarray(avail)
    H = avail.shape[0]
    _check_host_axis(H, mesh)
    if counts is None:
        counts = jnp.zeros((H,), jnp.int32)
    if live is None:
        live = jnp.ones((H,), bool)
    return _sharded_resident_init_fn(mesh)(
        avail,
        jnp.asarray(counts, jnp.int32),
        jnp.asarray(live, bool),
    )


def sharded_resident_span_run(
    mesh,
    carry,
    demands,
    arrive,
    n_ticks_dyn,
    *,
    policy: str,
    n_ticks: int,
    edit_idx=None,
    edit_avail=None,
    edit_counts=None,
    edit_live=None,
    uniforms=None,
    sort_norm=None,
    anchor_zone=None,
    bucket_id=None,
    cost_zz=None,
    bw_zz=None,
    host_zone=None,
    totals=None,
    risk_table=None,
    risk_seg=None,
    cost_stack=None,
    cost_seg=None,
    strict: bool = False,
    decreasing: bool = False,
    bin_pack: str = "first-fit",
    sort_tasks: bool = False,
    sort_hosts: bool = True,
    host_decay: bool = False,
    phase2="auto",
):
    """Host-sharded :func:`tickloop.resident_span_run` — same delta
    contract and ``(SpanResult, ResidentCarry)`` return, the carry kept
    shard-resident between SPANS (not just between ticks).  ``edit_idx``
    holds GLOBAL host indices; each shard projects them into its own
    block.  ``totals``/``phase2`` accepted for signature compatibility
    with the re-staged twin (speculation-free pass).  Bit-identical to
    :func:`sharded_fused_tick_run` on the post-edit host state."""
    _resolve_phase2(phase2)
    _check_host_axis(carry.avail.shape[0], mesh)
    return _sharded_resident_span_fn(
        mesh, policy, n_ticks, bool(strict), bool(decreasing), bin_pack,
        bool(sort_tasks), bool(sort_hosts), bool(host_decay),
    )(
        carry, edit_idx, edit_avail, edit_counts, edit_live, demands,
        arrive, n_ticks_dyn, uniforms, sort_norm, anchor_zone, bucket_id,
        cost_zz, bw_zz, host_zone, risk_table, risk_seg, cost_stack,
        cost_seg,
    )


# ---------------------------------------------------------------------------
# The batcher's 2-D entry points (``sched/batch.py``)
# ---------------------------------------------------------------------------

#: Single-device public kernel → its 1-D host-sharded twin.  The batcher
#: serves an uncoalesced (G=1) flush on a 2-D mesh through the twin so a
#: lone dispatch still runs host-sharded.
_SHARDED_TWINS = {
    opportunistic_kernel: opportunistic_kernel_sharded,
    first_fit_kernel: first_fit_kernel_sharded,
    best_fit_kernel: best_fit_kernel_sharded,
    cost_aware_kernel: cost_aware_kernel_sharded,
    fused_tick_run: sharded_fused_tick_run,
}

#: Single-device public kernel → its [G]-batched 2-D form.  What the
#: batcher's coalesced flushes resolve to when its mesh is 2-D.
_BATCHED_TWINS = {
    opportunistic_kernel: opportunistic_kernel_sharded_batched,
    first_fit_kernel: first_fit_kernel_sharded_batched,
    best_fit_kernel: best_fit_kernel_sharded_batched,
    cost_aware_kernel: cost_aware_kernel_sharded_batched,
    fused_tick_run: sharded_batched_tick_run,
}


#: Array-kwarg names that disqualify a dispatch from the sharded forms:
#: the realtime-bandwidth rows are per-tick host state the mesh cannot
#: hold (both sharded cost-aware forms raise on them), so a request
#: carrying them must stay on the single-device program.
UNSHARDABLE_KW = frozenset({"rt_bw_rows", "rt_bw_idx"})


def sharded_twin_of(kernel, arr_kw_keys=()):
    """The 1-D host-sharded twin of a single-device public kernel, or
    None when the family has no sharded form (e.g. the Pallas pair) or
    the request carries operands the sharded forms reject
    (:data:`UNSHARDABLE_KW` — the realtime-bw rows)."""
    if UNSHARDABLE_KW & set(arr_kw_keys):
        return None
    return _SHARDED_TWINS.get(kernel)


def batched_sharded_call(mesh, kernel, static_kw, n_args, kw_keys):
    """Resolve a coalesced batch of ``kernel`` dispatches to its 2-D
    ``replica × host`` program, or None when ``kernel`` has no batched
    sharded form (the batcher then falls back to the plain vmap
    program, bit-identically).

    The returned callable takes the batcher's flat positional leaves —
    stacked positional args first, stacked array-kwargs in ``kw_keys``
    order after — exactly like the ``jit(vmap(...))`` program it
    replaces, so ``batch_execute`` needs no 2-D special-casing at the
    call site."""
    batched = _BATCHED_TWINS.get(kernel)
    if batched is None:
        return None

    def call(*cols):
        return batched(
            mesh,
            *cols[:n_args],
            **dict(zip(kw_keys, cols[n_args:])),
            **static_kw,
        )

    return call


# ---------------------------------------------------------------------------
# Elastic re-layout helpers (round 20 — elastic mesh serving)
#
# When a mesh device dies mid-soak the serving stack shrinks onto the
# next rung of a DECLARED mesh-shape ladder (the descending divisor
# chain of the launch device count — a bounded set, so the per-shape
# compile caches stay bounded too).  Host-state arrays re-lay from the
# old shape onto the new one here: trim any old pad rows back to the
# true host count, then pad to the new shape's extent with DEAD-sentinel
# rows.  Pad rows are inert by construction — a :data:`DEAD_AVAIL`
# availability row can never satisfy a demand (fit requires
# ``demand <= avail`` per dimension, and demands are >= 0) and the pad
# live mask is False, so the masked-argmin reduces the kernels already
# obey can never select one.  Elasticity changes WHERE state lives,
# never WHAT is decided: placements on the shrunk mesh are bit-identical
# to a from-scratch run on that mesh over the same host truth
# (``tests/test_elastic.py`` pad-inertness + shrink-parity referees).
# ---------------------------------------------------------------------------

#: Availability fill for dead-sentinel pad hosts: strictly below any
#: demand (demands are >= 0), so a pad row fails every fit mask even
#: before the False live mask excludes it (belt and braces — the same
#: -1 convention ``_check_host_axis``'s error message documents).
DEAD_AVAIL = -1.0


def mesh_shape_ladder(n_devices: int):
    """The declared elastic shapes for a ``n_devices`` launch mesh: its
    divisors, descending (8 → ``(8, 4, 2, 1)``).  Shrink steps walk DOWN
    the ladder to the largest rung the survivors can fill; regrow walks
    back UP.  The ladder bounds the compile cache: one program per
    (rung, span config), zero recompiles after warmup per shape."""
    n = int(n_devices)
    if n < 1:
        raise ValueError(f"mesh ladder needs n_devices >= 1, got {n}")
    return tuple(s for s in range(n, 0, -1) if n % s == 0)


def next_ladder_shape(ladder, n_live: int) -> int:
    """Largest ladder rung fillable by ``n_live`` surviving devices —
    the shrink target after a loss.  Raises when nothing survives."""
    for s in ladder:
        if s <= n_live:
            return int(s)
    raise ValueError(
        f"no ladder rung <= {n_live} surviving devices (ladder {ladder})"
    )


def elastic_host_extent(H: int, n_shards: int) -> int:
    """Smallest multiple of ``n_shards`` >= ``H`` — the padded host
    extent a non-dividing host count re-lays onto (pad rows are
    dead-sentinel, inert by masked-argmin; see module comment)."""
    if H < 1 or n_shards < 1:
        raise ValueError(
            f"elastic extent needs H >= 1 and n_shards >= 1, "
            f"got H={H}, n_shards={n_shards}"
        )
    return -(-H // n_shards) * n_shards


def elastic_pad_rows(arr, extent: int, fill):
    """Pad a host-leading array's axis 0 to ``extent`` with ``fill``
    rows (no-op when already there).  numpy in, numpy out — re-layout
    runs on host truth between device programs, never inside one."""
    arr = np.asarray(arr)
    H = arr.shape[0]
    if H > extent:
        raise ValueError(f"host axis {H} exceeds elastic extent {extent}")
    if H == extent:
        return arr
    pad = np.full((extent - H,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def elastic_trim_rows(arr, H: int):
    """Drop pad rows: the first ``H`` host rows of a padded array (the
    inverse of :func:`elastic_pad_rows`, used when re-laying from one
    rung's extent onto another's)."""
    arr = np.asarray(arr)
    if arr.shape[0] < H:
        raise ValueError(
            f"cannot trim to H={H}: array has {arr.shape[0]} host rows"
        )
    return arr[:H]


def elastic_pad_state(H: int, n_shards: int, *, avail=None, counts=None,
                      live=None, risk_rows=None, host_zone=None,
                      base_task_counts=None):
    """Re-lay global host-state arrays (true host count ``H``) onto a
    ``n_shards`` mesh: returns ``(extent, dict)`` with every provided
    array padded to the elastic extent.  Fill values make pad hosts
    inert: :data:`DEAD_AVAIL` availability, False live mask, zero
    counts/risk/zone.  ``live`` defaults to all-true over ``H`` whenever
    padding occurs and ``avail`` was provided — a None live mask means
    "every host selectable", which would include the pad rows.
    ``risk_rows`` pads its TRAILING axis ([K, H] layout)."""
    extent = elastic_host_extent(H, n_shards)
    out = {}
    if avail is not None:
        avail = np.asarray(avail)
        if avail.shape[0] != H:
            raise ValueError(
                f"avail has {avail.shape[0]} host rows, expected H={H}"
            )
        out["avail"] = elastic_pad_rows(avail, extent, DEAD_AVAIL)
        if live is None and extent != H:
            live = np.ones((H,), bool)
    if counts is not None:
        out["counts"] = elastic_pad_rows(
            np.asarray(counts, np.int32), extent, 0
        )
    if live is not None:
        out["live"] = elastic_pad_rows(np.asarray(live, bool), extent, False)
    if risk_rows is not None:
        risk_rows = np.asarray(risk_rows)
        if risk_rows.shape[-1] != H:
            raise ValueError(
                f"risk_rows trailing axis {risk_rows.shape[-1]} != H={H}"
            )
        pad = extent - H
        if pad:
            widths = [(0, 0)] * (risk_rows.ndim - 1) + [(0, pad)]
            risk_rows = np.pad(risk_rows, widths, constant_values=0.0)
        out["risk_rows"] = risk_rows
    if host_zone is not None:
        out["host_zone"] = elastic_pad_rows(
            np.asarray(host_zone, np.int32), extent, 0
        )
    if base_task_counts is not None:
        out["base_task_counts"] = elastic_pad_rows(
            np.asarray(base_task_counts, np.int32), extent, 0
        )
    return extent, out


def elastic_fold_carry(carry, H: int, mesh):
    """Re-lay a resident span carry onto ``mesh`` (or onto the
    single-device layout when ``mesh`` is None): D2H export, trim the
    OLD shape's pad rows back to the true host count ``H``, pad to the
    new shape's extent, re-init device-owned on the new layout.

    Donation safety: ``carry`` must be a PENDING carry or a clone (the
    same window :func:`tickloop.resident_carry_export` documents) — a
    shrink always holds the pending carry, never a donated one.  The
    returned carry is bit-equal to the source on the true host rows:
    folding is a pure re-layout, decisions made from it are identical
    (the shrink-parity referee's state-map leg)."""
    snap = resident_carry_export(carry)
    if mesh is None:
        return resident_carry_init(
            elastic_trim_rows(snap["avail"], H),
            counts=elastic_trim_rows(snap["counts"], H),
            live=elastic_trim_rows(snap["live"], H),
        )
    n = host_axis_size(mesh)
    _, padded = elastic_pad_state(
        H, n,
        avail=elastic_trim_rows(snap["avail"], H),
        counts=elastic_trim_rows(snap["counts"], H),
        live=elastic_trim_rows(snap["live"], H),
    )
    return sharded_resident_carry_init(
        mesh, padded["avail"], counts=padded["counts"], live=padded["live"]
    )
