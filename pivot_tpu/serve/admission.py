"""Bounded, tier-aware admission control for the stream driver.

The admission queue bounds **in-flight work** — jobs admitted into a
scheduling session but not yet completed.  (A buffer of *unrouted*
arrivals would always be drained instantly by the router and never
exert backpressure; what an always-on scheduler must bound is the work
it has accepted responsibility for.)  ``depth`` is therefore the
service's concurrent-job capacity, and the queue-depth histogram the
SLO meter records at each offer is the in-flight count.

Three backpressure policies when the queue is full:

  * ``shed``  — reject the arrival, recording the reason
    (``queue_full``) in the SLO meter.  Lossy, latency-protecting.
  * ``spill`` — defer the arrival to a spill buffer; the driver
    re-offers it at the next completion boundary with its submission
    time pushed to the following scheduler grid point ("spill to next
    tick").  Lossless, order-preserving, latency-paying.
  * ``block`` — the producer waits for capacity.  Lossless with the
    original timestamps, but couples the arrival loop to completion
    wall-time; in replay mode the driver advances the sim-release gate
    while blocked so the wait can resolve deterministically.

**Priority tiers** (round 9, the Borg-NG batch/serving split —
PAPERS.md): every :class:`~pivot_tpu.serve.arrivals.JobArrival` carries
a ``tier`` (0 = most important), and the queue can be built with

  * ``tier_reserve`` — per-tier depth reservations: ``reserve[t]``
    slots are off-limits to arrivals of tier ``t`` (tiers beyond the
    sequence use its last entry), so tier t's effective depth is
    ``depth − reserve[t]``.  Tier 0 conventionally reserves 0: under
    load the low tiers run out of queue *first*, which is exactly the
    "shed low tiers before blocking high ones" ordering.
  * ``tier_policies`` — per-tier backpressure override (same indexing),
    e.g. ``("spill", "shed", "shed")``: tier 0 is lossless while lower
    tiers absorb the sheds.

The spill buffer re-offers in **(tier, arrival-timestamp) order** — the
highest surviving tier first, original arrival order within a tier,
*including* preemption victims re-entering at their original arrival
position (the single-tier case degenerates to pure FIFO, the documented
re-offer ordering guarantee ``tests/test_serve.py`` pins).  Both tier
knobs default to off, under which every decision, counter, and re-offer
is bit-identical to the single-tenant queue.

Decisions are returned as module constants (``ADMITTED`` / ``SHED`` /
``SPILLED`` / ``BLOCKED``); the blocking dance itself lives in the
driver, which owns the condition variable the completions notify (as
does in-queue *preemption*, which frees low-tier in-flight capacity
when a high-tier arrival would otherwise degrade — ``serve/driver.py``).
"""

from __future__ import annotations

import bisect
from typing import List, Optional, Sequence

from pivot_tpu.infra.meter import SloMeter

__all__ = [
    "ADMITTED",
    "AdmissionQueue",
    "BLOCKED",
    "SHED",
    "SPILLED",
]

ADMITTED = "admitted"
SHED = "shed"
SPILLED = "spilled"
BLOCKED = "blocked"

_POLICIES = ("block", "shed", "spill")


class AdmissionQueue:
    """In-flight bound + backpressure decision.  NOT thread-safe on its
    own: the driver serializes every call under its coordination lock
    (the same lock completions notify), so decision + counter update are
    atomic with respect to releases."""

    def __init__(self, depth: int, policy: str = "shed",
                 slo: Optional[SloMeter] = None,
                 tier_reserve: Optional[Sequence[int]] = None,
                 tier_policies: Optional[Sequence[str]] = None):
        if depth < 1:
            raise ValueError("admission queue depth must be >= 1")
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r} (use one of "
                f"{_POLICIES})"
            )
        if tier_reserve is not None:
            tier_reserve = tuple(int(r) for r in tier_reserve)
            if not tier_reserve or any(r < 0 for r in tier_reserve):
                raise ValueError(
                    f"tier_reserve must be non-empty, non-negative, got "
                    f"{tier_reserve!r}"
                )
            if max(tier_reserve) >= depth:
                raise ValueError(
                    f"tier_reserve {tier_reserve!r} leaves no capacity at "
                    f"depth {depth}"
                )
        if tier_policies is not None:
            tier_policies = tuple(tier_policies)
            bad = [p for p in tier_policies if p not in _POLICIES]
            if not tier_policies or bad:
                raise ValueError(
                    f"tier_policies must be drawn from {_POLICIES}, got "
                    f"{tier_policies!r}"
                )
        self.depth = depth
        self.policy = policy
        self.tier_reserve = tier_reserve
        self.tier_policies = tier_policies
        self.slo = slo or SloMeter()
        self.in_flight = 0
        #: Spill buffer, kept sorted by (tier, arrival ts): re-offers
        #: serve the most important surviving tier first and preserve
        #: original arrival order within a tier.
        self.spilled: List = []
        self._spill_keys: List[tuple] = []
        self._arrival_seq = 0

    @staticmethod
    def _tier_of(arrival) -> int:
        return int(getattr(arrival, "tier", 0))

    def _per_tier(self, table, tier: int, default):
        if table is None:
            return default
        return table[min(tier, len(table) - 1)]

    def reserve_for(self, tier: int) -> int:
        return self._per_tier(self.tier_reserve, tier, 0)

    def policy_for(self, tier: int) -> str:
        return self._per_tier(self.tier_policies, tier, self.policy)

    @property
    def full(self) -> bool:
        return self.in_flight >= self.depth

    def has_room(self, tier: int) -> bool:
        """Capacity check at ``tier``'s effective depth (reservations for
        more-important tiers subtracted)."""
        return self.in_flight < self.depth - self.reserve_for(tier)

    def offer(self, arrival) -> str:
        """One admission decision.  ``ADMITTED`` increments the in-flight
        count (the caller routes the job); ``BLOCKED`` means the caller
        must wait for capacity and re-offer."""
        tier = self._tier_of(arrival)
        self.slo.count("arrived")
        self.slo.count_tier(tier, "arrived")
        self.slo.record_queue_depth(self.in_flight)
        if self.has_room(tier):
            self._admit_one(tier)
            return ADMITTED
        policy = self.policy_for(tier)
        if policy == "shed":
            self.slo.record_shed("queue_full", tier=tier)
            return SHED
        if policy == "spill":
            self.spill(arrival)
            return SPILLED
        return BLOCKED

    def _admit_one(self, tier: int) -> None:
        self.in_flight += 1
        self.slo.count("admitted")
        self.slo.count_tier(tier, "admitted")

    def spill(self, arrival, count: bool = True) -> None:
        """Park an arrival in the spill buffer, sorted by (tier,
        original arrival timestamp, insertion seq): re-offers serve the
        most important surviving tier first and ORIGINAL arrival order
        within a tier.  Keying on the arrival's own timestamp (not
        insertion order) is what keeps the guarantee through
        preemption — a victim requeued here re-enters at its original
        arrival position, ahead of same-tier jobs that arrived later
        but spilled earlier.  ``count=False`` skips the ``spilled``
        counters — the preemption path meters its victim as
        ``preempted``, not as a fresh spill."""
        tier = self._tier_of(arrival)
        key = (tier, float(getattr(arrival, "ts", 0.0)), self._arrival_seq)
        self._arrival_seq += 1
        idx = bisect.bisect(self._spill_keys, key)
        self._spill_keys.insert(idx, key)
        self.spilled.insert(idx, arrival)
        if count:
            self.slo.count("spilled")
            self.slo.count_tier(tier, "spilled")

    def peek_spill(self):
        """Head of the spill buffer (highest tier, oldest) or None."""
        return self.spilled[0] if self.spilled else None

    def pop_spill(self):
        self._spill_keys.pop(0)
        return self.spilled.pop(0)

    def readmit(self, arrival) -> bool:
        """Re-offer a spilled/blocked arrival (no double counting of the
        ``arrived`` counter).  True = admitted."""
        tier = self._tier_of(arrival)
        if not self.has_room(tier):
            return False
        self._admit_one(tier)
        return True

    def release(self, n: int = 1) -> None:
        """A job completed (or was preempted) — free its capacity.
        Reservations are headroom carved out of the shared bound, not
        per-tier occupancy quotas, so release is tier-blind by design —
        ``has_room`` only ever consults the global ``in_flight``."""
        self.in_flight -= n
        assert self.in_flight >= 0, "admission release underflow"
