"""Bounded admission control for the stream driver.

The admission queue bounds **in-flight work** — jobs admitted into a
scheduling session but not yet completed.  (A buffer of *unrouted*
arrivals would always be drained instantly by the router and never
exert backpressure; what an always-on scheduler must bound is the work
it has accepted responsibility for.)  ``depth`` is therefore the
service's concurrent-job capacity, and the queue-depth histogram the
SLO meter records at each offer is the in-flight count.

Three backpressure policies when the queue is full:

  * ``shed``  — reject the arrival, recording the reason
    (``queue_full``) in the SLO meter.  Lossy, latency-protecting.
  * ``spill`` — defer the arrival to a spill buffer; the driver
    re-offers it at the next completion boundary with its submission
    time pushed to the following scheduler grid point ("spill to next
    tick").  Lossless, order-preserving, latency-paying.
  * ``block`` — the producer waits for capacity.  Lossless with the
    original timestamps, but couples the arrival loop to completion
    wall-time; in replay mode the driver advances the sim-release gate
    while blocked so the wait can resolve deterministically.

Decisions are returned as module constants (``ADMITTED`` / ``SHED`` /
``SPILLED`` / ``BLOCKED``); the blocking dance itself lives in the
driver, which owns the condition variable the completions notify.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from pivot_tpu.infra.meter import SloMeter

__all__ = [
    "ADMITTED",
    "AdmissionQueue",
    "BLOCKED",
    "SHED",
    "SPILLED",
]

ADMITTED = "admitted"
SHED = "shed"
SPILLED = "spilled"
BLOCKED = "blocked"

_POLICIES = ("block", "shed", "spill")


class AdmissionQueue:
    """In-flight bound + backpressure decision.  NOT thread-safe on its
    own: the driver serializes every call under its coordination lock
    (the same lock completions notify), so decision + counter update are
    atomic with respect to releases."""

    def __init__(self, depth: int, policy: str = "shed",
                 slo: Optional[SloMeter] = None):
        if depth < 1:
            raise ValueError("admission queue depth must be >= 1")
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r} (use one of "
                f"{_POLICIES})"
            )
        self.depth = depth
        self.policy = policy
        self.slo = slo or SloMeter()
        self.in_flight = 0
        self.spilled = deque()

    @property
    def full(self) -> bool:
        return self.in_flight >= self.depth

    def offer(self, arrival) -> str:
        """One admission decision.  ``ADMITTED`` increments the in-flight
        count (the caller routes the job); ``BLOCKED`` means the caller
        must wait for capacity and re-offer."""
        self.slo.count("arrived")
        self.slo.record_queue_depth(self.in_flight)
        if not self.full:
            self.in_flight += 1
            self.slo.count("admitted")
            return ADMITTED
        if self.policy == "shed":
            self.slo.record_shed("queue_full")
            return SHED
        if self.policy == "spill":
            self.spilled.append(arrival)
            self.slo.count("spilled")
            return SPILLED
        return BLOCKED

    def readmit(self, arrival) -> bool:
        """Re-offer a spilled/blocked arrival (no double counting of the
        ``arrived`` counter).  True = admitted."""
        if self.full:
            return False
        self.in_flight += 1
        self.slo.count("admitted")
        return True

    def release(self, n: int = 1) -> None:
        """A job completed — free its capacity."""
        self.in_flight -= n
        assert self.in_flight >= 0, "admission release underflow"
