"""Bounded, tier-aware admission control for the stream driver.

The admission queue bounds **in-flight work** — jobs admitted into a
scheduling session but not yet completed.  (A buffer of *unrouted*
arrivals would always be drained instantly by the router and never
exert backpressure; what an always-on scheduler must bound is the work
it has accepted responsibility for.)  ``depth`` is therefore the
service's concurrent-job capacity, and the queue-depth histogram the
SLO meter records at each offer is the in-flight count.

Three backpressure policies when the queue is full:

  * ``shed``  — reject the arrival, recording the reason
    (``queue_full``) in the SLO meter.  Lossy, latency-protecting.
  * ``spill`` — defer the arrival to a spill buffer; the driver
    re-offers it at the next completion boundary with its submission
    time pushed to the following scheduler grid point ("spill to next
    tick").  Lossless, order-preserving, latency-paying.
  * ``block`` — the producer waits for capacity.  Lossless with the
    original timestamps, but couples the arrival loop to completion
    wall-time; in replay mode the driver advances the sim-release gate
    while blocked so the wait can resolve deterministically.

**Priority tiers** (round 9, the Borg-NG batch/serving split —
PAPERS.md): every :class:`~pivot_tpu.serve.arrivals.JobArrival` carries
a ``tier`` (0 = most important), and the queue can be built with

  * ``tier_reserve`` — per-tier depth reservations: ``reserve[t]``
    slots are off-limits to arrivals of tier ``t`` (tiers beyond the
    sequence use its last entry), so tier t's effective depth is
    ``depth − reserve[t]``.  Tier 0 conventionally reserves 0: under
    load the low tiers run out of queue *first*, which is exactly the
    "shed low tiers before blocking high ones" ordering.
  * ``tier_policies`` — per-tier backpressure override (same indexing),
    e.g. ``("spill", "shed", "shed")``: tier 0 is lossless while lower
    tiers absorb the sheds.

The spill buffer re-offers in **(tier, arrival-timestamp) order** — the
highest surviving tier first, original arrival order within a tier,
*including* preemption victims re-entering at their original arrival
position (the single-tier case degenerates to pure FIFO, the documented
re-offer ordering guarantee ``tests/test_serve.py`` pins).  Both tier
knobs default to off, under which every decision, counter, and re-offer
is bit-identical to the single-tenant queue.

**Tenant fairness within a tier** (round 17, the DRF shape — Ghodsi et
al.'s dominant-resource fairness under Borg's quota/priority split,
PAPERS.md): tiers order *importance classes*, but inside one tier every
tenant competes for the same reservation, and a single chatty tenant
can occupy a tier's whole effective depth.  With ``tenant_quota=q``
(0 < q ≤ 1) the queue tracks each tenant's **dominant-resource
occupancy** per tier — the sum of its in-flight jobs' dominant shares,
where a job's dominant share is ``max_r(demand_r / capacity_r)``
against the ``capacity`` reference vector (job-count shares when no
capacity is given) — and an arrival whose admission would push its
tenant past ``q`` of the tier's total occupancy is *over quota*: it is
shed/spilled by the tier's backpressure policy with the recorded
reason ``tenant_quota``.  Work-conserving: a tenant alone in its tier
is never quota-limited (idle capacity is not wasted on fairness), and
occupancy releases exactly when the admission settles, so the serve
conservation audit (``infra/audit.py::audit_serve``) can assert the
ledger drains to zero.  ``tenant_quota=None`` (default) keeps every
decision bit-identical to the quota-free queue.

Decisions are returned as module constants (``ADMITTED`` / ``SHED`` /
``SPILLED`` / ``BLOCKED``); the blocking dance itself lives in the
driver, which owns the condition variable the completions notify (as
does in-queue *preemption*, which frees low-tier in-flight capacity
when a high-tier arrival would otherwise degrade — ``serve/driver.py``).
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

from pivot_tpu.infra.meter import SloMeter

__all__ = [
    "ADMITTED",
    "AdmissionQueue",
    "BLOCKED",
    "SHED",
    "SPILLED",
    "dominant_share",
]


def dominant_share(app, capacity: Optional[Sequence[float]]) -> float:
    """A job's DRF dominant share: its total demand's largest fraction
    of the ``capacity`` reference vector (cpus, mem, disk, gpus).
    Falls back to 1.0 — job-count shares — when no capacity vector or
    demand is available (synthetic/unit-test apps)."""
    if capacity is None or app is None:
        return 1.0
    totals = [0.0, 0.0, 0.0, 0.0]
    for group in getattr(app, "groups", ()) or ():
        n = len(getattr(group, "tasks", ()) or ())
        for i, dim in enumerate(("cpus", "mem", "disk", "gpus")):
            totals[i] += n * float(getattr(group, dim, 0.0) or 0.0)
    share = 0.0
    for used, cap in zip(totals, capacity):
        if cap and cap > 0:
            share = max(share, used / float(cap))
    return share if share > 0 else 1.0

ADMITTED = "admitted"
SHED = "shed"
SPILLED = "spilled"
BLOCKED = "blocked"

_POLICIES = ("block", "shed", "spill")


class AdmissionQueue:
    """In-flight bound + backpressure decision.  NOT thread-safe on its
    own: the driver serializes every call under its coordination lock
    (the same lock completions notify), so decision + counter update are
    atomic with respect to releases."""

    def __init__(self, depth: int, policy: str = "shed",
                 slo: Optional[SloMeter] = None,
                 tier_reserve: Optional[Sequence[int]] = None,
                 tier_policies: Optional[Sequence[str]] = None,
                 tenant_quota: Optional[float] = None,
                 capacity: Optional[Sequence[float]] = None):
        if depth < 1:
            raise ValueError("admission queue depth must be >= 1")
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r} (use one of "
                f"{_POLICIES})"
            )
        if tier_reserve is not None:
            tier_reserve = tuple(int(r) for r in tier_reserve)
            if not tier_reserve or any(r < 0 for r in tier_reserve):
                raise ValueError(
                    f"tier_reserve must be non-empty, non-negative, got "
                    f"{tier_reserve!r}"
                )
            if max(tier_reserve) >= depth:
                raise ValueError(
                    f"tier_reserve {tier_reserve!r} leaves no capacity at "
                    f"depth {depth}"
                )
        if tier_policies is not None:
            tier_policies = tuple(tier_policies)
            bad = [p for p in tier_policies if p not in _POLICIES]
            if not tier_policies or bad:
                raise ValueError(
                    f"tier_policies must be drawn from {_POLICIES}, got "
                    f"{tier_policies!r}"
                )
        if tenant_quota is not None and not (0.0 < tenant_quota <= 1.0):
            raise ValueError(
                f"tenant_quota must be in (0, 1], got {tenant_quota!r}"
            )
        if capacity is not None:
            capacity = tuple(float(c) for c in capacity)
            if len(capacity) != 4 or any(c < 0 for c in capacity):
                raise ValueError(
                    "capacity must be 4 non-negative totals "
                    f"(cpus, mem, disk, gpus), got {capacity!r}"
                )
        self.depth = depth
        self.policy = policy
        self.tier_reserve = tier_reserve
        self.tier_policies = tier_policies
        #: DRF tenant fairness (module docstring): a tenant's dominant-
        #: resource occupancy within a tier may not exceed this share of
        #: the tier's total occupancy.  None = quota off (bit-parity).
        self.tenant_quota = tenant_quota
        self.capacity = capacity
        #: (tier, tenant) → in-flight dominant-share occupancy.  Only
        #: maintained when the quota is on; audited to drain to zero.
        self.tenant_occupancy: Dict[Tuple[int, str], float] = {}
        self.slo = slo or SloMeter()
        self.in_flight = 0
        #: Spill buffer, kept sorted by (tier, arrival ts): re-offers
        #: serve the most important surviving tier first and preserve
        #: original arrival order within a tier.
        self.spilled: List = []
        self._spill_keys: List[tuple] = []
        self._arrival_seq = 0

    @staticmethod
    def _tier_of(arrival) -> int:
        return int(getattr(arrival, "tier", 0))

    @staticmethod
    def _tenant_of(arrival) -> str:
        return str(getattr(arrival, "tenant", "default"))

    def _dom_of(self, arrival) -> float:
        """The arrival's dominant share, computed once and cached on the
        app (preemption victims and spill re-offers reuse the SAME
        share their admission charged, so occupancy balances exactly)."""
        app = getattr(arrival, "app", None)
        if app is None:
            return 1.0
        d = getattr(app, "_serve_dom_share", None)
        if d is None:
            d = dominant_share(app, self.capacity)
            try:
                app._serve_dom_share = d
            except AttributeError:
                pass  # slotted test double; recompute next time
        return d

    def over_quota(self, arrival) -> bool:
        """Would admitting ``arrival`` push its tenant past its DRF
        share of the tier's occupancy?  Work-conserving: False whenever
        the tenant is alone in the tier (no other occupancy to be
        unfair to).  Always False with the quota off."""
        if self.tenant_quota is None:
            return False
        tier = self._tier_of(arrival)
        tenant = self._tenant_of(arrival)
        d = self._dom_of(arrival)
        mine = self.tenant_occupancy.get((tier, tenant), 0.0)
        total = sum(
            v for (t, _), v in self.tenant_occupancy.items() if t == tier
        )
        others = total - mine
        if others <= 1e-12:
            return False
        return (mine + d) > self.tenant_quota * (total + d) + 1e-9

    def admissible(self, arrival) -> bool:
        """Room at the arrival's tier AND within its tenant's quota —
        the one predicate the driver's readmission paths consult."""
        return self.has_room(self._tier_of(arrival)) and not (
            self.over_quota(arrival)
        )

    def _per_tier(self, table, tier: int, default):
        if table is None:
            return default
        return table[min(tier, len(table) - 1)]

    def reserve_for(self, tier: int) -> int:
        return self._per_tier(self.tier_reserve, tier, 0)

    def policy_for(self, tier: int) -> str:
        return self._per_tier(self.tier_policies, tier, self.policy)

    @property
    def full(self) -> bool:
        return self.in_flight >= self.depth

    def has_room(self, tier: int) -> bool:
        """Capacity check at ``tier``'s effective depth (reservations for
        more-important tiers subtracted)."""
        return self.in_flight < self.depth - self.reserve_for(tier)

    def offer(self, arrival) -> str:
        """One admission decision.  ``ADMITTED`` increments the in-flight
        count (the caller routes the job); ``BLOCKED`` means the caller
        must wait for capacity and re-offer.  An arrival with room at
        its tier but OVER its tenant's quota takes the tier's
        backpressure policy with the shed reason ``tenant_quota``."""
        tier = self._tier_of(arrival)
        self.slo.count("arrived")
        self.slo.count_tier(tier, "arrived")
        self.slo.record_queue_depth(self.in_flight)
        if self.has_room(tier):
            if self.over_quota(arrival):
                policy = self.policy_for(tier)
                if policy == "shed":
                    self.slo.record_shed("tenant_quota", tier=tier)
                    return SHED
                if policy == "spill":
                    self.spill(arrival)
                    return SPILLED
                return BLOCKED
            self._admit_one(arrival)
            return ADMITTED
        policy = self.policy_for(tier)
        if policy == "shed":
            self.slo.record_shed("queue_full", tier=tier)
            return SHED
        if policy == "spill":
            self.spill(arrival)
            return SPILLED
        return BLOCKED

    def _admit_one(self, arrival) -> None:
        tier = self._tier_of(arrival)
        self.in_flight += 1
        self.slo.count("admitted")
        self.slo.count_tier(tier, "admitted")
        if self.tenant_quota is not None:
            key = (tier, self._tenant_of(arrival))
            self.tenant_occupancy[key] = (
                self.tenant_occupancy.get(key, 0.0) + self._dom_of(arrival)
            )

    def spill(self, arrival, count: bool = True) -> None:
        """Park an arrival in the spill buffer, sorted by (tier,
        original arrival timestamp, insertion seq): re-offers serve the
        most important surviving tier first and ORIGINAL arrival order
        within a tier.  Keying on the arrival's own timestamp (not
        insertion order) is what keeps the guarantee through
        preemption — a victim requeued here re-enters at its original
        arrival position, ahead of same-tier jobs that arrived later
        but spilled earlier.  ``count=False`` skips the ``spilled``
        counters — the preemption path meters its victim as
        ``preempted``, not as a fresh spill."""
        tier = self._tier_of(arrival)
        key = (tier, float(getattr(arrival, "ts", 0.0)), self._arrival_seq)
        self._arrival_seq += 1
        idx = bisect.bisect(self._spill_keys, key)
        self._spill_keys.insert(idx, key)
        self.spilled.insert(idx, arrival)
        if count:
            self.slo.count("spilled")
            self.slo.count_tier(tier, "spilled")

    def peek_spill(self):
        """Head of the spill buffer (highest tier, oldest) or None."""
        return self.spilled[0] if self.spilled else None

    def pop_spill(self, idx: int = 0):
        """Remove and return the ``idx``-th spilled arrival (head by
        default; the driver's re-offer loop passes an index to skip
        past quota-blocked tenants without disturbing the order of
        what stays spilled)."""
        self._spill_keys.pop(idx)
        return self.spilled.pop(idx)

    def readmit(self, arrival) -> bool:
        """Re-offer a spilled/blocked arrival (no double counting of the
        ``arrived`` counter).  True = admitted; quota-aware like
        :meth:`offer` (a re-entering victim must not dodge its tenant's
        share)."""
        if not self.admissible(arrival):
            return False
        self._admit_one(arrival)
        return True

    def release(self, n: int = 1, tier: Optional[int] = None,
                tenant: Optional[str] = None,
                share: Optional[float] = None) -> None:
        """A job completed (or was preempted) — free its capacity.
        Depth reservations are headroom carved out of the shared bound,
        so the in-flight count is tier-blind; the DRF occupancy ledger
        is NOT — when the quota is on, the settling admission's
        (tier, tenant, dominant share) must come back so the tenant's
        occupancy drains exactly (``audit_serve`` asserts the residue
        is zero).  The tier-blind call shape stays valid for quota-free
        services (today's call sites, bit-identical)."""
        self.in_flight -= n
        assert self.in_flight >= 0, "admission release underflow"
        if self.tenant_quota is not None and tier is not None:
            key = (int(tier), tenant or "default")
            left = self.tenant_occupancy.get(key, 0.0) - (
                share if share is not None else 1.0
            )
            if abs(left) < 1e-9:
                self.tenant_occupancy.pop(key, None)
            else:
                self.tenant_occupancy[key] = left
