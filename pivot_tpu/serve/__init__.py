"""Online serving layer: streaming arrivals, admission control, and
SLO-metered continuous scheduling.

Every other entry point in the framework is batch-shaped — a fixed
workload in, run to exhaustion, exit.  This package is the layer the
ROADMAP's "serves heavy traffic" north star needs above the batched
dispatch engine (PR 1's ``sched/batch.py``): an unbounded stream of job
arrivals (:mod:`~pivot_tpu.serve.arrivals`) flows through a bounded
admission queue with configurable backpressure
(:mod:`~pivot_tpu.serve.admission`) into G always-on scheduling
sessions (:mod:`~pivot_tpu.serve.session`) whose per-tick placement
dispatches coalesce into single vmapped device calls via idle-aware,
deadline-flushed ``DispatchBatcher`` slots, all coordinated by the
stream driver (:mod:`~pivot_tpu.serve.driver`) and metered by the
serving-grade :class:`~pivot_tpu.infra.meter.SloMeter`.

Entry points: ``python -m pivot_tpu.experiments.cli serve`` (the CLI
service), ``bench.py``'s ``serve_stream`` row (sustained decisions/sec
+ p99 decision latency at a fixed arrival rate), and the classes below
for embedding.  The correctness bar is inherited from the batch layer:
a served schedule is **bit-identical** to the same job set run through
batch-mode ``ExperimentRun`` (``tests/test_serve.py``).

Round 7 makes the layer *self-healing*: ``ServeDriver`` grows a session
supervisor (``session_factory`` / ``stall_timeout`` / ``max_restarts``
— crashed or stalled sessions are replaced on fresh batcher slots with
their in-flight jobs requeued), sessions forward retry governance
(``retry`` / ``breaker``, ``sched/retry.py``) into their schedulers and
reap dead-lettered jobs as ``failed_jobs``, and device policies degrade
to their CPU twins after repeated kernel failures rather than taking
the service down (``sched/tpu.py`` ``degrade_after``).

Round 9 makes it *multi-tenant*: arrivals carry priority tiers
(:data:`~pivot_tpu.serve.arrivals.TIER_NAMES`), the admission queue
gets per-tier depth reservations and per-tier backpressure policies,
high-tier arrivals can **preempt** admitted-but-unplaced low-tier jobs
(cancel + requeue-to-spill, fully metered and audited), routing can be
least-loaded instead of round-robin, and an **SLO-driven autoscaler**
(:mod:`~pivot_tpu.serve.autoscale`) grows/shrinks the session pool
between ``g_min``/``g_max`` against windowed per-tier p99
decision-latency targets — drain-then-retire on the way down, fresh
batcher slots on the way up.  All knobs default off: the single-tenant
fixed-pool service (and its bit-parity proof) is unchanged.
"""

from pivot_tpu.serve.admission import (
    ADMITTED,
    BLOCKED,
    SHED,
    SPILLED,
    AdmissionQueue,
)
from pivot_tpu.serve.arrivals import (
    TIER_NAMES,
    JobArrival,
    mixed_tier_arrivals,
    poisson_arrivals,
    synthetic_app_factory,
    trace_arrivals,
)
from pivot_tpu.serve.autoscale import AutoscaleConfig, SloAutoscaler
from pivot_tpu.serve.driver import ServeDriver, closed_loop_source
from pivot_tpu.serve.elastic import ElasticConfig, ElasticMeshManager
from pivot_tpu.serve.session import STOP, PreemptRequest, ServeSession

# Crash-safe serving (round 21): the recovery plane's config rides the
# serve namespace so `ServeDriver(recovery=RecoveryConfig(...))` is one
# import away from the driver it arms.
from pivot_tpu.recover import RecoveryConfig

__all__ = [
    "ADMITTED",
    "AdmissionQueue",
    "AutoscaleConfig",
    "BLOCKED",
    "ElasticConfig",
    "ElasticMeshManager",
    "JobArrival",
    "PreemptRequest",
    "RecoveryConfig",
    "SHED",
    "SPILLED",
    "STOP",
    "ServeDriver",
    "ServeSession",
    "SloAutoscaler",
    "TIER_NAMES",
    "closed_loop_source",
    "mixed_tier_arrivals",
    "poisson_arrivals",
    "synthetic_app_factory",
    "trace_arrivals",
]
