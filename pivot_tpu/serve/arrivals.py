"""Arrival generators for the online serving layer.

A serving workload is a stream of :class:`JobArrival` items — an
:class:`~pivot_tpu.workload.Application` stamped with the *sim-time*
instant at which it enters the system.  Two sources:

  * :func:`poisson_arrivals` — synthetic jobs from the
    ``workload/gen.py`` generators at exponential inter-arrival gaps
    (rate λ jobs per sim-second), the classic open-loop load model;
  * :func:`trace_arrivals` — replay of a sampled Alibaba trace window
    (YAML or the converter's columnar ``.npz``, ``workload/convert.py``)
    at its recorded submit times, optionally re-timed onto a Poisson
    process so a fixed trace can be replayed at any target load.

Both are plain generators: the stream driver consumes lazily, so an
unbounded stream (``n_jobs=None``) is just a generator that never ends.
Arrival times are drawn from a seeded ``numpy`` Generator — the stream
is deterministic per seed, which is what makes a served schedule
bit-comparable to the same jobs through batch-mode ``ExperimentRun``
(``tests/test_serve.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, Optional

import numpy as np

from pivot_tpu.workload import Application
from pivot_tpu.workload.gen import (
    SequentialApplicationGenerator,
    _RangeSpec,
)

__all__ = [
    "JobArrival",
    "poisson_arrivals",
    "synthetic_app_factory",
    "trace_arrivals",
]


@dataclasses.dataclass
class JobArrival:
    """One job entering the service at sim-time ``ts``."""

    ts: float
    app: Application


def synthetic_app_factory(
    seed: int = 0,
    n_nodes=(2, 4),
    runtime=(5.0, 60.0),
    instances_hint: int = 4,
) -> Callable[[], Application]:
    """Deterministic factory of small chain-DAG applications.

    Alibaba-trace-like demands (fractional CPUs, fractional memory of a
    7.68 GB-normalized machine) via the same ``_RangeSpec`` sampling the
    batch generators use; suitable for load tests where the *arrival
    process*, not DAG structure, is under study.
    """
    spec = _RangeSpec(
        cpus=(0.5, 2.0),
        mem=(64, 2048),
        runtime=runtime,
        output_size=(0, 200),
    )
    gen = SequentialApplicationGenerator(n_nodes, spec, seed=seed)
    return gen.generate


def poisson_arrivals(
    rate: float,
    n_jobs: Optional[int],
    seed: int = 0,
    make_app: Optional[Callable[[], Application]] = None,
    start: float = 0.0,
) -> Iterator[JobArrival]:
    """Open-loop Poisson stream: exponential gaps at ``rate`` jobs per
    sim-second, apps from ``make_app`` (default: the synthetic chain-DAG
    factory seeded with ``seed``).  ``n_jobs=None`` streams forever."""
    if rate <= 0:
        raise ValueError("arrival rate must be positive")
    rng = np.random.default_rng(seed)
    if make_app is None:
        make_app = synthetic_app_factory(seed=seed)
    t = float(start)
    produced = 0
    while n_jobs is None or produced < n_jobs:
        # Gap first: arrivals at start + Exp gaps, never exactly at the
        # scheduler's t=0 grid point (same-instant submission/tick races
        # are the one thing the bit-parity contract cannot absorb).
        t += float(rng.exponential(1.0 / rate))
        yield JobArrival(t, make_app())
        produced += 1


def trace_arrivals(
    trace_file: str,
    n_apps: Optional[int] = None,
    scale_factor: float = 1000.0,
    rate: Optional[float] = None,
    seed: int = 0,
) -> Iterator[JobArrival]:
    """Replay a sampled Alibaba trace window as an arrival stream.

    With ``rate=None`` jobs keep their recorded submit times (shifted so
    the first arrival lands at its absolute trace offset — the batch
    runner's schedule semantics).  With a ``rate``, the same job
    *sequence* is re-timed onto a seeded Poisson process, which turns
    one trace window into a load dial.
    """
    from pivot_tpu.workload.trace import load_trace_jobs

    schedule = load_trace_jobs(trace_file, scale_factor)
    if n_apps:
        schedule = schedule.take(n_apps)
    if rate is None:
        for ts, apps in schedule.bins:
            for app in apps:
                yield JobArrival(float(ts), app)
        return
    rng = np.random.default_rng(seed)
    t = 0.0
    for _ts, apps in schedule.bins:
        for app in apps:
            t += float(rng.exponential(1.0 / rate))
            yield JobArrival(t, app)
