"""Arrival generators for the online serving layer.

A serving workload is a stream of :class:`JobArrival` items — an
:class:`~pivot_tpu.workload.Application` stamped with the *sim-time*
instant at which it enters the system.  Two sources:

  * :func:`poisson_arrivals` — synthetic jobs from the
    ``workload/gen.py`` generators at exponential inter-arrival gaps
    (rate λ jobs per sim-second), the classic open-loop load model;
  * :func:`trace_arrivals` — replay of a sampled Alibaba trace window
    (YAML or the converter's columnar ``.npz``, ``workload/convert.py``)
    at its recorded submit times, optionally re-timed onto a Poisson
    process so a fixed trace can be replayed at any target load.

Both are plain generators: the stream driver consumes lazily, so an
unbounded stream (``n_jobs=None``) is just a generator that never ends.
Arrival times are drawn from a seeded ``numpy`` Generator — the stream
is deterministic per seed, which is what makes a served schedule
bit-comparable to the same jobs through batch-mode ``ExperimentRun``
(``tests/test_serve.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from pivot_tpu.workload import Application
from pivot_tpu.workload.gen import (
    SequentialApplicationGenerator,
    _RangeSpec,
)

__all__ = [
    "JobArrival",
    "TIER_NAMES",
    "mixed_tier_arrivals",
    "poisson_arrivals",
    "synthetic_app_factory",
    "trace_arrivals",
]

#: Canonical tier vocabulary (Borg-NG's production split, PAPERS.md):
#: tier 0 = latency-sensitive serving (never shed), tier 1 = batch
#: (preemptible, retried), tier 2 = best-effort (first to go).  Tiers
#: are plain ints everywhere — smaller is more important — and this
#: tuple just names the conventional first three for CLI/docs/tenants.
TIER_NAMES = ("serving", "batch", "best_effort")


def tier_name(tier: int) -> str:
    return TIER_NAMES[tier] if 0 <= tier < len(TIER_NAMES) else f"tier{tier}"


@dataclasses.dataclass
class JobArrival:
    """One job entering the service at sim-time ``ts``.

    ``tier`` is the job's priority class (0 = most important — see
    :data:`TIER_NAMES`); ``tenant`` a free-form owner label for
    attribution.  Both default to the single-tenant values, under which
    the serving pipeline is bit-identical to its pre-tier behavior."""

    ts: float
    app: Application
    tier: int = 0
    tenant: str = "default"


def synthetic_app_factory(
    seed: int = 0,
    n_nodes=(2, 4),
    runtime=(5.0, 60.0),
    instances_hint: int = 4,
) -> Callable[[], Application]:
    """Deterministic factory of small chain-DAG applications.

    Alibaba-trace-like demands (fractional CPUs, fractional memory of a
    7.68 GB-normalized machine) via the same ``_RangeSpec`` sampling the
    batch generators use; suitable for load tests where the *arrival
    process*, not DAG structure, is under study.
    """
    spec = _RangeSpec(
        cpus=(0.5, 2.0),
        mem=(64, 2048),
        runtime=runtime,
        output_size=(0, 200),
    )
    gen = SequentialApplicationGenerator(n_nodes, spec, seed=seed)
    return gen.generate


def poisson_arrivals(
    rate: float,
    n_jobs: Optional[int],
    seed: int = 0,
    make_app: Optional[Callable[[], Application]] = None,
    start: float = 0.0,
    tier: int = 0,
    tenant: Optional[str] = None,
) -> Iterator[JobArrival]:
    """Open-loop Poisson stream: exponential gaps at ``rate`` jobs per
    sim-second, apps from ``make_app`` (default: the synthetic chain-DAG
    factory seeded with ``seed``).  ``n_jobs=None`` streams forever.
    Every arrival is stamped ``tier``/``tenant`` (defaults: tier 0).

    Validation is eager (this is a plain function returning a
    generator): a non-positive ``rate`` raises here, at the call site,
    not on first iteration — a silent zero-arrival stream looks exactly
    like a healthy drained service."""
    if not rate > 0:
        raise ValueError(
            f"arrival rate must be positive, got {rate!r} — a non-positive "
            "rate would silently produce a zero-arrival stream"
        )
    rng = np.random.default_rng(seed)
    if make_app is None:
        make_app = synthetic_app_factory(seed=seed)
    if tenant is None:
        tenant = tier_name(tier)

    def gen():
        t = float(start)
        produced = 0
        while n_jobs is None or produced < n_jobs:
            # Gap first: arrivals at start + Exp gaps, never exactly at
            # the scheduler's t=0 grid point (same-instant submission/
            # tick races are the one thing the bit-parity contract
            # cannot absorb).
            t += float(rng.exponential(1.0 / rate))
            yield JobArrival(t, make_app(), tier=tier, tenant=tenant)
            produced += 1

    return gen()


def mixed_tier_arrivals(
    rate: float,
    n_jobs: Optional[int],
    weights: Sequence[float],
    seed: int = 0,
    make_app: Optional[Callable[[], Application]] = None,
    start: float = 0.0,
) -> Iterator[JobArrival]:
    """One Poisson stream carrying several priority tiers: each arrival's
    tier is an independent seeded categorical draw over ``weights``
    (index = tier; weights need not sum to 1).  This is the multi-tenant
    load model the chaos soak and the ``serve_tiers`` bench row drive —
    a single arrival process whose *mix* is under test, not per-tier
    processes (which would decorrelate tier pressure from total load).
    """
    if not rate > 0:
        raise ValueError(
            f"arrival rate must be positive, got {rate!r} — a non-positive "
            "rate would silently produce a zero-arrival stream"
        )
    w = np.asarray(list(weights), dtype=np.float64)
    if w.size == 0 or (w < 0).any() or w.sum() <= 0:
        raise ValueError(
            f"tier weights must be non-negative with a positive sum, got "
            f"{list(weights)!r}"
        )
    w = w / w.sum()
    rng = np.random.default_rng(seed)
    if make_app is None:
        make_app = synthetic_app_factory(seed=seed)

    def gen():
        t = float(start)
        produced = 0
        while n_jobs is None or produced < n_jobs:
            t += float(rng.exponential(1.0 / rate))
            tier = int(rng.choice(w.size, p=w))
            yield JobArrival(
                t, make_app(), tier=tier, tenant=tier_name(tier)
            )
            produced += 1

    return gen()


def trace_arrivals(
    trace_file: str,
    n_apps: Optional[int] = None,
    scale_factor: float = 1000.0,
    rate: Optional[float] = None,
    seed: int = 0,
) -> Iterator[JobArrival]:
    """Replay a sampled Alibaba trace window as an arrival stream.

    With ``rate=None`` jobs keep their recorded submit times (shifted so
    the first arrival lands at its absolute trace offset — the batch
    runner's schedule semantics).  With a ``rate``, the same job
    *sequence* is re-timed onto a seeded Poisson process, which turns
    one trace window into a load dial.

    An empty replay window (no jobs survive the load/``n_apps`` cut) and
    a non-positive re-timing ``rate`` both raise ``ValueError``, eagerly
    at the call site — either would otherwise produce a silent
    zero-arrival stream and a service that "drains instantly" while
    measuring nothing.
    """
    from pivot_tpu.workload.trace import load_trace_jobs

    if rate is not None and not rate > 0:
        raise ValueError(
            f"trace re-timing rate must be positive, got {rate!r} (use "
            "rate=None to replay the recorded submit times)"
        )
    schedule = load_trace_jobs(trace_file, scale_factor)
    if n_apps:
        schedule = schedule.take(n_apps)
    n_jobs = sum(len(apps) for _ts, apps in schedule.bins)
    if n_jobs == 0:
        raise ValueError(
            f"trace replay window from {trace_file!r} is empty (n_apps="
            f"{n_apps!r}) — nothing would ever arrive"
        )

    def gen():
        if rate is None:
            for ts, apps in schedule.bins:
                for app in apps:
                    yield JobArrival(float(ts), app)
            return
        rng = np.random.default_rng(seed)
        t = 0.0
        for _ts, apps in schedule.bins:
            for app in apps:
                t += float(rng.exponential(1.0 / rate))
                yield JobArrival(t, app)

    return gen()
