"""One always-on scheduling session: a continuous DES under live arrivals.

A :class:`ServeSession` is the serving analog of one batch
``ExperimentRun``: the same construction — fresh event kernel, meter,
cluster clone, ``GlobalScheduler`` wired to a policy — but instead of
replaying a fixed schedule to event exhaustion, the session's thread
*drains on demand*: it blocks on a job inbox, injects admitted arrivals
at their sim-time instants, and steps the event kernel until the live
work completes, then goes idle again.  The scheduler is never
``stop()``-ed until shutdown, so its tick grid (``k × interval`` from
sim time 0) keeps running exactly as a batch run's would — idle ticks
are no-ops (empty ready batch ⇒ no policy call, no tick_seq advance,
no meter traffic), which is what makes a served schedule bit-comparable
to the same jobs through batch mode (``tests/test_serve.py``).

Two serving-specific couplings:

  * **dispatch batching** — when the driver hands the session a
    ``BatchClient``, every device placement call parks the thread at its
    tick boundary and coalesces with the other sessions' co-pending
    ticks (``sched/batch.py``); the session marks its slot idle while
    waiting for work so an empty session never stalls a busy one.
  * **the release gate** — an online scheduler may not simulate past
    "now": before stepping an event at sim time t the session waits for
    the driver's release frontier to reach t (the driver advances it as
    arrivals stream in, and to ∞ when the stream ends).  This is what
    guarantees an arrival is injected before the session's clock passes
    its timestamp — without the gate, a fast session could race ahead
    of the arrival stream and every later job would slip.
"""

from __future__ import annotations

import queue
import time
from typing import List, Optional

import numpy as np

from pivot_tpu.des import Environment
from pivot_tpu.infra.meter import Meter, SloMeter
from pivot_tpu.obs import NULL_TRACER, ObsClock
from pivot_tpu.sched import GlobalScheduler
from pivot_tpu.utils import LogMixin

from pivot_tpu.serve.arrivals import JobArrival

__all__ = ["STOP", "PreemptRequest", "ServeSession"]

#: Inbox sentinel: the driver ends a session's loop with this.
STOP = object()


class PreemptRequest:
    """Driver → session mailbox message: cancel ``app`` if it is still
    admitted-but-unplaced here (in-queue preemption).  Delivered through
    the inbox so it executes on the session thread — the only thread
    allowed to mutate this session's event kernel — and FIFO-after any
    arrival it targets.  The session answers via
    ``driver.on_preempt_result`` either way (hit or miss)."""

    __slots__ = ("app",)

    def __init__(self, app):
        self.app = app


def _is_batchable(policy) -> bool:
    """Device-backed, deterministic-routing policies may share a batched
    dispatch (the ``run_grid_lockstep`` criterion, checked structurally
    so pure-numpy serving never imports jax)."""
    return (
        hasattr(policy, "enable_batching")
        and not getattr(policy, "adaptive", False)
        and not getattr(policy, "use_pallas", False)
    )


class ServeSession(LogMixin):
    """One live scheduling context multiplexed by the serve driver."""

    def __init__(
        self,
        label: str,
        cluster,
        policy,
        seed: Optional[int] = None,
        interval: float = 5.0,
        slo: Optional[SloMeter] = None,
        retry=None,
        breaker=None,
        clock: Optional[ObsClock] = None,
        fuse_spans=False,
    ):
        if fuse_spans not in (False, "slo"):
            raise ValueError(
                'ServeSession fuse_spans must be False (per-tick '
                'dispatch, the bit-parity default) or "slo" (fused '
                "spans bounded by the driver's admission window, one "
                "SLO latency sample per span) — unbounded True is a "
                "batch-mode knob: an online scheduler may not "
                "speculate past the stream's revealed frontier "
                "without an SLO checkpoint"
            )
        self.label = label
        self.policy = policy
        self.seed = seed
        self.interval = interval
        #: Serve-span mode (round 17): ``False`` keeps per-tick
        #: dispatch; ``"slo"`` lets the scheduler fuse spans between
        #: SLO checkpoints — spans bounded by the serve driver's
        #: release frontier (``GlobalScheduler.span_horizon``, wired by
        #: the driver), the SLO meter recording ONE decision latency
        #: per span with the span length in the snapshot.  The frontier
        #: bound is INCLUSIVE (round 18): a tick landing exactly on the
        #: revealed frontier joins the span — same instant
        #: ``wait_released`` admits at — so mixed-horizon sessions no
        #: longer truncate spans to one below their gate and fragment
        #: the ragged batcher's K-buckets.  Placements are
        #: bit-identical either way (the span parity contract).
        self.fuse_spans = fuse_spans
        #: One injected obs wall clock for everything this session
        #: meters (round 14): the run Meter and the fallback SLO meter
        #: share it, so their wall snapshots agree exactly.
        self.clock = clock or ObsClock()
        self.slo = slo or SloMeter(clock=self.clock)
        #: Causal trace timeline — swapped for the service-wide tracer
        #: by the driver (like ``slo``); NULL = zero-cost.
        self.tracer = NULL_TRACER
        self.error: Optional[BaseException] = None
        self.completed: List = []
        self.failed: List = []  # dead-lettered (retry-governed) apps
        self._inbox: "queue.Queue" = queue.Queue()
        self._live: List = []  # injected, not yet finished apps
        self._injected: List = []  # every app ever injected, in order
        self._driver = None  # attached by ServeDriver
        self._client = None  # this session's BatchClient (driver-owned)
        self._recovery = None  # RecoveryPlane (driver-owned, round 21)
        self.slot = -1
        #: Supervisor liveness: wall clock of the last event-kernel step
        #: (or inbox wait) — the stall watchdog's heartbeat.
        self.last_progress = time.perf_counter()
        #: Set by the supervisor when this session is declared dead and
        #: replaced; an abandoned session's late callbacks are ignored.
        self.abandoned = False
        #: Drain-then-retire state (autoscaler scale-down): ``retiring``
        #: stops the router sending new work here; ``_retired`` guards
        #: the retire from ever being finalized twice (the finalize path
        #: and a crash-during-drain race on it under the driver's lock).
        self.retiring = False
        self._retired = False
        #: EWMA of recent decision latency (wall s) — the routing
        #: tie-breaker for least-loaded dispatch.  Written only by this
        #: session's decision tap, read by the router (stale reads are
        #: fine: it is a heuristic, not a correctness input).
        self.recent_decision_s = 0.0
        self._kernel_failures_seen = 0

        # Mirror ExperimentRun.run()'s construction exactly — the parity
        # contract depends on the two modes building identical worlds.
        self.env = Environment()
        self.meter = Meter(self.env, cluster.meta, clock=self.clock)
        self.cluster = cluster.clone(self.env, self.meter)
        self.scheduler = GlobalScheduler(
            self.env,
            self.cluster,
            policy,
            interval=interval,
            seed=seed,
            meter=self.meter,
            retry=retry,
            breaker=breaker,
            slo=self.slo,
            # Per-tick dispatch (fuse_spans=False, the default): the SLO
            # meter counts one decision latency per dispatch and the
            # driver's amortization is coalescing per-tick calls ACROSS
            # sessions.  fuse_spans="slo" (round 17) turns span fusion
            # ON — the driver bounds each span at its release frontier
            # (scheduler.span_horizon) so serving never speculates past
            # revealed arrivals, and the span tap below records one SLO
            # latency per span.  Span outputs are bit-identical either
            # way — the serve-vs-batch parity test and the round-17
            # per-tick-referee test both pin it.
            fuse_spans=bool(fuse_spans),
        )
        self.cluster.start()
        self.scheduler.start()
        self._last_unfinished = 0
        self._install_decision_tap()
        if fuse_spans == "slo":
            self._install_span_tap()

    @property
    def batchable(self) -> bool:
        return _is_batchable(self.policy)

    def _install_decision_tap(self) -> None:
        """Wrap ``policy.place`` with the SLO decision-latency recorder.
        Measures the full wall duration of each placement call — batcher
        park time included, which is exactly the latency a caller of an
        online scheduler experiences."""
        orig = self.policy.place

        def timed_place(ctx):
            t0 = time.perf_counter()
            out = orig(ctx)
            dt = time.perf_counter() - t0
            arr = np.asarray(out)
            # Late-bound through the session: the driver swaps in the
            # service-wide SLO meter after construction.
            self.slo.record_decision(dt, int(arr.shape[0]),
                                     int((arr >= 0).sum()))
            if self.tracer.enabled:
                # The dispatch lane of the service timeline: one span
                # per placement call (batcher wait included) — what
                # obs_report's top-N slow dispatches ranks.
                self.tracer.record_span(
                    "dispatch", "place", dt, sim=ctx.env_now,
                    session=self.label, n_tasks=int(arr.shape[0]),
                    n_placed=int((arr >= 0).sum()),
                )
            # Per-tier attribution: the batch's latency counts toward
            # every tier with work in it (mixed-tier ticks are the
            # norm — a tier's histogram must see the latency its jobs
            # actually experienced).  Tier counts weight by tasks.
            tier_tasks = {}
            for t in ctx.tasks:
                tier = int(getattr(t.application, "_serve_tier", 0))
                tier_tasks[tier] = tier_tasks.get(tier, 0) + 1
            for tier, n in tier_tasks.items():
                self.slo.record_decision_tier(tier, dt, n_tasks=n)
            # Routing telemetry: EWMA over this session's recent calls.
            self.recent_decision_s = (
                0.8 * self.recent_decision_s + 0.2 * dt
            )
            # Degradation telemetry (device policies only): surface
            # kernel failures absorbed by the CPU-twin fallback and
            # ticks served degraded (``sched/tpu.py`` degrade_after).
            failures = getattr(self.policy, "kernel_failures", 0)
            if failures > self._kernel_failures_seen:
                self.slo.count(
                    "kernel_failures", failures - self._kernel_failures_seen
                )
                self._kernel_failures_seen = failures
            if getattr(self.policy, "degraded", False):
                self.slo.count("degraded_decisions")
            return out

        self.policy.place = timed_place

    def _install_span_tap(self) -> None:
        """Wrap ``policy.place_span`` with the SLO span recorder
        (``fuse_spans="slo"`` only).  A served span is ONE dispatch —
        the latency its jobs actually experienced — so it lands as one
        decision-latency sample plus the span length
        (``SloMeter.record_span_decision``); a DECLINED span (None)
        records nothing (the per-tick path then serves the tick through
        the ordinary decision tap).  Ticks a replay aborts are
        re-served per-tick and meter there — same accounting rule as
        the per-tick path: every dispatch counts the batch it decided.
        No-op for policies without a span tier (numpy arms)."""
        orig = getattr(self.policy, "place_span", None)
        if orig is None:
            return

        def timed_place_span(ctx, plan):
            t0 = time.perf_counter()
            out = orig(ctx, plan)
            dt = time.perf_counter() - t0
            if out is None:
                return None
            k_dyn = plan.n_ticks
            placements = out.placements[:k_dyn]
            n_placed = int((placements >= 0).sum())
            n_tasks = len(plan.slots)
            self.slo.record_span_decision(dt, k_dyn, n_tasks, n_placed)
            if self.tracer.enabled:
                self.tracer.record_span(
                    "dispatch", "place_span", dt, sim=ctx.env_now,
                    session=self.label, n_ticks=k_dyn,
                    n_tasks=n_tasks, n_placed=n_placed,
                )
            # Per-tier attribution mirrors the per-tick tap: the span's
            # latency counts toward every tier with slots in it.
            tier_tasks = {}
            for t in plan.slots:
                tier = int(getattr(t.application, "_serve_tier", 0))
                tier_tasks[tier] = tier_tasks.get(tier, 0) + 1
            for tier, n in tier_tasks.items():
                self.slo.record_decision_tier(tier, dt, n_tasks=n)
            self.recent_decision_s = (
                0.8 * self.recent_decision_s + 0.2 * dt
            )
            return out

        self.policy.place_span = timed_place_span

    def attach_recovery(self, plane) -> None:
        """Wire the serve recovery plane (round 21) into this session's
        dispatch path.  Three hooks, each honoring the write-ahead
        contract:

          * a ``span`` journal record is appended BEFORE each
            ``place_span`` dispatch, a ``splice`` record before each
            ``span_splice`` — the decision is durable-before-effective;
          * the snapshot cadence tap fires AFTER a span dispatch
            returns — the pending carry is the previous jit OUTPUT,
            the same safe pre-donation window the resident mirror-diff
            reads in;
          * when the plane's watchdog is armed
            (``RecoveryConfig.dispatch_timeout_s``), the dispatch runs
            under its timeout + capped deterministic-backoff retries.

        Installed by the driver AFTER the session's own SLO taps, so
        the journal wraps the outermost dispatch surface — the latency
        the taps measure includes any watchdog retries, which is the
        latency the caller really experienced."""
        self._recovery = plane
        armed = plane.config.dispatch_timeout_s is not None
        orig_span = getattr(self.policy, "place_span", None)
        if orig_span is not None:

            def recovered_place_span(ctx, plan, _orig=orig_span):
                plane.journal_span(
                    self.label, ctx.env_now, plan.n_ticks,
                    len(plan.slots),
                )
                if armed:
                    out = plane.watchdog.guard(
                        lambda: _orig(ctx, plan),
                        key=f"{self.label}:span",
                    )
                else:
                    out = _orig(ctx, plan)
                if out is not None:
                    plane.note_span(self.policy)
                return out

            self.policy.place_span = recovered_place_span
        orig_splice = getattr(self.policy, "span_splice", None)
        if orig_splice is not None:

            def recovered_span_splice(ctx, plan, k, new_tasks,
                                      _orig=orig_splice):
                plane.journal_splice(
                    self.label, ctx.env_now, k, len(new_tasks)
                )
                out = _orig(ctx, plan, k, new_tasks)
                if out is not None:
                    plane.note_splice()
                return out

            self.policy.span_splice = recovered_span_splice
        if armed:
            orig_place = self.policy.place

            def guarded_place(ctx, _orig=orig_place):
                return plane.watchdog.guard(
                    lambda: _orig(ctx), key=f"{self.label}:place",
                )

            self.policy.place = guarded_place

    # -- driver-facing ----------------------------------------------------
    def offer(self, arrival: JobArrival) -> None:
        """Route one admitted arrival to this session (driver thread)."""
        self._inbox.put(arrival)

    def request_preempt(self, app) -> None:
        """Ask this session (driver thread) to cancel an admitted-but-
        unplaced app; answered asynchronously on the session thread."""
        self._inbox.put(PreemptRequest(app))

    @property
    def load(self) -> int:
        """Routing load signal: queued + live jobs on this session.
        Approximate by design (both ends mutate concurrently) — the
        least-loaded router only needs relative ordering."""
        return self._inbox.qsize() + len(self._live)

    def shutdown(self) -> None:
        self._inbox.put(STOP)

    # -- the session thread ----------------------------------------------
    def loop(self, client=None) -> None:
        """Thread body: wait for work, inject, drain, repeat until STOP."""
        try:
            while True:
                if client is not None:
                    client.set_idle(True)
                item = self._inbox.get()
                self.last_progress = time.perf_counter()
                if client is not None:
                    client.set_idle(False)
                if item is STOP or self.abandoned:
                    break
                if isinstance(item, PreemptRequest):
                    self._handle_preempt(item.app)
                    continue
                self._inject(item)
                self._drain(client)
        except BaseException as exc:  # noqa: BLE001 — surfaced by driver
            self.error = exc
            if self._driver is not None:
                self._driver.on_session_error(self, exc)
        finally:
            self.scheduler.stop()
            if client is not None:
                client.close()

    def _poll_inbox(self) -> None:
        while True:
            try:
                item = self._inbox.get_nowait()
            except queue.Empty:
                return
            if item is STOP:
                # Re-queue so the outer loop sees it after the drain.
                self._inbox.put(item)
                return
            if isinstance(item, PreemptRequest):
                self._handle_preempt(item.app)
                continue
            self._inject(item)

    def _handle_preempt(self, app) -> None:
        """Serve one preemption request on the session thread.  A hit
        requires the app to still be admitted-but-unplaced: either its
        submission callback has not fired yet (cancel it — the scheduler
        never saw the app) or every materialized task is still NASCENT
        (``GlobalScheduler.withdraw``).  Anything else — placed, running,
        finished, already reaped — is a miss; the job keeps its capacity
        and terminates through the normal paths."""
        ok = False
        if app in self._live:
            cb = getattr(app, "_serve_submit_cb", None)
            if cb is not None:
                # Submission still pending on the heap: cancel in place.
                cb.cancel()
                app._serve_submit_cb = None
                ok = True
            else:
                ok = self.scheduler.withdraw(app)
            if ok:
                self._live.remove(app)
                self._injected.remove(app)
        if self._driver is not None:
            self._driver.on_preempt_result(self, app, ok, self.env.now)

    def _inject(self, arrival: JobArrival) -> None:
        """Enter one job: submission scheduled at its sim-time instant,
        or immediately (a recorded *late injection*) when the session's
        clock has already passed it."""
        env = self.env
        app = arrival.app
        self._live.append(app)
        self._injected.append(app)
        app._serve_admit_ts = arrival.ts
        app._serve_tier = int(getattr(arrival, "tier", 0))
        app._serve_tenant = getattr(arrival, "tenant", "default")
        if self.tracer.enabled:
            trace = getattr(app, "_obs_trace", None)
            if trace is not None:
                self.tracer.stage(
                    trace, "injected",
                    sim=max(arrival.ts, env.now),
                    session=self.label, late=arrival.ts < env.now,
                )
        if arrival.ts >= env.now:
            # The callback handle rides on the app so an in-queue
            # preemption arriving before it fires can cancel the
            # submission outright (the cheapest possible victim).
            def _submit(app=app):
                app._serve_submit_cb = None
                self.scheduler.submit(app)

            app._serve_submit_cb = env.schedule_callback_at(
                arrival.ts, _submit
            )
        else:
            app._serve_submit_cb = None
            self.slo.count("late_injections")
            self.scheduler.submit(app)

    def _work_pending(self) -> bool:
        return bool(self._live)

    def _drain(self, client=None) -> None:
        env = self.env
        driver = self._driver
        while self._work_pending():
            if self.abandoned:
                return  # supervisor replaced this session mid-drain
            self._poll_inbox()
            t_next = env.peek()
            if t_next == float("inf"):
                break  # defensive: nothing scheduled at all
            if driver is not None and not driver.wait_released(
                self, t_next, client
            ):
                return  # shutdown requested mid-drain
            self._poll_inbox()  # arrivals routed while gated
            env.step()
            self.last_progress = time.perf_counter()
            if self.scheduler._n_unfinished != self._last_unfinished:
                self._last_unfinished = self.scheduler._n_unfinished
                self._reap_completions()
        # Close out the current instant (same-time meter/bookkeeping
        # events) so the idle state the session parks in is final.
        now = env.now
        while env.peek() <= now:
            env.step()
        self._reap_completions()

    def _reap_completions(self) -> None:
        done = [
            a for a in self._live
            if a.is_finished or getattr(a, "failed", False)
        ]
        if not done:
            return
        self._live = [a for a in self._live if a not in done]
        for app in done:
            if app.is_finished:
                self.completed.append(app)
                admit_ts = getattr(app, "_serve_admit_ts", app.start_time)
                self.slo.record_sojourn(
                    max(app.end_time - admit_ts, 0.0),
                    tier=int(getattr(app, "_serve_tier", 0)),
                )
            else:
                # Dead-lettered by retry governance: the job terminates
                # as failed — its admission capacity is still released
                # (the service must not wedge on a lost job).
                self.failed.append(app)
            if self._driver is not None:
                self._driver.on_completed(
                    self, app, self.env.now, failed=not app.is_finished
                )

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        s = self.meter.summary()
        # Injection order, not completion order: the float sum must run
        # in the same order batch-mode ``ExperimentRun`` sums its
        # schedule, or avg_runtime drifts by an ULP (the parity test
        # compares exact values).
        runtimes = [
            a.end_time - a.start_time for a in self._injected
            if a.is_finished
        ]
        s["label"] = self.label
        s["n_apps"] = len(self.completed)
        s["avg_runtime"] = (
            sum(runtimes) / len(runtimes) if runtimes else 0.0
        )
        s["n_failed"] = len(self.failed)
        s["degraded"] = bool(getattr(self.policy, "degraded", False))
        # Span-fusion observability (fuse_spans="slo"): fused spans
        # served, ticks they covered, replay aborts, fast-forwarded
        # no-op ticks — all zero under per-tick dispatch.
        s["span_stats"] = dict(self.scheduler.span_stats)
        s["kernel_failures"] = int(
            getattr(self.policy, "kernel_failures", 0)
        )
        s["dead_letters"] = len(self.scheduler.dead_letters)
        return s
