"""The stream driver: admission, routing, release gate, lifecycle.

Topology (docs/ARCHITECTURE.md "The online serving layer")::

    arrivals ──▶ AdmissionQueue ──▶ round-robin router ──▶ session inboxes
    (Poisson /     (bounded;          (deterministic)        │ one thread
     trace-replay)  block/shed/spill)                        ▼ per session
                                                   ServeSession event loops
                                                         │ placement ticks
                                                         ▼
                                                   DispatchBatcher slots
                                              (idle-aware, deadline flush)
                                                         │
                                                         ▼
                                           ONE [G]-vmapped device dispatch

The driver owns one condition variable that serializes every control
decision: admission (in-flight accounting + backpressure), routing
(round-robin over sessions — deterministic, which is what lets a served
schedule be compared bit-for-bit against per-session batch runs), the
**release gate** (sessions may not step an event past the largest
arrival timestamp the stream has revealed — an online scheduler cannot
simulate past "now"), completions (capacity release + spill re-offers +
closed-loop refill), and shutdown.

Wall-clock pacing is optional (``pace`` sim-seconds per wall-second);
the default *replay* mode runs as fast as the sessions can step, which
is both the bench configuration and the deterministic one.
"""

from __future__ import annotations

import math
import queue as _pyqueue
import threading
from typing import Callable, Iterable, List, Optional

import time

from pivot_tpu.infra.meter import SloMeter
from pivot_tpu.utils import LogMixin

from pivot_tpu.serve.admission import ADMITTED, BLOCKED, AdmissionQueue
from pivot_tpu.serve.arrivals import JobArrival
from pivot_tpu.serve.session import STOP, ServeSession

__all__ = ["ServeDriver", "closed_loop_source"]


class ServeDriver(LogMixin):
    """Always-on scheduling service over G concurrent sessions.

    **Session supervision** (round 7): when constructed with a
    ``session_factory``, the driver self-heals instead of fail-stopping —
    a session that crashes (its thread raises) or stalls past
    ``stall_timeout`` wall-seconds with live work is *abandoned*: its
    in-flight jobs (un-injected inbox arrivals plus a clone of every
    live, partially-run job) are requeued, a replacement session from the
    factory takes its place on a FRESH :class:`DispatchBatcher` slot
    (``respawn_client`` — the dead slot's state is never inherited), and
    the service keeps serving.  Requeued jobs retain their admission
    capacity across the restart: re-offering them past the backpressure
    bound could shed an already-admitted job, which would break the
    at-least-once contract the supervisor exists to provide; the
    admission queue still governs them (their completion releases
    capacity exactly once).  ``max_restarts`` bounds the recovery budget
    — exhausting it falls back to the fail-stop path.
    """

    #: Wall seconds between capacity re-checks while a ``block``-policy
    #: producer waits; each expiry also advances the release gate one
    #: scheduler tick so blocked admission cannot freeze sim time.
    _BLOCK_POLL_S = 0.02

    def __init__(
        self,
        sessions: List[ServeSession],
        queue_depth: int = 64,
        backpressure: str = "shed",
        flush_after: Optional[float] = None,
        slo: Optional[SloMeter] = None,
        session_factory: Optional[Callable[[str], ServeSession]] = None,
        max_restarts: int = 2,
        stall_timeout: Optional[float] = None,
    ):
        if not sessions:
            raise ValueError("ServeDriver needs at least one session")
        if stall_timeout is not None and stall_timeout <= 0:
            raise ValueError("stall_timeout must be positive (or None)")
        self.sessions = list(sessions)
        self.slo = slo or SloMeter()
        self.queue = AdmissionQueue(queue_depth, backpressure, self.slo)
        self.flush_after = flush_after
        self.interval = sessions[0].interval
        self.batcher = None
        self._cv = threading.Condition()
        self._released = 0.0
        self._stop = False
        self._errors: List[BaseException] = []
        self._rr = 0
        self._completion_hooks: List[Callable] = []
        #: Supervisor state (inert when ``session_factory`` is None).
        self._session_factory = session_factory
        self._max_restarts = max_restarts
        self.stall_timeout = stall_timeout
        self._restarts = 0
        #: (session, thread) for every session thread ever spawned.
        self._threads: List = []
        self._abandoned: List[ServeSession] = []
        self._watch_stop = threading.Event()
        for slot, s in enumerate(self.sessions):
            s._driver = self
            s.slot = slot
            s.slo = self.slo  # one service-wide SLO meter
            s.scheduler.slo = self.slo  # dead-letter sheds land here too

    # -- gate + coordination ----------------------------------------------
    def wait_released(self, session: ServeSession, t: float,
                      client=None) -> bool:
        """Block ``session`` until the release frontier reaches sim time
        ``t`` (or new work lands in its inbox, or shutdown).  The
        session's batcher slot is marked idle for the duration so gated
        sessions never park co-pending dispatches.  Returns False on
        shutdown."""
        with self._cv:
            if self._released >= t or not session._inbox.empty():
                return not self._stop
            if client is not None:
                client.set_idle(True)
            try:
                self._cv.wait_for(
                    lambda: (
                        self._stop
                        or self._released >= t
                        or not session._inbox.empty()
                    )
                )
            finally:
                if client is not None:
                    client.set_idle(False)
            return not self._stop

    def _release_to(self, ts: float) -> None:
        if ts > self._released:
            self._released = ts
            self._cv.notify_all()

    def _next_tick(self, t: float) -> float:
        return (math.floor(t / self.interval) + 1) * self.interval

    def advance_gate(self) -> None:
        """Let sim time flow one scheduler tick with no new arrivals —
        the "time passes while we wait" primitive behind block-mode
        admission and the closed-loop load generator (both wait on
        completions that can only happen if the sessions may advance)."""
        with self._cv:
            if self._released != float("inf"):
                self._release_to(self._next_tick(self._released))

    # -- completions -------------------------------------------------------
    def add_completion_hook(self, fn: Callable) -> None:
        """``fn(session, app, sim_now)`` after every job completion —
        the closed-loop load generator's refill tap."""
        self._completion_hooks.append(fn)

    def on_completed(self, session: ServeSession, app, sim_now: float,
                     failed: bool = False):
        if session.abandoned:
            return  # a replaced session's stale thread reporting late
        with self._cv:
            self.queue.release()
            self.slo.count("failed_jobs" if failed else "completed")
            self._reoffer_spilled(after_sim=sim_now)
            self._cv.notify_all()
        for fn in self._completion_hooks:
            fn(session, app, sim_now)

    def on_session_error(self, session: ServeSession, exc) -> None:
        if session.abandoned:
            return  # already replaced by the supervisor; nothing to do
        if (
            self._session_factory is not None
            and self._restarts < self._max_restarts
            and not self._stop
        ):
            self.logger.error(
                "session %s crashed (%s) — supervisor restarting",
                session.label, exc,
            )
            self._restart_session(session, close_client=False)
            return
        with self._cv:
            self._errors.append(exc)
            self._stop = True
            self._cv.notify_all()
        for s in self.sessions + self._abandoned:
            s.shutdown()

    # -- the session supervisor --------------------------------------------
    def _restart_session(self, dead: ServeSession,
                         close_client: bool) -> None:
        """Replace a crashed/stalled session: requeue its in-flight jobs
        into a factory-fresh session on a fresh batcher slot.  Called
        from the dying session's own thread (crash path — its client
        closes itself in the loop's ``finally``) or from the watchdog
        (stall path — ``close_client=True``, the stalled thread may never
        reach its finally).

        Stall-path caveat (best effort by design): the wedged thread may
        still be mid-``env.step`` while this reads ``dead._live`` and
        clones its apps — Python threads cannot be paused, so a
        truly-concurrent mutation can tear a clone.  The crash path (the
        common case) has no such window: the dying thread is parked in
        its own except handler while it runs this."""
        with self._cv:
            if self._stop or dead.abandoned:
                return
            dead.abandoned = True
            self._restarts += 1
            self._abandoned.append(dead)
            self.slo.count("session_restarts")
            idx = self.sessions.index(dead)
            # In-flight work to recover: arrivals routed but never
            # injected keep their original timestamps; live (possibly
            # partially-run) jobs are resubmitted as clones — the dead
            # session's world is gone, so their execution restarts, but
            # their admission capacity is retained (see class docstring).
            lost: List[JobArrival] = []
            while True:
                try:
                    item = dead._inbox.get_nowait()
                except _pyqueue.Empty:
                    break
                if item is not STOP:
                    lost.append(item)
            for app in dead._live:
                if app.is_finished or getattr(app, "failed", False):
                    # Terminated inside the dead session but never reaped
                    # (the crash/stall hit between the state flip and
                    # _reap_completions): settle its admission capacity
                    # HERE — the abandoned thread's late reap is ignored
                    # by on_completed, so skipping it would leak a queue
                    # slot per restart.
                    self.queue.release()
                    self.slo.count(
                        "completed" if app.is_finished else "failed_jobs"
                    )
                    continue
                ts = getattr(app, "_serve_admit_ts", 0.0)
                lost.append(JobArrival(ts, app.clone()))
            self._reoffer_spilled()
            new = self._session_factory(f"{dead.label}-r{self._restarts}")
            new._driver = self
            new.slot = dead.slot
            new.slo = self.slo
            new.scheduler.slo = self.slo
            self.sessions[idx] = new
            client = None
            if self.batcher is not None:
                client = self.batcher.respawn_client()
                new.policy.enable_batching(client)
            new._client = client
            thread = threading.Thread(
                target=new.loop, args=(client,),
                name=f"serve-{new.label}", daemon=True,
            )
            self._threads.append((new, thread))
            thread.start()
            # Requeue: submission times never before the release
            # frontier's next tick (a readmission cannot land in the new
            # session's past).
            floor_t = (
                self._released if self._released != float("inf") else None
            )
            for arr in lost:
                ts = (
                    arr.ts if floor_t is None
                    else max(arr.ts, self._next_tick(floor_t))
                )
                self.slo.count("requeued")
                new.offer(JobArrival(ts, arr.app))
            self._cv.notify_all()
        # Unblock the dead session outside the lock: wake it if parked on
        # its inbox (it sees ``abandoned`` and exits), and reclaim its
        # batcher slot on the stall path.
        dead.shutdown()
        if close_client and getattr(dead, "_client", None) is not None:
            dead._client.close()

    def _watchdog(self) -> None:
        """Stall detector: a session with live work whose event loop has
        not stepped for ``stall_timeout`` wall-seconds is declared dead
        and replaced (its wedged thread is abandoned — Python threads
        cannot be killed — and ignored when it eventually wakes)."""
        poll = self.stall_timeout / 4.0
        while not self._watch_stop.wait(poll):
            if self._stop:
                return
            now = time.perf_counter()
            for s in list(self.sessions):
                if s.abandoned or s.error is not None or not s._live:
                    continue
                if now - s.last_progress <= self.stall_timeout:
                    continue
                if (
                    self._session_factory is None
                    or self._restarts >= self._max_restarts
                ):
                    self.on_session_error(
                        s,
                        RuntimeError(
                            f"session {s.label} stalled "
                            f"> {self.stall_timeout}s with live work"
                        ),
                    )
                    return
                self.logger.error(
                    "session %s stalled > %.3fs — supervisor restarting",
                    s.label, self.stall_timeout,
                )
                self._restart_session(s, close_client=True)

    def _reoffer_spilled(self, after_sim: Optional[float] = None) -> None:
        """Drain the spill buffer into freed capacity (cv held).  A
        spilled job's submission lands no earlier than the scheduler
        grid point after the instant that freed its slot — the "spill to
        next tick" contract.  ``after_sim`` is the freeing completion's
        sim time; the belt-and-braces call sites without one (capacity
        cannot actually be free there — every release re-offers
        immediately) fall back to the release frontier so a readmission
        can never land in a session's past."""
        while self.queue.spilled and not self.queue.full:
            arr = self.queue.spilled.popleft()
            floor_t = after_sim
            if floor_t is None and self._released != float("inf"):
                floor_t = self._released
            if floor_t is not None:
                arr = JobArrival(
                    max(arr.ts, self._next_tick(floor_t)), arr.app
                )
            self.queue.readmit(arr)
            self._route(arr)

    # -- admission + routing ----------------------------------------------
    def _route(self, arrival: JobArrival) -> None:
        target = self.sessions[self._rr % len(self.sessions)]
        self._rr += 1
        target.offer(arrival)
        self._cv.notify_all()

    def _admit(self, arrival: JobArrival) -> None:
        with self._cv:
            # An arrival at ts proves the stream silent before ts: time
            # may flow to it even while admission deliberates.
            self._release_to(arrival.ts)
            self._reoffer_spilled()
            status = self.queue.offer(arrival)
            while (
                status == BLOCKED and not self._stop and not self._errors
            ):
                self.slo.count("blocked_waits")
                notified = self._cv.wait(timeout=self._BLOCK_POLL_S)
                if not notified and self._released != float("inf"):
                    # No completion freed capacity: advance sim time one
                    # tick so in-flight work can progress toward one.
                    self._release_to(self._next_tick(self._released))
                if not self.queue.full:
                    self.queue.readmit(arrival)
                    status = ADMITTED
            if status == ADMITTED:
                self._route(arrival)

    def _produce(self, arrivals: Iterable[JobArrival],
                 pace: Optional[float]) -> None:
        wall0 = time.perf_counter()
        try:
            for arr in arrivals:
                if self._stop:
                    return
                if pace:
                    lag = arr.ts / pace - (time.perf_counter() - wall0)
                    if lag > 0:
                        time.sleep(lag)
                self._admit(arr)
            # Stream exhausted: reveal the open horizon, wait for the
            # admitted work (and any spilled stragglers) to drain.
            with self._cv:
                self._release_to(float("inf"))
                while not self._stop and not self._errors and (
                    self.queue.in_flight > 0 or self.queue.spilled
                ):
                    self._reoffer_spilled()
                    if self.queue.in_flight == 0 and not self.queue.spilled:
                        break
                    self._cv.wait(timeout=0.5)
        except BaseException as exc:  # noqa: BLE001 — surfaced by run()
            with self._cv:
                self._errors.append(exc)
                self._stop = True
                self._cv.notify_all()
        finally:
            with self._cv:
                self._release_to(float("inf"))
            for s in self.sessions:
                s.shutdown()

    # -- lifecycle ---------------------------------------------------------
    def run(self, arrivals: Iterable[JobArrival],
            pace: Optional[float] = None) -> dict:
        """Serve the stream to completion; returns the service report.

        Batching engages when every session's policy qualifies (device
        backend, deterministic routing — the ``run_grid_lockstep``
        criterion): each session gets a ``DispatchBatcher`` slot and the
        caller's thread runs the flush coordinator.  Otherwise sessions
        run free (numpy/naive policies have no dispatch to coalesce).
        """
        clients = [None] * len(self.sessions)
        if all(s.batchable for s in self.sessions):
            # Initialize the backend once, here, before any session
            # thread dispatches — concurrent first-touch PJRT client
            # creation is not safe (same guard as run_grid_lockstep).
            import jax

            jax.default_backend()
            from pivot_tpu.sched.batch import DispatchBatcher

            self.batcher = DispatchBatcher(
                len(self.sessions), flush_after=self.flush_after
            )
            clients = [self.batcher.client() for _ in self.sessions]
            for s, c in zip(self.sessions, clients):
                s.policy.enable_batching(c)
        for s, c in zip(self.sessions, clients):
            s._client = c
            self._threads.append(
                (
                    s,
                    threading.Thread(
                        target=s.loop, args=(c,),
                        name=f"serve-{s.label}", daemon=True,
                    ),
                )
            )
        for _s, t in list(self._threads):
            t.start()
        watchdog = None
        if self.stall_timeout is not None:
            watchdog = threading.Thread(
                target=self._watchdog, name="serve-watchdog", daemon=True,
            )
            watchdog.start()
        producer = threading.Thread(
            target=self._produce, args=(arrivals, pace),
            name="serve-producer", daemon=True,
        )
        producer.start()
        if self.batcher is not None:
            self.batcher.serve()
        # Supervisor restarts append replacement threads while we join —
        # loop until every NON-ABANDONED thread has exited.  Abandoned
        # sessions' threads are excluded: a permanently wedged thread is
        # exactly what the stall watchdog replaced (it cannot be killed,
        # only out-lived — daemon threads die with the process), and
        # waiting on it would hang the service shutdown the restart just
        # saved.
        while True:
            pending = [
                t for s, t in self._threads
                if t.is_alive() and not s.abandoned
            ]
            if not pending:
                break
            for t in pending:
                t.join(timeout=0.5)
        producer.join()
        self._watch_stop.set()
        if watchdog is not None:
            watchdog.join()
        errors = self._errors + [
            s.error for s in self.sessions if s.error is not None
        ]
        if errors:
            raise errors[0]
        return self.report()

    def report(self) -> dict:
        return {
            "sessions": len(self.sessions),
            "backpressure": self.queue.policy,
            "queue_depth": self.queue.depth,
            "flush_after_s": self.flush_after,
            "restarts": self._restarts,
            "slo": self.slo.snapshot(),
            "batcher": dict(self.batcher.stats) if self.batcher else None,
            "per_session": [s.summary() for s in self.sessions],
        }


def closed_loop_source(
    driver: ServeDriver,
    make_app: Callable,
    concurrency: int,
    n_jobs: int,
    stagger: float = 1e-3,
):
    """Closed-loop load generator: keep ``concurrency`` jobs in flight;
    every completion injects the next job at the scheduler grid point
    after the completing session's clock — the N-users-think-time-zero
    model, the complement of the open-loop Poisson stream."""
    import queue as _queue

    feed: "_queue.Queue" = _queue.Queue()
    produced = {"n": 0}
    lock = threading.Lock()

    def emit(ts: float) -> None:
        with lock:
            if produced["n"] >= n_jobs:
                return
            produced["n"] += 1
        feed.put(JobArrival(ts, make_app()))

    for i in range(min(concurrency, n_jobs)):
        emit(stagger * (i + 1))
    driver.add_completion_hook(
        lambda _s, _a, sim_now: emit(driver._next_tick(sim_now))
    )

    def gen():
        yielded = 0
        while yielded < n_jobs:
            if driver._stop:
                return
            try:
                item = feed.get(timeout=0.02)
            except _queue.Empty:
                # No completion yet: the in-flight jobs need sim time to
                # finish, and only the producer can grant it.
                driver.advance_gate()
                continue
            yield item
            yielded += 1

    return gen()
