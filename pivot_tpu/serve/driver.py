"""The stream driver: admission, routing, release gate, lifecycle.

Topology (docs/ARCHITECTURE.md "The online serving layer")::

    arrivals ──▶ AdmissionQueue ──▶ router (rr / least-loaded) ──▶ inboxes
    (Poisson /     (bounded, tiered;                               │ one
     trace-replay)  block/shed/spill                               ▼ thread
     × priority     + in-queue                           ServeSession event
       tiers)         preemption)                        loops │ placement
                                                               ▼ ticks
                                                   DispatchBatcher slots
                                              (idle-aware, deadline flush,
                                               autoscaler-resized)
                                                         │
                                                         ▼
                                           ONE [G]-vmapped device dispatch

The driver owns one condition variable that serializes every control
decision: admission (in-flight accounting + tier-ordered backpressure +
in-queue preemption), routing (deterministic round-robin by default —
what lets a served schedule be compared bit-for-bit against per-session
batch runs — or least-loaded over inbox depth + recent decision
latency), the **release gate** (sessions may not step an event past the
largest arrival timestamp the stream has revealed — an online scheduler
cannot simulate past "now"), completions (capacity release + spill
re-offers + closed-loop refill), pool resizing (supervisor restarts,
autoscaler grow/retire), and shutdown.

**Multi-tenant serving** (round 9): every arrival carries a priority
tier (0 = most important).  Under pressure the service *degrades, never
fails* (SpotServe, PAPERS.md): per-tier depth reservations and per-tier
backpressure policies shed/spill the low tiers first, and — with
``preempt=True`` — a high-tier arrival that would still degrade
preempts an admitted-but-unplaced lower-tier job instead: the victim is
cancelled on its session's thread (submission callback cancelled, or
``GlobalScheduler.withdraw`` if already submitted but never placed),
its capacity freed, and the victim requeued to the spill buffer, from
which it re-enters — original arrival order within its tier — once
pressure subsides.  Every preemption is metered per tier and reconciled
by the serve conservation audit (``infra/audit.py::audit_serve``):
every admitted or preempted job terminates exactly once.

Wall-clock pacing is optional (``pace`` sim-seconds per wall-second);
the default *replay* mode runs as fast as the sessions can step, which
is both the bench configuration and the deterministic one.
"""

from __future__ import annotations

import math
import queue as _pyqueue
import threading
from typing import Callable, Dict, Iterable, List, Optional

import time

from pivot_tpu.infra.meter import SloMeter
from pivot_tpu.obs import NULL_TRACER, ObsClock
from pivot_tpu.utils import LogMixin

from pivot_tpu.serve.admission import ADMITTED, BLOCKED, AdmissionQueue
from pivot_tpu.serve.arrivals import JobArrival
from pivot_tpu.serve.autoscale import AutoscaleConfig, SloAutoscaler
from pivot_tpu.serve.session import STOP, PreemptRequest, ServeSession

__all__ = ["ServeDriver", "closed_loop_source"]

_ROUTINGS = ("rr", "least_loaded")


def _cluster_capacity(cluster) -> List[float]:
    """Total (cpus, mem, disk, gpus) of a cluster — the DRF dominant-
    share normalizer for tenant quotas (``serve/admission.py``)."""
    caps = [0.0, 0.0, 0.0, 0.0]
    for host in cluster.hosts:
        r = host.resource
        for i, dim in enumerate(("t_cpus", "t_mem", "t_disk", "t_gpus")):
            caps[i] += float(getattr(r, dim, 0.0) or 0.0)
    return caps


class _Inflight:
    """Ledger entry for one admitted job — what preemption victims are
    chosen from and what completions settle against."""

    __slots__ = ("app", "ts", "tier", "tenant", "seq", "session",
                 "requested", "preemptible", "dom")

    def __init__(self, app, ts, tier, tenant, seq, dom=1.0):
        self.app = app
        self.ts = ts
        self.tier = tier
        self.tenant = tenant
        self.seq = seq  # admission order (victim tie-break: youngest)
        self.session: Optional[ServeSession] = None
        self.requested = False  # a preempt request is in flight
        self.preemptible = True  # False after a miss (it placed/ran)
        #: Dominant share this admission charged against its tenant's
        #: DRF occupancy (1.0 when the quota is off) — what release
        #: gives back, surviving supervisor clones (the rec re-keys).
        self.dom = dom


class ServeDriver(LogMixin):
    """Always-on scheduling service over a (resizable) pool of sessions.

    **Session supervision** (round 7): when constructed with a
    ``session_factory``, the driver self-heals instead of fail-stopping —
    a session that crashes (its thread raises) or stalls past
    ``stall_timeout`` wall-seconds with live work is *abandoned*: its
    in-flight jobs (un-injected inbox arrivals plus a clone of every
    live, partially-run job) are requeued, a replacement session from the
    factory takes its place on a FRESH :class:`DispatchBatcher` slot
    (``respawn_client`` — the dead slot's state is never inherited), and
    the service keeps serving.  Requeued jobs retain their admission
    capacity across the restart: re-offering them past the backpressure
    bound could shed an already-admitted job, which would break the
    at-least-once contract the supervisor exists to provide; the
    admission queue still governs them (their completion releases
    capacity exactly once).  ``max_restarts`` bounds the recovery budget
    — exhausting it falls back to the fail-stop path.

    **Tiers, preemption, routing, autoscaling** (round 9): see the
    module docstring; all four knobs (``tier_reserve``/``tier_policies``,
    ``preempt``, ``routing``, ``autoscale``) default to off, under which
    the service is bit-identical to the single-tenant fixed-pool driver
    (the PR-2 parity tests run unmodified).
    """

    #: Wall seconds between capacity re-checks while a ``block``-policy
    #: producer (or a preempting admission) waits; each expiry also
    #: advances the release gate one scheduler tick so a blocked
    #: admission cannot freeze sim time.
    _BLOCK_POLL_S = 0.02

    def __init__(
        self,
        sessions: List[ServeSession],
        queue_depth: int = 64,
        backpressure: str = "shed",
        flush_after: Optional[float] = None,
        slo: Optional[SloMeter] = None,
        session_factory: Optional[Callable[[str], ServeSession]] = None,
        max_restarts: int = 2,
        stall_timeout: Optional[float] = None,
        tier_reserve=None,
        tier_policies=None,
        routing: str = "rr",
        preempt: bool = False,
        preempt_timeout: float = 5.0,
        autoscale: Optional[AutoscaleConfig] = None,
        mpc=None,
        tracer=None,
        registry=None,
        clock: Optional[ObsClock] = None,
        profiler=None,
        mesh=None,
        tenant_quota: Optional[float] = None,
        ragged: bool = True,
        resident: bool = False,
        splice_tier: int = 0,
        recovery=None,
        elastic=None,
    ):
        if not sessions:
            raise ValueError("ServeDriver needs at least one session")
        if stall_timeout is not None and stall_timeout <= 0:
            raise ValueError("stall_timeout must be positive (or None)")
        if routing not in _ROUTINGS:
            raise ValueError(
                f"unknown routing {routing!r} (use one of {_ROUTINGS})"
            )
        if preempt_timeout <= 0:
            raise ValueError("preempt_timeout must be positive")
        if autoscale is not None:
            if session_factory is None and autoscale.g_max > len(sessions):
                raise ValueError(
                    "autoscale growth (g_max > initial pool) needs a "
                    "session_factory"
                )
            if len(sessions) < autoscale.g_min:
                raise ValueError(
                    f"initial pool {len(sessions)} below autoscale.g_min "
                    f"{autoscale.g_min}"
                )
        if mpc is not None:
            if session_factory is None and mpc.g_max > len(sessions):
                raise ValueError(
                    "mpc growth (g_max > initial pool) needs a "
                    "session_factory"
                )
            if len(sessions) < mpc.g_min:
                raise ValueError(
                    f"initial pool {len(sessions)} below mpc.g_min "
                    f"{mpc.g_min}"
                )
        self.sessions = list(sessions)
        #: Observability plane (round 14).  ``tracer`` records the
        #: causal chain of every admitted job (arrival → admission →
        #: routing → injection → placement → completion) plus batcher
        #: flushes and autoscaler actions on the same timeline —
        #: ``None`` is the zero-cost NULL tracer, under which the
        #: service is bit-identical to the untraced driver (pinned by
        #: tests/test_obs.py replay parity).  ``registry`` receives the
        #: unified metrics snapshot at :meth:`report`.  ``clock`` is
        #: the one injected wall source the SLO meter reports through.
        self.tracer = tracer or NULL_TRACER
        self.registry = registry
        self.clock = clock or ObsClock()
        #: Sampled dispatch profiler (round 15, ``obs/profiler.py``):
        #: attached to every device-backed session policy (direct
        #: dispatches) AND the shared batcher (coalesced flushes); its
        #: census lands in :meth:`publish_metrics` and its ``device``
        #: spans on the service trace timeline.  ``None`` = zero cost.
        self.profiler = profiler
        if profiler is not None and profiler.tracer is None:
            # Device spans land on the service-wide timeline unless the
            # caller attached a dedicated tracer explicitly.
            profiler.tracer = self.tracer
        self.slo = slo or SloMeter(clock=self.clock)
        #: DRF tenant fairness (round 17, ``serve/admission.py``): the
        #: dominant-share reference capacity is the first session's
        #: cluster totals — every session clones the same topology, and
        #: fairness only needs a consistent normalizer.
        capacity = None
        if tenant_quota is not None:
            capacity = _cluster_capacity(sessions[0].cluster)
        self.queue = AdmissionQueue(
            queue_depth, backpressure, self.slo,
            tier_reserve=tier_reserve, tier_policies=tier_policies,
            tenant_quota=tenant_quota, capacity=capacity,
        )
        self.flush_after = flush_after
        #: 2-D serving mesh (round 17): handed to the DispatchBatcher so
        #: coalesced flushes shard [G] over ``replica`` — and, when the
        #: session policies also have ``enable_sharding`` on, the host
        #: axis over ``host`` (the composed 2-D program).  ``None``
        #: keeps today's single-device vmap dispatch.
        self.mesh = mesh
        #: Ragged continuous batching (round 18): the batcher repacks
        #: co-pending mixed-horizon ``place_span`` dispatches into one
        #: (K′, B′) bucket so a tier-0 2-tick span and a tier-2 16-tick
        #: span ride ONE device program (``sched/batch.py``; bit-
        #: identical by the inert-tail contract).  ``False`` keeps the
        #: PR-15 exact-shape coalescing — the bench A/B arm.
        self.ragged = bool(ragged)
        #: Resident span carries (round 20): every session policy keeps
        #: its [H] span state device-persistent between spans
        #: (``sched/tpu.py:enable_resident``) and ships per-span deltas
        #: instead of full re-staged operands.  Mutually exclusive with
        #: the shared DispatchBatcher (whose flush re-stages every
        #: operand from host numpy — exactly the cost residency
        #: removes), so a resident pool runs its sessions free; the
        #: ``serve_resident`` bench row is the A/B.  ``splice_tier``
        #: gates MID-SPAN admission: an arrival whose tier is at most
        #: this joins a running span via the resident checkpoint splice
        #: (``GlobalScheduler.splice_gate``); higher tiers wait for the
        #: flush boundary as before.
        self.resident = bool(resident)
        self.splice_tier = int(splice_tier)
        #: Crash-safe serving (round 21, ``pivot_tpu.recover``):
        #: ``recovery`` is a ``RecoveryConfig`` or None.  None — the
        #: default — builds nothing and leaves the service bit-identical
        #: to the PR-18 stack (pinned by tests/test_recovery.py).  A
        #: config constructs the plane HERE (its write-ahead journal
        #: must be open before the first admission); the snapshot
        #: worker starts/stops inside :meth:`run`.
        self._recovery = None
        if recovery is not None:
            from pivot_tpu.recover import RecoveryPlane

            self._recovery = RecoveryPlane(recovery, tracer=self.tracer)
        #: Elastic mesh serving (round 20, ``serve/elastic.py``):
        #: ``elastic`` is an ``ElasticMeshManager``, an ``ElasticConfig``,
        #: a ``ChaosSchedule`` with device events, or None.  None — the
        #: default — builds nothing and leaves the service bit-identical
        #: to the inelastic stack (pinned by tests/test_elastic.py).
        #: Otherwise the manager gates every session policy's dispatches
        #: against the device-fault plan: a covered dispatch raises
        #: ``DeviceLostError``, the supervisor requeues through the
        #: existing restart machinery (tier 0 first out of the queue),
        #: and the replacement session is resharded onto the
        #: surviving-shard mesh before it serves a decision.  Mutually
        #: exclusive with the shared DispatchBatcher (fixed 2-D mesh) —
        #: an elastic pool runs resident or free.
        self._elastic = None
        if elastic is not None:
            from pivot_tpu.serve.elastic import (
                ElasticConfig, ElasticMeshManager,
            )

            if isinstance(elastic, ElasticMeshManager):
                self._elastic = elastic
            elif isinstance(elastic, ElasticConfig):
                self._elastic = ElasticMeshManager(elastic)
            else:  # a ChaosSchedule with device_fault/restore events
                self._elastic = ElasticMeshManager(
                    ElasticConfig(schedule=elastic)
                )
            if session_factory is None:
                raise ValueError(
                    "elastic serving needs a session_factory — a shrink "
                    "replaces the crashed session on the smaller mesh"
                )
            if mesh is not None:
                raise ValueError(
                    "elastic serving does not compose with the driver's "
                    "2-D batcher mesh (fixed at construction) — shard "
                    "the session policies instead (enable_sharding)"
                )
        self.routing = routing
        self.preempt = preempt
        self.preempt_timeout = preempt_timeout
        self.autoscale = autoscale
        self.interval = sessions[0].interval
        self.batcher = None
        self._cv = threading.Condition()
        self._released = 0.0
        self._stop = False
        #: Set (under the cv) once the stream has fully drained and the
        #: shutdown STOPs are being delivered: pool GROWTH past this
        #: point would spawn a session nobody ever stops (run()'s join
        #: loop would spin on it forever), so grow_pool refuses and a
        #: supervisor replacement immediately queues its own STOP
        #: behind the requeued jobs.
        self._draining = False
        self._errors: List[BaseException] = []
        self._rr = 0
        self._completion_hooks: List[Callable] = []
        #: Admission ledger: app.id -> _Inflight for every job currently
        #: holding queue capacity (preemption victims come from here).
        self._inflight: Dict[str, _Inflight] = {}
        self._admit_seq = 0
        #: Tier of the arrival the producer is currently parked on (the
        #: preempt dance / block wait): spill re-offers must not hand
        #: freed capacity to anything less important, or a preempted
        #: victim would re-enter the instant its preemption freed the
        #: slot it was preempted FOR (livelock).
        self._waiting_tier: Optional[int] = None
        self._preempt_outstanding = 0
        #: Supervisor state (inert when ``session_factory`` is None).
        self._session_factory = session_factory
        self._max_restarts = max_restarts
        self.stall_timeout = stall_timeout
        self._restarts = 0
        self._n_grown = 0
        #: (session, thread) for every session thread ever spawned.
        self._threads: List = []
        self._abandoned: List[ServeSession] = []
        self._retired: List[ServeSession] = []
        self._autoscaler: Optional[SloAutoscaler] = None
        #: Model-predictive serving (``pivot_tpu/mpc``): the config is
        #: an ``MpcConfig`` or None.  ``None`` — the default — never
        #: imports the package, starts no thread, and leaves the
        #: reactive driver bit-identical (pinned by tests/test_mpc.py).
        #: The controller is built in :meth:`run` before the producer
        #: thread starts, so ``_mpc`` is set-once-then-read (no lock).
        self.mpc = mpc
        self._mpc = None
        self._watch_stop = threading.Event()
        for slot, s in enumerate(self.sessions):
            s._driver = self
            s.slot = slot
            s.slo = self.slo  # one service-wide SLO meter
            s.scheduler.slo = self.slo  # dead-letter sheds land here too
            s.tracer = self.tracer  # one service-wide trace timeline
            s.scheduler.tracer = self.tracer
            # ONE wall epoch service-wide: the sessions' run meters
            # report through the driver's clock, so their wall
            # snapshots agree with the SLO meter's (the round-14
            # clock-unification contract).
            s.clock = self.clock
            s.meter.clock = self.clock
            if getattr(s, "fuse_spans", False) == "slo":
                # The SLO-checkpoint span bound: spans end at the
                # stream's revealed frontier (serve/session.py).
                s.scheduler.span_horizon = self.release_frontier
            if self.profiler is not None and hasattr(
                s.policy, "enable_profiler"
            ):
                s.policy.enable_profiler(self.profiler)

    # -- gate + coordination ----------------------------------------------
    def release_frontier(self) -> float:
        """The admission window's edge: the largest sim instant the
        arrival stream has revealed (∞ once it drains).  What
        ``fuse_spans="slo"`` sessions bound their fused spans at
        (``GlobalScheduler.span_horizon``) — read under the cv so the
        thread-guard discipline holds."""
        with self._cv:
            return self._released

    def wait_released(self, session: ServeSession, t: float,
                      client=None) -> bool:
        """Block ``session`` until the release frontier reaches sim time
        ``t`` (or new work lands in its inbox, or shutdown).  The
        session's batcher slot is marked idle for the duration so gated
        sessions never park co-pending dispatches.  Returns False on
        shutdown."""
        with self._cv:
            if self._released >= t or not session._inbox.empty():
                return not self._stop
            if client is not None:
                client.set_idle(True)
            try:
                self._cv.wait_for(
                    lambda: (
                        self._stop
                        or self._released >= t
                        or not session._inbox.empty()
                    )
                )
            finally:
                if client is not None:
                    client.set_idle(False)
            return not self._stop

    def _release_to(self, ts: float) -> None:
        if ts > self._released:
            self._released = ts
            self._cv.notify_all()

    def _next_tick(self, t: float) -> float:
        return (math.floor(t / self.interval) + 1) * self.interval

    def advance_gate(self) -> None:
        """Let sim time flow one scheduler tick with no new arrivals —
        the "time passes while we wait" primitive behind block-mode
        admission and the closed-loop load generator (both wait on
        completions that can only happen if the sessions may advance)."""
        with self._cv:
            if self._released != float("inf"):
                self._release_to(self._next_tick(self._released))

    # -- completions -------------------------------------------------------
    def add_completion_hook(self, fn: Callable) -> None:
        """``fn(session, app, sim_now)`` after every job completion —
        the closed-loop load generator's refill tap."""
        self._completion_hooks.append(fn)

    def on_completed(self, session: ServeSession, app, sim_now: float,
                     failed: bool = False):
        if session.abandoned:
            return  # a replaced session's stale thread reporting late
        with self._cv:
            rec = self._inflight.pop(app.id, None)
            tier = (
                rec.tier if rec is not None
                else int(getattr(app, "_serve_tier", 0))
            )
            self._release_one(rec, app, tier)
            key = "failed_jobs" if failed else "completed"
            self.slo.count(key)
            self.slo.count_tier(tier, key)
            if self.tracer.enabled:
                self._stage(app, "failed" if failed else "completed",
                            sim=sim_now, session=session.label)
            self._reoffer_spilled(after_sim=sim_now)
            self._cv.notify_all()
        for fn in self._completion_hooks:
            fn(session, app, sim_now)

    def _release_one(self, rec: Optional[_Inflight], app, tier: int) -> None:
        """Free one settled admission's capacity AND its tenant's DRF
        occupancy (cv held).  The (tenant, dominant share) pair comes
        from the ledger record when one survives, else from the app's
        cached share — either way the exact values the admission
        charged, so the occupancy ledger drains to zero
        (``audit_serve``)."""
        if rec is not None:
            tenant, share = rec.tenant, rec.dom
        else:
            tenant = getattr(app, "_serve_tenant", "default")
            share = getattr(app, "_serve_dom_share", None)
        self.queue.release(tier=tier, tenant=tenant, share=share)

    def on_session_error(self, session: ServeSession, exc) -> None:
        if session.abandoned:
            return  # already replaced by the supervisor; nothing to do
        # Snapshot the routing decision's inputs under the cv
        # (graftcheck thread-guard: unlocked reads of _stop/_restarts
        # here raced the producer's stop and concurrent crash handlers).
        # The snapshot is advisory — _restart_session re-validates the
        # stop flag AND the restart budget under the cv and reports a
        # lost race by returning False, in which case we fall through
        # to the fail-stop path below.
        with self._cv:
            stopped = self._stop
            can_restart = (
                self._session_factory is not None
                and self._restarts < self._max_restarts
            )
        if self._elastic is not None:
            from pivot_tpu.serve.elastic import is_device_loss

            if is_device_loss(exc):
                # Mesh-level loss: record it, then let the ordinary
                # supervisor path below replace the session — the
                # replacement is resharded onto the survivors by
                # _wire_and_start, and _requeue routes its in-flight
                # work back through the admission queue (tier 0 first).
                self._elastic.note_loss(exc, session.label)
                self.slo.count("device_losses")
        if session.retiring and not stopped:
            # A crash DURING a scale-down drain: the retire was already
            # decided — settle it (requeue the in-flight jobs onto the
            # surviving pool, retire the slot exactly once) instead of
            # spawning a replacement we were about to drain anyway.
            self.logger.error(
                "session %s crashed mid-retire (%s) — settling retire",
                session.label, exc,
            )
            self._retire_crashed(session, close_client=False)
            return
        if can_restart and not stopped:
            self.logger.error(
                "session %s crashed (%s) — supervisor restarting",
                session.label, exc,
            )
            if self._restart_session(session, close_client=False):
                return
            if session.abandoned:
                return  # a concurrent handler replaced it first
        with self._cv:
            self._errors.append(exc)
            self._stop = True
            survivors = list(self.sessions) + list(self._abandoned)
            self._cv.notify_all()
        for s in survivors:
            s.shutdown()

    # -- the session supervisor --------------------------------------------
    def _recover_inflight(self, dead: ServeSession) -> List[JobArrival]:
        """Harvest a dead/retiring-crashed session's recoverable work
        (cv held): un-injected inbox arrivals keep their original
        timestamps and app objects; live (possibly partially-run) jobs
        are resubmitted as clones — the dead session's world is gone, so
        their execution restarts, but their admission capacity is
        retained (see class docstring).  Jobs that terminated inside the
        dead session but were never reaped are settled here — the
        abandoned thread's late reap is ignored by ``on_completed``, so
        skipping them would leak a queue slot per restart.  Pending
        preempt requests addressed to the dead session resolve as
        misses."""
        lost: List[JobArrival] = []
        while True:
            try:
                item = dead._inbox.get_nowait()
            except _pyqueue.Empty:
                break
            if item is STOP:
                continue
            if isinstance(item, PreemptRequest):
                self._preempt_outstanding -= 1
                self.slo.count("preempt_misses")
                rec = self._inflight.get(item.app.id)
                if rec is not None:
                    rec.requested = False
                continue
            lost.append(item)
        for app in dead._live:
            rec = self._inflight.pop(app.id, None)
            tier = (
                rec.tier if rec is not None
                else int(getattr(app, "_serve_tier", 0))
            )
            if app.is_finished or getattr(app, "failed", False):
                self._release_one(rec, app, tier)
                key = "completed" if app.is_finished else "failed_jobs"
                self.slo.count(key)
                self.slo.count_tier(tier, key)
                if self.tracer.enabled:
                    # Anchored at the dead session's sim clock: a
                    # sim-less terminal would export on the wall
                    # fallback BEFORE its sim-anchored parent and fail
                    # the obs_report --check parent-ordering gate.
                    self._stage(
                        app, "completed" if app.is_finished else "failed",
                        sim=dead.env.now, session=dead.label,
                        late_reap=True,
                    )
                continue
            ts = getattr(app, "_serve_admit_ts", 0.0)
            clone = app.clone()
            trace = self._trace_of(app)
            if trace is not None:
                # The clone continues the SAME causal chain — its
                # restart stages parent-link onto the dead session's.
                clone._obs_trace = trace
            if rec is not None:
                rec.app = clone
                rec.requested = False
                self._inflight[clone.id] = rec
            lost.append(
                JobArrival(
                    ts, clone, tier=tier,
                    tenant=getattr(app, "_serve_tenant", "default"),
                )
            )
        return lost

    def _requeue(self, lost: List[JobArrival]) -> None:
        """Route recovered jobs back into the pool (cv held), submission
        times never before the release frontier's next tick (a
        readmission cannot land in a session's past)."""
        floor_t = (
            self._released if self._released != float("inf") else None
        )
        for arr in lost:
            ts = (
                arr.ts if floor_t is None
                else max(arr.ts, self._next_tick(floor_t))
            )
            self.slo.count("requeued")
            if self.tracer.enabled:
                self._stage(arr.app, "requeued", sim=ts)
            self._route(
                JobArrival(ts, arr.app, tier=arr.tier, tenant=arr.tenant)
            )

    def _restart_session(self, dead: ServeSession,
                         close_client: bool) -> bool:
        """Replace a crashed/stalled session: requeue its in-flight jobs
        into a factory-fresh session on a fresh batcher slot.  Called
        from the dying session's own thread (crash path — its client
        closes itself in the loop's ``finally``) or from the watchdog
        (stall path — ``close_client=True``, the stalled thread may never
        reach its finally).  Returns False without acting when the
        restart lost a race — service stopped, session already replaced,
        or the recovery budget consumed by a CONCURRENT crash between
        the caller's check and this cv acquisition (the budget is
        re-validated here, under the cv, authoritatively); the caller
        then falls back to its no-restart path.

        Stall-path caveat (best effort by design): the wedged thread may
        still be mid-``env.step`` while this reads ``dead._live`` and
        clones its apps — Python threads cannot be paused, so a
        truly-concurrent mutation can tear a clone.  The crash path (the
        common case) has no such window: the dying thread is parked in
        its own except handler while it runs this."""
        with self._cv:
            if (
                self._stop or dead.abandoned
                or self._restarts >= self._max_restarts
            ):
                return False
            dead.abandoned = True
            self._restarts += 1
            self._abandoned.append(dead)
            self.slo.count("session_restarts")
            idx = self.sessions.index(dead)
            lost = self._recover_inflight(dead)
            self._reoffer_spilled()
            new = self._session_factory(f"{dead.label}-r{self._restarts}")
            new.slot = dead.slot
            self.sessions[idx] = new
            self._wire_and_start(new)
            self._requeue(lost)
            if self._draining:
                # The stream-end STOPs already went out; this
                # replacement must stop itself once the requeued jobs
                # (FIFO ahead of the STOP in its inbox) have drained.
                new.shutdown()
            self._cv.notify_all()
        # Unblock the dead session outside the lock: wake it if parked on
        # its inbox (it sees ``abandoned`` and exits), and reclaim its
        # batcher slot on the stall path.
        dead.shutdown()
        if close_client and getattr(dead, "_client", None) is not None:
            dead._client.close()
        return True

    def _wire_and_start(self, new: ServeSession) -> None:
        """Attach a factory session to the service and start its thread
        (cv held): service-wide SLO meter, a FRESH batcher slot when the
        pool is batched, thread registration.  Shared by the supervisor
        restart path and the autoscaler grow path — pool membership
        (``self.sessions``) is the caller's business."""
        new._driver = self
        new.slo = self.slo
        new.scheduler.slo = self.slo
        new.tracer = self.tracer
        new.scheduler.tracer = self.tracer
        new.clock = self.clock  # one wall epoch service-wide
        new.meter.clock = self.clock
        if getattr(new, "fuse_spans", False) == "slo":
            new.scheduler.span_horizon = self.release_frontier
        if self.profiler is not None and hasattr(
            new.policy, "enable_profiler"
        ):
            new.policy.enable_profiler(self.profiler)
        client = None
        if self.batcher is not None:
            client = self.batcher.respawn_client()
            new.policy.enable_batching(client)
            new.slot = client.slot
        elif self.resident:
            self._enable_resident(new)
        if self._recovery is not None:
            # Supervisor replacements and autoscaler growth join the
            # recovery plane too — a restarted session's spans journal
            # and snapshot exactly like the original's.
            new.attach_recovery(self._recovery)
        if self._elastic is not None:
            # The replacement's factory-fresh policy is gated AND
            # resharded onto the current surviving-shard mesh here —
            # before its thread starts — or its first gated dispatch
            # would hit the same down window and burn another restart.
            self._elastic.attach(new.policy)
        new._client = client
        thread = threading.Thread(
            target=new.loop, args=(client,),
            name=f"serve-{new.label}", daemon=True,
        )
        self._threads.append((new, thread))
        thread.start()

    def _watchdog(self) -> None:
        """Stall detector: a session with live work whose event loop has
        not stepped for ``stall_timeout`` wall-seconds is declared dead
        and replaced (its wedged thread is abandoned — Python threads
        cannot be killed — and ignored when it eventually wakes)."""
        poll = self.stall_timeout / 4.0
        while not self._watch_stop.wait(poll):
            # graftcheck: ignore[thread-guard] -- monotonic stop flag; a stale read costs one extra poll, and the replace paths re-check under the cv
            if self._stop:
                return
            now = time.perf_counter()
            # graftcheck: ignore[thread-guard] -- snapshot iteration: list() copies under the GIL; pool surgery happens under the cv, so the worst case is judging a just-replaced session one poll late
            for s in list(self.sessions):
                if s.abandoned or s.error is not None or not s._live:
                    continue
                if now - s.last_progress <= self.stall_timeout:
                    continue
                if s.retiring:
                    # Wedged mid-retire: settle the retire, requeue.
                    self.logger.error(
                        "session %s stalled mid-retire — settling",
                        s.label,
                    )
                    self._retire_crashed(s, close_client=True)
                    continue
                if (
                    self._session_factory is None
                    # graftcheck: ignore[thread-guard] -- advisory budget read; _restarts only grows, so a stale value can at worst defer fail-stop by one poll (on_session_error re-reads it under the cv)
                    or self._restarts >= self._max_restarts
                ):
                    self.on_session_error(
                        s,
                        RuntimeError(
                            f"session {s.label} stalled "
                            f"> {self.stall_timeout}s with live work"
                        ),
                    )
                    return
                self.logger.error(
                    "session %s stalled > %.3fs — supervisor restarting",
                    s.label, self.stall_timeout,
                )
                self._restart_session(s, close_client=True)

    # -- autoscaler pool surgery -------------------------------------------
    def pool_size(self) -> int:
        """Sessions currently accepting work (retiring excluded)."""
        with self._cv:
            return len(
                [s for s in self.sessions if not s.retiring]
            )

    def policy_pool(self) -> List:
        """``[(label, policy)]`` snapshot of the active pool (retiring
        and abandoned excluded) — the MPC rollout's promotion surface.
        The list is a snapshot; the policy objects are live (attribute
        swaps via ``Policy.apply_weights`` take effect on the session's
        next decision)."""
        with self._cv:
            return [
                (s.label, s.policy)
                for s in self.sessions
                if not s.retiring and not s.abandoned
            ]

    def grow_pool(self, reason: str = "") -> bool:
        """Add one factory session to the pool (autoscaler thread)."""
        with self._cv:
            if (
                self._stop or self._draining
                or self._session_factory is None
            ):
                return False
            # Un-retire in preference to spawning: a session still
            # draining is warm capacity we were about to throw away.
            for s in self.sessions:
                if s.retiring and not s._retired and not s.abandoned:
                    s.retiring = False
                    self.slo.count("scale_up_events")
                    self.logger.info(
                        "autoscaler un-retired %s (%s)", s.label, reason
                    )
                    self._cv.notify_all()
                    return True
            self._n_grown += 1
            new = self._session_factory(f"scale-{self._n_grown}")
            new.slot = len(self.sessions)
            self.sessions.append(new)
            self._wire_and_start(new)
            self.slo.count("scale_up_events")
            self.logger.info(
                "autoscaler grew pool to %d (%s)",
                len(self.sessions), reason,
            )
            self._cv.notify_all()
        return True

    def begin_retire(self) -> Optional[ServeSession]:
        """Mark the least-loaded session retiring (drain-then-retire);
        the router stops feeding it immediately, the autoscaler
        finalizes once its live set drains.  Returns the victim, or
        None when no session can be spared."""
        with self._cv:
            active = [
                s for s in self.sessions
                if not s.retiring and not s.abandoned
            ]
            if self._stop or len(active) <= 1:
                return None
            victim = min(
                active, key=lambda s: (s.load, -s.slot)
            )
            victim.retiring = True
            self.slo.count("scale_down_events")
            self._cv.notify_all()
            return victim

    def finish_drained_retires(self) -> int:
        """Finalize every retiring session whose drain completed: STOP
        its loop (closing its batcher slot), move it to the retired
        list.  Idempotent; returns how many were finalized."""
        done: List[ServeSession] = []
        with self._cv:
            for s in list(self.sessions):
                if (
                    s.retiring and not s._retired and not s.abandoned
                    and not s._live and s._inbox.empty()
                ):
                    s._retired = True
                    self.sessions.remove(s)
                    self._retired.append(s)
                    done.append(s)
            if done:
                self._cv.notify_all()
        for s in done:
            s.shutdown()
        return len(done)

    def _retire_crashed(self, dead: ServeSession,
                        close_client: bool) -> None:
        """A retiring session crashed/stalled before its drain finished:
        complete the retire exactly once — requeue its in-flight jobs
        onto the surviving pool (capacity retained, same contract as a
        supervisor restart) and retire the slot, WITHOUT spawning a
        replacement (the pool was shrinking)."""
        with self._cv:
            if self._stop or dead.abandoned or dead._retired:
                return
            dead.abandoned = True
            dead._retired = True
            self._abandoned.append(dead)
            if dead in self.sessions:
                self.sessions.remove(dead)
            lost = self._recover_inflight(dead)
            self._requeue(lost)
            self._reoffer_spilled()
            self._cv.notify_all()
        dead.shutdown()
        if close_client and getattr(dead, "_client", None) is not None:
            dead._client.close()

    # -- in-queue preemption -----------------------------------------------
    def _try_preempt(self, tier: int) -> bool:
        """Request preemption of the least important, youngest
        admitted-but-unplaced job of a tier strictly below ``tier``
        (cv held).  Returns True when a request was dispatched."""
        victim: Optional[_Inflight] = None
        for rec in self._inflight.values():
            if (
                rec.tier <= tier or rec.requested or not rec.preemptible
                or rec.session is None or rec.session.abandoned
            ):
                continue
            if victim is None or (rec.tier, rec.seq) > (
                victim.tier, victim.seq
            ):
                victim = rec
        if victim is None:
            return False
        victim.requested = True
        self._preempt_outstanding += 1
        self.slo.count("preempt_requests")
        victim.session.request_preempt(victim.app)
        self._cv.notify_all()
        return True

    def on_preempt_result(self, session: ServeSession, app, ok: bool,
                          sim_now: float) -> None:
        """A session answered a preempt request (session thread).  A hit
        frees the victim's capacity and requeues it to the spill buffer
        (metered ``preempted``/``preempt_requeued``, NOT as a fresh
        spill); a miss marks the record non-preemptible so the victim
        search never retries it."""
        with self._cv:
            self._preempt_outstanding -= 1
            rec = self._inflight.get(app.id)
            if rec is None:
                # Completed (and settled) before the request landed.
                self.slo.count("preempt_misses")
                self._cv.notify_all()
                return
            rec.requested = False
            if not ok:
                rec.preemptible = False
                self.slo.count("preempt_misses")
                self._cv.notify_all()
                return
            del self._inflight[app.id]
            self._release_one(rec, app, rec.tier)
            self.slo.count("preempted")
            self.slo.count_tier(rec.tier, "preempted")
            if self.tracer.enabled:
                self._stage(app, "preempted", sim=sim_now,
                            tier=rec.tier)
            # Requeue-to-spill with the ORIGINAL arrival timestamp; the
            # re-offer path floors it to the next grid tick when it
            # finally readmits.  The app object is reused as-is — it
            # never executed (that is what made it a victim), so no
            # session state refers to it.
            self.queue.spill(
                JobArrival(rec.ts, rec.app, tier=rec.tier,
                           tenant=rec.tenant),
                count=False,
            )
            self.slo.count("preempt_requeued")
            self._cv.notify_all()

    def shed_pressure(self, tier: int) -> bool:
        """Autoscaler tap: at g_max with the SLO still breached, preempt
        one admitted-but-unplaced job below ``tier``."""
        if not self.preempt:
            return False
        with self._cv:
            if self._stop:
                return False
            return self._try_preempt(tier)

    # -- spill + routing ---------------------------------------------------
    def _reoffer_spilled(self, after_sim: Optional[float] = None) -> None:
        """Drain the spill buffer into freed capacity (cv held), in
        (tier, original arrival order).  A spilled job's submission
        lands no earlier than the scheduler grid point after the instant
        that freed its slot — the "spill to next tick" contract.
        ``after_sim`` is the freeing completion's sim time; the
        belt-and-braces call sites without one fall back to the release
        frontier so a readmission can never land in a session's past.
        While an admission is parked waiting for capacity, tiers less
        important than it stay spilled — the head check suffices because
        the buffer is tier-ordered."""
        while self.queue.spilled:
            # Pick the first admissible entry in (tier, arrival) order.
            # Room and the waiting-tier gate stop the scan (both are
            # monotone in buffer order); a QUOTA-blocked entry is
            # skipped instead — its tenant's occupancy blocking other
            # tenants' admissible jobs behind it would waste idle
            # capacity on fairness (the work-conserving contract;
            # review finding, round 17).  Quota off ⇒ the head is
            # always picked ⇒ bit-identical to the pre-quota loop.
            picked = None
            for i, arr in enumerate(self.queue.spilled):
                if (
                    self._waiting_tier is not None
                    and arr.tier > self._waiting_tier
                ):
                    break
                if not self.queue.has_room(arr.tier):
                    # Capacity frees on completions, and every
                    # completion re-runs this loop.
                    break
                if self.queue.over_quota(arr):
                    continue
                picked = i
                break
            if picked is None:
                break
            arr = self.queue.pop_spill(picked)
            floor_t = after_sim
            if floor_t is None and self._released != float("inf"):
                floor_t = self._released
            if floor_t is not None:
                arr = JobArrival(
                    max(arr.ts, self._next_tick(floor_t)), arr.app,
                    tier=arr.tier, tenant=arr.tenant,
                )
            self.queue.readmit(arr)
            if self.tracer.enabled:
                self._stage(arr.app, "readmitted", sim=arr.ts,
                            tier=arr.tier)
            self._register_inflight(arr)
            self._route(arr)

    def _register_inflight(self, arrival: JobArrival) -> None:
        """Ledger a freshly admitted/readmitted arrival (cv held)."""
        self._admit_seq += 1
        self._inflight[arrival.app.id] = _Inflight(
            arrival.app, arrival.ts, arrival.tier, arrival.tenant,
            self._admit_seq,
            dom=getattr(arrival.app, "_serve_dom_share", 1.0),
        )

    def _route(self, arrival: JobArrival) -> None:
        eligible = [
            s for s in self.sessions
            if not s.retiring and not s.abandoned
        ]
        if not eligible:  # every session retiring: least bad fallback
            eligible = [s for s in self.sessions if not s.abandoned]
        if not eligible:
            eligible = self.sessions
        if self.routing == "least_loaded":
            # Primary: queued + live jobs; tie-break: recent decision
            # latency EWMA, then slot order (deterministic given equal
            # telemetry — which is why "rr" stays the parity default).
            target = min(
                eligible,
                key=lambda s: (s.load, s.recent_decision_s, s.slot),
            )
        else:
            target = eligible[self._rr % len(eligible)]
            self._rr += 1
        rec = self._inflight.get(arrival.app.id)
        if rec is not None:
            rec.session = target
        if self.tracer.enabled:
            # Emitted BEFORE the inbox put: the session's "injected"
            # stage happens-after this append, so the chain order is
            # routed → injected on every interleaving.  Anchored at the
            # arrival's sim instant — the routing decision is part of
            # the admission instant on the sim timeline.
            self._stage(arrival.app, "routed", sim=arrival.ts,
                        session=target.label, slot=target.slot)
        target.offer(arrival)
        self._cv.notify_all()

    # -- admission ---------------------------------------------------------
    def _trace_of(self, app) -> Optional[int]:
        return getattr(app, "_obs_trace", None)

    def _stage(self, app, name: str, sim: Optional[float] = None,
               **args) -> None:
        """Causal-trace hook: one parent-linked stage of ``app``'s job
        chain (no-op when tracing is off or the app carries no trace —
        e.g. jobs admitted before a tracer was attached)."""
        trace = getattr(app, "_obs_trace", None)
        if trace is not None:
            self.tracer.stage(trace, name, sim=sim, **args)

    def _admit(self, arrival: JobArrival) -> None:
        tier = int(getattr(arrival, "tier", 0))
        if self._recovery is not None:
            # Write-ahead: the admission is journaled BEFORE any effect
            # (gate release, queue offer, routing) — after a crash the
            # journal tail is exactly the set of arrivals the dead
            # server had committed to.
            self._recovery.journal_admit(arrival)
        if self._mpc is not None:
            # Forecast tap: sim timestamp + tier, before any admission
            # verdict — shed/spilled arrivals are still demand.
            self._mpc.forecaster.observe(arrival.ts, tier)
        if self.tracer.enabled:
            # Trace ids are allocated in admission order (the producer
            # thread is the only allocator), so replaying a seeded
            # stream yields the same ids.  The id rides on the app —
            # every later layer (router, session, scheduler) links its
            # stages through it.
            trace = self.tracer.new_trace()
            arrival.app._obs_trace = trace
            self.tracer.stage(
                trace, "arrived", sim=arrival.ts, tier=tier,
                tenant=getattr(arrival, "tenant", "default"),
                app=arrival.app.id,
            )
        with self._cv:
            # An arrival at ts proves the stream silent before ts: time
            # may flow to it even while admission deliberates.
            self._release_to(arrival.ts)
            self._reoffer_spilled()
            if (
                self.preempt
                and not self.queue.has_room(tier)
                and not self._stop
            ):
                self._preempt_for(tier)
            status = self.queue.offer(arrival)
            if self.tracer.enabled:
                self._stage(arrival.app, status, sim=arrival.ts)
            try:
                self._waiting_tier = tier
                while (
                    status == BLOCKED
                    and not self._stop and not self._errors
                ):
                    self.slo.count("blocked_waits")
                    notified = self._cv.wait(timeout=self._BLOCK_POLL_S)
                    if not notified and self._released != float("inf"):
                        # No completion freed capacity: advance sim time
                        # one tick so in-flight work can progress.
                        self._release_to(
                            self._next_tick(self._released)
                        )
                    if self.preempt and not self.queue.has_room(tier):
                        # Keep one preempt request in flight while
                        # victims remain — block-policy high tiers drain
                        # the low tiers rather than waiting them out.
                        if self._preempt_outstanding == 0:
                            self._try_preempt(tier)
                    if self.queue.readmit(arrival):
                        # readmit re-checks room AND the tenant quota
                        # (a blocked over-quota arrival waits for its
                        # tenant's occupancy to drain, not just depth).
                        status = ADMITTED
                        if self.tracer.enabled:
                            self._stage(arrival.app, "admitted",
                                        sim=arrival.ts, after="blocked")
            finally:
                self._waiting_tier = None
            if status == ADMITTED:
                self._register_inflight(arrival)
                self._route(arrival)

    def _preempt_for(self, tier: int) -> None:
        """The preempt dance (cv held): keep a preemption in flight and
        wait — bounded by ``preempt_timeout`` wall seconds — until the
        arrival's tier has room or victims run out.  Falls back to the
        tier's configured backpressure policy on exhaustion."""
        deadline = time.perf_counter() + self.preempt_timeout
        self._waiting_tier = tier
        try:
            requested = self._try_preempt(tier)
            while (
                requested
                and not self.queue.has_room(tier)
                and not self._stop and not self._errors
                and time.perf_counter() < deadline
            ):
                notified = self._cv.wait(timeout=self._BLOCK_POLL_S)
                if not notified and self._released != float("inf"):
                    # Victim sessions may be gated: let sim time flow so
                    # their threads reach the preempt request.
                    self._release_to(self._next_tick(self._released))
                if (
                    self._preempt_outstanding == 0
                    and not self.queue.has_room(tier)
                ):
                    requested = self._try_preempt(tier)
        finally:
            self._waiting_tier = None

    def _produce(self, arrivals: Iterable[JobArrival],
                 pace: Optional[float]) -> None:
        wall0 = time.perf_counter()
        try:
            for arr in arrivals:
                # graftcheck: ignore[thread-guard] -- monotonic stop flag polled between admissions; _admit re-checks under the cv before blocking
                if self._stop:
                    return
                if pace:
                    lag = arr.ts / pace - (time.perf_counter() - wall0)
                    if lag > 0:
                        time.sleep(lag)
                self._admit(arr)
            # Stream exhausted: reveal the open horizon, wait for the
            # admitted work (and any spilled stragglers) to drain.
            with self._cv:
                self._release_to(float("inf"))
                while not self._stop and not self._errors and (
                    self.queue.in_flight > 0 or self.queue.spilled
                ):
                    self._reoffer_spilled()
                    if self.queue.in_flight == 0 and not self.queue.spilled:
                        break
                    self._cv.wait(timeout=0.5)
        except BaseException as exc:  # noqa: BLE001 — surfaced by run()
            with self._cv:
                self._errors.append(exc)
                self._stop = True
                self._cv.notify_all()
        finally:
            with self._cv:
                self._release_to(float("inf"))
                self._draining = True
                # Snapshot under the cv: grow_pool refuses once
                # _draining is set, so this list is the final pool.
                pool = list(self.sessions)
            for s in pool:
                s.shutdown()

    def _splice_gate(self, task) -> bool:
        """Mid-span admission predicate handed to every session's
        scheduler: only arrivals at or below ``splice_tier`` may join a
        RUNNING span (latency-critical work skips the flush-boundary
        wait); everything else aborts the span exactly as before."""
        return (
            int(getattr(task.application, "_serve_tier", 0))
            <= self.splice_tier
        )

    def _enable_resident(self, s: ServeSession) -> None:
        """(cv held) Turn the resident span tier on for one session:
        device-persistent carry on the policy, tier-gated mid-span
        splice on its scheduler.  Skips policies without the tier
        (numpy arms serve per-tick regardless)."""
        if hasattr(s.policy, "enable_resident"):
            s.policy.enable_resident()
            s.scheduler.splice_gate = self._splice_gate

    def _batching_compatible(self) -> bool:
        """(cv held) Whether the pool can share a DispatchBatcher: every policy
        batchable (device-backed, deterministic routing), and — when
        sharding is in play — the driver's mesh host axis agreeing with
        every sharded policy's (the composed 2-D program partitions one
        [H] layout).  A sharded pool WITHOUT a compatible driver mesh
        runs free: 1-D host-sharded per-session dispatches, no
        coalescing — the ``serve_sharded`` bench's 1-D-sharding arm."""
        if not all(s.batchable for s in self.sessions):
            return False
        if self.mesh is not None:
            # The batcher's flush machinery keys on both axes: a mesh
            # missing either would crash the first coalesced flush.
            from pivot_tpu.ops.shard import HOST_AXIS, REPLICA_AXIS

            if (
                HOST_AXIS not in self.mesh.shape
                or REPLICA_AXIS not in self.mesh.shape
            ):
                return False
        for s in self.sessions:
            pmesh = getattr(s.policy, "_mesh", None)
            if pmesh is None:
                continue
            if self.mesh is None:
                return False
            # Import inside the sharded branch only: pure-numpy serving
            # must never import jax (parallel.mesh does at module scope).
            from pivot_tpu.parallel.mesh import host_axis_size

            if host_axis_size(self.mesh) != host_axis_size(pmesh):
                return False
        return True

    # -- lifecycle ---------------------------------------------------------
    def run(self, arrivals: Iterable[JobArrival],
            pace: Optional[float] = None) -> dict:
        """Serve the stream to completion; returns the service report.

        Batching engages when every session's policy qualifies (device
        backend, deterministic routing — the ``run_grid_lockstep``
        criterion): each session gets a ``DispatchBatcher`` slot and the
        caller's thread runs the flush coordinator.  Otherwise sessions
        run free (numpy/naive policies have no dispatch to coalesce).
        """
        # Setup under the cv: no session/producer/watchdog thread
        # exists yet, so the lock is uncontended — holding it keeps the
        # thread-guard discipline checkable instead of exempting run()
        # wholesale (which would also hide the join loop below, where
        # the pass caught a real _threads iteration race).
        started: List[threading.Thread] = []
        with self._cv:
            clients = [None] * len(self.sessions)
            if self.resident:
                # Resident pool: no batcher (see __init__) — but the
                # backend still initializes HERE, once, before any
                # session thread's first dispatch (concurrent
                # first-touch PJRT client creation is not safe).
                import jax

                jax.default_backend()
                for s in self.sessions:
                    self._enable_resident(s)
            elif self._elastic is None and self._batching_compatible():
                # Initialize the backend once, here, before any session
                # thread dispatches — concurrent first-touch PJRT client
                # creation is not safe (same guard as run_grid_lockstep).
                import jax

                jax.default_backend()
                from pivot_tpu.sched.batch import DispatchBatcher

                self.batcher = DispatchBatcher(
                    len(self.sessions), flush_after=self.flush_after,
                    mesh=self.mesh,
                    tracer=self.tracer, profiler=self.profiler,
                    ragged=self.ragged,
                    journal=(
                        self._recovery.journal
                        if self._recovery is not None else None
                    ),
                )
                clients = [self.batcher.client() for _ in self.sessions]
                for s, c in zip(self.sessions, clients):
                    s.policy.enable_batching(c)
                self.slo.attach_dispatch_stats(self.batcher.stats)
            if self._recovery is not None:
                self._recovery.start()
                for s in self.sessions:
                    s.attach_recovery(self._recovery)
            if self._elastic is not None:
                # Gate + align every launch policy before its thread
                # exists (attach may reshard — cv held, no races).
                for s in self.sessions:
                    self._elastic.attach(s.policy)
            for s, c in zip(self.sessions, clients):
                s._client = c
                thread = threading.Thread(
                    target=s.loop, args=(c,),
                    name=f"serve-{s.label}", daemon=True,
                )
                self._threads.append((s, thread))
                started.append(thread)
        for t in started:
            t.start()
        watchdog = None
        if self.stall_timeout is not None:
            watchdog = threading.Thread(
                target=self._watchdog, name="serve-watchdog", daemon=True,
            )
            watchdog.start()
        if self.autoscale is not None:
            self._autoscaler = SloAutoscaler(self, self.autoscale)
            self._autoscaler.start()
        if self.mpc is not None:
            # Imported here, not at module scope: mpc=None serving must
            # never pay for (or depend on) the search/planner stack.
            from pivot_tpu.mpc.controller import MpcController

            self._mpc = MpcController(self, self.mpc)
            self._mpc.start()
        producer = threading.Thread(
            target=self._produce, args=(arrivals, pace),
            name="serve-producer", daemon=True,
        )
        producer.start()
        if self.batcher is not None:
            self.batcher.serve()
        # Supervisor restarts append replacement threads while we join —
        # loop until every NON-ABANDONED thread has exited.  Abandoned
        # sessions' threads are excluded: a permanently wedged thread is
        # exactly what the stall watchdog replaced (it cannot be killed,
        # only out-lived — daemon threads die with the process), and
        # waiting on it would hang the service shutdown the restart just
        # saved.
        while True:
            # Snapshot under the cv: supervisor restarts and autoscaler
            # growth append to _threads concurrently with this loop.
            with self._cv:
                pending = [
                    t for s, t in self._threads
                    if t.is_alive() and not s.abandoned
                ]
            if not pending:
                break
            for t in pending:
                t.join(timeout=0.5)
        producer.join()
        self._watch_stop.set()
        if watchdog is not None:
            watchdog.join()
        if self._autoscaler is not None:
            self._autoscaler.stop()
        if self._mpc is not None:
            self._mpc.stop()
        if self._recovery is not None:
            # Drain the pending snapshot and fsync the journal tail —
            # runs on the error path too (the whole point is that the
            # journal is trustworthy after ANY exit).
            self._recovery.stop()
        with self._cv:
            errors = self._errors + [
                s.error
                for s in self.sessions + self._retired
                if s.error is not None
            ]
        if errors:
            raise errors[0]
        return self.report()

    def publish_metrics(self, registry=None) -> Optional[dict]:
        """Publish the service's full metrics state into the unified
        registry (``pivot_tpu.obs.MetricsRegistry``) — the SLO meter
        (counters, tiers, distributions, dispatch mix), the autoscaler
        action log, per-session run meters, and the dispatch-profiler
        census — and return the JSON snapshot.  Uses the driver's
        attached registry when none is passed; None when neither
        exists.

        Scrape-safe (round 15, ``serve --metrics-port``): callable
        mid-run from the HTTP endpoint's worker thread — the mutable
        pool state is snapshotted under the cv, the SLO meter and
        registry lock internally, and publish-style ``set`` makes
        republishing idempotent."""
        registry = registry or self.registry
        if registry is None:
            return None
        self.slo.publish_metrics(registry)
        with self._cv:
            sessions = list(self.sessions) + list(self._retired)
        if self._autoscaler is not None:
            registry.counter(
                "pivot_autoscale_actions_total",
                "autoscaler actions (grow/shrink/preempt)",
                labelnames=("action",),
            )
            actions: Dict[str, int] = {}
            for evt in list(self._autoscaler.events):
                actions[evt["action"]] = actions.get(evt["action"], 0) + 1
            for action, n in actions.items():
                registry.set(
                    "pivot_autoscale_actions_total", n, action=action
                )
        if self._mpc is not None:
            registry.counter(
                "pivot_mpc_actions_total",
                "mpc planner actions (hold/grow/drain/shed/canary)",
                labelnames=("action",),
            )
            for action, n in self._mpc.action_counts().items():
                registry.set("pivot_mpc_actions_total", n, action=action)
            registry.counter(
                "pivot_mpc_stage_events_total",
                "mpc rollout stage transitions",
                labelnames=("stage",),
            )
            stages: Dict[str, int] = {}
            for evt in list(self._mpc.rollout.events):
                stages[evt["stage"]] = stages.get(evt["stage"], 0) + 1
            for stage, n in stages.items():
                registry.set("pivot_mpc_stage_events_total", n, stage=stage)
        for s in sessions:
            s.meter.publish_metrics(registry, run=s.label)
        if self.profiler is not None:
            self.profiler.publish_metrics(registry)
        if self._recovery is not None:
            self._recovery.publish(registry)
        return registry.to_json()

    def report(self) -> dict:
        return {
            "sessions": len(self.sessions),
            "backpressure": self.queue.policy,
            "queue_depth": self.queue.depth,
            "flush_after_s": self.flush_after,
            "ragged": self.ragged,
            "resident": self.resident,
            "splice_tier": self.splice_tier,
            "routing": self.routing,
            "preempt": self.preempt,
            "tenant_quota": self.queue.tenant_quota,
            # 2-D serving mesh (round 17): axis sizes when one is
            # attached — how coalesced dispatches partitioned.
            "mesh": (
                {str(k): int(v) for k, v in self.mesh.shape.items()}
                if self.mesh is not None else None
            ),
            "tier_reserve": (
                list(self.queue.tier_reserve)
                if self.queue.tier_reserve else None
            ),
            "tier_policies": (
                list(self.queue.tier_policies)
                if self.queue.tier_policies else None
            ),
            "restarts": self._restarts,
            "pool": {
                "final": len(self.sessions),
                "grown": self._n_grown,
                "retired": len(self._retired),
                "abandoned": len(self._abandoned),
            },
            "autoscaler": (
                {
                    "g_min": self.autoscale.g_min,
                    "g_max": self.autoscale.g_max,
                    "slo_p99_s": self.autoscale.slo_p99_s,
                    "events": list(self._autoscaler.events),
                }
                if self._autoscaler is not None else None
            ),
            "mpc": (
                self._mpc.summary() if self._mpc is not None else None
            ),
            # Recovery plane (round 21): journal / snapshot / watchdog
            # state when crash-safety is armed; None = legacy stack.
            "recovery": (
                self._recovery.summary()
                if self._recovery is not None else None
            ),
            "slo": self.slo.snapshot(),
            "batcher": dict(self.batcher.stats) if self.batcher else None,
            # Dispatch-profiler census (round 15): per-family sampled
            # latency + model-ratio medians; present when profiling.
            **(
                {"profiler": self.profiler.summary()}
                if self.profiler is not None else {}
            ),
            "per_session": [
                s.summary() for s in self.sessions + self._retired
            ],
            # The unified registry snapshot (round 14): present exactly
            # when the driver was built with a MetricsRegistry.
            **(
                {"metrics": self.publish_metrics()}
                if self.registry is not None else {}
            ),
        }

    def audit(self, context: str = "serve drain") -> None:
        """Raise ``AuditError`` unless the drained service satisfies the
        serve conservation law (``infra/audit.py::audit_serve``): every
        admitted or preempted job terminated exactly once, capacity and
        spill fully drained, and every surviving session's world passes
        the cluster/conservation/billing audits."""
        from pivot_tpu.infra.audit import AuditError, audit_serve

        violations = audit_serve(self)
        if violations:
            raise AuditError(
                f"serve state corrupted ({context}):\n  "
                + "\n  ".join(violations)
            )


def closed_loop_source(
    driver: ServeDriver,
    make_app: Callable,
    concurrency: int,
    n_jobs: int,
    stagger: float = 1e-3,
):
    """Closed-loop load generator: keep ``concurrency`` jobs in flight;
    every completion injects the next job at the scheduler grid point
    after the completing session's clock — the N-users-think-time-zero
    model, the complement of the open-loop Poisson stream."""
    import queue as _queue

    feed: "_queue.Queue" = _queue.Queue()
    produced = {"n": 0}
    lock = threading.Lock()

    def emit(ts: float) -> None:
        with lock:
            if produced["n"] >= n_jobs:
                return
            produced["n"] += 1
        feed.put(JobArrival(ts, make_app()))

    for i in range(min(concurrency, n_jobs)):
        emit(stagger * (i + 1))
    driver.add_completion_hook(
        lambda _s, _a, sim_now: emit(driver._next_tick(sim_now))
    )

    def gen():
        yielded = 0
        while yielded < n_jobs:
            # graftcheck: ignore[thread-guard] -- monotonic stop flag polled by the feed loop; the producer thread consuming this generator re-checks under the cv
            if driver._stop:
                return
            try:
                item = feed.get(timeout=0.02)
            except _queue.Empty:
                # No completion yet: the in-flight jobs need sim time to
                # finish, and only the producer can grant it.
                driver.advance_gate()
                continue
            yield item
            yielded += 1

    return gen()
