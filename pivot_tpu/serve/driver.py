"""The stream driver: admission, routing, release gate, lifecycle.

Topology (docs/ARCHITECTURE.md "The online serving layer")::

    arrivals ──▶ AdmissionQueue ──▶ round-robin router ──▶ session inboxes
    (Poisson /     (bounded;          (deterministic)        │ one thread
     trace-replay)  block/shed/spill)                        ▼ per session
                                                   ServeSession event loops
                                                         │ placement ticks
                                                         ▼
                                                   DispatchBatcher slots
                                              (idle-aware, deadline flush)
                                                         │
                                                         ▼
                                           ONE [G]-vmapped device dispatch

The driver owns one condition variable that serializes every control
decision: admission (in-flight accounting + backpressure), routing
(round-robin over sessions — deterministic, which is what lets a served
schedule be compared bit-for-bit against per-session batch runs), the
**release gate** (sessions may not step an event past the largest
arrival timestamp the stream has revealed — an online scheduler cannot
simulate past "now"), completions (capacity release + spill re-offers +
closed-loop refill), and shutdown.

Wall-clock pacing is optional (``pace`` sim-seconds per wall-second);
the default *replay* mode runs as fast as the sessions can step, which
is both the bench configuration and the deterministic one.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Iterable, List, Optional

import time

from pivot_tpu.infra.meter import SloMeter
from pivot_tpu.utils import LogMixin

from pivot_tpu.serve.admission import ADMITTED, BLOCKED, AdmissionQueue
from pivot_tpu.serve.arrivals import JobArrival
from pivot_tpu.serve.session import ServeSession

__all__ = ["ServeDriver", "closed_loop_source"]


class ServeDriver(LogMixin):
    """Always-on scheduling service over G concurrent sessions."""

    #: Wall seconds between capacity re-checks while a ``block``-policy
    #: producer waits; each expiry also advances the release gate one
    #: scheduler tick so blocked admission cannot freeze sim time.
    _BLOCK_POLL_S = 0.02

    def __init__(
        self,
        sessions: List[ServeSession],
        queue_depth: int = 64,
        backpressure: str = "shed",
        flush_after: Optional[float] = None,
        slo: Optional[SloMeter] = None,
    ):
        if not sessions:
            raise ValueError("ServeDriver needs at least one session")
        self.sessions = list(sessions)
        self.slo = slo or SloMeter()
        self.queue = AdmissionQueue(queue_depth, backpressure, self.slo)
        self.flush_after = flush_after
        self.interval = sessions[0].interval
        self.batcher = None
        self._cv = threading.Condition()
        self._released = 0.0
        self._stop = False
        self._errors: List[BaseException] = []
        self._rr = 0
        self._completion_hooks: List[Callable] = []
        for slot, s in enumerate(self.sessions):
            s._driver = self
            s.slot = slot
            s.slo = self.slo  # one service-wide SLO meter

    # -- gate + coordination ----------------------------------------------
    def wait_released(self, session: ServeSession, t: float,
                      client=None) -> bool:
        """Block ``session`` until the release frontier reaches sim time
        ``t`` (or new work lands in its inbox, or shutdown).  The
        session's batcher slot is marked idle for the duration so gated
        sessions never park co-pending dispatches.  Returns False on
        shutdown."""
        with self._cv:
            if self._released >= t or not session._inbox.empty():
                return not self._stop
            if client is not None:
                client.set_idle(True)
            try:
                self._cv.wait_for(
                    lambda: (
                        self._stop
                        or self._released >= t
                        or not session._inbox.empty()
                    )
                )
            finally:
                if client is not None:
                    client.set_idle(False)
            return not self._stop

    def _release_to(self, ts: float) -> None:
        if ts > self._released:
            self._released = ts
            self._cv.notify_all()

    def _next_tick(self, t: float) -> float:
        return (math.floor(t / self.interval) + 1) * self.interval

    def advance_gate(self) -> None:
        """Let sim time flow one scheduler tick with no new arrivals —
        the "time passes while we wait" primitive behind block-mode
        admission and the closed-loop load generator (both wait on
        completions that can only happen if the sessions may advance)."""
        with self._cv:
            if self._released != float("inf"):
                self._release_to(self._next_tick(self._released))

    # -- completions -------------------------------------------------------
    def add_completion_hook(self, fn: Callable) -> None:
        """``fn(session, app, sim_now)`` after every job completion —
        the closed-loop load generator's refill tap."""
        self._completion_hooks.append(fn)

    def on_completed(self, session: ServeSession, app, sim_now: float):
        with self._cv:
            self.queue.release()
            self.slo.count("completed")
            self._reoffer_spilled(after_sim=sim_now)
            self._cv.notify_all()
        for fn in self._completion_hooks:
            fn(session, app, sim_now)

    def on_session_error(self, session: ServeSession, exc) -> None:
        with self._cv:
            self._errors.append(exc)
            self._stop = True
            self._cv.notify_all()
        for s in self.sessions:
            s.shutdown()

    def _reoffer_spilled(self, after_sim: Optional[float] = None) -> None:
        """Drain the spill buffer into freed capacity (cv held).  A
        spilled job's submission lands no earlier than the scheduler
        grid point after the instant that freed its slot — the "spill to
        next tick" contract.  ``after_sim`` is the freeing completion's
        sim time; the belt-and-braces call sites without one (capacity
        cannot actually be free there — every release re-offers
        immediately) fall back to the release frontier so a readmission
        can never land in a session's past."""
        while self.queue.spilled and not self.queue.full:
            arr = self.queue.spilled.popleft()
            floor_t = after_sim
            if floor_t is None and self._released != float("inf"):
                floor_t = self._released
            if floor_t is not None:
                arr = JobArrival(
                    max(arr.ts, self._next_tick(floor_t)), arr.app
                )
            self.queue.readmit(arr)
            self._route(arr)

    # -- admission + routing ----------------------------------------------
    def _route(self, arrival: JobArrival) -> None:
        target = self.sessions[self._rr % len(self.sessions)]
        self._rr += 1
        target.offer(arrival)
        self._cv.notify_all()

    def _admit(self, arrival: JobArrival) -> None:
        with self._cv:
            # An arrival at ts proves the stream silent before ts: time
            # may flow to it even while admission deliberates.
            self._release_to(arrival.ts)
            self._reoffer_spilled()
            status = self.queue.offer(arrival)
            while (
                status == BLOCKED and not self._stop and not self._errors
            ):
                self.slo.count("blocked_waits")
                notified = self._cv.wait(timeout=self._BLOCK_POLL_S)
                if not notified and self._released != float("inf"):
                    # No completion freed capacity: advance sim time one
                    # tick so in-flight work can progress toward one.
                    self._release_to(self._next_tick(self._released))
                if not self.queue.full:
                    self.queue.readmit(arrival)
                    status = ADMITTED
            if status == ADMITTED:
                self._route(arrival)

    def _produce(self, arrivals: Iterable[JobArrival],
                 pace: Optional[float]) -> None:
        wall0 = time.perf_counter()
        try:
            for arr in arrivals:
                if self._stop:
                    return
                if pace:
                    lag = arr.ts / pace - (time.perf_counter() - wall0)
                    if lag > 0:
                        time.sleep(lag)
                self._admit(arr)
            # Stream exhausted: reveal the open horizon, wait for the
            # admitted work (and any spilled stragglers) to drain.
            with self._cv:
                self._release_to(float("inf"))
                while not self._stop and not self._errors and (
                    self.queue.in_flight > 0 or self.queue.spilled
                ):
                    self._reoffer_spilled()
                    if self.queue.in_flight == 0 and not self.queue.spilled:
                        break
                    self._cv.wait(timeout=0.5)
        except BaseException as exc:  # noqa: BLE001 — surfaced by run()
            with self._cv:
                self._errors.append(exc)
                self._stop = True
                self._cv.notify_all()
        finally:
            with self._cv:
                self._release_to(float("inf"))
            for s in self.sessions:
                s.shutdown()

    # -- lifecycle ---------------------------------------------------------
    def run(self, arrivals: Iterable[JobArrival],
            pace: Optional[float] = None) -> dict:
        """Serve the stream to completion; returns the service report.

        Batching engages when every session's policy qualifies (device
        backend, deterministic routing — the ``run_grid_lockstep``
        criterion): each session gets a ``DispatchBatcher`` slot and the
        caller's thread runs the flush coordinator.  Otherwise sessions
        run free (numpy/naive policies have no dispatch to coalesce).
        """
        clients = [None] * len(self.sessions)
        if all(s.batchable for s in self.sessions):
            # Initialize the backend once, here, before any session
            # thread dispatches — concurrent first-touch PJRT client
            # creation is not safe (same guard as run_grid_lockstep).
            import jax

            jax.default_backend()
            from pivot_tpu.sched.batch import DispatchBatcher

            self.batcher = DispatchBatcher(
                len(self.sessions), flush_after=self.flush_after
            )
            clients = [self.batcher.client() for _ in self.sessions]
            for s, c in zip(self.sessions, clients):
                s.policy.enable_batching(c)
        threads = [
            threading.Thread(
                target=s.loop, args=(c,),
                name=f"serve-{s.label}", daemon=True,
            )
            for s, c in zip(self.sessions, clients)
        ]
        for t in threads:
            t.start()
        producer = threading.Thread(
            target=self._produce, args=(arrivals, pace),
            name="serve-producer", daemon=True,
        )
        producer.start()
        if self.batcher is not None:
            self.batcher.serve()
        for t in threads:
            t.join()
        producer.join()
        errors = self._errors + [
            s.error for s in self.sessions if s.error is not None
        ]
        if errors:
            raise errors[0]
        return self.report()

    def report(self) -> dict:
        return {
            "sessions": len(self.sessions),
            "backpressure": self.queue.policy,
            "queue_depth": self.queue.depth,
            "flush_after_s": self.flush_after,
            "slo": self.slo.snapshot(),
            "batcher": dict(self.batcher.stats) if self.batcher else None,
            "per_session": [s.summary() for s in self.sessions],
        }


def closed_loop_source(
    driver: ServeDriver,
    make_app: Callable,
    concurrency: int,
    n_jobs: int,
    stagger: float = 1e-3,
):
    """Closed-loop load generator: keep ``concurrency`` jobs in flight;
    every completion injects the next job at the scheduler grid point
    after the completing session's clock — the N-users-think-time-zero
    model, the complement of the open-loop Poisson stream."""
    import queue as _queue

    feed: "_queue.Queue" = _queue.Queue()
    produced = {"n": 0}
    lock = threading.Lock()

    def emit(ts: float) -> None:
        with lock:
            if produced["n"] >= n_jobs:
                return
            produced["n"] += 1
        feed.put(JobArrival(ts, make_app()))

    for i in range(min(concurrency, n_jobs)):
        emit(stagger * (i + 1))
    driver.add_completion_hook(
        lambda _s, _a, sim_now: emit(driver._next_tick(sim_now))
    )

    def gen():
        yielded = 0
        while yielded < n_jobs:
            if driver._stop:
                return
            try:
                item = feed.get(timeout=0.02)
            except _queue.Empty:
                # No completion yet: the in-flight jobs need sim time to
                # finish, and only the producer can grant it.
                driver.advance_gate()
                continue
            yield item
            yielded += 1

    return gen()
