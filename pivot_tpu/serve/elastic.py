"""Elastic mesh serving (round 20): survive device loss mid-span.

The serving stack (``serve/driver.py``) assumed an immortal compute
plane: ``enable_sharding`` pins every session policy to a fixed device
set, and the only device-failure story was ``degrade_after``'s
permanent CPU-twin fallback.  This module makes the mesh ELASTIC — the
pool shrinks around a lost device, keeps serving on the surviving
shards, and regrows when the device returns:

* :class:`ElasticConfig` — the knob bundle ``ServeDriver(elastic=...)``
  takes.  Device faults come from the same seeded, serializable
  :class:`~pivot_tpu.infra.faults.ChaosSchedule` every other chaos
  source uses (``device_fault`` / ``device_restore`` event kinds),
  compiled to a :class:`~pivot_tpu.infra.faults.DeviceFaultPlan` of
  half-open per-ordinal down windows.

* :class:`ElasticMeshManager` — owns the launch device set, the
  mesh-shape ladder (descending divisors of the launch device count),
  the per-rung mesh cache, and the shrink/regrow state machine.  It
  installs a FAULT GATE on every session policy
  (``_DevicePolicyBase.enable_fault_gate``) that runs at each dispatch:

  - **loss**: the dispatch instant falls inside a down window covering
    a device of the policy's CURRENT mesh → raise
    :class:`~pivot_tpu.infra.faults.DeviceLostError`.  The session
    crashes, the driver's existing supervisor requeues its in-flight
    work (tier 0 first out — the admission queue's tier ordering) and
    builds a replacement whose policy this manager RESHARDS onto the
    surviving-shard mesh before it serves a single decision.

  - **regrow**: the down-set no longer covers an excluded device and
    the ladder admits a larger rung → SHADOW-PROBE the candidate mesh
    (a canonical fused-span dispatch diffed bit-for-bit against the
    single-device reference program) and, on an exact match, promote by
    resharding IN-THREAD at the dispatch boundary — the policy is only
    ever touched by its own session thread, so promotion is race-free.
    A failed probe holds the device out and retries on the half-open
    cadence (every ``probe_every`` gated dispatches).

The bit-parity referee: placements depend only on the global ``[H]``
state — the sharded kernels are bit-identical to the single-device
reference on every mesh shape (``tests/test_shard.py``), so a shrink
changes *where* state lives, never *what* is decided.  Post-shrink
placements are therefore bit-identical to a from-scratch run on the
smaller mesh over the same admitted stream (``tests/test_elastic.py``),
and regrow timing — wall-clock-dependent by nature — can never change a
decision.  Compile cost is bounded by the ladder: meshes are cached per
surviving-ordinal tuple and the jitted sharded programs are
``lru_cache``'d on the mesh, so revisiting a rung compiles nothing.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from pivot_tpu.infra.faults import (
    ChaosSchedule,
    DeviceFaultPlan,
    DeviceLostError,
)

__all__ = [
    "DeviceLostError",
    "ElasticConfig",
    "ElasticMeshManager",
    "is_device_loss",
]


def is_device_loss(exc: BaseException) -> bool:
    """Classify a session error as a device loss.  Injected faults
    arrive as :class:`DeviceLostError` (the gate's own type); real
    losses surface as XLA runtime errors whose text names the device —
    matched loosely here so a production backend's "device lost" /
    "failed to enqueue" family routes to shrink instead of fail-stop."""
    if isinstance(exc, DeviceLostError):
        return True
    text = str(exc).lower()
    return type(exc).__name__ == "XlaRuntimeError" and (
        "device" in text and ("lost" in text or "halted" in text)
    )


@dataclass
class ElasticConfig:
    """Elastic mesh serving knobs (``ServeDriver(elastic=...)``).

    ``schedule``: a :class:`ChaosSchedule` whose ``device_fault`` /
    ``device_restore`` events define the injected down windows —
    seeded, serializable, replayable (``tools/chaos_replay.py``).
    ``plan`` wins over ``schedule`` when both are given (a pre-built
    :class:`DeviceFaultPlan`, e.g. from a replay diff).  Neither →
    no injected faults; the manager still classifies real losses and
    serves ``mark_dead`` (tests, external watchdogs).

    ``probe``: shadow-probe a returning device before promoting the
    larger mesh (the half-open regrow contract).  ``probe_every``: a
    failed probe is retried after this many gated dispatches.
    ``probe_ticks`` / ``probe_tasks``: the canonical probe span's
    (K, B) extents; ``seed`` feeds its synthetic operands."""

    schedule: Optional[ChaosSchedule] = None
    plan: Optional[DeviceFaultPlan] = None
    probe: bool = True
    probe_every: int = 64
    probe_ticks: int = 2
    probe_tasks: int = 3
    seed: int = 0


class ElasticMeshManager:
    """The shrink/reshard/regrow brain behind ``ServeDriver(elastic=)``.

    Thread model: ``attach``/``align`` run under the driver's cv (pool
    surgery); gates run on session threads.  The manager's own mutable
    state (mesh cache, probe verdicts, counters, frontier) is guarded by
    ``_lock``; each POLICY is only ever resharded by its owning session
    thread (gate) or under the cv before its thread starts (attach) —
    never concurrently."""

    def __init__(self, config: Optional[ElasticConfig] = None):
        self.config = config or ElasticConfig()
        self.logger = logging.getLogger("pivot_tpu.serve.elastic")
        self._lock = threading.Lock()
        #: Launch device set (ordinal order), derived from the first
        #: attached policy's mesh — ordinal i == plan ordinal i.
        self.devices: Optional[List] = None
        self.ladder: Tuple[int, ...] = ()
        self.plan: Optional[DeviceFaultPlan] = None
        self._launch_mesh = None
        #: Mesh cache keyed on the chosen surviving-ordinal tuple —
        #: bounded by the ladder (one entry per visited rung + survivor
        #: choice), so compile count is bounded too.
        self._meshes: Dict[Tuple[int, ...], object] = {}
        #: Manually marked dead ordinals (real losses / tests) — the
        #: plan-driven windows are time-indexed and need no marking.
        self._dead: set = set()
        #: Largest dispatch instant any gate has observed: the sim time
        #: ``align`` evaluates the down-set at when wiring a replacement
        #: session (whose own env clock restarts behind the frontier).
        self._frontier = 0.0
        #: Probe verdicts per candidate ordinal tuple: True = promoted
        #: once already (never re-probe), int = gate-call countdown
        #: until the half-open retry after a failed probe.
        self._probe_state: Dict[Tuple[int, ...], object] = {}
        # Event log + counters (bench / tests read these).
        self.events: List[Tuple[float, str, Tuple[int, ...]]] = []
        self.shrinks = 0
        self.regrows = 0
        self.probes = 0
        self.probe_failures = 0

    # -- wiring ------------------------------------------------------------
    def attach(self, policy) -> None:
        """Adopt a session policy: derive the launch device set from the
        first mesh seen, build the fault plan against it, install the
        dispatch gate, and align the policy onto the current target mesh
        (a replacement session built after a shrink must come up ON the
        shrunk mesh, or its first gated dispatch would re-crash it and
        burn the restart budget)."""
        mesh = getattr(policy, "_mesh", None)
        if mesh is None:
            raise ValueError(
                "elastic serving needs host-sharded session policies — "
                "call enable_sharding(host_sharded_mesh(...)) in the "
                "session factory"
            )
        from pivot_tpu.ops.shard import REPLICA_AXIS, mesh_shape_ladder

        if int(mesh.shape.get(REPLICA_AXIS, 1)) > 1:
            raise ValueError(
                "elastic serving shrinks the 1-D host axis; a mesh with "
                "a non-trivial replica axis (the batcher's 2-D layout) "
                "is fixed at construction"
            )
        with self._lock:
            if self.devices is None:
                self.devices = list(np.asarray(mesh.devices).ravel())
                self.ladder = mesh_shape_ladder(len(self.devices))
                self._launch_mesh = mesh
                self._meshes[tuple(range(len(self.devices)))] = mesh
                cfg = self.config
                if cfg.plan is not None:
                    self.plan = cfg.plan
                elif cfg.schedule is not None:
                    self.plan = DeviceFaultPlan.from_schedule(
                        cfg.schedule, len(self.devices)
                    )
                else:
                    self.plan = DeviceFaultPlan({}, len(self.devices))
            frontier = self._frontier
        policy.enable_fault_gate(self._gate_for(policy))
        self.align(policy, frontier)

    def align(self, policy, now: float) -> None:
        """Reshard ``policy`` onto the target mesh for the down-set at
        sim time ``now`` (no-op when already there).  The attach-time
        shrink path — no probe: shrinking is always safe, and a
        replacement session has no in-flight work to quarantine."""
        target = self._target_mesh(self._down_at(now))
        if getattr(policy, "_mesh", None) != target:
            policy.reshard(target)

    # -- the down-set ------------------------------------------------------
    def _down_at(self, now: float) -> frozenset:
        plan_down = self.plan.down_at(now) if self.plan is not None else ()
        return frozenset(plan_down) | frozenset(self._dead)

    def mark_dead(self, ordinal: int) -> None:
        """Record a non-injected (real) loss — the classification path
        for watchdog timeouts and raised executions that carry no
        ordinal windows."""
        with self._lock:
            self._dead.add(int(ordinal))

    def mark_restored(self, ordinal: int) -> None:
        with self._lock:
            self._dead.discard(int(ordinal))

    # -- mesh geometry -----------------------------------------------------
    def _survivor_key(self, down: frozenset) -> Tuple[int, ...]:
        """The chosen surviving-ordinal tuple for a down-set: the first
        ``shape`` survivors in ordinal order, where ``shape`` is the
        largest ladder rung the survivor count admits — deterministic,
        so replaying the same fault plan rebuilds the same meshes."""
        survivors = [
            o for o in range(len(self.devices)) if o not in down
        ]
        if not survivors:
            raise DeviceLostError(sorted(down), self._frontier)
        from pivot_tpu.ops.shard import next_ladder_shape

        shape = next_ladder_shape(self.ladder, len(survivors))
        return tuple(survivors[:shape])

    def _target_mesh(self, down: frozenset):
        key = self._survivor_key(down)
        with self._lock:
            mesh = self._meshes.get(key)
            if mesh is None:
                from pivot_tpu.parallel.mesh import host_sharded_mesh

                mesh = host_sharded_mesh(
                    len(key), devices=[self.devices[o] for o in key]
                )
                self._meshes[key] = mesh
        return mesh

    def _mesh_ordinals(self, mesh) -> frozenset:
        devs = list(np.asarray(mesh.devices).ravel())
        index = {id(d): o for o, d in enumerate(self.devices)}
        return frozenset(index[id(d)] for d in devs)

    # -- the dispatch gate -------------------------------------------------
    def _gate_for(self, policy):
        """The per-policy dispatch gate (closure over ``policy``; runs
        on the owning session thread only)."""

        def _gate(now: float) -> None:
            now = float(now)
            with self._lock:
                if now > self._frontier:
                    self._frontier = now
                frontier = self._frontier
            down = self._down_at(now)
            mesh = policy._mesh
            hit = down & self._mesh_ordinals(mesh)
            if hit:
                with self._lock:
                    self.shrinks += 1
                    self.events.append((now, "loss", tuple(sorted(hit))))
                raise DeviceLostError(hit, now)
            # Regrow is judged at the SERVICE-WIDE frontier, not this
            # session's local clock: a supervisor replacement replays
            # sim times from before the fault window, and promoting on
            # those "healthy past" instants would march the pool
            # straight back onto the dead device (crash loop).  Shrink
            # above stays on ``now`` — a dispatch before the window is
            # genuinely healthy and must serve (determinism: the gate
            # raises at the first dispatch INSIDE the window, replayed
            # identically).
            down_front = self._down_at(frontier)
            target = self._target_mesh(down_front)
            if mesh != target and not (down_front & self._mesh_ordinals(mesh)):
                # Regrow candidate (never a shrink: a frontier down-set
                # covering this mesh is excluded above): half-open
                # probe, promote in-thread.
                self._try_promote(policy, target, frontier)

        return _gate

    def _try_promote(self, policy, target, now: float) -> None:
        key = self._survivor_key(self._down_at(now))
        with self._lock:
            state = self._probe_state.get(key)
            # NB ``state`` is True (certified), an int cooldown, or None
            # — test identity first (bool IS an int to isinstance).
            if state is not True and isinstance(state, int) and state > 0:
                self._probe_state[key] = state - 1
                return  # failed probe cooling down (half-open cadence)
        if state is not True and self.config.probe:
            ok = self.shadow_probe(policy, target)
            with self._lock:
                self.probes += 1
                if not ok:
                    self.probe_failures += 1
                    self._probe_state[key] = int(self.config.probe_every)
                    self.events.append(
                        (now, "probe_failed", tuple(sorted(key)))
                    )
                    return
                self._probe_state[key] = True
        policy.reshard(target)
        with self._lock:
            self.regrows += 1
            self.events.append((now, "regrow", tuple(sorted(key))))
        self.logger.info(
            "elastic regrow: mesh promoted to %d shard(s) at t=%g",
            len(key), now,
        )

    # -- the shadow probe --------------------------------------------------
    def shadow_probe(self, policy, mesh) -> bool:
        """Run a canonical fused span on the CANDIDATE mesh and diff its
        placements bit-for-bit against the single-device reference
        program — the same oracle the sharded parity suite holds every
        mesh shape to.  An exact match certifies the returning device
        computes what the live program would (promotion is safe by the
        bit-parity referee); any mismatch or raise holds it out."""
        from pivot_tpu.ops.shard import sharded_fused_tick_run
        from pivot_tpu.ops.tickloop import fused_tick_run
        from pivot_tpu.parallel.mesh import host_axis_size

        cfg = self.config
        S = host_axis_size(mesh)
        topo = getattr(policy, "topology", None)
        H = topo.n_hosts if topo is not None else S * 4
        if H % S:  # pragma: no cover — ladder rungs always divide H
            H = -(-H // S) * S
        dtype = np.dtype(getattr(policy, "dtype", np.float64))
        rng = np.random.default_rng(cfg.seed)
        K, B = int(cfg.probe_ticks), int(cfg.probe_tasks)
        avail = rng.uniform(1.0, 4.0, size=(H, 4)).astype(dtype)
        demands = rng.uniform(0.1, 0.9, size=(B, 4)).astype(dtype)
        arrive = np.zeros(B, dtype=np.int32)
        kw = dict(policy="first-fit", n_ticks=K)
        try:
            want = fused_tick_run(avail, demands, arrive, K, **kw)
            got = sharded_fused_tick_run(
                mesh, avail, demands, arrive, K, **kw
            )
        except Exception as exc:  # noqa: BLE001 — a dead probe holds out
            self.logger.warning("elastic shadow probe raised: %s", exc)
            return False
        return bool(
            np.array_equal(
                np.asarray(want.placements), np.asarray(got.placements)
            )
        )

    # -- reporting ---------------------------------------------------------
    def note_loss(self, exc, label: str = "?") -> None:
        """Record a classified device loss from the supervisor path (the
        gate already logged injected ones; real losses without ordinals
        land here as bare events)."""
        ordinals = tuple(getattr(exc, "ordinals", ()))
        self.logger.error(
            "session %s lost device(s) %s — shrinking mesh",
            label, list(ordinals) or "?",
        )

    def describe(self) -> str:
        lines = [
            f"ladder: {list(self.ladder)}",
            f"shrinks: {self.shrinks}  regrows: {self.regrows}  "
            f"probes: {self.probes} ({self.probe_failures} failed)",
        ]
        if self.plan is not None:
            lines.extend(self.plan.describe())
        return "\n".join(lines)
