"""SLO-driven autoscaling of the ``ServeSession`` pool.

The serving pool (PR 2) was a fixed G chosen at launch; this module
closes the loop against the *measured* service level instead: a
supervisor thread samples the governed tier's p99 decision latency over
the window since its last check (``SloMeter.tier_decision_p99_since`` —
windowed, not lifetime, so an hour of calm cannot drown a fresh breach)
and resizes the pool between ``g_min`` and ``g_max``:

  * **grow** — ``breach_checks`` consecutive windows over the target
    spawn one factory session on a fresh ``DispatchBatcher`` slot
    (``respawn_client`` — the same machinery supervisor restarts use,
    so growth composes with self-healing);
  * **shrink** — ``calm_checks`` consecutive windows under
    ``shrink_factor × target`` begin a **drain-then-retire**: the least
    loaded session stops receiving new work (the router skips
    ``retiring`` sessions) and is finalized — STOP, scheduler stopped,
    batcher slot closed — only once its live set and inbox are empty.
    In-flight jobs are never moved or lost by a scale-down; a session
    that *crashes* mid-drain is settled by the driver's retire-crash
    path (jobs requeued, slot retired exactly once).

Hysteresis is deliberate and triple: consecutive-check counts in both
directions, a wall-clock ``cooldown_s`` between any two scaling events,
and the shrink threshold sitting well under the grow threshold — the
classic guard against limit-cycling the pool on a noisy latency signal.
Every action lands in :attr:`SloAutoscaler.events` (and the SLO meter's
``scale_up_events`` / ``scale_down_events`` counters), so a soak report
shows *when* and *why* the pool moved.

Defaults are inert: ``ServeDriver(autoscale=None)`` never starts the
thread, preserving the fixed-pool behavior bit for bit.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional

from pivot_tpu.utils import LogMixin

__all__ = ["AutoscaleConfig", "SloAutoscaler"]


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Pool bounds + the latency SLO the pool is sized against."""

    g_min: int = 1
    g_max: int = 8
    #: p99 decision-latency target (wall seconds) for the governed tier.
    slo_p99_s: float = 0.05
    #: Which tier's latency governs scaling (0 = the serving tier).
    tier: int = 0
    #: Wall seconds between control-loop checks (one latency window).
    check_interval_s: float = 0.05
    #: Consecutive breached windows before growing.
    breach_checks: int = 2
    #: Consecutive calm windows before shrinking.
    calm_checks: int = 8
    #: A window is "calm" when p99 < shrink_factor × slo_p99_s (empty
    #: windows count as calm — an idle service shrinks toward g_min).
    shrink_factor: float = 0.3
    #: Minimum wall gap between any two scaling events.
    cooldown_s: float = 0.25
    #: At g_max with the SLO still breached, shed pressure instead of
    #: capacity: ask the driver to preempt one admitted-but-unplaced
    #: job of a lower tier per breached window (requires the driver's
    #: ``preempt=True``).  The last resort of "degrade, never fail".
    preempt_on_breach: bool = False

    def __post_init__(self):
        if self.g_min < 1:
            raise ValueError(f"g_min must be >= 1, got {self.g_min}")
        if self.g_max < self.g_min:
            raise ValueError(
                f"g_max ({self.g_max}) must be >= g_min ({self.g_min})"
            )
        if not self.slo_p99_s > 0:
            raise ValueError("slo_p99_s must be positive")
        if not self.check_interval_s > 0:
            raise ValueError("check_interval_s must be positive")
        if self.breach_checks < 1 or self.calm_checks < 1:
            raise ValueError("breach_checks/calm_checks must be >= 1")
        if not 0 < self.shrink_factor <= 1:
            raise ValueError(
                f"shrink_factor must be in (0, 1], got {self.shrink_factor}"
            )
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")


class SloAutoscaler(LogMixin):
    """The control loop.  Owned and started by ``ServeDriver.run`` when
    the driver is built with an :class:`AutoscaleConfig`; all pool
    mutations go through driver methods under the driver's lock."""

    def __init__(self, driver, config: AutoscaleConfig):
        self.driver = driver
        self.config = config
        #: Scaling-event log: dicts with wall time, action, pool sizes,
        #: and the measured p99 that triggered the move.
        self.events: List[dict] = []
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="serve-autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join()

    def record(self, action: str, p99: float, pool: int,
               detail: str = "") -> None:
        self.events.append(
            {
                "wall_s": round(self.driver.slo.wall_clock, 4),
                "action": action,
                "p99_s": round(p99, 6),
                "target_s": self.config.slo_p99_s,
                "pool": pool,
                "detail": detail,
            }
        )
        # Observability (round 14): every scaling action is a wall-
        # domain instant on the shared trace timeline — pool moves read
        # in context with the dispatch spans that triggered them.
        self.driver.tracer.mark(
            "autoscale", action, p99_s=round(p99, 6), pool=pool,
            detail=detail,
        )

    def _loop(self) -> None:
        cfg = self.config
        driver = self.driver
        baseline = driver.slo.tier_decision_baseline(cfg.tier)
        breach = calm = 0
        last_event = -float("inf")
        while not self._stop_evt.wait(cfg.check_interval_s):
            # graftcheck: ignore[thread-guard] -- monotonic stop flag; a stale read costs one control-loop tick, and every pool mutation below re-validates under the driver's cv
            if driver._stop:
                return
            # Finalize any retiring session whose drain completed —
            # polling here (not on completions) keeps the retire path
            # single-threaded and simple.
            driver.finish_drained_retires()
            p99 = driver.slo.tier_decision_p99_since(cfg.tier, baseline)
            baseline = driver.slo.tier_decision_baseline(cfg.tier)
            if p99 > cfg.slo_p99_s:
                breach += 1
                calm = 0
            else:
                calm += 1
                breach = 0
            now = time.perf_counter()
            if now - last_event < cfg.cooldown_s:
                continue
            pool = driver.pool_size()
            if breach >= cfg.breach_checks:
                breach = 0
                if pool < cfg.g_max:
                    if driver.grow_pool(reason=f"p99 {p99:.4f}s > SLO"):
                        self.record("grow", p99, pool + 1)
                        last_event = now
                elif cfg.preempt_on_breach:
                    if driver.shed_pressure(cfg.tier):
                        self.record(
                            "preempt", p99, pool,
                            detail="at g_max; shedding a lower tier",
                        )
                        last_event = now
            elif (
                calm >= cfg.calm_checks
                and p99 < cfg.shrink_factor * cfg.slo_p99_s
                and pool > cfg.g_min
            ):
                calm = 0
                victim = driver.begin_retire()
                if victim is not None:
                    self.record(
                        "shrink", p99, pool - 1,
                        detail=f"draining {victim.label}",
                    )
                    last_event = now
        # One last sweep so sessions already drained retire cleanly
        # before the driver joins threads.
        driver.finish_drained_retires()
