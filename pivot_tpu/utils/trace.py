"""Structured event tracing + device profiling.

The reference's only observability is per-class debug logging with sim
timestamps (``util.py:5-16``) and the meter's end-of-run JSON dumps
(``resources/meter.py:108-133``).  This module adds what SURVEY.md §5
prescribes for the rebuild: a structured, chronological event trace of the
simulation (scheduler ticks, policy latency, task lifecycle) that can be
written as JSONL or as a Chrome ``chrome://tracing`` / Perfetto file, plus
a ``jax.profiler`` context for capturing device (TPU) traces around the
kernel hot path.

Events carry BOTH clocks: ``sim`` (discrete-event virtual seconds) and
``wall`` (host seconds since tracer creation) — the sim timeline shows
*what the simulated system did*; the wall timeline shows *what the
framework paid to compute it* (policy/kernel latency per tick).

Tracing is opt-in and zero-cost when disabled: the module-level
``NULL_TRACER`` short-circuits ``emit``/``span`` before touching any
clock.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Dict, List, Optional

__all__ = ["Tracer", "NULL_TRACER", "device_profile"]


class Tracer:
    """Append-only structured event log with sim + wall timestamps."""

    __slots__ = ("enabled", "events", "_wall0")

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.events: List[Dict[str, Any]] = []
        self._wall0 = time.perf_counter()

    # -- recording -------------------------------------------------------
    def emit(self, cat: str, name: str, sim: float, **args: Any) -> None:
        """Record an instant event at sim time ``sim``."""
        if not self.enabled:
            return
        evt: Dict[str, Any] = {
            "cat": cat,
            "name": name,
            "sim": sim,
            "wall": time.perf_counter() - self._wall0,
        }
        if args:
            evt["args"] = args
        self.events.append(evt)

    @contextlib.contextmanager
    def span(self, cat: str, name: str, sim: float, **args: Any):
        """Record a wall-clock duration span (e.g. one policy invocation).

        The span's ``dur`` is *wall* seconds — sim time does not advance
        inside a synchronous block.  Mutations to ``args`` made inside the
        block (e.g. recording the number of placed tasks once known) are
        captured because the dict is attached at exit.
        """
        if not self.enabled:
            yield args
            return
        t0 = time.perf_counter()
        try:
            yield args
        finally:
            evt = {
                "cat": cat,
                "name": name,
                "sim": sim,
                "wall": t0 - self._wall0,
                "dur": time.perf_counter() - t0,
            }
            if args:
                evt["args"] = args
            self.events.append(evt)

    # -- serialization ---------------------------------------------------
    def save_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for evt in self.events:
                f.write(json.dumps(evt) + "\n")

    def save_chrome(self, path: str, timeline: str = "sim") -> None:
        """Write a Chrome/Perfetto trace (``chrome://tracing`` loadable).

        ``timeline='sim'`` places events at their simulated time (µs = sim
        seconds × 1e6, so 1 simulated second reads as 1 s in the viewer);
        ``timeline='wall'`` places them at host time — use this to inspect
        where the framework itself spends wall clock (policy spans carry
        real durations on either timeline).
        """
        assert timeline in ("sim", "wall")
        out = []
        for evt in self.events:
            ts = evt[timeline] * 1e6
            rec: Dict[str, Any] = {
                "name": evt["name"],
                "cat": evt["cat"],
                "pid": 0,
                "tid": evt["cat"],
                "ts": ts,
            }
            if "dur" in evt:
                rec["ph"] = "X"
                rec["dur"] = max(evt["dur"] * 1e6, 1.0)
            else:
                rec["ph"] = "i"
                rec["s"] = "t"
            if "args" in evt:
                rec["args"] = evt["args"]
            out.append(rec)
        with open(path, "w") as f:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)

    # -- analysis helpers ------------------------------------------------
    def by_category(self, cat: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e["cat"] == cat]

    def total_dur(self, cat: str, name: Optional[str] = None) -> float:
        """Σ wall-clock duration of matching spans (e.g. total policy time)."""
        return sum(
            e.get("dur", 0.0)
            for e in self.events
            if e["cat"] == cat and (name is None or e["name"] == name)
        )


NULL_TRACER = Tracer(enabled=False)


@contextlib.contextmanager
def device_profile(logdir: Optional[str]):
    """Capture a ``jax.profiler`` device trace around the enclosed block.

    The resulting TensorBoard-loadable trace shows XLA/Pallas kernel
    timings on the accelerator — the microscope for the decision-kernel
    hot path.  No-op when ``logdir`` is falsy (so call sites can thread an
    optional CLI flag straight through).
    """
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
