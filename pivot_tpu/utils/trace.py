"""Compatibility shim over :mod:`pivot_tpu.obs.tracer`.

The round-1 seed tracer lived here; round 14 grew it into the
observability plane (``pivot_tpu/obs/`` — causal task tracing, the
unified metrics registry, Perfetto export).  Every existing import
(``from pivot_tpu.utils.trace import Tracer, NULL_TRACER,
device_profile``) keeps working through this module; new code should
import from :mod:`pivot_tpu.obs` directly.
"""

from __future__ import annotations

from pivot_tpu.obs.tracer import (  # noqa: F401 — re-exports
    NULL_TRACER,
    TERMINAL_STAGES,
    Tracer,
    device_profile,
)

__all__ = ["Tracer", "NULL_TRACER", "TERMINAL_STAGES", "device_profile"]
