"""Shared utilities: logging, time bucketing, deterministic id generation.

Capability parity with the reference's ``util.py`` (Loggable / Singleton /
floor / ceil — /root/reference/util.py:5-34) but organized as plain module
functions; no singleton metaclass is needed because metadata is passed
explicitly (see ``pivot_tpu.infra.locality``).
"""

from __future__ import annotations

import itertools
import logging
import sys

_LOG_FORMAT = "%(name)s.%(funcName)s:%(lineno)s\t%(message)s"
_configured = False


def get_logger(name: str) -> logging.Logger:
    """Return a stdout logger configured once per process (INFO level)."""
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(_LOG_FORMAT))
        root = logging.getLogger("pivot_tpu")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True
    return logging.getLogger("pivot_tpu." + name)


class LogMixin:
    """Per-class logger property, analogous to the reference ``Loggable``."""

    @property
    def logger(self) -> logging.Logger:
        return get_logger(type(self).__name__)


def floor_bucket(n: float, bucket: float) -> float:
    """Round ``n`` down to a multiple of ``bucket`` (meter time bucketing)."""
    return n // bucket * bucket


def ceil_bucket(n: float, bucket: float) -> float:
    """Round ``n`` up to the next multiple of ``bucket`` (exclusive upper)."""
    return (n // bucket + 1) * bucket


_id_counters = {}


def fresh_id(prefix: str) -> str:
    """Deterministic, process-local unique id (``prefix-N``).

    The reference uses random UUID4 node ids (``resources/__init__.py:170``);
    deterministic ids make simulations reproducible and placements loggable
    as dense integer indices, which is what the TPU kernels consume.
    """
    counter = _id_counters.setdefault(prefix, itertools.count())
    return f"{prefix}-{next(counter)}"


def reset_ids() -> None:
    """Reset id counters (used by tests for reproducibility)."""
    _id_counters.clear()


def probe_backend_alive(timeout: float = 150.0) -> bool:
    """True iff ``import jax; jax.devices()`` completes in a child process.

    The first device touch blocks inside a PJRT client init that no signal
    handler can interrupt when a remote accelerator backend is
    unresponsive, so liveness must be probed in a disposable child
    (killable regardless of where it blocks).  Any probe failure —
    timeout, spawn error, nonzero exit — reads as "not alive"; the caller
    decides the fallback.  Shared by ``bench.py`` and the device policy
    backend (``pivot_tpu.sched.tpu``).
    """
    import subprocess

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
        return probe.returncode == 0 and "ok" in probe.stdout
    except Exception:
        return False
