"""Shared utilities: logging, time bucketing, deterministic id generation.

Capability parity with the reference's ``util.py`` (Loggable / Singleton /
floor / ceil — /root/reference/util.py:5-34) but organized as plain module
functions; no singleton metaclass is needed because metadata is passed
explicitly (see ``pivot_tpu.infra.locality``).
"""

from __future__ import annotations

import itertools
import logging
import sys

_LOG_FORMAT = "%(name)s.%(funcName)s:%(lineno)s\t%(message)s"
_configured = False


def get_logger(name: str) -> logging.Logger:
    """Return a stdout logger configured once per process (INFO level)."""
    global _configured
    if not _configured:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(_LOG_FORMAT))
        root = logging.getLogger("pivot_tpu")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True
    return logging.getLogger("pivot_tpu." + name)


class LogMixin:
    """Per-class logger property, analogous to the reference ``Loggable``."""

    @property
    def logger(self) -> logging.Logger:
        return get_logger(type(self).__name__)


def floor_bucket(n: float, bucket: float) -> float:
    """Round ``n`` down to a multiple of ``bucket`` (meter time bucketing)."""
    return n // bucket * bucket


def ceil_bucket(n: float, bucket: float) -> float:
    """Round ``n`` up to the next multiple of ``bucket`` (exclusive upper)."""
    return (n // bucket + 1) * bucket


_id_counters = {}


def fresh_id(prefix: str) -> str:
    """Deterministic, process-local unique id (``prefix-N``).

    The reference uses random UUID4 node ids (``resources/__init__.py:170``);
    deterministic ids make simulations reproducible and placements loggable
    as dense integer indices, which is what the TPU kernels consume.
    """
    counter = _id_counters.setdefault(prefix, itertools.count())
    return f"{prefix}-{next(counter)}"


def reset_ids() -> None:
    """Reset id counters (used by tests for reproducibility)."""
    _id_counters.clear()


_cache_enabled = False


def enable_compilation_cache() -> None:
    """Persist XLA executables across processes (``~/.cache/pivot_tpu_xla``).

    Each (bucket, H) program costs seconds to compile; without a persistent
    cache every fresh experiment process pays full compiles again, which can
    exceed the device's entire per-tick win at moderate scale.  Called from
    every device entry point: the policy backend (``pivot_tpu.sched.tpu``),
    the ensemble/autotune/capacity/apps CLI paths, ``bench.py``, and the
    driver's ``dryrun_multichip``.  Safe to call repeatedly; never lets a
    caching failure break scheduling.
    """
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    import os

    import jax

    try:
        cache_dir = os.environ.get(
            "PIVOT_XLA_CACHE", os.path.expanduser("~/.cache/pivot_tpu_xla")
        )
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    except Exception as exc:  # never let caching break scheduling
        get_logger("utils").warning(
            "persistent compilation cache unavailable: %s", exc
        )


#: stderr substrings that mark XLA:CPU AOT cache-portability noise — the
#: persistent compilation cache replaying an executable compiled on a
#: machine with different CPU features logs a screen-filling
#: feature-matrix "error" per load (``cpu_aot_loader.cc``) that is
#: advisory on this fleet (the fallback recompiles).  Multichip capture
#: artifacts record stderr tails; these lines would drown the signal.
_XLA_AOT_NOISE = ("cpu_aot_loader", "XLA:CPU AOT")


def filter_xla_aot_noise(text: str) -> str:
    """Drop the XLA:CPU AOT feature-mismatch log lines from ``text``
    (artifact stderr tails), keeping every other line — and the
    trailing newline, so re-emitting with ``end=''`` cannot glue the
    last kept line onto the caller's next write."""
    kept = "\n".join(
        ln for ln in text.splitlines()
        if not any(m in ln for m in _XLA_AOT_NOISE)
    )
    if kept and text.endswith("\n"):
        kept += "\n"
    return kept


def pin_virtual_cpu_mesh(n_devices: int) -> bool:
    """Pin this process to an ``n_devices`` virtual-CPU JAX backend.

    Must run before the first device touch.  Two layers are required
    (``tests/conftest.py`` recipe): the ``XLA_FLAGS`` device count is read
    once at backend init, and the config-level platform pin is the only
    override that beats the accelerator site package, which force-registers
    the remote (single-tenant, possibly wedged) backend over ``JAX_PLATFORMS``
    env vars at interpreter start.

    Returns True iff the pin is effective in this process — i.e. JAX
    backends were not yet initialized (or already satisfy the request).
    Returns False when it is too late (backends already up with the wrong
    platform or too few devices; XLA parses the device-count flag only
    once per process, so the caller must re-exec in a child to recover) —
    and restores the caller's environment, so a long-lived process that
    keeps running after a failed pin does not leak ``JAX_PLATFORMS=cpu``
    into every subprocess it spawns later (which would silently turn its
    accelerator benchmarks into CPU runs).
    """
    import os

    saved = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    os.environ.update(virtual_cpu_env(n_devices))

    import jax

    jax.config.update("jax_platforms", "cpu")

    # Whether backends were already up or init just now under the pin,
    # the postcondition is the same: enough CPU devices in this process.
    devs = jax.devices()
    ok = devs[0].platform == "cpu" and len(devs) >= n_devices
    if not ok:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return ok


def virtual_cpu_env(n_devices: int, base=None) -> dict:
    """The env-var pins for an ``n_devices`` virtual-CPU JAX process.

    Returns only the two keys to overlay (``JAX_PLATFORMS``,
    ``XLA_FLAGS``), preserving unrelated flags in the base ``XLA_FLAGS``
    and upgrading an existing smaller device count.  ``base`` defaults to
    ``os.environ``.
    """
    import os
    import re

    if base is None:
        base = os.environ
    flags = base.get("XLA_FLAGS", "")
    match = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if match is None:
        flags = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    elif int(match.group(1)) < n_devices:
        flags = flags.replace(
            match.group(0), f"--xla_force_host_platform_device_count={n_devices}"
        )
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags}


_live_backend_checked = False


def ensure_live_backend() -> None:
    """Refuse to hang this process on a wedged accelerator tunnel.

    The first device touch blocks inside a PJRT client init that no
    signal handler can interrupt if the remote backend is unresponsive —
    ``jax.devices()`` itself hangs.  Probe liveness in a disposable child
    process first; on a stalled or failing probe, config-pin the CPU
    backend (the kernels are bit-compatible there) and warn, so every
    device entry point degrades instead of wedging.  Checked once per
    process; skipped when the PRIMARY platform is already explicitly cpu
    (tests, ``pin_virtual_cpu_mesh`` runs — nothing remote to probe).

    Call this before the FIRST device touch of any user-facing device
    path: the policy ``bind`` (``sched.tpu``), the ensemble/calibrate/
    autotune/capacity/apps CLI preambles.  (Round-1 carried the guard on
    the policy path only; a wedged tunnel could still hang the estimator
    CLI flows un-interruptibly.)
    """
    global _live_backend_checked
    if _live_backend_checked:
        return
    _live_backend_checked = True
    import jax

    # Skip only when the PRIMARY platform is cpu: the deployment default
    # is a list like "axon,cpu", where the accelerator still initializes
    # first — "cpu" merely appearing in the list must not skip the probe.
    pinned = jax.config.jax_platforms
    if pinned and str(pinned).split(",")[0] == "cpu":
        return
    if not probe_backend_alive():
        get_logger("pivot_tpu").warning(
            "accelerator backend unresponsive — device programs fall back "
            "to the CPU backend for this process"
        )
        jax.config.update("jax_platforms", "cpu")


def probe_backend_alive(timeout: float = 150.0) -> bool:
    """True iff ``import jax; jax.devices()`` completes in a child process.

    The first device touch blocks inside a PJRT client init that no signal
    handler can interrupt when a remote accelerator backend is
    unresponsive, so liveness must be probed in a disposable child
    (killable regardless of where it blocks).  Any probe failure —
    timeout, spawn error, nonzero exit — reads as "not alive"; the caller
    decides the fallback.  Shared by ``bench.py`` and the device policy
    backend (``pivot_tpu.sched.tpu``).
    """
    import subprocess

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            timeout=timeout,
            capture_output=True,
            text=True,
        )
        return probe.returncode == 0 and "ok" in probe.stdout
    except Exception:
        return False
