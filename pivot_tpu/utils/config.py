"""Typed configuration tree for experiments.

The reference scatters configuration across argparse defaults, env vars,
``**kwargs`` popped in scheduler constructors, and class constants
(SURVEY.md §5 "Config / flag system"); here one dataclass tree describes a
whole experiment and every component is constructed from it.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

__all__ = ["HostShape", "ClusterConfig", "PolicyConfig", "ExperimentConfig", "make_policy"]


@dataclasses.dataclass(frozen=True)
class HostShape:
    cpus: int = 16
    mem: int = 128 * 1024  # MB
    disk: int = 100  # GB
    gpus: int = 1


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    n_hosts: int = 100
    shape: HostShape = HostShape()
    uniform: bool = True
    seed: Optional[int] = 0
    #: 'python' serves network chunks on the event kernel; 'native' runs the
    #: chunk-service loop in the C++ co-simulator (pivot_tpu.native).
    network: str = "python"
    #: 'fast' drives executions with bare callbacks; 'process' mirrors the
    #: reference's one-process-per-execution shape.  Bit-identical runs.
    executor: str = "fast"


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    """Which placement policy, on which backend.

    ``device``: 'naive' (reference-faithful Python), 'numpy' (vectorized
    CPU), or 'tpu' (fused device kernels).
    """

    name: str = "cost-aware"  # opportunistic | first-fit | best-fit | cost-aware
    device: str = "numpy"
    decreasing: bool = False  # first/best-fit
    bin_pack: str = "first-fit"  # cost-aware
    sort_tasks: bool = False
    sort_hosts: bool = False
    realtime_bw: bool = False
    host_decay: bool = False
    #: tpu backend only: route each tick to the device or the in-process
    #: numpy twin, whichever an online latency model predicts faster
    #: (small ticks cannot amortize the fixed per-call device latency).
    adaptive: bool = True
    label: Optional[str] = None

    @property
    def display_label(self) -> str:
        return self.label or f"{self.name}-{self.device}"


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    cluster: ClusterConfig = ClusterConfig()
    policies: Tuple[PolicyConfig, ...] = ()
    trace_files: Tuple[str, ...] = ()
    n_apps: Optional[int] = 100
    output_size_scale_factor: float = 1000.0
    interval: float = 5.0
    seed: Optional[int] = 0
    data_dir: Optional[str] = None


def build_cluster(cfg: ClusterConfig, meta=None):
    """Construct the cluster described by ``cfg`` (deterministic per seed)."""
    from pivot_tpu.des import Environment
    from pivot_tpu.infra.gen import RandomClusterGenerator
    from pivot_tpu.infra.locality import ResourceMetadata

    meta = meta if meta is not None else ResourceMetadata(seed=cfg.seed)
    s = cfg.shape
    gen = RandomClusterGenerator(
        Environment(),
        (s.cpus, s.cpus),
        (s.mem, s.mem),
        (s.disk, s.disk),
        (s.gpus, s.gpus),
        meta=meta,
        seed=cfg.seed,
        network_backend=cfg.network,
        executor_backend=cfg.executor,
    )
    return gen.generate(cfg.n_hosts, uniform=cfg.uniform)


#: The reference's three experiment arms with their exact hyperparameters
#: (``alibaba/sim.py:179-186``), on a chosen device backend.
def reference_policy_set(
    device: str = "numpy", adaptive: bool = True
) -> Tuple[PolicyConfig, ...]:
    return (
        PolicyConfig(
            name="opportunistic", device=device, adaptive=adaptive,
            label="Opportunistic",
        ),
        PolicyConfig(
            name="first-fit", device=device, decreasing=True, adaptive=adaptive,
            label="VBP",
        ),
        PolicyConfig(
            name="cost-aware",
            device=device,
            bin_pack="first-fit",
            sort_tasks=True,
            sort_hosts=True,
            adaptive=adaptive,
            label="Cost-Aware",
        ),
    )


def make_policy(cfg: PolicyConfig):
    """Instantiate the policy object described by ``cfg``."""
    if cfg.device == "tpu":
        from pivot_tpu.sched import tpu as dev

        if cfg.name == "opportunistic":
            return dev.TpuOpportunisticPolicy(adaptive=cfg.adaptive)
        if cfg.name == "first-fit":
            return dev.TpuFirstFitPolicy(
                decreasing=cfg.decreasing, adaptive=cfg.adaptive
            )
        if cfg.name == "best-fit":
            return dev.TpuBestFitPolicy(
                decreasing=cfg.decreasing, adaptive=cfg.adaptive
            )
        if cfg.name == "cost-aware":
            return dev.TpuCostAwarePolicy(
                bin_pack=cfg.bin_pack,
                sort_tasks=cfg.sort_tasks,
                sort_hosts=cfg.sort_hosts,
                host_decay=cfg.host_decay,
                realtime_bw=cfg.realtime_bw,
                adaptive=cfg.adaptive,
            )
        raise ValueError(f"unknown policy {cfg.name!r}")

    from pivot_tpu.sched import policies as cpu

    mode = cfg.device
    if mode not in ("naive", "numpy"):
        raise ValueError(f"unknown device {cfg.device!r}")
    if cfg.name == "opportunistic":
        return cpu.OpportunisticPolicy(mode)
    if cfg.name == "first-fit":
        return cpu.FirstFitPolicy(decreasing=cfg.decreasing, mode=mode)
    if cfg.name == "best-fit":
        return cpu.BestFitPolicy(decreasing=cfg.decreasing, mode=mode)
    if cfg.name == "cost-aware":
        return cpu.CostAwarePolicy(
            bin_pack=cfg.bin_pack,
            sort_tasks=cfg.sort_tasks,
            sort_hosts=cfg.sort_hosts,
            realtime_bw=cfg.realtime_bw,
            host_decay=cfg.host_decay,
            mode=mode,
        )
    raise ValueError(f"unknown policy {cfg.name!r}")
