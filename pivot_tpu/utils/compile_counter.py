"""Compile-counter hook: observe recompiles instead of assuming none.

The retrace pass (``pivot_tpu/analysis/retrace.py``) bans the *static*
shapes of recompilation hazards; this module supplies the falsifying
runtime observable — chaos-engineering style, the steady-state
hypothesis "zero recompiles after warmup" is *measured*, not assumed.

Implementation: ``jax.monitoring`` duration events.  Every XLA backend
compile fires ``/jax/core/compile/backend_compile_duration`` and every
fresh trace fires ``/jax/core/compile/jaxpr_trace_duration``; a cache
hit (the steady state) fires neither.  JAX offers listener registration
but no deregistration, so ONE process-wide listener is installed
lazily and fans out to the currently-active counters.

Usage::

    with count_compiles() as counter:
        serve_many_ticks()
    assert counter.compiles == 0 and counter.traces == 0

Tracking both numbers matters: a persistent-compilation-cache hit
skips the backend compile but still pays the trace — and per-call
tracing is exactly the dispatch-floor regression the fused paths
exist to avoid.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, List

__all__ = ["CompileCounter", "count_compiles"]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_lock = threading.Lock()
_active: List["CompileCounter"] = []
_installed = False


class CompileCounter:
    """Counts of XLA backend compiles and jaxpr traces in a window."""

    def __init__(self) -> None:
        self.compiles = 0
        self.traces = 0

    def _record(self, event: str) -> None:
        if event == _COMPILE_EVENT:
            self.compiles += 1
        elif event == _TRACE_EVENT:
            self.traces += 1


def _install_listener() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax

        def _on_event(event: str, duration_secs: float, **kw) -> None:
            with _lock:
                for counter in _active:
                    counter._record(event)

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _installed = True


@contextlib.contextmanager
def count_compiles() -> Iterator[CompileCounter]:
    """Count XLA compiles/traces while the block runs.  Nestable; each
    context gets its own counter."""
    _install_listener()
    counter = CompileCounter()
    with _lock:
        _active.append(counter)
    try:
        yield counter
    finally:
        with _lock:
            _active.remove(counter)
