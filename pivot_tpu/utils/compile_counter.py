"""Compile-counter hook: observe recompiles instead of assuming none.

The retrace pass (``pivot_tpu/analysis/retrace.py``) bans the *static*
shapes of recompilation hazards; this module supplies the falsifying
runtime observable — chaos-engineering style, the steady-state
hypothesis "zero recompiles after warmup" is *measured*, not assumed.

Implementation: ``jax.monitoring`` duration events.  Every XLA backend
compile fires ``/jax/core/compile/backend_compile_duration`` and every
fresh trace fires ``/jax/core/compile/jaxpr_trace_duration``; a cache
hit (the steady state) fires neither.  JAX offers listener registration
but no deregistration, so ONE process-wide listener is installed
lazily and fans out to the currently-active counters.

Usage::

    with count_compiles() as counter:
        serve_many_ticks()
    assert counter.compiles == 0 and counter.traces == 0

Tracking both numbers matters: a persistent-compilation-cache hit
skips the backend compile but still pays the trace — and per-call
tracing is exactly the dispatch-floor regression the fused paths
exist to avoid.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Iterator, List

__all__ = [
    "CompileCounter",
    "add_observer",
    "count_compiles",
    "remove_observer",
]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_KIND_OF = {
    _COMPILE_EVENT: "backend_compile",
    _TRACE_EVENT: "jaxpr_trace",
}

_lock = threading.Lock()
_active: List["CompileCounter"] = []
#: Observer fan-out (round 14, ``pivot_tpu.obs``): callables invoked
#: with the event *kind* ("backend_compile" / "jaxpr_trace") on every
#: compile event — how a recompile becomes a registry counter bump and
#: a visible instant on the trace timeline instead of only a test
#: assertion.  The JAX listener is process-permanent; this list is not
#: (``remove_observer``).  Observers run under the module lock — keep
#: them O(1) and non-reentrant (no jax calls).
_observers: List[Callable[[str], None]] = []
_installed = False


class CompileCounter:
    """Counts of XLA backend compiles and jaxpr traces in a window."""

    def __init__(self) -> None:
        self.compiles = 0
        self.traces = 0

    def _record(self, event: str) -> None:
        if event == _COMPILE_EVENT:
            self.compiles += 1
        elif event == _TRACE_EVENT:
            self.traces += 1


def _install_listener() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax

        def _on_event(event: str, duration_secs: float, **kw) -> None:
            kind = _KIND_OF.get(event)
            with _lock:
                for counter in _active:
                    counter._record(event)
                if kind is not None:
                    for fn in _observers:
                        fn(kind)

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _installed = True


def add_observer(fn: Callable[[str], None]) -> None:
    """Register a compile-event observer (called with the event kind,
    under the module lock).  Installs the process-wide JAX listener on
    first use; pair with :func:`remove_observer`."""
    _install_listener()
    with _lock:
        _observers.append(fn)


def remove_observer(fn: Callable[[str], None]) -> None:
    with _lock:
        try:
            _observers.remove(fn)
        except ValueError:
            pass


@contextlib.contextmanager
def count_compiles() -> Iterator[CompileCounter]:
    """Count XLA compiles/traces while the block runs.  Nestable; each
    context gets its own counter."""
    _install_listener()
    counter = CompileCounter()
    with _lock:
        _active.append(counter)
    try:
        yield counter
    finally:
        with _lock:
            _active.remove(counter)
