"""Amortized host-side snapshots of the resident serve carry.

PR 18 made the serving hot path device-persistent: the span carry
(``ops/tickloop.py`` — [H, 4] availability, [H] decay counts, [H] live
mask) is donated forward from span to span and never re-staged from
host, which means there is deliberately NO host copy to fall back on
after a crash.  This module restores one — off the hot path:

  * every N spans (``RecoveryConfig.snapshot_every``) the recovery
    plane clones the pending carry (``resident_carry_clone`` — a cheap
    device-side copy on the span boundary, the same safe window the
    mirror-diff already reads in) and *submits* the clone here;
  * a background worker thread performs the D2H fetch, fingerprints the
    arrays with the same versioned-config + shape + ``tobytes`` sha256
    scheme ``parallel/ensemble/checkpoint.py`` uses, and writes a
    double-buffered ``.npz`` (tmp + ``os.replace``, alternating between
    two slots) — the dispatch loop never blocks on snapshot I/O, and a
    crash mid-write leaves the other slot's last good snapshot intact;
  * the submission queue holds ONE pending snapshot: if the worker is
    still writing when the next cadence fires, the older pending clone
    is dropped (latest-wins) — snapshots are a recovery floor, not a
    log, so falling behind degrades recovery-point age, never
    throughput.

Donation safety: the worker only ever touches CLONES.  The pending
carry itself is donated to the next dispatch and must never be read
after that — the ``analysis/donation.py`` host-read-after-donate check
(extended in this round) is the lint that keeps this path honest.

No jax import at module scope: ``np.asarray`` performs the D2H on
whatever array type is submitted, so pure-numpy serving can import the
recovery plane freely.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time
from typing import Any, Dict, Mapping, Optional, Tuple

import numpy as np

__all__ = ["SnapshotStore", "fingerprint_arrays"]

_STOP = object()


def fingerprint_arrays(arrays: Mapping[str, np.ndarray],
                       meta: Mapping[str, Any]) -> str:
    """Content fingerprint of one snapshot (checkpoint.py scheme).

    sha256 over the repr of a versioned config tuple — the format
    version, the sorted array names, and the canonical meta — then each
    array's name, shape, dtype, and raw bytes; truncated to 16 hex
    chars.  Two snapshots of bit-identical state fingerprint
    identically (what the kill-and-resume referee compares), and any
    drift in layout or content changes the digest.
    """
    h = hashlib.sha256()
    cfg = (
        "v1",
        tuple(sorted(arrays)),
        json.dumps(dict(meta), sort_keys=True, separators=(",", ":")),
    )
    h.update(repr(cfg).encode())
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


class SnapshotStore:
    """Double-buffered, fingerprinted, background-written snapshots."""

    def __init__(self, directory: str, seed: int = 0):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.seed = int(seed)
        self.paths = (
            os.path.join(directory, "carry-a.npz"),
            os.path.join(directory, "carry-b.npz"),
        )
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.written = 0
        self.dropped = 0  # latest-wins replacements of a pending clone
        self.errors = 0
        self.last_fingerprint: Optional[str] = None
        self.last_meta: Optional[dict] = None
        self._last_wall: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._worker, name="recover-snapshot", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        """Drain the pending snapshot (if any) and join the worker."""
        if self._thread is None:
            return
        self._q.put(_STOP)
        self._thread.join()
        self._thread = None

    # -- hot-path side -----------------------------------------------------
    def submit(self, payload: Mapping[str, Any], meta: Dict[str, Any]
               ) -> bool:
        """Enqueue one snapshot without ever blocking the caller.

        ``payload`` maps array names to device (or host) arrays — for
        the resident path, a *clone* of the pending carry plus any
        host-side rows (risk table).  Returns False when an older
        pending snapshot was displaced (latest-wins).
        """
        item = (dict(payload), dict(meta))
        while True:
            try:
                self._q.put_nowait(item)
                return True
            except queue.Full:
                try:
                    stale = self._q.get_nowait()
                except queue.Empty:
                    continue  # worker grabbed it first — retry the put
                if stale is _STOP:
                    # Never displace shutdown: re-queue it after us is
                    # wrong (we are stopping) — drop the new snapshot.
                    self._q.put(stale)
                    return False
                self.dropped += 1

    # -- worker side -------------------------------------------------------
    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            payload, meta = item
            try:
                self._write(payload, meta)
            except Exception:  # noqa: BLE001 — snapshot loss ≠ crash
                # A failed snapshot degrades the recovery point; it must
                # never take the serving loop down with it.
                with self._lock:
                    self.errors += 1

    def _write(self, payload: Mapping[str, Any],
               meta: Dict[str, Any]) -> None:
        # The D2H fetch happens HERE, on the worker, overlapped with the
        # next dispatch — np.asarray on a jax array device_get's it.
        arrays = {k: np.asarray(v) for k, v in payload.items()}
        fp = fingerprint_arrays(arrays, meta)
        record = dict(meta)
        record["fingerprint"] = fp
        record["snapshot_seq"] = self.written
        path = self.paths[self.written % 2]
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(
                f, __meta__=np.array(
                    json.dumps(record, sort_keys=True)
                ),
                **arrays,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers see old-or-new, never torn
        with self._lock:
            self.written += 1
            self.last_fingerprint = fp
            self.last_meta = record
            self._last_wall = time.monotonic()

    # -- read side ---------------------------------------------------------
    def latest(self) -> Optional[Tuple[Dict[str, np.ndarray], dict]]:
        """Newest VALID snapshot across both buffers, or None.

        Each candidate is re-fingerprinted on load; a corrupt or torn
        buffer is skipped (the double-buffer's whole point), and
        ``allow_pickle=False`` keeps the loader content-only.
        """
        best: Optional[Tuple[Dict[str, np.ndarray], dict]] = None
        for path in self.paths:
            if not os.path.exists(path):
                continue
            try:
                with np.load(path, allow_pickle=False) as z:
                    meta = json.loads(str(z["__meta__"]))
                    arrays = {
                        k: z[k] for k in z.files if k != "__meta__"
                    }
            except (OSError, ValueError, KeyError,
                    json.JSONDecodeError):
                continue
            want = meta.pop("fingerprint", None)
            seq = meta.get("snapshot_seq", -1)
            # The fingerprint was computed over the SUBMIT-side meta (no
            # fingerprint/snapshot_seq keys) — rebuild that view.
            submit_meta = {
                k: v for k, v in meta.items() if k != "snapshot_seq"
            }
            if fingerprint_arrays(arrays, submit_meta) != want:
                continue
            meta["fingerprint"] = want
            if best is None or seq > best[1].get("snapshot_seq", -1):
                best = (arrays, meta)
        return best

    @property
    def age_s(self) -> Optional[float]:
        """Wall seconds since the last completed snapshot (the
        ``pivot_recover_snapshot_age_s`` gauge); None before the
        first."""
        with self._lock:
            if self._last_wall is None:
                return None
            return time.monotonic() - self._last_wall

    def summary(self) -> dict:
        with self._lock:
            return {
                "written": self.written,
                "dropped": self.dropped,
                "errors": self.errors,
                "last_fingerprint": self.last_fingerprint,
                "last_meta": dict(self.last_meta or {}),
            }
