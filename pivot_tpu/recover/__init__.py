"""Crash-safe serving: the recovery plane (``pivot_tpu.recover``).

PR 18's resident-carry serving made the hot path device-persistent —
and thereby crash-naked: span state lives in donated device buffers
with deliberately no host copy, so a process kill, a hung dispatch, or
one non-finite row loses the pool's state outright.  This package is
the opt-in recovery plane ``ServeDriver(recovery=RecoveryConfig(...))``
wires around that stack, three mechanisms plus a referee:

  * :mod:`~pivot_tpu.recover.journal` — a write-ahead journal: every
    admission, flush, span splice, and MPC actuation appends a compact
    seeded record *before* it takes effect (fsync-batched; journal +
    world seeds replay the service deterministically).
  * :mod:`~pivot_tpu.recover.snapshot` — amortized resident-carry
    snapshots: every N spans the pending device carry is cloned on the
    span boundary and written host-side by a background worker
    (double-buffered, checkpoint-fingerprinted, never blocking a
    dispatch).
  * :mod:`~pivot_tpu.recover.watchdog` — a dispatch timeout with
    bounded deterministic-jitter retries behind a concurrent-retry cap,
    plus batch bisection that corners poisoned rows into a per-tenant,
    tier-aware penalty box.
  * the kill-and-resume referee (``tests/test_recovery.py``): a server
    killed mid-soak and resumed from snapshot + journal-tail replay
    must be **bit-identical** per tick to an uninterrupted run — and
    ``recovery=None`` stays bit-identical to the PR-18 stack.

Module-scope imports are jax-free: a pure-numpy serving stack can
construct the whole plane; only the resident snapshot hook touches
device arrays (and only via ``np.asarray`` on clones).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from pivot_tpu.recover.journal import Journal, JournalError
from pivot_tpu.recover.journal import replay_prefix_check
from pivot_tpu.recover.snapshot import SnapshotStore, fingerprint_arrays
from pivot_tpu.recover.watchdog import (
    DispatchFailed,
    DispatchTimeout,
    DispatchWatchdog,
    PenaltyBox,
)
from pivot_tpu.sched.retry import RetryPolicy

__all__ = [
    "DispatchFailed",
    "DispatchTimeout",
    "DispatchWatchdog",
    "Journal",
    "JournalError",
    "PenaltyBox",
    "RecoveryConfig",
    "RecoveryPlane",
    "SnapshotStore",
    "fingerprint_arrays",
    "replay_prefix_check",
]


@dataclass(frozen=True)
class RecoveryConfig:
    """Knobs for one serve recovery plane.

    ``directory`` holds the journal (``journal.jsonl``) and the two
    snapshot buffers.  ``snapshot_every`` is the span cadence (0
    disables snapshots; the default 8 measured ≤5% serve throughput
    overhead — the ``serve_recovery`` bench row's gate).
    ``dispatch_timeout_s=None`` (default) keeps the watchdog's
    thread-per-dispatch machinery off the hot path — journal +
    snapshots only; set it to arm the timeout/retry/bisect guard.
    ``resume=True`` appends to an existing journal and loads the latest
    valid snapshot for fingerprint verification against the replayed
    state (the kill-and-resume referee's restore half).
    """

    directory: str
    snapshot_every: int = 8
    fsync_every: int = 32
    seed: int = 0
    resume: bool = False
    dispatch_timeout_s: Optional[float] = None
    retry: Optional[RetryPolicy] = None
    max_concurrent_retries: int = 2

    def __post_init__(self):
        if not self.directory:
            raise ValueError("RecoveryConfig.directory is required")
        if self.snapshot_every < 0:
            raise ValueError(
                f"snapshot_every must be >= 0, got {self.snapshot_every}"
            )
        if self.fsync_every < 1:
            raise ValueError(
                f"fsync_every must be >= 1, got {self.fsync_every}"
            )
        if (
            self.dispatch_timeout_s is not None
            and self.dispatch_timeout_s <= 0
        ):
            raise ValueError(
                "dispatch_timeout_s must be positive (or None), got "
                f"{self.dispatch_timeout_s}"
            )
        if self.max_concurrent_retries < 1:
            raise ValueError(
                "max_concurrent_retries must be >= 1, got "
                f"{self.max_concurrent_retries}"
            )


class RecoveryPlane:
    """One driver's recovery wiring: journal + snapshots + watchdog.

    Constructed by ``ServeDriver.__init__`` when ``recovery`` is not
    None; the journal opens immediately (admissions must be journalable
    before ``run()``), the snapshot worker starts/stops with the
    service.  All hooks are cheap no-ops along dimensions the config
    leaves off (no snapshots without a resident carry, no watchdog
    threads without a timeout).
    """

    def __init__(self, config: RecoveryConfig, tracer=None):
        if not isinstance(config, RecoveryConfig):
            raise TypeError(
                "ServeDriver(recovery=...) takes a RecoveryConfig, got "
                f"{type(config).__name__}"
            )
        self.config = config
        self.tracer = tracer
        os.makedirs(config.directory, exist_ok=True)
        self.journal = Journal(
            os.path.join(config.directory, "journal.jsonl"),
            seed=config.seed, fsync_every=config.fsync_every,
            resume=config.resume,
        )
        self.snapshots = SnapshotStore(
            config.directory, seed=config.seed,
        )
        self.watchdog = DispatchWatchdog(
            policy=config.retry, timeout_s=config.dispatch_timeout_s,
            max_concurrent_retries=config.max_concurrent_retries,
            seed=config.seed,
        )
        self._lock = threading.Lock()
        self._spans = 0
        self._splices = 0
        #: Resume verification (the referee's restore half): the latest
        #: valid snapshot of the KILLED run, fingerprint-checked against
        #: the replayed carry when the resumed run reaches the same span.
        self.restored = None
        self.resume_verified: Optional[bool] = None
        if config.resume:
            self.restored = self.snapshots.latest()
            if self.restored is not None:
                self.resume_verified = False  # pending until re-reached

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self.config.snapshot_every:
            self.snapshots.start()

    def stop(self) -> None:
        self.snapshots.stop()
        self.journal.close()

    # -- journal hooks (each BEFORE its effect) ----------------------------
    def journal_admit(self, arrival) -> None:
        self.journal.append(
            "admit", ts=arrival.ts,
            tier=int(getattr(arrival, "tier", 0)),
            tenant=getattr(arrival, "tenant", "default"),
            app=arrival.app.id,
        )

    def journal_flush(self, n_groups: int, n_reqs: int) -> None:
        self.journal.append(
            "flush", groups=int(n_groups), reqs=int(n_reqs),
        )

    def journal_span(self, label: str, sim: float, k: int,
                     slots: int) -> None:
        self.journal.append(
            "span", session=label, sim=float(sim), k=int(k),
            slots=int(slots),
        )

    def journal_splice(self, label: str, sim: float, k: int,
                       n_new: int) -> None:
        self.journal.append(
            "splice", session=label, sim=float(sim), k=int(k),
            n_new=int(n_new),
        )

    def journal_mpc(self, action: str, pool: int) -> None:
        self.journal.append("mpc", action=str(action), pool=int(pool))

    # -- snapshot hook (span boundary, post-dispatch) ----------------------
    def note_span(self, policy) -> None:
        """Span-cadence snapshot tap: called AFTER a span dispatch
        returns, i.e. inside the same safe window the resident
        mirror-diff reads in — the pending carry is the previous jit
        OUTPUT, not yet donated to the next dispatch.  The device-side
        clone is the only hot-path cost; D2H + fingerprint + write all
        happen on the snapshot worker."""
        every = self.config.snapshot_every
        with self._lock:
            self._spans += 1
            n = self._spans
        if not every or n % every:
            return
        rs = getattr(policy, "_resident", None)
        if rs is None or rs.carry is None:
            return
        from pivot_tpu.ops.tickloop import resident_carry_clone

        clone = resident_carry_clone(rs.carry)
        payload = {
            "avail": clone.avail, "counts": clone.counts,
            "live": clone.live,
        }
        if rs.risk_table_np is not None:
            payload["risk"] = rs.risk_table_np
        meta = dict(
            span=n, policy_spans=int(rs.spans),
            splices=int(rs.splices), journal_seq=self.journal.appended,
        )
        self._verify_resume(payload, meta)
        self.snapshots.submit(payload, meta)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.mark("recover", "snapshot", span=n)

    def _verify_resume(self, payload, meta) -> None:
        """Referee discipline on resume: when the replayed service
        reaches the span the killed run last snapshotted, the live
        carry must fingerprint bit-identically to the restored
        snapshot — proof the snapshot IS the replayed state (and could
        seed a kernel-level warm resume, ``resident_carry_restore``)."""
        if self.restored is None:
            return
        arrays, rmeta = self.restored
        if meta["span"] != rmeta.get("span"):
            return
        import numpy as np

        live = {k: np.asarray(v) for k, v in payload.items()}
        # Re-fingerprint the LIVE state under the restored snapshot's
        # own submit-side meta: identical digests ⟺ bit-identical
        # arrays under the same config view (belt: the digest; braces:
        # the element-wise compare, which localizes a mismatch).
        submit_meta = {
            k: v for k, v in rmeta.items()
            if k not in ("fingerprint", "snapshot_seq")
        }
        self.resume_verified = bool(
            set(live) == set(arrays)
            and fingerprint_arrays(live, submit_meta)
            == rmeta.get("fingerprint")
            and all(np.array_equal(live[k], arrays[k]) for k in arrays)
        )

    def note_splice(self) -> None:
        with self._lock:
            self._splices += 1

    # -- metrics / reporting -----------------------------------------------
    def publish(self, registry) -> None:
        from pivot_tpu.obs.registry import declare_recovery_metrics

        declare_recovery_metrics(registry)
        age = self.snapshots.age_s
        if age is not None:
            registry.set("pivot_recover_snapshot_age_s", age)
        registry.set("pivot_recover_journal_lag", self.journal.lag)
        registry.set(
            "pivot_recover_retries_total", self.watchdog.retries_total
        )
        counts = self.watchdog.penalty.counts()
        if counts:
            for tenant, n in counts.items():
                registry.set(
                    "pivot_recover_quarantined_rows", n, tenant=tenant
                )
        else:
            registry.set(
                "pivot_recover_quarantined_rows", 0, tenant="default"
            )

    def summary(self) -> dict:
        with self._lock:
            spans, splices = self._spans, self._splices
        return {
            "journal": {
                "path": self.journal.path,
                "records": self.journal.appended,
                "fsyncs": self.journal.fsyncs,
                "lag": self.journal.lag,
            },
            "snapshots": self.snapshots.summary(),
            "watchdog": self.watchdog.summary(),
            "spans_seen": spans,
            "splices_seen": splices,
            "resume": self.config.resume,
            "resume_verified": self.resume_verified,
        }
