"""Write-ahead journal for the serve recovery plane.

Every externally-visible serving decision — an admission, a coalesced
flush, a mid-span splice, an MPC actuation — appends one compact record
HERE, *before* it takes effect.  The service's worlds are deterministic
discrete-event simulations (seeded streams, seeded policies, seeded
chaos), so the journal does not need to capture any world state: the
admission records plus the world seeds are sufficient to replay the
entire service bit-identically (``tests/test_recovery.py`` pins this —
the kill-and-resume referee).  What the journal buys over "just re-run
the generator" is crash truth: after an abrupt stop, the journal tail
says exactly which arrivals the dead server had admitted, in order, so
a resumed server can verify its regenerated stream against what
actually happened instead of trusting that nothing drifted.

Hot-path cost is amortized two ways:

  * records are buffered line-appends (one small ``dict`` → one JSON
    line); ``fsync`` runs every ``fsync_every`` records, not per record
    — the classic group-commit trade (a crash can lose at most the
    un-synced tail, and the referee's replay regenerates exactly that
    tail from the seeds);
  * each record carries a short blake2b tag chained from the journal
    seed, the sequence number, and the canonical payload — torn or
    hand-edited lines fail :func:`Journal.read` loudly instead of
    silently replaying a corrupted history.  A torn FINAL line is the
    expected crash artifact and is tolerated (reported, not raised).

Pure stdlib + no jax import: the journal must be constructible from a
pure-numpy serving stack.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["Journal", "JournalError"]


class JournalError(RuntimeError):
    """A journal failed integrity validation (bad tag, non-monotone
    sequence, unreadable header) — the history cannot be trusted."""


def _canonical(payload: dict) -> str:
    """Stable serialization for tagging: sorted keys, no whitespace."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":"))


def _tag(seed: int, seq: int, kind: str, payload: dict) -> str:
    """Seeded per-record integrity tag (blake2b, 8 hex chars)."""
    digest = hashlib.blake2b(
        f"{seed}:{seq}:{kind}:{_canonical(payload)}".encode(),
        digest_size=4,
    )
    return digest.hexdigest()


class Journal:
    """Append-only, fsync-batched, seed-tagged decision log.

    Thread-safe: the producer (admissions), session threads (spans,
    splices), the batcher coordinator (flushes), and the MPC thread
    (actuations) all append under one lock — appends are a dict build
    plus a buffered write, so the lock is never held across I/O stalls
    longer than an ``fsync`` every ``fsync_every``-th record.
    """

    VERSION = 1

    def __init__(self, path: str, seed: int = 0, fsync_every: int = 32,
                 resume: bool = False):
        if fsync_every < 1:
            raise ValueError(
                f"fsync_every must be >= 1, got {fsync_every}"
            )
        self.path = path
        self.seed = int(seed)
        self.fsync_every = int(fsync_every)
        self._lock = threading.Lock()
        self._pending = 0  # records appended since the last fsync
        self.appended = 0  # records appended by THIS process
        self.fsyncs = 0
        self._seq = 0
        prior: List[dict] = []
        if resume and os.path.exists(path):
            prior, torn = Journal.read(path, seed=self.seed)
            if prior:
                self._seq = prior[-1]["seq"] + 1
            if torn:
                # The crash artifact: amputate the torn final line so
                # the resume header never lands mid-garbage.  Records
                # re-serialize byte-identically (_canonical is how they
                # were written), so the tags stay valid.
                with open(path, "w", encoding="utf-8") as f:
                    for rec in prior:
                        f.write(_canonical(rec) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
        self._f = open(path, "a" if resume else "w", encoding="utf-8")
        # The header is itself a journaled (tagged) record, so read()
        # validates the epoch boundary like any other decision.
        self.append(
            "resume" if prior else "open",
            version=self.VERSION, seed=self.seed,
            prior_records=len(prior),
        )
        self.sync()

    # -- writing -----------------------------------------------------------
    def append(self, kind: str, **fields) -> int:
        """Journal one decision BEFORE it takes effect; returns its seq.

        ``fields`` must be JSON-serializable and deterministic under the
        run's seeds (no wall-clock values — two seeded runs must produce
        byte-identical journals, which is what the replay-determinism
        test compares).
        """
        with self._lock:
            if self._f is None:
                raise JournalError(f"journal {self.path} is closed")
            seq = self._seq
            self._seq += 1
            rec = dict(fields)
            rec["seq"] = seq
            rec["kind"] = kind
            rec["tag"] = _tag(self.seed, seq, kind, fields)
            self._f.write(_canonical(rec) + "\n")
            self.appended += 1
            self._pending += 1
            if self._pending >= self.fsync_every:
                self._sync_locked()
            return seq

    def _sync_locked(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())
        self._pending = 0
        self.fsyncs += 1

    def sync(self) -> None:
        """Force the buffered tail to disk (span boundaries, shutdown)."""
        with self._lock:
            if self._f is not None:
                self._sync_locked()

    @property
    def lag(self) -> int:
        """Records appended but not yet fsynced — what a crash right now
        would lose (the ``pivot_recover_journal_lag`` gauge)."""
        with self._lock:
            return self._pending

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._sync_locked()
                self._f.close()
                self._f = None

    # -- reading -----------------------------------------------------------
    @staticmethod
    def read(path: str, seed: Optional[int] = None
             ) -> Tuple[List[dict], int]:
        """Load and validate a journal; returns ``(records, torn)``.

        ``torn`` counts unparseable trailing bytes (0 or 1 lines): a
        crash mid-append tears at most the final line, which is the one
        corruption read() forgives.  Anything else — a bad tag, a
        sequence gap, garbage in the middle — raises
        :class:`JournalError`.  ``seed`` defaults to the seed declared
        in the header record, so a reader needs only the path.
        """
        with open(path, "r", encoding="utf-8") as f:
            lines = f.read().splitlines()
        records: List[dict] = []
        torn = 0
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    torn = 1  # the expected crash artifact
                    break
                raise JournalError(
                    f"{path}:{i + 1}: unparseable mid-journal line"
                )
            records.append(rec)
        if not records:
            return records, torn
        head = records[0]
        if head.get("kind") not in ("open", "resume"):
            raise JournalError(
                f"{path}: first record is {head.get('kind')!r}, "
                "expected an open/resume header"
            )
        if seed is None:
            seed = int(head.get("seed", 0))
        for i, rec in enumerate(records):
            if rec.get("seq") != i and records[0]["seq"] == 0:
                raise JournalError(
                    f"{path}: sequence gap at record {i} "
                    f"(seq {rec.get('seq')})"
                )
            payload = {
                k: v for k, v in rec.items()
                if k not in ("seq", "kind", "tag")
            }
            want = _tag(seed, rec["seq"], rec["kind"], payload)
            if rec.get("tag") != want:
                raise JournalError(
                    f"{path}: bad tag on record seq={rec['seq']} "
                    f"({rec.get('tag')} != {want}) — corrupted or "
                    "wrong seed"
                )
        return records, torn

    @staticmethod
    def admissions(records: List[dict]) -> List[dict]:
        """The admission sub-history: what a resumed server verifies its
        regenerated arrival stream against (ts/tier/tenant/app in
        admission order)."""
        return [r for r in records if r["kind"] == "admit"]


def replay_prefix_check(records: List[dict], arrivals) -> int:
    """Verify journaled admissions against a regenerated arrival stream.

    ``arrivals`` is the full regenerated stream (same seeds as the
    killed run).  Each journaled admission must match the stream's
    arrival at the same position on (ts, tier, tenant, app id) — the
    deterministic-replay contract.  Returns the number of journaled
    admissions (the crash frontier: everything after it is fresh work),
    or raises :class:`JournalError` on the first divergence.
    """
    admits = Journal.admissions(records)
    for i, rec in enumerate(admits):
        if i >= len(arrivals):
            raise JournalError(
                f"journal has {len(admits)} admissions but the "
                f"regenerated stream only {len(arrivals)} arrivals"
            )
        a = arrivals[i]
        got = dict(
            ts=a.ts, tier=int(getattr(a, "tier", 0)),
            tenant=getattr(a, "tenant", "default"), app=a.app.id,
        )
        want = {k: rec.get(k) for k in got}
        if got != want:
            raise JournalError(
                f"replay divergence at admission {i}: journal {want} "
                f"vs regenerated stream {got} — the world seeds do not "
                "reproduce the killed run"
            )
    return len(admits)
