"""Dispatch watchdog: timeout, bounded seeded retry, bisect, quarantine.

A device dispatch on the serving hot path can fail three ways the DES
fault machinery (``infra/faults.py`` — *simulated* host kills) never
models: it can hang (a wedged runtime), it can raise (a poisoned
program or transient backend error), or it can *succeed with garbage*
(one non-finite row silently corrupting every decision built on it).
This module is the serve recovery plane's answer to all three:

  * :meth:`DispatchWatchdog.guard` runs one dispatch under a wall-clock
    timeout with bounded retries.  Backoff delays come from
    ``sched/retry.py::RetryPolicy.backoff`` — jitter is a pure hash of
    ``(seed, key, attempt)``, so a journaled replay backs off
    identically — and every retry must win a slot from a shared
    :class:`~pivot_tpu.sched.retry.RetryGate` first: total retry
    concurrency is CAPPED, and a dispatch that cannot get a slot sheds
    instead of piling onto a degraded device (the metastable-failure
    guard).
  * :meth:`DispatchWatchdog.run_batch` isolates poison: when a batch
    fails (or validates non-finite) it is bisected — halves, quarters,
    singletons — until the failing rows are cornered; those rows go to
    a per-tenant, tier-aware :class:`PenaltyBox` and the surviving rows
    are re-served, so one poisoned tenant row costs its own slot, never
    the pool's (tier 0 is shed last, mirroring the admission queue's
    priority contract).

Timeout mechanics: the guarded callable runs on a daemon worker
thread; on timeout the watchdog abandons the thread (Python threads
cannot be killed — the same abandonment contract the serve driver's
stall supervisor already documents) and counts/raises.  A truly wedged
dispatch therefore leaks one parked thread, which dies with the
process — the price of keeping the flush loop alive.

No jax at module scope; finiteness validation is the caller's
``finite_of`` callback over whatever result type its dispatch returns.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from pivot_tpu.sched.retry import RetryGate, RetryPolicy

__all__ = [
    "DispatchFailed",
    "DispatchTimeout",
    "DispatchWatchdog",
    "PenaltyBox",
]


class DispatchTimeout(RuntimeError):
    """One guarded dispatch exceeded its wall timeout."""


class DispatchFailed(RuntimeError):
    """A guarded dispatch exhausted its retry budget (or was shed by
    the concurrent-retry cap) — the caller's failure path owns it."""


class PenaltyBox:
    """Per-tenant quarantine for poisoned rows (tier-aware).

    A row lands here when the bisection corners it as non-finite or
    repeatedly failing.  Quarantine is bookkeeping, not enforcement —
    the caller decides what a quarantined row means (drop the request,
    dead-letter the app, bill the tenant); the box supplies the counts
    the ``pivot_recover_quarantined_rows`` gauge publishes and a shed
    order that releases tier 0 LAST (the admission queue's priority
    contract, applied to eviction).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: List[dict] = []

    def add(self, row: Any, tenant: str = "default", tier: int = 0,
            reason: str = "nonfinite") -> None:
        with self._lock:
            self._rows.append(dict(
                row=row, tenant=str(tenant), tier=int(tier),
                reason=str(reason), order=len(self._rows),
            ))

    @property
    def n(self) -> int:
        with self._lock:
            return len(self._rows)

    def counts(self) -> Dict[str, int]:
        """Quarantined rows per tenant (the metrics label set)."""
        out: Dict[str, int] = {}
        with self._lock:
            for rec in self._rows:
                out[rec["tenant"]] = out.get(rec["tenant"], 0) + 1
        return out

    def rows(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._rows]

    def shed_order(self) -> List[dict]:
        """Eviction order under pressure: highest tier (least
        important) first, tier 0 last; FIFO within a tier."""
        with self._lock:
            return sorted(
                (dict(r) for r in self._rows),
                key=lambda r: (-r["tier"], r["order"]),
            )


class DispatchWatchdog:
    """Timeout + bounded deterministic retry + bisection quarantine."""

    def __init__(
        self,
        policy: Optional[RetryPolicy] = None,
        timeout_s: Optional[float] = None,
        max_concurrent_retries: int = 2,
        acquire_timeout_s: float = 0.0,
        seed: int = 0,
        sleep: Callable[[float], None] = None,
    ):
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(
                f"timeout_s must be positive (or None), got {timeout_s}"
            )
        #: base=0.0 keeps retries immediate by default — wall backoff is
        #: an operator knob (RecoveryConfig.retry), not a hidden sleep.
        self.policy = policy or RetryPolicy(seed=seed, base=0.0)
        self.timeout_s = timeout_s
        self.gate = RetryGate(max_concurrent_retries)
        self.acquire_timeout_s = acquire_timeout_s
        self.penalty = PenaltyBox()
        self._sleep = sleep or time.sleep
        self._lock = threading.Lock()
        self.retries_total = 0
        self.timeouts = 0
        self.failures = 0
        self.sheds = 0

    # -- one guarded call --------------------------------------------------
    def _call(self, fn: Callable[[], Any], key: str) -> Any:
        if self.timeout_s is None:
            return fn()
        box: dict = {}
        done = threading.Event()

        def _run():
            try:
                box["out"] = fn()
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                box["exc"] = exc
            finally:
                done.set()

        t = threading.Thread(
            target=_run, name=f"recover-dispatch-{key}", daemon=True,
        )
        t.start()
        if not done.wait(self.timeout_s):
            with self._lock:
                self.timeouts += 1
            raise DispatchTimeout(
                f"dispatch {key!r} exceeded {self.timeout_s}s — worker "
                "thread abandoned"
            )
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    def guard(self, fn: Callable[[], Any], key: str = "dispatch",
              tier: int = 0) -> Any:
        """Run ``fn`` with timeout + capped, seeded-backoff retries.

        The FIRST attempt never consults the gate (normal traffic must
        not contend on the retry cap); every retry holds a gate slot
        for its whole backoff + re-dispatch, which is what makes
        ``gate.peak`` the honest concurrency high-water mark.
        """
        try:
            return self._call(fn, key)
        except BaseException as exc:  # noqa: BLE001 — governed below
            last = exc
        attempt = 1
        while not self.policy.exhausted(attempt, tier):
            if not self.gate.acquire(timeout=self.acquire_timeout_s):
                with self._lock:
                    self.sheds += 1
                raise DispatchFailed(
                    f"dispatch {key!r} shed: concurrent-retry cap "
                    f"{self.gate.max_concurrent} saturated (metastable-"
                    "storm guard)"
                ) from last
            try:
                delay = self.policy.backoff(attempt, key)
                if delay > 0.0:
                    self._sleep(delay)
                with self._lock:
                    self.retries_total += 1
                return self._call(fn, key)
            except BaseException as exc:  # noqa: BLE001 — loop re-judges
                last = exc
                attempt += 1
            finally:
                self.gate.release()
        with self._lock:
            self.failures += 1
        bound = self.policy.max_attempts(tier)
        raise DispatchFailed(
            f"dispatch {key!r} failed after {attempt} attempt(s) "
            f"(tier {tier} bound: {bound})"
        ) from last

    # -- poison isolation --------------------------------------------------
    def run_batch(
        self,
        rows: Sequence[Any],
        run_rows: Callable[[List[int]], Any],
        finite_of: Optional[Callable[[Any, List[int]], Any]] = None,
        key: str = "batch",
        tenant_of: Optional[Callable[[Any], str]] = None,
        tier_of: Optional[Callable[[Any], int]] = None,
    ) -> Dict[int, Any]:
        """Serve ``rows`` through ``run_rows``, cornering poison.

        ``run_rows(idxs)`` dispatches the subset of row indices and
        returns its result; ``finite_of(result, idxs)`` returns a
        per-row validity mask (or a scalar bool for "all good/bad").
        A failing or poisoned subset is bisected down to singletons;
        cornered rows are quarantined (per-tenant, tier-aware — tier 0
        gets its full per-tier retry budget before quarantine) and the
        healthy survivors re-served.  Returns ``{row index: subset
        result}`` for every healthy subset served — poisoned rows are
        absent, present in :attr:`penalty` instead.
        """
        results: Dict[int, Any] = {}
        self._bisect(
            list(range(len(rows))), rows, run_rows, finite_of, key,
            tenant_of or (lambda r: getattr(r, "tenant", "default")),
            tier_of or (lambda r: int(getattr(r, "tier", 0))),
            results,
        )
        return results

    def _bisect(self, idxs, rows, run_rows, finite_of, key,
                tenant_of, tier_of, results) -> None:
        if not idxs:
            return
        tier = min(tier_of(rows[i]) for i in idxs)
        sub_key = f"{key}[{idxs[0]}:{idxs[-1] + 1}]"
        try:
            out = self.guard(
                lambda: run_rows(list(idxs)), key=sub_key, tier=tier,
            )
        except DispatchFailed:
            if len(idxs) == 1:
                i = idxs[0]
                self.penalty.add(
                    i, tenant=tenant_of(rows[i]), tier=tier_of(rows[i]),
                    reason="failing",
                )
                return
            mid = len(idxs) // 2
            self._bisect(idxs[:mid], rows, run_rows, finite_of, key,
                         tenant_of, tier_of, results)
            self._bisect(idxs[mid:], rows, run_rows, finite_of, key,
                         tenant_of, tier_of, results)
            return
        bad = self._bad_mask(out, idxs, finite_of)
        if not bad.any():
            for i in idxs:
                results[i] = out
            return
        if len(idxs) == 1:
            i = idxs[0]
            self.penalty.add(
                i, tenant=tenant_of(rows[i]), tier=tier_of(rows[i]),
                reason="nonfinite",
            )
            return
        # The validity mask names the poison directly: quarantine those
        # rows via singleton re-judgement (their own retry budget — a
        # transient NaN deserves the same patience as a transient
        # failure) and re-serve the clean remainder WITHOUT the poison
        # (a non-finite row can contaminate cross-row reductions, so
        # the mixed result is discarded).
        bad_idxs = [i for i, b in zip(idxs, bad) if b]
        good_idxs = [i for i, b in zip(idxs, bad) if not b]
        for i in bad_idxs:
            self._bisect([i], rows, run_rows, finite_of, key,
                         tenant_of, tier_of, results)
        self._bisect(good_idxs, rows, run_rows, finite_of, key,
                     tenant_of, tier_of, results)

    @staticmethod
    def _bad_mask(out, idxs, finite_of) -> np.ndarray:
        if finite_of is None:
            return np.zeros(len(idxs), dtype=bool)
        verdict = finite_of(out, list(idxs))
        arr = np.asarray(verdict)
        if arr.shape == ():  # scalar: True = all valid
            return np.full(len(idxs), not bool(arr))
        if arr.shape[0] != len(idxs):
            raise ValueError(
                f"finite_of returned {arr.shape[0]} verdicts for "
                f"{len(idxs)} rows"
            )
        return ~arr.astype(bool)

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        with self._lock:
            return {
                "retries_total": self.retries_total,
                "timeouts": self.timeouts,
                "failures": self.failures,
                "sheds": self.sheds + self.gate.shed,
                "retry_concurrency_peak": self.gate.peak,
                "retry_concurrency_cap": self.gate.max_concurrent,
                "quarantined_rows": self.penalty.n,
                "quarantined_by_tenant": self.penalty.counts(),
            }
