"""A minimal, deterministic discrete-event simulation kernel.

This replaces SimPy (which the reference builds on — ``requirements.txt:2``)
with a purpose-built core designed for this framework:

  * **Deterministic total order**: every scheduled event carries a
    ``(time, priority, seq)`` key; ``seq`` is a monotonically increasing
    counter, so simulations are bit-reproducible run-to-run.
  * **Hookable dispatch points**: processes are plain Python generators that
    yield ``Event`` objects; the scheduler tick is just another process, so
    the TPU decision backend can be invoked synchronously at tick boundaries
    without leaving the event loop.
  * **Passive services**: components like network routes do not need a
    dedicated generator process each (the reference spawns one SimPy process
    per route — ~16k at 100 hosts, ``resources/network.py:56``); they can
    schedule bare callbacks instead, which is how
    ``pivot_tpu.infra.network.Route`` implements chunked fair sharing.

Public surface: ``Environment``, ``Event``, ``Timeout``, ``Process``,
``Store`` (FIFO queue with blocking get), ``Callback`` (bare passive-service
heap entry), and ``Interrupt``-free cooperative semantics (the reference
never interrupts processes either).
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Generator, Iterable, Optional

__all__ = ["Environment", "Event", "Timeout", "Process", "Store", "Callback", "SimError"]


class SimError(Exception):
    """Raised for invalid kernel usage (double trigger, yield of non-event)."""


#: Priority bands — lower runs first at equal timestamps.  URGENT is used for
#: store hand-offs so a put at time t is visible to a getter woken at t.
URGENT, NORMAL = 0, 1


class Event:
    """A one-shot occurrence; callbacks fire when the event is processed."""

    __slots__ = ("env", "callbacks", "_value", "_staged", "_scheduled", "_ok")

    _PENDING = object()

    def __init__(self, env: "Environment"):
        self.env = env
        self.callbacks: Optional[list] = []
        self._value: Any = Event._PENDING
        # Value applied when the event is processed (used by Timeout,
        # which is "triggered" only once it fires).
        self._staged: Any = Event._PENDING
        self._scheduled = False
        self._ok = True

    @property
    def triggered(self) -> bool:
        return self._value is not Event._PENDING

    @property
    def processed(self) -> bool:
        return self.callbacks is None

    @property
    def value(self) -> Any:
        if self._value is Event._PENDING:
            raise SimError("event value not yet available")
        return self._value

    def succeed(self, value: Any = None, priority: int = NORMAL) -> "Event":
        if self.triggered:
            raise SimError("event already triggered")
        self._value = value
        self.env._schedule(self, priority)
        return self

    def fail(self, exc: BaseException, priority: int = NORMAL) -> "Event":
        if self.triggered:
            raise SimError("event already triggered")
        self._ok = False
        self._value = exc
        self.env._schedule(self, priority)
        return self


class Timeout(Event):
    """An event that fires ``delay`` sim-seconds after creation."""

    __slots__ = ()

    def __init__(self, env: "Environment", delay: float, value: Any = None):
        if delay < 0:
            raise SimError(f"negative delay {delay}")
        super().__init__(env)
        self._staged = value
        env._schedule(self, NORMAL, delay)


class Process(Event):
    """Runs a generator; each yielded Event suspends it until that event fires.

    The Process is itself an Event that succeeds with the generator's return
    value, so processes can wait on each other.
    """

    __slots__ = ("_gen",)

    def __init__(self, env: "Environment", gen: Generator):
        super().__init__(env)
        self._gen = gen
        # Bootstrap: start executing at the current time, after already
        # scheduled events at this instant (matches cooperative semantics).
        init = Event(env)
        init.callbacks.append(self._resume)
        init.succeed()

    def _resume(self, trigger: Event) -> None:
        if not trigger._ok:
            try:
                target = self._gen.throw(trigger._value)
            except StopIteration as stop:
                self._conclude(stop.value)
                return
        else:
            try:
                target = self._gen.send(trigger._value if trigger is not None else None)
            except StopIteration as stop:
                self._conclude(stop.value)
                return
        if not isinstance(target, Event):
            raise SimError(f"process yielded non-event: {target!r}")
        if target.callbacks is None:  # already processed -> resume immediately
            immediate = Event(self.env)
            immediate.callbacks.append(self._resume)
            immediate._value = target._value
            immediate._ok = target._ok
            self.env._schedule(immediate, URGENT)
        else:
            target.callbacks.append(self._resume)

    def _conclude(self, value: Any) -> None:
        self._value = value
        self.env._schedule(self, NORMAL)


class Callback:
    """Lightweight heap entry: a bare function fired at its instant.

    The passive-service primitive behind ``schedule_callback`` — no Event
    allocation, no callbacks list, no staged value.  On the hottest paths
    (route chunk service, executor compute timers: hundreds of thousands
    per run) this halves per-event kernel overhead.  Not awaitable: a
    process cannot yield one (``Process._resume`` rejects it), which is
    exactly the contract — passive services never have waiters.

    ``owner`` is an optional tag a scheduler component may attach to
    recognize its own entries during a heap scan (the pure-tick-run
    extractor classifies local-pump callbacks by it, ``scan_window``).
    :meth:`cancel` disarms the entry in place — popping from the middle
    of a heap is O(n), so cancelled entries stay queued and ``step``
    skips them.  The fast-forward sleep uses this to move its wake when
    a submission lands mid-window (``GlobalScheduler
    ._reschedule_ff_wake``).  Note that folded pump deliveries are NOT
    cancelled — a fused span leaves them armed and firing (their epoch
    bumps are expected by the replay), which is what keeps event
    ordering identical to sequential execution.
    """

    __slots__ = ("fn", "owner")

    def __init__(self, fn: Callable[[], None], owner: Any = None):
        self.fn = fn
        self.owner = owner

    def cancel(self) -> None:
        self.fn = None

    @property
    def cancelled(self) -> bool:
        return self.fn is None


class StoreGet(Event):
    __slots__ = ()


class Store:
    """Unbounded FIFO queue with blocking ``get`` and immediate ``put``.

    Mirrors the two-queue plugin boundary of the reference (``dispatch_q`` /
    ``notify_q``, ``resources/__init__.py:40``): puts never block; gets yield
    until an item is available.  Hand-offs are scheduled URGENT so an item
    put at time t is consumed at time t ahead of NORMAL events.
    """

    __slots__ = ("env", "items", "_getters")

    def __init__(self, env: "Environment"):
        self.env = env
        self.items: list = []
        self._getters: list = []

    def __len__(self) -> int:
        return len(self.items)

    def put(self, item: Any) -> None:
        """Enqueue an item; never blocks (the store is unbounded).

        Unlike SimPy there is no put-event to wait on — an unbounded FIFO
        cannot reject a put, so producers just call this and move on.  This
        removes one heap round-trip per message on the hottest queues.
        """
        self.items.append(item)
        self._dispatch()

    def get(self) -> StoreGet:
        evt = StoreGet(self.env)
        self._getters.append(evt)
        self._dispatch()
        return evt

    def drain(self) -> list:
        """Synchronously take every queued item (no events).

        Valid only from the consuming side at a dispatch point; equivalent
        to get-ing ``len(items)`` times in a row at one instant.
        """
        items, self.items = self.items, []
        return items

    def _dispatch(self) -> None:
        while self.items and self._getters:
            getter = self._getters.pop(0)
            getter.succeed(self.items.pop(0), priority=URGENT)


class Environment:
    """The event loop: a heap of ``(time, priority, seq, event)`` entries."""

    def __init__(self, initial_time: float = 0.0):
        self._now = float(initial_time)
        self._heap: list = []
        self._seq = 0
        self._observers: list = []

    @property
    def now(self) -> float:
        return self._now

    # -- scheduling ------------------------------------------------------
    def _schedule(
        self,
        event: Event,
        priority: int = NORMAL,
        delay: float = 0.0,
        at: Optional[float] = None,
    ) -> None:
        if event._scheduled:
            raise SimError("event already scheduled")
        event._scheduled = True
        when = self._now + delay if at is None else at
        heapq.heappush(self._heap, (when, priority, self._seq, event))
        self._seq += 1

    def schedule_callback(
        self, delay: float, fn: Callable[[], None], priority: int = NORMAL
    ) -> Callback:
        """Run ``fn()`` after ``delay`` — the passive-service primitive."""
        if delay < 0:
            raise SimError(f"negative delay {delay}")
        cb = Callback(fn)
        heapq.heappush(self._heap, (self._now + delay, priority, self._seq, cb))
        self._seq += 1
        return cb

    def schedule_callback_at(
        self, at: float, fn: Callable[[], None], priority: int = NORMAL
    ) -> Callback:
        """Run ``fn()`` at absolute sim time ``at`` (must be >= now).

        Unlike ``schedule_callback(at - now, ...)`` this avoids the
        relative-delay round-trip ``fl(now + fl(at - now))``, which can land
        one ulp past ``at`` — co-simulators (``pivot_tpu.native``) need
        their wake to fire at *exactly* the completion instant.
        """
        if at < self._now:
            raise SimError(f"cannot schedule at {at} < now {self._now}")
        cb = Callback(fn)
        heapq.heappush(self._heap, (at, priority, self._seq, cb))
        self._seq += 1
        return cb

    # -- public factory methods -----------------------------------------
    def process(self, gen: Generator) -> Process:
        return Process(self, gen)

    def timeout(self, delay: float, value: Any = None) -> Timeout:
        return Timeout(self, delay, value)

    def event(self) -> Event:
        return Event(self)

    def store(self) -> Store:
        return Store(self)

    def all_of(self, events: Iterable[Event]) -> Event:
        """Barrier: succeeds once every event in ``events`` has fired."""
        events = list(events)
        barrier = Event(self)
        remaining = [len(events)]
        if remaining[0] == 0:
            barrier.succeed()
            return barrier

        def _arm(evt: Event) -> None:
            def _on_fire(_e: Event) -> None:
                remaining[0] -= 1
                if remaining[0] == 0:
                    barrier.succeed([e._value for e in events])

            if evt.callbacks is None:
                remaining[0] -= 1
                if remaining[0] == 0:
                    barrier.succeed([e._value for e in events])
            else:
                evt.callbacks.append(_on_fire)

        for e in events:
            _arm(e)
        return barrier

    def any_of(self, events: Iterable[Event]) -> Event:
        """Race: succeeds when the FIRST of ``events`` fires, with that
        event as its value.  Later finishers are ignored (their own
        callbacks still run).  The primitive behind abortable waits —
        e.g. compute racing a host-failure abort
        (``pivot_tpu.infra.faults``)."""
        events = list(events)
        race = Event(self)
        if not events:
            raise SimError("any_of of no events")

        def _settle(fired: Event) -> None:
            if race.triggered:
                return
            if fired._ok:
                race.succeed(fired)
            else:  # propagate the loser-less failure, don't swallow it
                race.fail(fired._value)

        def _arm(evt: Event) -> None:
            if evt.callbacks is None:  # already processed
                _settle(evt)
            else:
                evt.callbacks.append(_settle)

        for e in events:
            _arm(e)
        return race

    # -- execution -------------------------------------------------------
    def add_step_observer(self, fn: Callable[[], None]) -> None:
        """Register ``fn`` to run after every processed event (the
        hookable-dispatch-point design goal): zero heap traffic, never
        advances sim time, sees state only at event boundaries — which is
        where state can change.  Used by the invariant auditor."""
        self._observers.append(fn)

    def step(self) -> None:
        t, _prio, _seq, event = heapq.heappop(self._heap)
        self._now = t
        if type(event) is Callback:
            if event.fn is not None:  # cancelled entries are inert
                event.fn()
        else:
            if event._value is Event._PENDING:
                event._value = (
                    event._staged if event._staged is not Event._PENDING else None
                )
            callbacks, event.callbacks = event.callbacks, None
            for cb in callbacks:
                cb(event)
        if self._observers:
            for ob in self._observers:
                ob()

    def run(self, until: Optional[float] = None) -> None:
        """Run to event exhaustion, or until sim time reaches ``until``."""
        if until is not None:
            limit = float(until)
            while self._heap and self._heap[0][0] <= limit:
                self.step()
            # Sim time always lands exactly on the limit (SimPy-compatible),
            # regardless of whether later events remain.
            self._now = max(self._now, limit)
        else:
            while self._heap:
                self.step()

    def peek(self) -> float:
        """Timestamp of the next scheduled event, or +inf if none."""
        return self._heap[0][0] if self._heap else float("inf")

    def scan_window(self, exclude=(), allow=None):
        """Classify the pending heap for pure-tick-run extraction.

        Returns ``(t_foreign, allowed)`` where ``t_foreign`` is the
        earliest scheduled instant of any entry that is neither in
        ``exclude`` (identity membership) nor approved by the ``allow``
        predicate, or ``+inf`` when no such entry exists; ``allowed`` is
        every approved entry scheduled STRICTLY before ``t_foreign``, as
        ``(time, priority, seq, event)`` tuples in firing order.  An
        approved entry at or after the first foreign instant is dropped
        from ``allowed`` — its firing order against the foreign event is
        the heap's business, not the caller's.

        Cancelled callbacks are invisible (they fire as no-ops).  One
        O(heap) pass, no mutation — the caller decides what to do with
        the window (``GlobalScheduler`` fuses scheduling ticks across
        it).
        """
        t_foreign = float("inf")
        allowed: list = []
        for t, prio, seq, ev in self._heap:
            if type(ev) is Callback and ev.fn is None:
                continue
            if any(ev is x for x in exclude):
                continue
            if allow is not None and allow(ev):
                allowed.append((t, prio, seq, ev))
                continue
            if t < t_foreign:
                t_foreign = t
        allowed = [e for e in allowed if e[0] < t_foreign]
        allowed.sort(key=lambda e: (e[0], e[1], e[2]))
        return t_foreign, allowed
