"""Random cluster generation.

Capability parity with the reference ``RandomClusterGenerator``
(``resources/gen.py:11-74``): hosts round-robin across the 31 zones, one
storage node per occupied locality, uniform or per-host-sampled shapes drawn
from the same stepped ranges (cpus step 2, mem/disk step 1024, gpus
integer).  Routes are lazy (see ``pivot_tpu.infra.Cluster``) instead of the
reference's eager O(N²) construction.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from pivot_tpu.des import Environment
from pivot_tpu.infra import Cluster, Host, Storage
from pivot_tpu.infra.locality import ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.utils import LogMixin

__all__ = ["RandomClusterGenerator"]


class RandomClusterGenerator(LogMixin):
    def __init__(
        self,
        env: Environment,
        cpus: Tuple[float, float],
        mem: Tuple[float, float],
        disk: Tuple[float, float],
        gpus: Tuple[int, int],
        meta: Optional[ResourceMetadata] = None,
        meter: Optional[Meter] = None,
        seed: Optional[int] = None,
        network_backend: str = "python",
        executor_backend: str = "fast",
    ):
        assert 0 < cpus[0] <= cpus[1]
        assert 0 < mem[0] <= mem[1]
        assert 0 <= disk[0] <= disk[1]
        assert 0 <= gpus[0] <= gpus[1]
        self.env = env
        self.cpus, self.mem, self.disk, self.gpus = cpus, mem, disk, gpus
        self.meta = meta if meta is not None else ResourceMetadata()
        self.meter = meter
        self.network_backend = network_backend
        self.executor_backend = executor_backend
        self.rng = np.random.default_rng(seed)

    def _sample_shape(self) -> Tuple[int, int, int, int]:
        rng = self.rng
        cpus = int(rng.choice(np.arange(self.cpus[0], self.cpus[1] + 2, 2)))
        mem = int(rng.choice(np.arange(self.mem[0], self.mem[1] + 1024, 1024)))
        disk = int(rng.choice(np.arange(self.disk[0], self.disk[1] + 1024, 1024)))
        gpus = int(rng.integers(self.gpus[0], self.gpus[1] + 1))
        return cpus, mem, disk, gpus

    def generate(self, n_hosts: int, uniform: bool = True, seed: Optional[int] = None) -> Cluster:
        assert isinstance(n_hosts, int) and n_hosts > 0
        meta, meter, env = self.meta, self.meter, self.env
        if seed is None:
            # Derive the cluster's executor-RNG seed from the generator's
            # stream so a seeded generator yields a fully seeded cluster.
            seed = int(self.rng.integers(0, 2**31 - 1))
        zones = meta.zones
        if uniform:
            shape = self._sample_shape()
            hosts = [
                Host(env, *shape, locality=zones[i % len(zones)], meter=meter)
                for i in range(n_hosts)
            ]
        else:
            hosts = [
                Host(
                    env,
                    *self._sample_shape(),
                    locality=zones[i % len(zones)],
                    meter=meter,
                )
                for i in range(n_hosts)
            ]
        occupied = []
        seen = set()
        for h in hosts:
            if h.locality not in seen:
                seen.add(h.locality)
                occupied.append(h.locality)
        storage = [Storage(env, locality=l) for l in occupied]
        return Cluster(
            env,
            hosts=hosts,
            storage=storage,
            meta=meta,
            meter=meter,
            route_mode="local",
            seed=seed,
            network_backend=self.network_backend,
            executor_backend=self.executor_backend,
        )
