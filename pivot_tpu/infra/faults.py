"""Fault injection: host crash/recovery and bandwidth fluctuation.

The reference has **no fault model** (SURVEY.md §5): its only "failure" is
admission rejection, its ``NetworkRoute._fluctuate`` is an empty stub
(``resources/network.py:102-103``), and no host or link ever goes down.
It does, however, ship a complete failure-handling path — failed tasks are
reset to NASCENT and resubmitted forever (``scheduler/__init__.py:136-139``).
This module supplies the missing fault *sources* so that path (mirrored by
``GlobalScheduler._listen_loop``) is exercised as elastic recovery:

  * **Host crash** — ``Host.fail()`` aborts every resident task mid-flight
    (staging or compute) via abort events raced inside ``Host.execute``;
    each surfaces as ``(False, task)`` on ``notify_q`` and is rescheduled
    elsewhere by the existing retry loop.  Down hosts report zero
    availability, so no fit mask can select them.  ``Host.recover()``
    returns a fresh machine.
  * **Bandwidth fluctuation** — periodic multiplicative resampling of live
    route bandwidth (the reference's intended-but-unimplemented
    ``_fluctuate``), applied between chunks so in-flight transfers see the
    new rate from their next chunk on.

All draws come from a dedicated seeded RNG, so fault schedules are
deterministic and independent of workload/cluster RNG streams.

**The chaos engine** (round 7) grows the independent-fault injector into
a failure-domain model — the four production fault classes a resilient
scheduler must absorb (Borg / Bamboo / chaos-engineering lineage,
PAPERS.md):

  * **Correlated domain outages** — :meth:`FaultInjector.fail_domain`
    takes down every host sharing a failure domain (a zone, or a whole
    cloud region) in one draw, using the same locality topology the
    placement kernels score with.
  * **Spot preemption with a warning lead** —
    :meth:`FaultInjector.preempt_host`: at the warning instant the host
    starts *draining* (``Host.draining`` — still running and admitting
    its residents, but excluded from NEW placements via the scheduler's
    live mask), and the abort fires only after the lead window, so
    short tasks drain out the way real spot workloads do.
  * **Transient stragglers** — :meth:`FaultInjector.slow_host`: a
    multiplicative compute slowdown for a window; compute *started*
    during the window is stretched, in-flight compute keeps its
    already-scheduled finish time.
  * **Region-pair network partitions** —
    :meth:`FaultInjector.partition_regions`: every route between two
    cloud regions suspends (in-wire chunks finish, queues park, nothing
    is dropped) until the partition heals; lazily materialized routes
    are caught by a cluster route hook.

All of it is drivable from a :class:`ChaosSchedule` — a serializable,
seeded event list that can be saved, replayed, and diffed
(``tools/chaos_replay.py``), which is what makes chaos runs regression-
testable: same schedule ⇒ bit-identical fault log and meter snapshot
(``tests/test_chaos.py``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from pivot_tpu.des import Environment
from pivot_tpu.utils import LogMixin
from pivot_tpu.utils.trace import NULL_TRACER, Tracer

__all__ = [
    "ChaosEvent", "ChaosSchedule", "DeviceFaultPlan", "DeviceLostError",
    "FaultInjector", "check_schema_header", "device_ordinal",
]


class DeviceLostError(RuntimeError):
    """A dispatch targeted a mesh device that is down — raised
    deterministically at the dispatch boundary by the elastic fault gate
    (``serve/elastic.py``) when a :class:`DeviceFaultPlan` window covers
    the dispatch instant, or by real-loss classification.  Carries the
    dead ordinals so the elastic manager can shrink around them.  NOT
    swallowed by the ``degrade_after`` guard: device loss is a
    mesh-level event (shrink + reshard), not kernel flakiness (CPU-twin
    fallback)."""

    def __init__(self, ordinals, at: float):
        self.ordinals = tuple(sorted(int(o) for o in ordinals))
        self.at = float(at)
        super().__init__(
            f"mesh device(s) {list(self.ordinals)} down at t={self.at:g}"
        )


def device_ordinal(target: str) -> int:
    """Parse a ``"device:<ordinal>"`` chaos target into its ordinal.
    Raises ``ValueError`` on anything else — device events address mesh
    device slots (the compute plane), not DES hosts, and a host id
    leaking into a device event must fail at load, not replay."""
    s = str(target)
    if not s.startswith("device:"):
        raise ValueError(
            f"device event target must be 'device:<ordinal>', got {target!r}"
        )
    try:
        ordinal = int(s.split(":", 1)[1])
    except ValueError:
        raise ValueError(
            f"device event target must be 'device:<ordinal>', got {target!r}"
        ) from None
    if ordinal < 0:
        raise ValueError(f"device ordinal must be >= 0, got {ordinal}")
    return ordinal


class FaultInjector(LogMixin):
    """Schedules host crashes, recoveries, and bandwidth fluctuation on a
    cluster's event kernel.

    Create it after the cluster, before ``env.run()``; faults fire at their
    scheduled sim times.  ``tracer`` (optional) records structured
    ``host.failed`` / ``host.recovered`` events.
    """

    def __init__(
        self,
        cluster,
        seed: Optional[int] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.cluster = cluster
        self.env: Environment = cluster.env
        self.rng = np.random.default_rng(seed)
        self.tracer = tracer or NULL_TRACER
        #: (sim_time, host_id, event) log of injected faults.
        self.log: List[Tuple[float, str, str]] = []
        # host_id -> sim time until which the host must stay down.
        # Overlapping outages extend to the union (max end), never truncate.
        self._down_until: dict = {}
        # Active region-pair partitions: frozenset of two (cloud, region)
        # tuples each.  Lazily materialized routes consult this through a
        # cluster route hook (installed on first partition).
        self._partitions: set = set()
        self._partition_hook_installed = False
        # Called with the Host at each spot-preemption WARNING instant
        # (after ``Host.draining`` is set): the proactive-survival hook
        # point — the scheduler registers its drain/migrate handler here
        # (``GlobalScheduler.on_preempt_warning``).  Empty by default, so
        # reactive worlds are untouched.
        self._warning_hooks: List = []
        # Called with (ordinal, kind, sim_now) at every device_fault /
        # device_restore instant — the elastic serving layer registers its
        # shrink/regrow trigger here.  Device events address mesh device
        # slots, not DES hosts, so the injector only logs and relays them.
        self._device_hooks: List = []

    def add_warning_hook(self, hook) -> None:
        """Register ``hook(host, lead)`` to run at every spot-preemption
        warning instant, after the host's drain flag is set (``lead`` is
        the seconds until the abort fires)."""
        self._warning_hooks.append(hook)

    def add_device_hook(self, hook) -> None:
        """Register ``hook(ordinal, kind, now)`` to run at every
        ``device_fault`` / ``device_restore`` instant (``kind`` is the
        event kind string).  The serving stack's elastic manager is the
        intended consumer; the DES-side cluster is untouched."""
        self._device_hooks.append(hook)

    # -- device (compute-plane) faults -------------------------------------
    def _device_event(self, ordinal: int, kind: str, at: float) -> None:
        """Schedule a device-plane event: log + tracer + relay to the
        registered device hooks.  Unlike host faults there is no DES-side
        state to mutate — the dispatch layer consults the
        :class:`DeviceFaultPlan` (and/or these hooks) directly."""

        def _fire():
            label = f"device:{ordinal}"
            self.log.append((self.env.now, label, kind))
            self.tracer.emit("device", kind, self.env.now, id=label)
            self.logger.debug("[%.3f] %s %s", self.env.now, label, kind)
            for hook in self._device_hooks:
                hook(ordinal, kind, self.env.now)

        self.env.schedule_callback_at(at, _fire)

    def fail_device(
        self, ordinal: int, at: float, duration: Optional[float] = None
    ) -> None:
        """Kill mesh device slot ``ordinal`` at sim time ``at``; restore
        it ``duration`` seconds later (never, if ``duration`` is None).
        The DES cluster is untouched — targeted dispatches raise through
        the :class:`DeviceFaultPlan` consulted at the dispatch boundary."""
        if ordinal < 0:
            raise ValueError(f"device ordinal must be >= 0, got {ordinal}")
        if duration is not None and duration <= 0:
            raise ValueError(
                f"device outage duration must be > 0 (or None for "
                f"permanent), got {duration}"
            )
        self._device_event(int(ordinal), "device_fault", at)
        if duration is not None:
            self._device_event(int(ordinal), "device_restore", at + duration)

    # -- host faults -----------------------------------------------------
    def fail_host(self, host_id: str, at: float, duration: Optional[float] = None):
        """Crash ``host_id`` at sim time ``at``; recover it ``duration``
        seconds later (never, if ``duration`` is None)."""
        host = self.cluster.get_host(host_id)
        if host is None:
            raise KeyError(f"unknown host {host_id!r}")
        if duration is not None and duration <= 0:
            raise ValueError(
                f"outage duration must be > 0 (or None for permanent), "
                f"got {duration}"
            )

        recover_at = at + duration if duration is not None else float("inf")

        def _fail():
            self._down_until[host.id] = max(
                self._down_until.get(host.id, 0.0), recover_at
            )
            if not host.up:  # already down: outage extended, no new event
                return
            n_resident = host.n_tasks
            host.fail()
            self.log.append((self.env.now, host.id, "failed"))
            self.tracer.emit(
                "host", "failed", self.env.now, id=host.id, n_aborted=n_resident
            )
            self.logger.debug(
                "[%.3f] host %s failed (%d tasks aborted)",
                self.env.now, host.id, n_resident,
            )

        def _recover():
            # Only the recovery matching the *latest* outage end fires —
            # overlapping outages union (a shorter second outage must not
            # resurrect the host mid-way through a longer first one).
            if self.env.now < self._down_until.get(host.id, 0.0):
                return
            if host.up:
                return
            host.recover()
            self.log.append((self.env.now, host.id, "recovered"))
            self.tracer.emit("host", "recovered", self.env.now, id=host.id)

        self.env.schedule_callback_at(at, _fail)
        if duration is not None:
            self.env.schedule_callback_at(recover_at, _recover)

    def random_host_failures(
        self,
        n_failures: int,
        horizon: float,
        mttr: Optional[float] = None,
        start: float = 0.0,
    ) -> List[Tuple[float, str]]:
        """Schedule ``n_failures`` crashes at uniform times in
        ``[start, horizon)`` on uniformly drawn hosts; each recovers after
        an Exp(mean=``mttr``) outage (never, if ``mttr`` is None).
        Returns the (time, host_id) schedule for assertions/reporting."""
        hosts = self.cluster.hosts
        if not hosts:
            raise ValueError(
                "random_host_failures needs a cluster with at least one "
                "host (rng.integers(0, 0) would otherwise fail opaquely)"
            )
        times = np.sort(self.rng.uniform(start, horizon, size=n_failures))
        picks = self.rng.integers(0, len(hosts), size=n_failures)
        schedule = []
        for t, hi in zip(times, picks):
            duration = (
                float(self.rng.exponential(mttr)) if mttr is not None else None
            )
            self.fail_host(hosts[int(hi)].id, float(t), duration)
            schedule.append((float(t), hosts[int(hi)].id))
        return schedule

    # -- correlated / failure-domain faults -------------------------------
    def _domain_members(self, domain: str) -> List:
        """Hosts inside failure domain ``domain`` — ``"cloud/region/zone"``
        (one zone) or ``"cloud/region"`` (every zone of a region)."""
        parts = str(domain).split("/")
        if len(parts) == 3:
            match = lambda loc: (loc.cloud, loc.region, loc.zone) == tuple(parts)  # noqa: E731
        elif len(parts) == 2:
            match = lambda loc: (loc.cloud, loc.region) == tuple(parts)  # noqa: E731
        else:
            raise ValueError(
                f"failure domain must be 'cloud/region' or "
                f"'cloud/region/zone', got {domain!r}"
            )
        return [h for h in self.cluster.hosts if match(h.locality)]

    def fail_domain(
        self, domain: str, at: float, duration: Optional[float] = None
    ) -> List[str]:
        """Correlated outage: one draw takes down EVERY host in ``domain``
        at sim time ``at`` (all recover together after ``duration``).
        Returns the member host ids.  The log carries a ``domain_outage``
        marker ahead of the per-host ``failed`` events."""
        members = self._domain_members(domain)
        if not members:
            raise ValueError(f"failure domain {domain!r} has no hosts")

        def _mark():
            self.log.append((self.env.now, str(domain), "domain_outage"))
            self.tracer.emit(
                "domain", "outage", self.env.now, id=str(domain),
                n_hosts=len(members),
            )

        self.env.schedule_callback_at(at, _mark)
        for h in members:
            self.fail_host(h.id, at, duration)
        return [h.id for h in members]

    def preempt_host(
        self,
        host_id: str,
        at: float,
        lead: float,
        outage: Optional[float] = None,
    ) -> None:
        """Spot preemption with a warning lead: at ``at`` the host starts
        *draining* (no NEW placements via the scheduler live mask; its
        residents keep running — tasks shorter than the lead drain out),
        and at ``at + lead`` the abort fires (``fail_host`` semantics;
        ``outage`` None = the capacity never comes back)."""
        host = self.cluster.get_host(host_id)
        if host is None:
            raise KeyError(f"unknown host {host_id!r}")
        if lead < 0:
            raise ValueError(f"preemption lead must be >= 0, got {lead}")

        def _warn():
            if not host.up:
                return  # already down: the preemption is moot
            host.draining = True
            self.log.append((self.env.now, host.id, "preempt_warning"))
            self.tracer.emit(
                "host", "preempt_warning", self.env.now, id=host.id,
                lead=lead,
            )
            for hook in self._warning_hooks:
                hook(host, lead)

        self.env.schedule_callback_at(at, _warn)
        self.fail_host(host_id, at + lead, outage)

    def spot_preemptions(
        self,
        n_preemptions: int,
        horizon: float,
        lead: float,
        outage: Optional[float] = None,
        zone_rates: Optional[Dict[str, float]] = None,
        start: float = 0.0,
    ) -> List[Tuple[float, str]]:
        """Schedule ``n_preemptions`` spot preemptions at uniform times in
        ``[start, horizon)``.  Victims are drawn per ``zone_rates`` — a
        ``{"cloud/region/zone": relative rate}`` map (unlisted zones get
        rate 0; ``None`` = uniform over hosts) — so capacity pools with
        hot spot markets are preempted proportionally more often.
        Returns the (warning time, host id) schedule."""
        hosts = self.cluster.hosts
        if not hosts:
            raise ValueError("spot_preemptions needs a non-empty cluster")
        if zone_rates is None:
            weights = np.ones(len(hosts))
        else:
            weights = np.array(
                [zone_rates.get(repr(h.locality), 0.0) for h in hosts],
                dtype=np.float64,
            )
            if weights.sum() <= 0:
                raise ValueError(
                    "zone_rates assigns zero total rate to this cluster's "
                    f"zones (keys must be locality strings like "
                    f"{next(iter(hosts)).locality!r})"
                )
        weights = weights / weights.sum()
        times = np.sort(self.rng.uniform(start, horizon, size=n_preemptions))
        picks = self.rng.choice(len(hosts), size=n_preemptions, p=weights)
        schedule = []
        for t, hi in zip(times, picks):
            self.preempt_host(hosts[int(hi)].id, float(t), lead, outage)
            schedule.append((float(t), hosts[int(hi)].id))
        return schedule

    def slow_host(
        self, host_id: str, at: float, duration: float, factor: float
    ) -> None:
        """Transient straggler: compute STARTED on ``host_id`` during
        ``[at, at + duration)`` is stretched by ``factor``; compute
        already in flight keeps its scheduled finish time (its timer is
        on the heap).  Overlapping windows: last writer wins, and the
        earliest expiry restores full speed — straggle windows are for
        chaos schedules, not precise overlap algebra (documented)."""
        host = self.cluster.get_host(host_id)
        if host is None:
            raise KeyError(f"unknown host {host_id!r}")
        if duration <= 0:
            raise ValueError(f"straggler duration must be > 0, got {duration}")
        if factor <= 1.0:
            raise ValueError(
                f"straggler factor must be > 1 (a slowdown), got {factor}"
            )

        def _start():
            if not host.up:
                return
            host.slowdown = factor
            self.log.append((self.env.now, host.id, "straggler_start"))
            self.tracer.emit(
                "host", "straggler_start", self.env.now, id=host.id,
                factor=factor,
            )

        def _end():
            if host.slowdown == 1.0:
                return  # crashed + recovered mid-window, or already ended
            host.slowdown = 1.0
            self.log.append((self.env.now, host.id, "straggler_end"))
            self.tracer.emit("host", "straggler_end", self.env.now, id=host.id)

        self.env.schedule_callback_at(at, _start)
        self.env.schedule_callback_at(at + duration, _end)

    # -- network partitions ------------------------------------------------
    @staticmethod
    def _region_of(node) -> Tuple[str, str]:
        return (node.locality.cloud, node.locality.region)

    def _route_partitioned(self, route) -> bool:
        key = frozenset((self._region_of(route.src), self._region_of(route.dst)))
        return key in self._partitions

    def partition_regions(
        self, region_a: str, region_b: str, at: float, duration: float
    ) -> None:
        """Partition the network between two cloud regions
        (``"cloud/region"`` strings) for ``[at, at + duration)``: every
        route crossing the pair suspends — in-wire chunks finish, queued
        transfers park, nothing is dropped — and resumes at heal time.
        Routes materialized during the partition are caught by a cluster
        route hook.  Python network backend only (native routes serve
        their queue in the C++ engine)."""
        if self.cluster.network_backend != "python":
            raise ValueError(
                "network partitions require network_backend='python' "
                "(native routes serve their queue in the C++ engine)"
            )
        if duration <= 0:
            raise ValueError(f"partition duration must be > 0, got {duration}")
        regs = []
        for r in (region_a, region_b):
            parts = str(r).split("/")
            if len(parts) != 2:
                raise ValueError(
                    f"partition endpoints are regions ('cloud/region'), got {r!r}"
                )
            regs.append(tuple(parts))
        if regs[0] == regs[1]:
            raise ValueError("a partition needs two distinct regions")
        pair = frozenset(regs)
        label = "|".join(sorted("/".join(r) for r in regs))
        if not self._partition_hook_installed:
            self.cluster.add_route_hook(
                lambda route: route.suspend()
                if self._route_partitioned(route)
                else None
            )
            self._partition_hook_installed = True

        def _cut():
            self._partitions.add(pair)
            for route in self.cluster._routes.values():
                if self._route_partitioned(route):
                    route.suspend()
            self.log.append((self.env.now, label, "partition_start"))
            self.tracer.emit("network", "partition_start", self.env.now, id=label)

        def _heal():
            self._partitions.discard(pair)
            for route in self.cluster._routes.values():
                if route.suspended and not self._route_partitioned(route):
                    route.resume()
            self.log.append((self.env.now, label, "partition_end"))
            self.tracer.emit("network", "partition_end", self.env.now, id=label)

        self.env.schedule_callback_at(at, _cut)
        self.env.schedule_callback_at(at + duration, _heal)

    # -- schedule replay ---------------------------------------------------
    def apply_schedule(self, schedule: "ChaosSchedule") -> "FaultInjector":
        """Install every event of a (possibly deserialized)
        :class:`ChaosSchedule` — the replay entry point: same schedule on
        the same seeded world ⇒ identical fault log and meter snapshot."""
        for ev in schedule.events:
            if ev.kind == "host_outage":
                self.fail_host(ev.target, ev.at, ev.duration)
            elif ev.kind == "domain_outage":
                self.fail_domain(ev.target, ev.at, ev.duration)
            elif ev.kind == "preemption":
                self.preempt_host(ev.target, ev.at, ev.lead, ev.duration)
            elif ev.kind == "straggler":
                self.slow_host(ev.target, ev.at, ev.duration, ev.factor)
            elif ev.kind == "partition":
                a, b = ev.target.split("|")
                self.partition_regions(a, b, ev.at, ev.duration)
            elif ev.kind == "device_fault":
                self.fail_device(device_ordinal(ev.target), ev.at, ev.duration)
            elif ev.kind == "device_restore":
                self._device_event(
                    device_ordinal(ev.target), "device_restore", ev.at
                )
            else:
                raise ValueError(f"unknown chaos event kind {ev.kind!r}")
        return self

    # -- network faults --------------------------------------------------
    def fluctuate_bandwidth(
        self,
        period: float,
        amplitude: float = 0.05,
        until: Optional[float] = None,
    ) -> None:
        """Every ``period`` sim-seconds, resample every *materialized*
        route's bandwidth as ``base × U(1−amplitude, 1+amplitude)``
        (the reference's empty ``_fluctuate`` stub, made real).

        Python network backend only: native routes pin their rate in the
        C++ engine at creation.
        """
        if self.cluster.network_backend != "python":
            raise ValueError(
                "bandwidth fluctuation requires network_backend='python' "
                "(native routes pin their rate in the C++ engine)"
            )
        if period <= 0:
            raise ValueError(f"period must be > 0, got {period}")
        if not 0.0 <= amplitude < 1.0:
            raise ValueError(
                f"amplitude must be in [0, 1), got {amplitude} "
                "(>= 1 could resample a route to non-positive bandwidth)"
            )
        base: dict = {}

        def _tick():
            # The window is half-open [start, until): a tick landing ON the
            # horizon must not resample (it could race the restore below).
            if until is not None and self.env.now >= until:
                return
            for key, route in self.cluster._routes.items():
                b = base.setdefault(key, route.bw)
                route.bw = b * float(
                    self.rng.uniform(1.0 - amplitude, 1.0 + amplitude)
                )
            if until is None or self.env.now + period <= until:
                self.env.schedule_callback(period, _tick)

        def _restore():
            # Bound the perturbation to the configured window: without the
            # restore, the final random draw would persist as a permanent
            # bias for the rest of the simulation.
            for key, b in base.items():
                self.cluster._routes[key].bw = b

        if until is None or period <= until:
            self.env.schedule_callback(period, _tick)
            if until is not None:
                self.env.schedule_callback_at(until, _restore)


# ---------------------------------------------------------------------------
# ChaosSchedule — the serializable, replayable fault plan
# ---------------------------------------------------------------------------


def check_schema_header(d: dict, schema: str, version: int, kind: str):
    """Validate the self-describing ``schema``/``schema_version`` header
    shared by :class:`ChaosSchedule` and ``MarketSchedule`` files — one
    implementation so the two loaders cannot drift.  Files without a
    ``schema`` field (pre-round-11) are accepted; ``version`` is the
    legacy fallback key."""
    got = d.get("schema")
    if got is not None and got != schema:
        raise ValueError(
            f"not a {kind} file: schema {got!r} (expected {schema!r})"
        )
    got_v = d.get("schema_version", d.get("version", 1))
    if got_v != version:
        raise ValueError(f"unsupported {kind} schema_version {got_v!r}")


@dataclass(frozen=True)
class ChaosEvent:
    """One fault in a :class:`ChaosSchedule`.

    ``kind`` selects the injector primitive; ``target`` is a host id, a
    failure-domain string (``"cloud/region"`` / ``"cloud/region/zone"``),
    or a sorted ``"regionA|regionB"`` pair for partitions.  ``duration``
    doubles as the preemption outage length (None = permanent) and is
    required for stragglers and partitions; ``lead`` / ``factor`` are the
    preemption warning lead and straggler slowdown."""

    kind: str  # host_outage | domain_outage | preemption | straggler | partition | device_fault | device_restore
    at: float
    target: str
    duration: Optional[float] = None
    lead: float = 0.0
    factor: float = 1.0

    KINDS = (
        "host_outage", "domain_outage", "preemption", "straggler",
        "partition", "device_fault", "device_restore",
    )
    #: Kinds addressing mesh device slots (the compute plane) rather than
    #: DES hosts — consumed by :class:`DeviceFaultPlan`, ignored by the
    #: DES-side injector primitives.
    DEVICE_KINDS = ("device_fault", "device_restore")

    def __post_init__(self):
        if self.kind not in self.KINDS:
            raise ValueError(f"unknown chaos event kind {self.kind!r}")
        if self.at < 0:
            raise ValueError(f"event time must be >= 0, got {self.at}")
        # Fail at construction/deserialization, not deep inside
        # apply_schedule: stragglers and partitions are windowed faults —
        # duration=None has no meaning for them (unlike outages and
        # preemptions, where None = the capacity never comes back).
        if self.kind in ("straggler", "partition") and (
            self.duration is None or self.duration <= 0
        ):
            raise ValueError(
                f"{self.kind} events require a positive duration, "
                f"got {self.duration!r}"
            )
        if self.kind in self.DEVICE_KINDS:
            device_ordinal(self.target)  # 'device:<ordinal>' or ValueError
            if self.kind == "device_restore" and self.duration is not None:
                raise ValueError(
                    "device_restore is instantaneous (a fail window ends "
                    f"at its restore's time), got duration={self.duration!r}"
                )
            if self.kind == "device_fault" and (
                self.duration is not None and self.duration <= 0
            ):
                raise ValueError(
                    "device_fault duration must be > 0 (or None, ended by "
                    f"an explicit device_restore), got {self.duration!r}"
                )

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "at": self.at, "target": self.target}
        if self.duration is not None:
            d["duration"] = self.duration
        if self.lead:
            d["lead"] = self.lead
        if self.factor != 1.0:
            d["factor"] = self.factor
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosEvent":
        # Eager schema validation: a malformed schedule file must fail at
        # load with a message naming the broken event, not deep inside
        # apply_schedule / replay (where a KeyError names nothing).
        for key in ("kind", "at", "target"):
            if key not in d:
                raise ValueError(f"chaos event missing {key!r}: {d!r}")
        try:
            at = float(d["at"])
        except (TypeError, ValueError):
            raise ValueError(
                f"chaos event time must be a number, got {d['at']!r}"
            ) from None
        return cls(
            kind=d["kind"],
            at=at,
            target=str(d["target"]),
            duration=(None if d.get("duration") is None else float(d["duration"])),
            lead=float(d.get("lead", 0.0)),
            factor=float(d.get("factor", 1.0)),
        )

    def describe(self) -> str:
        bits = [f"t={self.at:g}", self.kind, self.target]
        if self.duration is not None:
            bits.append(f"dur={self.duration:g}")
        if self.lead:
            bits.append(f"lead={self.lead:g}")
        if self.factor != 1.0:
            bits.append(f"x{self.factor:g}")
        return " ".join(bits)


class ChaosSchedule:
    """A seeded, serializable fault plan: generate once, save, replay, diff.

    Events are kept sorted by ``(at, kind, target)`` so two schedules
    with the same content compare equal regardless of construction
    order, and the JSON form is canonical (diffs are meaningful).
    Python's ``json`` round-trips floats exactly (repr-based), so a
    loaded schedule replays the *bit-identical* fault sequence — the
    determinism regression in ``tests/test_chaos.py`` holds a replayed
    run to the original's fault log and final meter snapshot.
    """

    SCHEMA = "chaos-schedule"
    VERSION = 1

    def __init__(
        self,
        events,
        seed: Optional[int] = None,
        meta: Optional[dict] = None,
    ):
        self.events: List[ChaosEvent] = sorted(
            events, key=lambda e: (e.at, e.kind, e.target)
        )
        self.seed = seed
        self.meta = dict(meta or {})

    def __len__(self) -> int:
        return len(self.events)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ChaosSchedule) and self.events == other.events
        )

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + 1
        return out

    # -- (de)serialization -------------------------------------------------
    def to_dict(self) -> dict:
        return {
            # Self-describing header (shared convention with
            # MarketSchedule, via ``check_schema_header``): a chaos file
            # handed to the market loader — or vice versa — fails at load
            # with a schema message, not with an opaque shape error
            # later.  ``version`` is kept for pre-round-11 files.
            "schema": self.SCHEMA,
            "schema_version": self.VERSION,
            "version": self.VERSION,
            "seed": self.seed,
            "meta": self.meta,
            "events": [e.to_dict() for e in self.events],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosSchedule":
        check_schema_header(d, cls.SCHEMA, cls.VERSION, "ChaosSchedule")
        return cls(
            [ChaosEvent.from_dict(e) for e in d.get("events", ())],
            seed=d.get("seed"),
            meta=d.get("meta"),
        )

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def loads(cls, text: str) -> "ChaosSchedule":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dumps())

    @classmethod
    def load(cls, path: str) -> "ChaosSchedule":
        with open(path) as f:
            return cls.loads(f.read())

    def diff(self, other: "ChaosSchedule") -> List[str]:
        """Human-readable event diff (empty = identical fault plans).
        Multiplicity-aware: a plan with an event twice vs once IS a
        diff (a set-based compare would silently call them identical)."""
        def counted(events) -> Dict[str, int]:
            out: Dict[str, int] = {}
            for e in events:
                key = e.describe()
                out[key] = out.get(key, 0) + 1
            return out

        mine, theirs = counted(self.events), counted(other.events)
        out = []
        for key in sorted(set(mine) | set(theirs)):
            n_m, n_t = mine.get(key, 0), theirs.get(key, 0)
            out += [f"- {key}"] * max(n_m - n_t, 0)
            out += [f"+ {key}"] * max(n_t - n_m, 0)
        return out

    # -- generation --------------------------------------------------------
    @classmethod
    def generate(
        cls,
        cluster,
        seed: int,
        horizon: float,
        *,
        n_domain_outages: int = 0,
        domain_level: str = "zone",
        outage_duration: float = 120.0,
        n_preemptions: int = 0,
        preempt_lead: float = 10.0,
        preempt_outage: Optional[float] = 300.0,
        zone_rates: Optional[Dict[str, float]] = None,
        n_stragglers: int = 0,
        straggler_factor: float = 4.0,
        straggler_duration: float = 60.0,
        n_partitions: int = 0,
        partition_duration: float = 60.0,
    ) -> "ChaosSchedule":
        """Draw a seeded chaos plan against ``cluster``'s topology.

        All draws come from one ``default_rng(seed)`` in a fixed order,
        so the plan is a pure function of (cluster topology, seed,
        parameters).  Domain outages pick occupied zones (or regions);
        preemptions pick hosts weighted by ``zone_rates`` (uniform when
        None — same contract as :meth:`FaultInjector.spot_preemptions`);
        partitions pick distinct occupied region pairs.  Event times are
        uniform over ``[0, horizon)``.
        """
        rng = np.random.default_rng(seed)
        hosts = cluster.hosts
        if not hosts:
            raise ValueError("chaos generation needs a non-empty cluster")
        zones = sorted({repr(h.locality) for h in hosts})
        regions = sorted(
            {f"{h.locality.cloud}/{h.locality.region}" for h in hosts}
        )
        events: List[ChaosEvent] = []

        if n_domain_outages:
            if domain_level == "zone":
                pool = zones
            elif domain_level == "region":
                pool = regions
            else:
                raise ValueError(
                    f"domain_level must be 'zone' or 'region', got {domain_level!r}"
                )
            for t in rng.uniform(0, horizon, size=n_domain_outages):
                events.append(
                    ChaosEvent(
                        "domain_outage",
                        float(t),
                        pool[int(rng.integers(0, len(pool)))],
                        duration=outage_duration,
                    )
                )

        if n_preemptions:
            if zone_rates is None:
                weights = np.ones(len(hosts))
            else:
                weights = np.array(
                    [zone_rates.get(repr(h.locality), 0.0) for h in hosts]
                )
                if weights.sum() <= 0:
                    raise ValueError("zone_rates cover none of the cluster")
            weights = weights / weights.sum()
            times = rng.uniform(0, horizon, size=n_preemptions)
            picks = rng.choice(len(hosts), size=n_preemptions, p=weights)
            for t, hi in zip(times, picks):
                events.append(
                    ChaosEvent(
                        "preemption",
                        float(t),
                        hosts[int(hi)].id,
                        duration=preempt_outage,
                        lead=preempt_lead,
                    )
                )

        for _ in range(n_stragglers):
            t = float(rng.uniform(0, horizon))
            hi = int(rng.integers(0, len(hosts)))
            events.append(
                ChaosEvent(
                    "straggler",
                    t,
                    hosts[hi].id,
                    duration=straggler_duration,
                    factor=straggler_factor,
                )
            )

        if n_partitions:
            if len(regions) < 2:
                raise ValueError(
                    "partitions need hosts in at least two regions "
                    f"(cluster spans {regions})"
                )
            for _ in range(n_partitions):
                t = float(rng.uniform(0, horizon))
                a, b = rng.choice(len(regions), size=2, replace=False)
                pair = sorted((regions[int(a)], regions[int(b)]))
                events.append(
                    ChaosEvent(
                        "partition",
                        t,
                        "|".join(pair),
                        duration=partition_duration,
                    )
                )

        return cls(
            events,
            seed=seed,
            meta={
                "horizon": horizon,
                "n_hosts": len(hosts),
                "zones": zones,
                "regions": regions,
            },
        )


# ---------------------------------------------------------------------------
# DeviceFaultPlan — the compute-plane fault plan (elastic mesh serving)
# ---------------------------------------------------------------------------


class DeviceFaultPlan:
    """The device-plane view of a :class:`ChaosSchedule`: per-ordinal fail
    windows, validated eagerly and consulted at the dispatch boundary.

    A ``device_fault`` opens a window at ``at`` (closed by its own
    ``duration``, or by a later explicit ``device_restore``; never, if
    neither).  Windows are half-open ``[fail, restore)`` — a dispatch at
    exactly the restore instant sees a healthy device.  The plan is a pure
    function of the schedule, so replaying the same schedule reproduces
    the identical loss sequence bit-for-bit (the elastic referee's
    determinism contract).

    Load-hardening (all rejected at construction, naming the event):
      * unknown device index (``ordinal >= n_devices``)
      * ``device_restore`` with no open fail window on that ordinal
      * overlapping fail windows on one ordinal (a fault while down)
    """

    def __init__(self, windows: Dict[int, List[Tuple[float, float]]],
                 n_devices: int):
        #: ordinal -> sorted list of half-open (fail_at, restore_at)
        #: windows; ``restore_at`` is ``inf`` for permanent faults.
        self.windows = {k: sorted(v) for k, v in windows.items()}
        self.n_devices = int(n_devices)

    @classmethod
    def from_schedule(
        cls, schedule: "ChaosSchedule", n_devices: int
    ) -> "DeviceFaultPlan":
        if n_devices <= 0:
            raise ValueError(f"n_devices must be > 0, got {n_devices}")
        # Events arrive (at, kind, target)-sorted from ChaosSchedule; that
        # orders a same-instant restore BEFORE a same-instant fault
        # ('device_fault' < 'device_restore' lexically is false — fault
        # sorts first), so walk with explicit open-window bookkeeping.
        open_at: Dict[int, float] = {}
        windows: Dict[int, List[Tuple[float, float]]] = {}
        for ev in schedule.events:
            if ev.kind not in ChaosEvent.DEVICE_KINDS:
                continue
            ordinal = device_ordinal(ev.target)
            if ordinal >= n_devices:
                raise ValueError(
                    f"device event targets unknown device index {ordinal} "
                    f"(mesh has {n_devices} devices): {ev.describe()}"
                )
            if ev.kind == "device_fault":
                if ordinal in open_at:
                    raise ValueError(
                        f"overlapping fail windows on device {ordinal}: "
                        f"fault at t={ev.at:g} while already down since "
                        f"t={open_at[ordinal]:g}"
                    )
                if ev.duration is not None:
                    windows.setdefault(ordinal, []).append(
                        (ev.at, ev.at + ev.duration)
                    )
                else:
                    open_at[ordinal] = ev.at
            else:  # device_restore
                if ordinal not in open_at:
                    raise ValueError(
                        f"device_restore at t={ev.at:g} for device "
                        f"{ordinal} with no preceding open device_fault "
                        "(self-closing faults carry their own duration)"
                    )
                windows.setdefault(ordinal, []).append(
                    (open_at.pop(ordinal), ev.at)
                )
        for ordinal, at in open_at.items():
            windows.setdefault(ordinal, []).append((at, float("inf")))
        # A self-closing fault can still overlap a later window; check the
        # assembled per-ordinal timelines.
        for ordinal, spans in windows.items():
            spans.sort()
            for (a0, a1), (b0, _b1) in zip(spans, spans[1:]):
                if b0 < a1:
                    raise ValueError(
                        f"overlapping fail windows on device {ordinal}: "
                        f"[{a0:g}, {a1:g}) and one starting at t={b0:g}"
                    )
        return cls(windows, n_devices)

    def down_at(self, t: float) -> frozenset:
        """Ordinals whose fail window covers sim time ``t`` (half-open:
        down at the fault instant, healthy at the restore instant)."""
        return frozenset(
            ordinal
            for ordinal, spans in self.windows.items()
            if any(a <= t < b for a, b in spans)
        )

    def hit(self, t: float, ordinals) -> frozenset:
        """The subset of ``ordinals`` down at ``t`` — the dispatch-boundary
        check: non-empty means this execution targets a dead device and
        must raise (deterministically, every replay)."""
        return self.down_at(t) & frozenset(int(o) for o in ordinals)

    def events_in(self, t0: float, t1: float) -> List[Tuple[float, str, int]]:
        """Chronological (time, kind, ordinal) transitions in ``[t0, t1)``
        — what ``tools/chaos_replay.py diff`` renders for device events."""
        out: List[Tuple[float, str, int]] = []
        for ordinal, spans in self.windows.items():
            for a, b in spans:
                if t0 <= a < t1:
                    out.append((a, "device_fault", ordinal))
                if b != float("inf") and t0 <= b < t1:
                    out.append((b, "device_restore", ordinal))
        return sorted(out)

    def describe(self) -> List[str]:
        out = []
        for ordinal in sorted(self.windows):
            for a, b in self.windows[ordinal]:
                end = "inf" if b == float("inf") else f"{b:g}"
                out.append(f"device:{ordinal} down [{a:g}, {end})")
        return out
