"""Fault injection: host crash/recovery and bandwidth fluctuation.

The reference has **no fault model** (SURVEY.md §5): its only "failure" is
admission rejection, its ``NetworkRoute._fluctuate`` is an empty stub
(``resources/network.py:102-103``), and no host or link ever goes down.
It does, however, ship a complete failure-handling path — failed tasks are
reset to NASCENT and resubmitted forever (``scheduler/__init__.py:136-139``).
This module supplies the missing fault *sources* so that path (mirrored by
``GlobalScheduler._listen_loop``) is exercised as elastic recovery:

  * **Host crash** — ``Host.fail()`` aborts every resident task mid-flight
    (staging or compute) via abort events raced inside ``Host.execute``;
    each surfaces as ``(False, task)`` on ``notify_q`` and is rescheduled
    elsewhere by the existing retry loop.  Down hosts report zero
    availability, so no fit mask can select them.  ``Host.recover()``
    returns a fresh machine.
  * **Bandwidth fluctuation** — periodic multiplicative resampling of live
    route bandwidth (the reference's intended-but-unimplemented
    ``_fluctuate``), applied between chunks so in-flight transfers see the
    new rate from their next chunk on.

All draws come from a dedicated seeded RNG, so fault schedules are
deterministic and independent of workload/cluster RNG streams.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from pivot_tpu.des import Environment
from pivot_tpu.utils import LogMixin
from pivot_tpu.utils.trace import NULL_TRACER, Tracer

__all__ = ["FaultInjector"]


class FaultInjector(LogMixin):
    """Schedules host crashes, recoveries, and bandwidth fluctuation on a
    cluster's event kernel.

    Create it after the cluster, before ``env.run()``; faults fire at their
    scheduled sim times.  ``tracer`` (optional) records structured
    ``host.failed`` / ``host.recovered`` events.
    """

    def __init__(
        self,
        cluster,
        seed: Optional[int] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.cluster = cluster
        self.env: Environment = cluster.env
        self.rng = np.random.default_rng(seed)
        self.tracer = tracer or NULL_TRACER
        #: (sim_time, host_id, event) log of injected faults.
        self.log: List[Tuple[float, str, str]] = []
        # host_id -> sim time until which the host must stay down.
        # Overlapping outages extend to the union (max end), never truncate.
        self._down_until: dict = {}

    # -- host faults -----------------------------------------------------
    def fail_host(self, host_id: str, at: float, duration: Optional[float] = None):
        """Crash ``host_id`` at sim time ``at``; recover it ``duration``
        seconds later (never, if ``duration`` is None)."""
        host = self.cluster.get_host(host_id)
        if host is None:
            raise KeyError(f"unknown host {host_id!r}")

        recover_at = at + duration if duration is not None else float("inf")

        def _fail():
            self._down_until[host.id] = max(
                self._down_until.get(host.id, 0.0), recover_at
            )
            if not host.up:  # already down: outage extended, no new event
                return
            n_resident = host.n_tasks
            host.fail()
            self.log.append((self.env.now, host.id, "failed"))
            self.tracer.emit(
                "host", "failed", self.env.now, id=host.id, n_aborted=n_resident
            )
            self.logger.debug(
                "[%.3f] host %s failed (%d tasks aborted)",
                self.env.now, host.id, n_resident,
            )

        def _recover():
            # Only the recovery matching the *latest* outage end fires —
            # overlapping outages union (a shorter second outage must not
            # resurrect the host mid-way through a longer first one).
            if self.env.now < self._down_until.get(host.id, 0.0):
                return
            if host.up:
                return
            host.recover()
            self.log.append((self.env.now, host.id, "recovered"))
            self.tracer.emit("host", "recovered", self.env.now, id=host.id)

        self.env.schedule_callback_at(at, _fail)
        if duration is not None:
            self.env.schedule_callback_at(recover_at, _recover)

    def random_host_failures(
        self,
        n_failures: int,
        horizon: float,
        mttr: Optional[float] = None,
        start: float = 0.0,
    ) -> List[Tuple[float, str]]:
        """Schedule ``n_failures`` crashes at uniform times in
        ``[start, horizon)`` on uniformly drawn hosts; each recovers after
        an Exp(mean=``mttr``) outage (never, if ``mttr`` is None).
        Returns the (time, host_id) schedule for assertions/reporting."""
        hosts = self.cluster.hosts
        times = np.sort(self.rng.uniform(start, horizon, size=n_failures))
        picks = self.rng.integers(0, len(hosts), size=n_failures)
        schedule = []
        for t, hi in zip(times, picks):
            duration = (
                float(self.rng.exponential(mttr)) if mttr is not None else None
            )
            self.fail_host(hosts[int(hi)].id, float(t), duration)
            schedule.append((float(t), hosts[int(hi)].id))
        return schedule

    # -- network faults --------------------------------------------------
    def fluctuate_bandwidth(
        self,
        period: float,
        amplitude: float = 0.05,
        until: Optional[float] = None,
    ) -> None:
        """Every ``period`` sim-seconds, resample every *materialized*
        route's bandwidth as ``base × U(1−amplitude, 1+amplitude)``
        (the reference's empty ``_fluctuate`` stub, made real).

        Python network backend only: native routes pin their rate in the
        C++ engine at creation.
        """
        if self.cluster.network_backend != "python":
            raise ValueError(
                "bandwidth fluctuation requires network_backend='python' "
                "(native routes pin their rate in the C++ engine)"
            )
        if period <= 0:
            raise ValueError(f"period must be > 0, got {period}")
        if not 0.0 <= amplitude < 1.0:
            raise ValueError(
                f"amplitude must be in [0, 1), got {amplitude} "
                "(>= 1 could resample a route to non-positive bandwidth)"
            )
        base: dict = {}

        def _tick():
            # The window is half-open [start, until): a tick landing ON the
            # horizon must not resample (it could race the restore below).
            if until is not None and self.env.now >= until:
                return
            for key, route in self.cluster._routes.items():
                b = base.setdefault(key, route.bw)
                route.bw = b * float(
                    self.rng.uniform(1.0 - amplitude, 1.0 + amplitude)
                )
            if until is None or self.env.now + period <= until:
                self.env.schedule_callback(period, _tick)

        def _restore():
            # Bound the perturbation to the configured window: without the
            # restore, the final random draw would persist as a permanent
            # bias for the rest of the simulation.
            for key, b in base.items():
                self.cluster._routes[key].bw = b

        if until is None or period <= until:
            self.env.schedule_callback(period, _tick)
            if until is not None:
                self.env.schedule_callback_at(until, _restore)
