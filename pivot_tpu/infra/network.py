"""Simulated network fabric: per-route chunked fair-share packet service.

Behavioral parity with the reference's ``NetworkRoute``/``Packet``
(``resources/network.py:10-103``):

  * A transfer is served one ``CHUNK_MB``-sized chunk at a time at
    ``chunk / bw`` sim-seconds per chunk; an unfinished transfer re-enters
    the tail of the queue after each chunk, so concurrent transfers share
    the route round-robin and **congestion emerges** from queueing.
  * ``realtime_bw`` estimates effective bandwidth as ``bw / (queued_mb + 1)``
    (ref ``resources/network.py:70-73``).

Redesign (the reference spawns one SimPy generator process per route —
~360k processes for a 600-host all-pairs fabric): a ``Route`` here is a
**passive service**: it keeps a deque and schedules bare completion
callbacks on the event kernel only while transfers are in flight.  Routes
are also created lazily by the cluster, so an idle pair costs nothing.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from pivot_tpu.des import Environment, Event
from pivot_tpu.utils import LogMixin

__all__ = ["Route", "NativeRoute", "Transfer", "CHUNK_MB"]

#: Chunk granularity in MB (ref ``Packet.PACKET_SIZE``, network.py:12).
CHUNK_MB = 1000.0


class Transfer:
    """An in-flight data transfer on one route."""

    __slots__ = ("remaining_mb", "done", "cancelled")

    def __init__(self, size_mb: float, done: Event):
        if size_mb <= 0:
            raise ValueError(f"transfer size must be > 0, got {size_mb}")
        self.remaining_mb = float(size_mb)
        self.done = done
        self.cancelled = False


class Route(LogMixin):
    """A directed (src, dst) link with FIFO round-robin chunk service."""

    __slots__ = (
        "env", "src", "dst", "bw", "meter", "_queue", "_busy",
        "_in_service", "_suspended",
    )

    def __init__(self, env: Environment, src, dst, bw: float, meter=None):
        self.env = env
        self.src = src
        self.dst = dst
        self.bw = float(bw)
        self.meter = meter
        self._queue: deque = deque()
        self._busy = False
        self._in_service: Optional[Transfer] = None
        # Network-partition state (``infra.faults.partition_regions``):
        # a suspended route parks its queue — the chunk already on the
        # wire finishes, nothing further is served until resume().
        self._suspended = False

    @property
    def queued_mb(self) -> float:
        """MB waiting in queue (excludes the chunk currently in service)."""
        return sum(t.remaining_mb for t in self._queue)

    @property
    def realtime_bw(self) -> float:
        """Congestion-discounted bandwidth estimate (ref network.py:70-73)."""
        return self.bw / (self.queued_mb + 1.0)

    def send(self, size_mb: float, done: Optional[Event] = None) -> Event:
        """Enqueue a transfer; returns the completion event."""
        if done is None:
            done = self.env.event()
        self._queue.append(Transfer(size_mb, done))
        if not self._busy:
            self._serve_next()
        return done

    def cancel(self, done: Event) -> None:
        """Drop the queued transfer whose completion event is ``done``.

        Used when a consumer dies mid-staging (host crash,
        ``pivot_tpu.infra.faults``): without cancellation the orphaned
        transfer would keep round-robin-stealing bandwidth from live
        transfers until served to completion.  The chunk currently in
        service (if any) finishes — data already on the wire — but nothing
        further is served and ``done`` never fires."""
        # Eager removal keeps queued_mb / realtime_bw exact immediately —
        # a lazily flagged dead transfer would inflate congestion estimates
        # (and steer bandwidth-aware placement) until it rotated to the
        # queue front.
        survivors = [t for t in self._queue if t.done is not done]
        if len(survivors) != len(self._queue):
            self._queue = deque(survivors)
        # The in-service transfer is not in the queue; its current chunk
        # (data already on the wire) finishes, then it is dropped.
        if self._in_service is not None and self._in_service.done is done:
            self._in_service.cancelled = True

    def suspend(self) -> None:
        """Partition this link: the in-service chunk (data already on the
        wire) completes, then service parks.  Queued transfers are kept,
        not dropped — a partition delays, a crash cancels."""
        self._suspended = True

    def resume(self) -> None:
        """Heal the partition; parked transfers resume round-robin."""
        if not self._suspended:
            return
        self._suspended = False
        if not self._busy and self._queue:
            self._serve_next()

    @property
    def suspended(self) -> bool:
        return self._suspended

    def _serve_next(self) -> None:
        if self._suspended or not self._queue:
            self._busy = False
            self._in_service = None
            return
        self._busy = True
        transfer = self._queue.popleft()
        self._in_service = transfer
        chunk = min(transfer.remaining_mb, CHUNK_MB)
        if self.meter:
            self.meter.route_check_in(self, transfer)
        service_time = chunk / self.bw if self.bw > 0 else 0.0
        self.env.schedule_callback(
            service_time, lambda: self._finish_chunk(transfer, chunk)
        )

    def _finish_chunk(self, transfer: Transfer, chunk: float) -> None:
        if self.meter:
            self.meter.route_check_out(self, transfer, chunk)
        transfer.remaining_mb -= chunk
        if transfer.cancelled:
            pass  # dropped: no completion, no re-enqueue
        elif transfer.remaining_mb <= 0:
            transfer.done.succeed()
        else:
            self._queue.append(transfer)  # round-robin fairness
        self._serve_next()

    def __repr__(self) -> str:
        return f"Route({self.src.id} -> {self.dst.id} @ {self.bw:.0f} Mbps)"


class NativeRoute(Route):
    """Route facade over the C++ co-simulator (``pivot_tpu.native``).

    Same queueing semantics and bit-identical completion times (the engine
    uses the same double arithmetic, ``start + chunk/bw``); the chunk
    service loop lives in native code, so a transfer costs the Python event
    kernel one wake callback instead of one event per chunk.  Per-slot
    meter logs are replaced by engine-accumulated per-route stats that the
    meter reads at summary time (``Meter.add_native_source``).
    """

    __slots__ = ("engine", "index")

    def __init__(self, env, src, dst, bw: float, engine, meter=None):
        super().__init__(env, src, dst, bw, meter)
        self.engine = engine
        self.index = engine.add_route(self.bw, self)

    @property
    def queued_mb(self) -> float:
        return self.engine.queued_mb(self.index)

    def suspend(self) -> None:
        raise NotImplementedError(
            "network partitions require network_backend='python' "
            "(native routes serve their queue inside the C++ engine)"
        )

    resume = suspend

    def send(self, size_mb: float, done: Optional[Event] = None) -> Event:
        if size_mb <= 0:
            raise ValueError(f"transfer size must be > 0, got {size_mb}")
        if done is None:
            done = self.env.event()
        self.engine.send(self.index, size_mb, done)
        return done

    def cancel(self, done: Event) -> None:
        """Drop the queued transfer whose completion event is ``done``.

        Same semantics as :meth:`Route.cancel`: a waiting transfer leaves
        the queue eagerly (``queued_mb`` stays exact), the in-service
        chunk — data already on the wire — finishes and the transfer is
        then dropped, and ``done`` never fires.  The queue surgery happens
        inside the engine (``net_cancel``)."""
        self.engine.cancel(done)
