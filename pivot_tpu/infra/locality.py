"""Geographic locality model and the zone×zone bandwidth / cost matrices.

Capability parity with the reference's ``Cloud``/``Region``/``Zone``/
``Locality`` enums and ``ResourceMetadata`` singleton
(``resources/__init__.py:479-589``), redesigned for the TPU decision
backend:

  * Localities are interned value objects with a **dense integer zone
    index** — the currency of the placement kernels.
  * Bandwidth and egress-cost live as dense ``[Z, Z]`` float arrays
    (``bw_matrix``, ``cost_matrix``) rather than 961-entry dicts; the same
    arrays are pushed to the device once per experiment
    (``pivot_tpu.ops.kernels.DeviceTopology``).
  * No singleton metaclass: ``ResourceMetadata(seed=...)`` is explicit, and
    the reference's ±5 % load-time bandwidth jitter
    (``resources/__init__.py:589``) is reproducible via the seed.

Data: ``data/locality.json`` — 31 AWS+GCP zones and 121 directed
region-pair records (intra-region 15 Gbps / $0, cross-cloud ~50-1120 Mbps at
$0.09-0.11/GB), transcribed from the reference network model
(``resources/locality.yml``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

__all__ = ["Locality", "ResourceMetadata", "DEFAULT_LOCALITY_FILE"]

DEFAULT_LOCALITY_FILE = os.path.join(os.path.dirname(__file__), "data", "locality.json")


class Locality(NamedTuple):
    """(cloud, region, zone) placement key, e.g. ('aws', 'us-east-1', 'a')."""

    cloud: str
    region: str
    zone: str

    @classmethod
    def parse(cls, text: str) -> "Locality":
        cloud, region, zone = text.split("/")
        return cls(cloud, region, zone)

    def __repr__(self) -> str:
        return f"{self.cloud}/{self.region}/{self.zone}"


class ResourceMetadata:
    """Zone catalog + dense inter-zone bandwidth (Mbps) and cost ($/GB) matrices.

    ``seed`` drives the reference-compatible ±5 % bandwidth jitter applied
    once at load; ``jitter=False`` disables it (used by parity tests).
    """

    def __init__(
        self,
        path: str = DEFAULT_LOCALITY_FILE,
        seed: Optional[int] = None,
        jitter: bool = True,
    ):
        with open(path) as f:
            doc = json.load(f)
        self.zones: List[Locality] = [Locality.parse(z) for z in doc["zones"]]
        self.zone_index: Dict[Locality, int] = {z: i for i, z in enumerate(self.zones)}
        n = len(self.zones)
        region_of = {}  # (cloud, region) -> [zone indices]
        for i, z in enumerate(self.zones):
            region_of.setdefault((z.cloud, z.region), []).append(i)

        cost = np.zeros((n, n), dtype=np.float64)
        bw = np.zeros((n, n), dtype=np.float64)
        rng = np.random.default_rng(seed)
        for rec in doc["region_pairs"]:
            sc, sr = rec["src"].split("/")
            dc, dr = rec["dst"].split("/")
            src_zones = region_of[(sc, sr)]
            dst_zones = region_of[(dc, dr)]
            for si in src_zones:
                for di in dst_zones:
                    cost[si, di] = rec["cost_per_gb"]
                    factor = rng.uniform(0.95, 1.05) if jitter else 1.0
                    bw[si, di] = rec["bw_mbps"] * factor
        self.cost_matrix = cost
        self.bw_matrix = bw

    @property
    def n_zones(self) -> int:
        return len(self.zones)

    def index_of(self, locality: Locality) -> int:
        return self.zone_index[locality]

    def cost(self, src: Locality, dst: Locality) -> float:
        return float(self.cost_matrix[self.zone_index[src], self.zone_index[dst]])

    def bw(self, src: Locality, dst: Locality) -> float:
        return float(self.bw_matrix[self.zone_index[src], self.zone_index[dst]])

    def calc_network_traffic_cost(
        self, src: Locality, dst: Locality, data_size_mb: float
    ) -> float:
        """$ cost of moving ``data_size_mb`` MB from src to dst.

        Same unit convention as the reference
        (``resources/__init__.py:565-569``): size / 8000 converts to GB.
        """
        return self.cost(src, dst) * data_size_mb / 8000.0

    def zone_vector(self, localities: List[Locality]) -> np.ndarray:
        """[N] int32 zone indices for a list of localities (kernel feed)."""
        return np.array([self.zone_index[l] for l in localities], dtype=np.int32)
