"""Spot-market environment: time-varying prices and preemption hazards.

Every experiment before this module priced the world statically: one
``[Z, Z]`` egress-cost matrix loaded at start, and spot preemptions (the
chaos engine, ``infra/faults.py``) drawn uniformly or from a hand-written
``zone_rates`` map with no notion of time.  Real spot markets are neither
static nor uniform (Bamboo / SpotServe, PAPERS.md): prices move on
coarse timescales, and the cheap capacity pools are exactly the ones
evicted most — a cost-aware scheduler that ignores that correlation
packs its work onto the most evictable zones.

:class:`MarketSchedule` is the seeded, serializable environment that
makes the correlation explicit — the market twin of
:class:`~pivot_tpu.infra.faults.ChaosSchedule`, with the same
generate / save / load / diff / replay lifecycle:

  * **piecewise-constant per-zone traces**: ``price[p, z]`` (a multiplier
    on the static egress-cost matrix and the per-zone instance rate) and
    ``hazard[p, z]`` (expected preemptions per host per sim-second),
    constant over segment ``[times[p], times[p+1])`` and extended past
    the last breakpoint;
  * **the time-varying cost tensor**: :meth:`cost_tensor` materializes
    the ``[P, Z, Z]`` egress-cost stack (base matrix × source-zone price
    — egress is billed by the *source* cloud), and
    :meth:`cost_matrix_at` hands any scheduling tick its ``[Z, Z]``
    slice.  The scheduling stack threads these through the CPU policies,
    the two-phase kernels, the Pallas kernel, the fused spans (a per-span
    ``[K]`` time-index row, the same pattern as the Philox uniform rows),
    and the host-sharded twins;
  * **the hazard vector**: :meth:`hazard_vector` maps the tick instant
    through host zones to the ``[H]`` per-host hazard the risk-aware
    scoring term consumes (``score += risk_weight × hazard ×
    expected-rework-cost`` — see ``sched/policies.py``);
  * **the preemption process**: :meth:`spot_schedule` samples a
    hazard-proportional piecewise-Poisson preemption plan — per segment
    and zone, ``Poisson(hazard × duration × hosts-in-zone)`` events at
    uniform times on uniformly-drawn zone members, each with the warning
    lead — and returns it as a plain :class:`ChaosSchedule`, so the
    existing ``FaultInjector`` replay / diff / audit machinery drives
    the market's faults unchanged.  Same (cluster, market, seed) ⇒
    bit-identical fault plan, fault log, and meter snapshot;
  * **spot billing**: :meth:`billed_instance_cost` integrates each
    host's metered busy intervals against its zone's price trace —
    the cost-per-completed-task numerator of the ``spot_survival``
    bench and the acceptance soak.

All draws come from one ``default_rng(seed)`` in a fixed order; JSON
round-trips floats exactly (repr-based), so a loaded schedule replays
bit-identically.  Files are self-describing (``schema`` +
``schema_version`` fields — shared convention with ``ChaosSchedule``).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from pivot_tpu.infra.faults import (
    ChaosEvent,
    ChaosSchedule,
    check_schema_header,
)

__all__ = ["MarketSchedule"]


class MarketSchedule:
    """A seeded, serializable spot-market plan: per-zone piecewise-constant
    price multipliers and preemption hazards.

    ``times`` is the sorted ``[P]`` list of segment start instants
    (``times[0]`` must be 0.0 so every sim time has a segment); ``zones``
    the ``[NZ]`` zone-name list (``"cloud/region/zone"`` strings, in the
    owning :class:`~pivot_tpu.infra.locality.ResourceMetadata`'s zone
    order — what lets the ``[P, NZ]`` rows index straight into the
    kernels' zone axis); ``price``/``hazard`` the ``[P, NZ]`` traces.
    """

    SCHEMA = "market-schedule"
    VERSION = 1

    def __init__(
        self,
        times,
        zones: List[str],
        price,
        hazard,
        seed: Optional[int] = None,
        meta: Optional[dict] = None,
    ):
        self.times = np.asarray(times, dtype=np.float64)
        self.zones = [str(z) for z in zones]
        self.price = np.asarray(price, dtype=np.float64)
        self.hazard = np.asarray(hazard, dtype=np.float64)
        self.seed = seed
        self.meta = dict(meta or {})
        P, NZ = len(self.times), len(self.zones)
        if self.price.shape != (P, NZ) or self.hazard.shape != (P, NZ):
            raise ValueError(
                f"price/hazard must be [{P}, {NZ}] (segments × zones), got "
                f"{self.price.shape} / {self.hazard.shape}"
            )
        if P == 0:
            raise ValueError("a MarketSchedule needs at least one segment")
        if self.times[0] != 0.0:
            raise ValueError(
                f"times[0] must be 0.0 so every sim instant has a segment, "
                f"got {self.times[0]}"
            )
        if np.any(np.diff(self.times) <= 0):
            raise ValueError("segment times must be strictly increasing")
        if np.any(~np.isfinite(self.times)):
            raise ValueError("segment times must be finite")
        if np.any(self.price < 0) or np.any(~np.isfinite(self.price)):
            raise ValueError("price multipliers must be finite and >= 0")
        if np.any(self.hazard < 0) or np.any(~np.isfinite(self.hazard)):
            raise ValueError("hazards must be finite and >= 0")
        # Per-segment cost-matrix cache for the last-validated metadata
        # object (a strong reference — an id()-keyed cache could serve a
        # stale matrix if a dead meta's address were recycled); cleared
        # on rebind to a different metadata object.
        self._cost_meta = None
        self._cost_cache: Dict[int, np.ndarray] = {}

    # -- segment lookup ----------------------------------------------------
    @property
    def n_segments(self) -> int:
        return len(self.times)

    def segment(self, t: float) -> int:
        """Index of the segment covering sim time ``t`` (clamped to the
        first/last segment outside the breakpoint range)."""
        return int(
            np.clip(
                np.searchsorted(self.times, t, side="right") - 1,
                0,
                self.n_segments - 1,
            )
        )

    def segment_indices(self, ts) -> np.ndarray:
        """[K] i32 segment index per instant — the fused spans' per-span
        time-index row (one row per span, like the Philox uniform rows)."""
        return np.clip(
            np.searchsorted(self.times, np.asarray(ts), side="right") - 1,
            0,
            self.n_segments - 1,
        ).astype(np.int32)

    def emit_timeline(self, tracer) -> None:
        """Stamp every price-segment boundary onto a trace timeline
        (round 14, ``pivot_tpu.obs``): each segment start becomes a
        ``market``/``price_segment`` instant carrying the segment's
        mean price multiplier and mean hazard, so cost/risk regime
        changes read in context with placements and chaos events.
        Deterministic — pure sim-time payloads; the tracer stamps the
        wall side inside ``obs/``."""
        if not getattr(tracer, "enabled", False):
            return
        for p in range(self.n_segments):
            tracer.emit(
                "market", "price_segment", float(self.times[p]),
                segment=p,
                mean_price=float(np.mean(self.price[p])),
                mean_hazard=float(np.mean(self.hazard[p])),
            )

    def price_row(self, t: float) -> np.ndarray:
        """[NZ] per-zone price multiplier at ``t``."""
        return self.price[self.segment(t)]

    def hazard_row(self, t: float) -> np.ndarray:
        """[NZ] per-zone preemption hazard (events/host/sec) at ``t``."""
        return self.hazard[self.segment(t)]

    def hazard_vector(self, t: float, host_zones) -> np.ndarray:
        """[H] per-host hazard at ``t``: the zone row gathered through the
        cluster's host→zone map — the risk term's kernel feed."""
        zones = np.asarray(host_zones)
        if zones.size and int(zones.max()) >= len(self.zones):
            raise ValueError(
                f"host zone index {int(zones.max())} is out of range for "
                f"this MarketSchedule's {len(self.zones)}-zone catalog; "
                "generate the schedule against the same locality file"
            )
        return self.hazard_row(t)[zones]

    # -- the time-varying egress-cost tensor -------------------------------
    def check_zones(self, meta) -> None:
        want = [repr(z) for z in meta.zones]
        if self.zones != want:
            raise ValueError(
                "MarketSchedule zones do not match the metadata's zone "
                f"catalog ({len(self.zones)} vs {len(want)} zones; "
                "generate the schedule against the same locality file)"
            )

    def cost_matrix_at(self, t: float, meta) -> np.ndarray:
        """[Z, Z] egress-cost matrix at sim time ``t``: the static matrix
        scaled by the SOURCE zone's price multiplier (egress is billed by
        the sending cloud).  Cached per segment — ticks inside one
        segment share the identical ndarray, so downstream staging can
        key on identity."""
        if meta is not self._cost_meta:
            # Validate once per metadata object, not per tick: the zone
            # catalog cannot change under an object we hold a reference to.
            self.check_zones(meta)
            self._cost_meta = meta
            self._cost_cache.clear()
        p = self.segment(t)
        mat = self._cost_cache.get(p)
        if mat is None:
            mat = meta.cost_matrix * self.price[p][:, None]
            mat.setflags(write=False)
            self._cost_cache[p] = mat
        return mat

    def cost_tensor(self, meta) -> np.ndarray:
        """The full ``[P, Z, Z]`` cost stack (segment-major) — the fused
        spans' device operand, indexed per tick by the ``[K]`` row from
        :meth:`segment_indices`."""
        self.check_zones(meta)
        return meta.cost_matrix[None, :, :] * self.price[:, :, None]

    # -- the preemption process --------------------------------------------
    def spot_schedule(
        self,
        cluster,
        seed: int,
        lead: float = 10.0,
        outage: Optional[float] = 300.0,
        horizon: Optional[float] = None,
    ) -> ChaosSchedule:
        """Draw the hazard-proportional spot-preemption plan against
        ``cluster``'s topology as a :class:`ChaosSchedule` of
        ``preemption`` events (warning at ``t``, abort at ``t + lead``,
        capacity back after ``outage`` — ``FaultInjector.apply_schedule``
        semantics).

        Per segment ``[t0, t1)`` and zone ``z``, the event count is
        ``Poisson(hazard[p, z] × (t1 − t0) × n_hosts_in_z)`` with event
        times uniform in the segment and victims uniform over the zone's
        hosts — a piecewise-constant Poisson process per host.  All
        draws come from one ``default_rng(seed)`` in (segment, zone)
        order, so the plan is a pure function of (cluster topology,
        market, seed, lead, outage, horizon).
        """
        if lead < 0:
            raise ValueError(f"preemption lead must be >= 0, got {lead}")
        hosts_by_zone: Dict[str, List] = {}
        for h in cluster.hosts:
            hosts_by_zone.setdefault(repr(h.locality), []).append(h)
        if horizon is None:
            horizon = self.meta.get("horizon")
        if horizon is None:
            # Falling back to times[-1] (the LAST segment's start) would
            # make the final segment's window empty and silently drop its
            # share of the expected preemptions.
            raise ValueError(
                "spot_schedule needs a horizon: this MarketSchedule "
                "records none (meta['horizon']); pass horizon= explicitly"
            )
        horizon = float(horizon)
        if horizon <= 0:
            raise ValueError(f"horizon must be > 0, got {horizon}")
        bounds = list(self.times) + [max(horizon, float(self.times[-1]))]
        rng = np.random.default_rng(seed)
        events: List[ChaosEvent] = []
        for p in range(self.n_segments):
            t0, t1 = bounds[p], min(bounds[p + 1], horizon)
            if t1 <= t0:
                continue
            for zi, zone in enumerate(self.zones):
                members = hosts_by_zone.get(zone)
                if not members:
                    continue
                lam = self.hazard[p, zi] * (t1 - t0) * len(members)
                n = int(rng.poisson(lam)) if lam > 0 else 0
                if n == 0:
                    continue
                ts = rng.uniform(t0, t1, size=n)
                picks = rng.integers(0, len(members), size=n)
                for t, hi in zip(ts, picks):
                    events.append(
                        ChaosEvent(
                            "preemption",
                            float(t),
                            members[int(hi)].id,
                            duration=outage,
                            lead=lead,
                        )
                    )
        return ChaosSchedule(
            events,
            seed=seed,
            meta={
                "source": "market",
                "market_seed": self.seed,
                "horizon": horizon,
                "lead": lead,
                "outage": outage,
            },
        )

    # -- spot billing -------------------------------------------------------
    def billed_instance_cost(
        self, meter, cluster, rate_per_hour: float = 1.0,
        end: Optional[float] = None,
    ) -> float:
        """$ cost of the run's metered busy intervals under this price
        trace: for every host interval ``[a, b)``, ``rate_per_hour / 3600
        × ∫ price(zone(host), t) dt`` — the exact piecewise-constant
        integral, so two replays of one run bill identically.  Intervals
        still open (crash-closed runs close them) are clamped to ``end``
        (default: the last breakpoint)."""
        zone_of = {h.id: repr(h.locality) for h in cluster.hosts}
        zidx = {z: i for i, z in enumerate(self.zones)}
        end = float(end if end is not None else self.times[-1])
        bounds = np.append(self.times, np.inf)
        total = 0.0
        for host, intervals in meter._host_intervals.items():
            zi = zidx.get(zone_of.get(host.id, ""), None)
            if zi is None:
                continue
            for iv in intervals:
                a = iv[0]
                b = iv[1] if len(iv) > 1 else max(end, a)
                for p in range(self.n_segments):
                    lo, hi = max(a, bounds[p]), min(b, bounds[p + 1])
                    if hi > lo:
                        total += (hi - lo) * self.price[p, zi]
        return total * rate_per_hour / 3600.0

    # -- (de)serialization --------------------------------------------------
    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MarketSchedule)
            and np.array_equal(self.times, other.times)
            and self.zones == other.zones
            and np.array_equal(self.price, other.price)
            and np.array_equal(self.hazard, other.hazard)
        )

    def to_dict(self) -> dict:
        return {
            "schema": self.SCHEMA,
            "schema_version": self.VERSION,
            "seed": self.seed,
            "meta": self.meta,
            "times": self.times.tolist(),
            "zones": list(self.zones),
            "price": self.price.tolist(),
            "hazard": self.hazard.tolist(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MarketSchedule":
        check_schema_header(d, cls.SCHEMA, cls.VERSION, "MarketSchedule")
        for key in ("times", "zones", "price", "hazard"):
            if key not in d:
                raise ValueError(f"MarketSchedule file missing {key!r}")
        return cls(
            d["times"], d["zones"], d["price"], d["hazard"],
            seed=d.get("seed"), meta=d.get("meta"),
        )

    def dumps(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def loads(cls, text: str) -> "MarketSchedule":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dumps())

    @classmethod
    def load(cls, path: str) -> "MarketSchedule":
        with open(path) as f:
            return cls.loads(f.read())

    def diff(self, other: "MarketSchedule") -> List[str]:
        """Human-readable trace diff (empty = identical markets)."""
        out: List[str] = []
        if self.zones != other.zones:
            out.append(f"- zones {self.zones}")
            out.append(f"+ zones {other.zones}")
            return out
        if not np.array_equal(self.times, other.times):
            out.append(f"- times {self.times.tolist()}")
            out.append(f"+ times {other.times.tolist()}")
            return out
        for name, a, b in (
            ("price", self.price, other.price),
            ("hazard", self.hazard, other.hazard),
        ):
            for p, z in zip(*np.nonzero(a != b)):
                out.append(
                    f"~ {name}[t={self.times[p]:g}, {self.zones[z]}]: "
                    f"{a[p, z]:g} -> {b[p, z]:g}"
                )
        return out

    # -- generation ---------------------------------------------------------
    @classmethod
    def generate(
        cls,
        meta,
        seed: int,
        horizon: float,
        *,
        n_segments: int = 8,
        base_hazard: float = 0.0,
        hot_fraction: float = 0.25,
        hot_hazard: float = 2e-3,
        hot_discount: float = 0.5,
        price_vol: float = 0.15,
    ) -> "MarketSchedule":
        """Draw a seeded spot market against ``meta``'s zone catalog.

        A ``hot_fraction`` of zones become *spot pools*: discounted to
        ``hot_discount`` of the on-demand price (cheap — exactly where
        cost-aware placement wants to pack) but carrying ``hot_hazard``
        preemptions/host/sec; the rest run at ~1.0× with ``base_hazard``.
        Every segment multiplies each zone's price by ``U(1 ± price_vol)``
        and jitters hot-zone hazard by ``U(0.5, 1.5)``, so both traces
        genuinely move over time.  Pure function of (meta zones, seed,
        params).
        """
        if n_segments < 1:
            raise ValueError(f"n_segments must be >= 1, got {n_segments}")
        if horizon <= 0:
            raise ValueError(f"horizon must be > 0, got {horizon}")
        if not 0.0 <= hot_fraction <= 1.0:
            raise ValueError(
                f"hot_fraction must be in [0, 1], got {hot_fraction}"
            )
        if not 0.0 <= price_vol < 1.0:
            raise ValueError(
                f"price_vol must be in [0, 1), got {price_vol} "
                "(>= 1 could draw a negative price)"
            )
        zones = [repr(z) for z in meta.zones]
        nz = len(zones)
        rng = np.random.default_rng(seed)
        n_hot = int(round(hot_fraction * nz))
        hot = np.zeros(nz, dtype=bool)
        if n_hot:
            hot[rng.choice(nz, size=n_hot, replace=False)] = True
        times = np.linspace(0.0, horizon, n_segments, endpoint=False)
        base_price = np.where(hot, hot_discount, 1.0)
        base_haz = np.where(hot, hot_hazard, base_hazard)
        price = base_price[None, :] * rng.uniform(
            1.0 - price_vol, 1.0 + price_vol, size=(n_segments, nz)
        )
        hazard = base_haz[None, :] * np.where(
            hot[None, :],
            rng.uniform(0.5, 1.5, size=(n_segments, nz)),
            1.0,
        )
        return cls(
            times, zones, price, hazard, seed=seed,
            meta={
                "horizon": horizon,
                "hot_zones": [z for z, h in zip(zones, hot) if h],
                "base_hazard": base_hazard,
                "hot_hazard": hot_hazard,
                "hot_discount": hot_discount,
            },
        )
