"""Simulated cross-cloud infrastructure: hosts, storage, cluster, executor.

Capability parity with the reference's ``resources/__init__.py``:

  * ``HostResource``  — 4-dim (cpus, mem, disk, gpus) capacity vector with
    all-or-nothing admission (ref ``:370-461``).  The reference guards four
    SimPy Containers with a mutex because its ``subscribe`` yields between
    checks; here admission is a single synchronous check-and-reserve, atomic
    by cooperative scheduling — same observable semantics, no locks.
  * ``Host.execute``  — the executor hot path (ref ``:244-314``): admit →
    meter check-in → pull predecessor outputs over the network fabric
    (with per-instance input sampling) → barrier → timed compute → release.
  * ``Cluster``       — the scheduler↔executor broker with the
    ``dispatch_q`` / ``notify_q`` queue pair (ref ``:40,119-135``) — the
    plugin boundary of the whole framework.

Redesigns (TPU-first):
  * **Lazy routes**: the reference pre-creates O(N²) route objects + one
    SimPy process each (``resources/gen.py:61-73``); here routes materialize
    on first use from the dense zone matrices.  An idle pair costs nothing.
  * **Dense state exports**: ``availability_matrix()`` ([H,4] f32) and
    ``host_zone_vector()`` ([H] i32) feed the placement kernels directly.
  * ``clone()`` re-derives *all* route bandwidth from zone metadata and
    meters every route, matching the reference's clone behavior
    (``resources/__init__.py:110-117`` — note this intentionally replaces
    generator-assigned self-route bandwidth with the intra-zone value, a
    reference quirk we preserve since every experiment runs on a clone).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pivot_tpu.des import Environment, Store
from pivot_tpu.infra.locality import Locality, ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.infra.network import Route
from pivot_tpu.utils import LogMixin, fresh_id
from pivot_tpu.workload import Task

__all__ = [
    "Node",
    "Host",
    "HostResource",
    "Storage",
    "Cluster",
    "LOCAL_BW",
]

#: Same-host loopback bandwidth in Mbps (ref ``resources/gen.py:13``).
LOCAL_BW = 2e5

RESOURCE_DIMS = ("cpus", "mem", "disk", "gpus")


class Node(LogMixin):
    """A network endpoint with a locality."""

    def __init__(self, env: Environment, locality: Locality, id: Optional[str] = None):
        self.env = env
        self.id = str(id) if id is not None else fresh_id(type(self).__name__.lower())
        self.locality = locality
        self.cluster: Optional["Cluster"] = None

    def __repr__(self) -> str:
        return self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other) -> bool:
        return isinstance(other, Node) and self.id == other.id


class HostResource:
    """Multi-dimensional host capacity with atomic acquire/release."""

    __slots__ = ("totals", "available")

    def __init__(self, cpus: float, mem: float, disk: float, gpus: float):
        self.totals = np.array([cpus, mem, disk, gpus], dtype=np.float64)
        self.available = self.totals.copy()

    @property
    def used(self) -> np.ndarray:
        return self.totals - self.available

    def try_acquire(self, demand: np.ndarray) -> bool:
        """All-or-nothing admission (ref ``subscribe``, ``:433-449``)."""
        if np.any(demand < 0) or np.any(demand > self.available):
            return False
        self.available -= demand
        return True

    def release(self, demand: np.ndarray) -> None:
        """Refund, clamped per-dimension (ref ``unsubscribe``, ``:451-461``)."""
        used = self.used
        refund = np.where((demand > 0) & (demand <= used), demand, 0.0)
        self.available += refund


class Storage(Node):
    """Zone-local object store — anchor for cost-aware grouping."""

    def clone(self, env: Environment) -> "Storage":
        return Storage(env, self.locality, id=self.id)


class Host(Node):
    """A simulated machine: admission control, data staging, timed compute."""

    def __init__(
        self,
        env: Environment,
        cpus: float,
        mem: float,
        disk: float,
        gpus: float,
        locality: Locality,
        meter: Optional[Meter] = None,
        id: Optional[str] = None,
    ):
        super().__init__(env, locality, id)
        self.resource = HostResource(cpus, mem, disk, gpus)
        self.meter = meter
        self._tasks: set = set()

    @property
    def tasks(self) -> List[Task]:
        return list(self._tasks)

    @property
    def n_tasks(self) -> int:
        return len(self._tasks)

    @property
    def in_use(self) -> bool:
        return bool(self._tasks)

    def clone(self, env: Environment, meter: Optional[Meter]) -> "Host":
        t = self.resource.totals
        return Host(env, t[0], t[1], t[2], t[3], self.locality, meter, id=self.id)

    def execute(self, task: Task):
        """Generator process: run one task on this host (ref ``:244-314``)."""
        env, meter, cluster = self.env, self.meter, self.cluster
        demand = task.demand
        if not self.resource.try_acquire(demand):
            avail = self.resource.available
            for dim, name in enumerate(RESOURCE_DIMS):
                if demand[dim] > avail[dim]:
                    self.logger.debug(
                        "[%.3f] %s demand %.3f > available %.3f on %s",
                        env.now,
                        name,
                        demand[dim],
                        avail[dim],
                        self.id,
                    )
            return False

        self._tasks.add(task)
        if meter:
            meter.host_check_in(self)
        task.set_running()

        # Stage input data from predecessor task outputs.
        pull_start = env.now
        preds = self._sample_predecessor_inputs(task)
        if preds:
            done_events = []
            for p in preds:
                route = cluster.get_route(p.placement, self.id)
                done_events.append(route.send(p.output_size))
            yield env.all_of(done_events)
            if meter:
                self._record_transfer(task, preds, pull_start)

        # Timed compute.
        self.logger.debug(
            "[%.3f] task %s starts on %s, etc %.3f", env.now, task.id, self.id, task.runtime
        )
        yield env.timeout(task.runtime)

        self.resource.release(demand)
        self._tasks.discard(task)
        if meter:
            meter.host_check_out(self)
        return True

    def _sample_predecessor_inputs(self, task: Task) -> List[Task]:
        """Predecessor tasks to pull from, sampled per instance count.

        A group with n replicas pulls from ~1/n of each predecessor group's
        tasks (with replacement), mirroring ref ``:263-267``.
        """
        group = task.group
        app = group.application
        rng = self.cluster.rng
        sampled: List[Task] = []
        for pred_group in app.get_predecessors(group.id):
            if pred_group.output_size <= 0:
                continue
            ptasks = pred_group.tasks
            if not ptasks:
                continue
            if group.instances > 1:
                k = max(round(len(ptasks) / group.instances), 1)
                idx = rng.integers(0, len(ptasks), size=k)
                sampled.extend(ptasks[i] for i in idx)
            else:
                sampled.extend(ptasks)
        return sampled

    def _record_transfer(self, task: Task, preds: List[Task], pull_start: float) -> None:
        env, cluster, meter = self.env, self.cluster, self.meter
        meta = cluster.meta
        bws, costs, prop_delays = [], [], []
        sources = set()
        for p in preds:
            p_host = cluster.get_host(p.placement)
            route = cluster.get_route(p_host.id, self.id)
            bws.append(route.bw)
            costs.append(meta.cost(p_host.locality, self.locality))
            prop_delays.append(p.output_size / route.bw if route.bw > 0 else 0.0)
            sources.add(p_host.locality)
        total_amt = sum(p.output_size for p in preds)
        total_delay = env.now - pull_start
        if meter:
            meter.add_data_transfer(
                env.now,
                sources,
                self.locality,
                total_amt,
                total_delay,
                max(prop_delays),
                float(np.mean(bws)),
                float(np.mean(costs)),
            )


class Cluster(LogMixin):
    """The simulated fabric and the scheduler↔executor message broker."""

    def __init__(
        self,
        env: Environment,
        hosts: Sequence[Host] = (),
        storage: Sequence[Storage] = (),
        meta: Optional[ResourceMetadata] = None,
        meter: Optional[Meter] = None,
        route_mode: str = "local",
        seed: Optional[int] = None,
    ):
        """``route_mode``: 'local' gives same-host loopback routes LOCAL_BW
        and meters only host↔storage pairs (generator behavior, ref
        ``resources/gen.py:61-73``); 'meta' derives every route from zone
        metadata and meters all routes (clone behavior, ref ``:110-117``).
        """
        if route_mode not in ("local", "meta"):
            raise ValueError(f"unknown route_mode {route_mode!r}")
        self.env = env
        self.meta = meta if meta is not None else ResourceMetadata()
        self.meter = meter
        self.route_mode = route_mode
        self.seed = seed
        self.rng = np.random.default_rng(seed)
        self._hosts: Dict[str, Host] = {}
        self._host_list: List[Host] = []
        self._storage: Dict[str, Storage] = {}
        self._storage_by_locality: Dict[Locality, Storage] = {}
        self._routes: Dict[Tuple[str, str], Route] = {}
        for h in hosts:
            self.add_host(h)
        for s in storage:
            self.add_storage(s)
        self.dispatch_q = Store(env)
        self.notify_q = Store(env)

    # -- membership ------------------------------------------------------
    @property
    def hosts(self) -> List[Host]:
        return list(self._host_list)

    @property
    def storage(self) -> List[Storage]:
        return list(self._storage.values())

    def add_host(self, host: Host) -> None:
        if host.id in self._hosts:
            raise ValueError(f"host {host.id!r} already exists")
        host.cluster = self
        self._hosts[host.id] = host
        self._host_list.append(host)

    def add_storage(self, storage: Storage) -> None:
        storage.cluster = self
        self._storage[storage.id] = storage
        self._storage_by_locality[storage.locality] = storage

    def get_host(self, hid: str) -> Optional[Host]:
        return self._hosts.get(hid)

    def get_storage(self, sid: str) -> Optional[Storage]:
        return self._storage.get(sid)

    def get_storage_by_locality(self, locality: Locality) -> Optional[Storage]:
        return self._storage_by_locality.get(locality)

    def _node(self, nid: str) -> Node:
        node = self._hosts.get(nid) or self._storage.get(nid)
        if node is None:
            raise KeyError(f"unknown node {nid!r}")
        return node

    def get_route(self, src_id: str, dst_id: str) -> Route:
        """Lazily materialize the directed route between two nodes."""
        key = (str(src_id), str(dst_id))
        route = self._routes.get(key)
        if route is None:
            src, dst = self._node(key[0]), self._node(key[1])
            if self.route_mode == "local" and src.id == dst.id:
                bw = LOCAL_BW
            else:
                bw = self.meta.bw(src.locality, dst.locality)
            if self.route_mode == "meta":
                metered = self.meter
            else:
                host_storage_pair = (
                    isinstance(src, Host) and isinstance(dst, Storage)
                ) or (isinstance(src, Storage) and isinstance(dst, Host))
                metered = self.meter if host_storage_pair else None
            route = Route(self.env, src, dst, bw, meter=metered)
            self._routes[key] = route
        return route

    # -- lifecycle -------------------------------------------------------
    def clone(
        self, env: Environment, meter: Optional[Meter], seed: Optional[int] = None
    ) -> "Cluster":
        hosts = [h.clone(env, meter) for h in self._host_list]
        storage = [s.clone(env) for s in self._storage.values()]
        return Cluster(
            env,
            hosts=hosts,
            storage=storage,
            meta=self.meta,
            meter=meter,
            route_mode="meta",
            seed=self.seed if seed is None else seed,
        )

    def start(self) -> None:
        self.env.process(self._dispatch_loop())

    def _dispatch_loop(self):
        while True:
            task = yield self.dispatch_q.get()
            if not isinstance(task, Task):
                self.logger.error("dispatched non-task item: %r", task)
                continue
            host = self._hosts.get(task.placement)
            if host is None:
                self.logger.error("unrecognized host %r", task.placement)
                continue
            self.env.process(self._execute_task(task, host))

    def _execute_task(self, task: Task, host: Host):
        success = yield self.env.process(host.execute(task))
        yield self.notify_q.put((success, task))

    # -- dense exports for the decision kernels --------------------------
    def availability_matrix(self, dtype=np.float64) -> np.ndarray:
        """[H, 4] current per-host availability snapshot."""
        return np.stack([h.resource.available for h in self._host_list]).astype(
            dtype, copy=False
        )

    def totals_matrix(self, dtype=np.float64) -> np.ndarray:
        return np.stack([h.resource.totals for h in self._host_list]).astype(
            dtype, copy=False
        )

    def host_zone_vector(self) -> np.ndarray:
        """[H] int32 zone index per host."""
        return self.meta.zone_vector([h.locality for h in self._host_list])

    def storage_zone_vector(self) -> np.ndarray:
        """[S] int32 zone index per storage node."""
        return self.meta.zone_vector([s.locality for s in self.storage])
