"""Simulated cross-cloud infrastructure: hosts, storage, cluster, executor.

Capability parity with the reference's ``resources/__init__.py``:

  * ``HostResource``  — 4-dim (cpus, mem, disk, gpus) capacity vector with
    all-or-nothing admission (ref ``:370-461``).  The reference guards four
    SimPy Containers with a mutex because its ``subscribe`` yields between
    checks; here admission is a single synchronous check-and-reserve, atomic
    by cooperative scheduling — same observable semantics, no locks.
  * ``Host.execute``  — the executor hot path (ref ``:244-314``): admit →
    meter check-in → pull predecessor outputs over the network fabric
    (with per-instance input sampling) → barrier → timed compute → release.
  * ``Cluster``       — the scheduler↔executor broker with the
    ``dispatch_q`` / ``notify_q`` queue pair (ref ``:40,119-135``) — the
    plugin boundary of the whole framework.

Redesigns (TPU-first):
  * **Lazy routes**: the reference pre-creates O(N²) route objects + one
    SimPy process each (``resources/gen.py:61-73``); here routes materialize
    on first use from the dense zone matrices.  An idle pair costs nothing.
  * **Dense state exports**: ``availability_matrix()`` ([H,4] f32) and
    ``host_zone_vector()`` ([H] i32) feed the placement kernels directly.
  * ``clone()`` re-derives *all* route bandwidth from zone metadata and
    meters every route, matching the reference's clone behavior
    (``resources/__init__.py:110-117`` — note this intentionally replaces
    generator-assigned self-route bandwidth with the intra-zone value, a
    reference quirk we preserve since every experiment runs on a clone).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pivot_tpu.des import Environment, Event, Store
from pivot_tpu.infra.locality import Locality, ResourceMetadata
from pivot_tpu.infra.meter import Meter
from pivot_tpu.infra.network import NativeRoute, Route
from pivot_tpu.utils import LogMixin, fresh_id
from pivot_tpu.workload import Task

__all__ = [
    "Node",
    "Host",
    "HostResource",
    "Storage",
    "Cluster",
    "LOCAL_BW",
]

#: Same-host loopback bandwidth in Mbps (ref ``resources/gen.py:13``).
LOCAL_BW = 2e5

RESOURCE_DIMS = ("cpus", "mem", "disk", "gpus")


class Node(LogMixin):
    """A network endpoint with a locality."""

    def __init__(self, env: Environment, locality: Locality, id: Optional[str] = None):
        self.env = env
        self.id = str(id) if id is not None else fresh_id(type(self).__name__.lower())
        self.locality = locality
        self.cluster: Optional["Cluster"] = None

    def __repr__(self) -> str:
        return self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other) -> bool:
        return isinstance(other, Node) and self.id == other.id


class HostResource:
    """Multi-dimensional host capacity with atomic acquire/release.

    Scalars, not arrays: admission runs once per task execution on the
    simulator's hottest path, where four float compares beat numpy
    dispatch overhead by ~10×.  Dense views are built per scheduling tick
    by ``Cluster.availability_matrix``.
    """

    __slots__ = (
        "t_cpus", "t_mem", "t_disk", "t_gpus",
        "cpus", "mem", "disk", "gpus",
        "_cache",
    )

    def __init__(self, cpus: float, mem: float, disk: float, gpus: float):
        self.t_cpus, self.t_mem, self.t_disk, self.t_gpus = (
            float(cpus),
            float(mem),
            float(disk),
            float(gpus),
        )
        self.cpus, self.mem, self.disk, self.gpus = self.t_cpus, self.t_mem, self.t_disk, self.t_gpus
        # Optional write-through row of the owning cluster's [H,4]
        # availability cache (``Cluster.availability_matrix``): scalars
        # stay authoritative, the row mirrors them after every mutation.
        self._cache = None

    def _sync_cache(self) -> None:
        c = self._cache
        if c is not None:
            c[0] = self.cpus
            c[1] = self.mem
            c[2] = self.disk
            c[3] = self.gpus

    @property
    def totals(self) -> np.ndarray:
        return np.array([self.t_cpus, self.t_mem, self.t_disk, self.t_gpus])

    @property
    def available(self) -> np.ndarray:
        return np.array([self.cpus, self.mem, self.disk, self.gpus])

    @property
    def used(self) -> np.ndarray:
        return self.totals - self.available

    def try_acquire(self, cpus: float, mem: float, disk: float, gpus: float) -> bool:
        """All-or-nothing admission (ref ``subscribe``, ``:433-449``)."""
        if (
            cpus < 0
            or mem < 0
            or disk < 0
            or gpus < 0
            or cpus > self.cpus
            or mem > self.mem
            or disk > self.disk
            or gpus > self.gpus
        ):
            return False
        self.cpus -= cpus
        self.mem -= mem
        self.disk -= disk
        self.gpus -= gpus
        self._sync_cache()
        return True

    def reset(self) -> None:
        """Restore full capacity (fresh machine after fault recovery)."""
        self.cpus, self.mem, self.disk, self.gpus = (
            self.t_cpus, self.t_mem, self.t_disk, self.t_gpus,
        )
        self._sync_cache()

    def release(self, cpus: float, mem: float, disk: float, gpus: float) -> None:
        """Refund, clamped per-dimension to what is actually in use (ref
        ``unsubscribe``, ``:451-461`` — but clamped with ``min`` rather than
        dropped outright: with fractional trace demands, float rounding can
        leave used capacity one ULP below the refund, and dropping it would
        leak host capacity permanently)."""
        if cpus > 0:
            self.cpus += min(cpus, max(self.t_cpus - self.cpus, 0.0))
        if mem > 0:
            self.mem += min(mem, max(self.t_mem - self.mem, 0.0))
        if disk > 0:
            self.disk += min(disk, max(self.t_disk - self.disk, 0.0))
        if gpus > 0:
            self.gpus += min(gpus, max(self.t_gpus - self.gpus, 0.0))
        self._sync_cache()


class Storage(Node):
    """Zone-local object store — anchor for cost-aware grouping."""

    def clone(self, env: Environment) -> "Storage":
        return Storage(env, self.locality, id=self.id)


class Host(Node):
    """A simulated machine: admission control, data staging, timed compute."""

    def __init__(
        self,
        env: Environment,
        cpus: float,
        mem: float,
        disk: float,
        gpus: float,
        locality: Locality,
        meter: Optional[Meter] = None,
        id: Optional[str] = None,
    ):
        super().__init__(env, locality, id)
        self.resource = HostResource(cpus, mem, disk, gpus)
        self.meter = meter
        self._tasks: set = set()
        #: Liveness flag — flipped by fault injection (``infra.faults``).
        #: A down host admits nothing and reports zero availability.
        self.up = True
        #: Spot-preemption drain flag (``infra.faults.preempt_host``):
        #: a draining host still runs its residents and still ADMITS (the
        #: machine is alive), but the scheduler's live mask excludes it
        #: from NEW placements so work drains ahead of the abort.
        self.draining = False
        #: Straggler multiplier (``infra.faults.slow_host``): compute
        #: started while > 1 takes ``runtime × slowdown`` sim-seconds.
        #: Exactly 1.0 when healthy — ``x * 1.0 == x`` bitwise, so the
        #: no-straggler trajectory is unchanged.
        self.slowdown = 1.0
        # task -> abort Event raced against its compute/staging waits.
        self._aborts: Dict[Task, Event] = {}

    @property
    def tasks(self) -> List[Task]:
        return list(self._tasks)

    @property
    def n_tasks(self) -> int:
        return len(self._tasks)

    @property
    def in_use(self) -> bool:
        return bool(self._tasks)

    def clone(self, env: Environment, meter: Optional[Meter]) -> "Host":
        t = self.resource.totals
        return Host(env, t[0], t[1], t[2], t[3], self.locality, meter, id=self.id)

    def execute(self, task: Task):
        """Generator: run one task on this host (ref ``:244-314``).

        Driven via ``yield from`` inside the cluster's execute process — no
        separate Process object per task execution.
        """
        env, meter, cluster = self.env, self.meter, self.cluster
        group = task.group
        resource = self.resource
        if not self.up:
            return False
        if not resource.try_acquire(group.cpus, group.mem, group.disk, group.gpus):
            return False

        self._tasks.add(task)
        abort = self._aborts[task] = env.event()
        if meter:
            meter.host_check_in(self)
        task.set_running()

        # Stage input data from predecessor task outputs.  Both the staging
        # barrier and the compute timeout race the abort event so a host
        # failure fails the task *now*, not at its original finish time.
        pull_start = env.now
        preds = self._sample_predecessor_inputs(task)
        if preds:
            done_events = []
            routes = []
            for p in preds:
                route = cluster.get_route(
                    self._output_source(p, cluster), self.id
                )
                routes.append(route)
                done_events.append(route.send(p.output_size))
            fired = yield env.any_of([env.all_of(done_events), abort])
            if fired is abort:
                # Cancel orphaned pulls so they stop round-robin-stealing
                # bandwidth from live transfers on shared routes.
                for route, evt in zip(routes, done_events):
                    route.cancel(evt)
                return self._conclude_aborted(task, pull_start)
            if meter:
                self._record_transfer(task, preds, routes, pull_start)

        # Timed compute (stretched while the host straggles).
        fired = yield env.any_of([env.timeout(task.runtime * self.slowdown), abort])
        if fired is abort:
            return self._conclude_aborted(task, pull_start)

        resource.release(group.cpus, group.mem, group.disk, group.gpus)
        self._tasks.discard(task)
        self._aborts.pop(task, None)
        if meter:
            meter.host_check_out(self)
        return True

    @staticmethod
    def _output_source(pred: Task, cluster: "Cluster") -> str:
        """Node serving ``pred``'s output: its host, or — if that host has
        crashed — the producing zone's storage node.

        Task outputs are durably staged to zone-local storage (the
        reference's intended storage-mediated pull path,
        ``resources/__init__.py:137-149`` — dead code there), so a finished
        predecessor's data survives its host.  Zone bw/cost matrices make
        the transfer parameters identical either way; only the metering
        source differs."""
        src = cluster.get_host(pred.placement)
        if src is not None and not src.up:
            store = cluster.get_storage_by_locality(src.locality)
            if store is not None:
                return store.id
        return pred.placement

    def _conclude_aborted(self, task: Task, started: float) -> bool:
        """This execution aborted mid-flight (host death, or a proactive
        ``FastExecutor.evict_task``): no resource refund here — a dead
        machine's capacity resets wholesale on ``recover``, and
        ``evict_task`` refunds BEFORE triggering the abort — but the
        meter interval closes so
        instance-hours stay correct, and the wasted work since ``started``
        is billed as rework (the spot-survival cost accounting)."""
        self._tasks.discard(task)
        self._aborts.pop(task, None)
        if self.meter:
            self.meter.host_check_out(self)
            self.meter.add_rework(self.env.now - started)
        return False

    def fail(self) -> None:
        """Take the host down, aborting every resident task (they surface as
        ``(False, task)`` on ``notify_q`` — the scheduler's existing retry
        path reschedules them elsewhere)."""
        if not self.up:
            return
        self.up = False
        # Fast-executor residents abort synchronously; process-executor
        # residents abort via their raced events.  At most one path has
        # live entries — they are mutually exclusive per cluster.
        if self.cluster is not None and self.cluster.executor is not None:
            self.cluster.executor.abort_host(self)
        for abort in list(self._aborts.values()):
            if not abort.triggered:
                abort.succeed()

    def recover(self) -> None:
        """Bring the host back as a fresh machine: full capacity, no
        tasks, no drain flag, no straggler slowdown."""
        if self.up:
            return
        self.up = True
        self.draining = False
        self.slowdown = 1.0
        self.resource.reset()
        self._tasks.clear()
        self._aborts.clear()

    def _sample_predecessor_inputs(self, task: Task) -> List[Task]:
        """Predecessor tasks to pull from, sampled per instance count.

        A group with n replicas pulls from ~1/n of each predecessor group's
        tasks (with replacement), mirroring ref ``:263-267``.
        """
        group = task.group
        app = group.application
        rng = self.cluster.pyrng
        sampled: List[Task] = []
        for pred_group in app.get_predecessors(group.id):
            if pred_group.output_size <= 0:
                continue
            ptasks = pred_group.tasks
            if not ptasks:
                continue
            if group.instances > 1:
                n = len(ptasks)
                k = max(round(n / group.instances), 1)
                sampled.extend(ptasks[rng.randrange(n)] for _ in range(k))
            else:
                sampled.extend(ptasks)
        return sampled

    def _record_transfer(
        self, task: Task, preds: List[Task], routes: List["Route"], pull_start: float
    ) -> None:
        env, cluster, meter = self.env, self.cluster, self.meter
        meta = cluster.meta
        sum_bw = sum_cost = max_prop = total_amt = 0.0
        sources = set()
        for p, route in zip(preds, routes):
            sum_bw += route.bw
            sum_cost += meta.cost(route.src.locality, self.locality)
            if route.bw > 0:
                prop = p.output_size / route.bw
                if prop > max_prop:
                    max_prop = prop
            total_amt += p.output_size
            sources.add(route.src.locality)
        n = len(preds)
        meter.add_data_transfer(
            env.now,
            sources,
            self.locality,
            total_amt,
            env.now - pull_start,
            max_prop,
            sum_bw / n,
            sum_cost / n,
        )


class Cluster(LogMixin):
    """The simulated fabric and the scheduler↔executor message broker."""

    def __init__(
        self,
        env: Environment,
        hosts: Sequence[Host] = (),
        storage: Sequence[Storage] = (),
        meta: Optional[ResourceMetadata] = None,
        meter: Optional[Meter] = None,
        route_mode: str = "local",
        seed: Optional[int] = None,
        network_backend: str = "python",
        executor_backend: str = "fast",
    ):
        """``route_mode``: 'local' gives same-host loopback routes LOCAL_BW
        and meters only host↔storage pairs (generator behavior, ref
        ``resources/gen.py:61-73``); 'meta' derives every route from zone
        metadata and meters all routes (clone behavior, ref ``:110-117``).

        ``network_backend``: 'python' serves chunks on the event kernel;
        'native' runs the whole chunk-service loop in the C++ co-simulator
        (``pivot_tpu.native``) — same completion times, far fewer events.

        ``executor_backend``: 'fast' drives each task execution with bare
        callbacks (``infra.executor.FastExecutor``); 'process' mirrors the
        reference's one-process-per-execution shape (``Host.execute``
        driven by ``_execute_task``).  Bit-identical trajectories — the
        parity suite in ``tests/test_executor.py`` holds both to it.
        """
        if route_mode not in ("local", "meta"):
            raise ValueError(f"unknown route_mode {route_mode!r}")
        if network_backend not in ("python", "native"):
            raise ValueError(f"unknown network_backend {network_backend!r}")
        if executor_backend not in ("process", "fast"):
            raise ValueError(f"unknown executor_backend {executor_backend!r}")
        self.env = env
        self.meta = meta if meta is not None else ResourceMetadata()
        self.meter = meter
        self.route_mode = route_mode
        self.network_backend = network_backend
        self.net_engine = None
        if network_backend == "native":
            from pivot_tpu.native import NativeNetworkEngine

            self.net_engine = NativeNetworkEngine(env)
            if meter is not None:
                meter.add_native_source(self.net_engine)
        self.executor_backend = executor_backend
        self.executor = None
        if executor_backend == "fast":
            from pivot_tpu.infra.executor import FastExecutor

            self.executor = FastExecutor(self)
        self.seed = seed
        self.rng = np.random.default_rng(seed)
        # Python RNG for the per-task predecessor sampling hot path (each
        # draw is a scalar; random.Random beats numpy dispatch ~10×).
        self.pyrng = random.Random(seed)
        self._hosts: Dict[str, Host] = {}
        self._host_list: List[Host] = []
        # Write-through [H,4] f64 availability mirror; (re)built lazily by
        # ``availability_matrix`` and invalidated when membership changes.
        self._avail_cache: Optional[np.ndarray] = None
        self._storage: Dict[str, Storage] = {}
        self._storage_by_locality: Dict[Locality, Storage] = {}
        self._routes: Dict[Tuple[str, str], Route] = {}
        # Called with each newly materialized route.  Routes are lazy, so
        # state that must cover the whole fabric (an active network
        # partition, ``infra.faults``) registers here to catch links that
        # materialize while it is in force.
        self._route_hooks: List = []
        for h in hosts:
            self.add_host(h)
        for s in storage:
            self.add_storage(s)
        self.dispatch_q = Store(env)
        self.notify_q = Store(env)

    # -- membership ------------------------------------------------------
    @property
    def hosts(self) -> List[Host]:
        return list(self._host_list)

    @property
    def storage(self) -> List[Storage]:
        return list(self._storage.values())

    def add_host(self, host: Host) -> None:
        if host.id in self._hosts:
            raise ValueError(f"host {host.id!r} already exists")
        host.cluster = self
        self._hosts[host.id] = host
        self._host_list.append(host)
        self._avail_cache = None  # membership changed; rebuild lazily

    def add_storage(self, storage: Storage) -> None:
        storage.cluster = self
        self._storage[storage.id] = storage
        self._storage_by_locality[storage.locality] = storage

    def get_host(self, hid: str) -> Optional[Host]:
        return self._hosts.get(hid)

    def get_storage(self, sid: str) -> Optional[Storage]:
        return self._storage.get(sid)

    def get_storage_by_locality(self, locality: Locality) -> Optional[Storage]:
        return self._storage_by_locality.get(locality)

    def _node(self, nid: str) -> Node:
        node = self._hosts.get(nid) or self._storage.get(nid)
        if node is None:
            raise KeyError(f"unknown node {nid!r}")
        return node

    def get_route(self, src_id: str, dst_id: str) -> Route:
        """Lazily materialize the directed route between two nodes."""
        key = (str(src_id), str(dst_id))
        route = self._routes.get(key)
        if route is None:
            src, dst = self._node(key[0]), self._node(key[1])
            if self.route_mode == "local" and src.id == dst.id:
                bw = LOCAL_BW
            else:
                bw = self.meta.bw(src.locality, dst.locality)
            if self.route_mode == "meta":
                metered = self.meter
            else:
                host_storage_pair = (
                    isinstance(src, Host) and isinstance(dst, Storage)
                ) or (isinstance(src, Storage) and isinstance(dst, Host))
                metered = self.meter if host_storage_pair else None
            if self.net_engine is not None:
                route = NativeRoute(
                    self.env, src, dst, bw, self.net_engine, meter=metered
                )
            else:
                route = Route(self.env, src, dst, bw, meter=metered)
            self._routes[key] = route
            for hook in self._route_hooks:
                hook(route)
        return route

    def add_route_hook(self, hook) -> None:
        """Register ``hook(route)`` to run on every future lazy route
        materialization (existing routes are the caller's to walk)."""
        self._route_hooks.append(hook)

    # -- lifecycle -------------------------------------------------------
    def clone(
        self,
        env: Environment,
        meter: Optional[Meter],
        seed: Optional[int] = None,
        network_backend: Optional[str] = None,
        executor_backend: Optional[str] = None,
    ) -> "Cluster":
        hosts = [h.clone(env, meter) for h in self._host_list]
        storage = [s.clone(env) for s in self._storage.values()]
        return Cluster(
            env,
            hosts=hosts,
            storage=storage,
            meta=self.meta,
            meter=meter,
            route_mode="meta",
            seed=self.seed if seed is None else seed,
            network_backend=network_backend or self.network_backend,
            executor_backend=executor_backend or self.executor_backend,
        )

    def start(self) -> None:
        self.env.process(self._dispatch_loop())

    def _dispatch_loop(self):
        while True:
            task = yield self.dispatch_q.get()
            # Same-instant batching: items put synchronously with the one
            # just handed off start in FIFO order without paying one
            # get-event round-trip each.
            batch = [task]
            batch.extend(self.dispatch_q.drain())
            if self.executor is not None:
                # One-hop deferral mirroring the process executor's
                # bootstrap events: admission/check-in must get a fresh seq
                # here so same-instant conclusions (older-seq events)
                # release first.  One callback covers the whole batch: the
                # only work that could interleave between per-task
                # bootstraps (URGENT listener resumes on admission failure)
                # touches no state dispatch reads, so batching is exact.
                executor = self.executor
                self.env.schedule_callback(
                    0.0, lambda b=batch: self._dispatch_batch(executor, b)
                )
            else:
                for item in batch:
                    self._dispatch_one(item)

    def _dispatch_batch(self, executor, batch) -> None:
        for task in batch:
            host = self._validate(task)
            if host is not None:
                executor.dispatch(task, host)

    def _validate(self, task) -> Optional[Host]:
        if not isinstance(task, Task):
            self.logger.error("dispatched non-task item: %r", task)
            return None
        host = self._hosts.get(task.placement)
        if host is None:
            self.logger.error("unrecognized host %r", task.placement)
            return None
        return host

    def _dispatch_one(self, task) -> None:
        host = self._validate(task)
        if host is None:
            return
        self.env.process(self._execute_task(task, host))

    def _execute_task(self, task: Task, host: Host):
        # ``yield from`` runs the host's generator inside this process —
        # one Process object per execution instead of two.
        success = yield from host.execute(task)
        self.notify_q.put((success, task))

    # -- dense exports for the decision kernels --------------------------
    def availability_matrix(self, dtype=np.float64) -> np.ndarray:
        """[H, 4] current per-host availability snapshot.

        Down hosts report −1 per dimension: every demand is ≥ 0, so no fit
        test (strict or non-strict) can select them — including zero-demand
        tasks, which a zero row would admit and livelock on a dead host.
        The sentinel is finite so downstream residual/norm arithmetic in
        the f32 kernels stays finite."""
        hosts = self._host_list
        if self._avail_cache is None or len(self._avail_cache) != len(hosts):
            cache = np.empty((len(hosts), 4), dtype=np.float64)
            for i, h in enumerate(hosts):
                h.resource._cache = cache[i]
                h.resource._sync_cache()
            self._avail_cache = cache
        out = self._avail_cache.astype(dtype, copy=True)
        for i, h in enumerate(hosts):
            if not h.up:
                out[i] = -1.0
        return out

    def totals_matrix(self, dtype=np.float64) -> np.ndarray:
        hosts = self._host_list
        out = np.empty((len(hosts), 4), dtype=dtype)
        for i, h in enumerate(hosts):
            r = h.resource
            out[i, 0] = r.t_cpus
            out[i, 1] = r.t_mem
            out[i, 2] = r.t_disk
            out[i, 3] = r.t_gpus
        return out

    def host_zone_vector(self) -> np.ndarray:
        """[H] int32 zone index per host."""
        return self.meta.zone_vector([h.locality for h in self._host_list])

    def storage_zone_vector(self) -> np.ndarray:
        """[S] int32 zone index per storage node."""
        return self.meta.zone_vector([s.locality for s in self.storage])
