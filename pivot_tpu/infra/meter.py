"""Metrics / telemetry subsystem.

Capability parity with the reference ``Meter`` (``resources/meter.py:13-187``):
host busy-interval tracking with merging, per-route per-chunk service logs,
per-task data-transfer records, scheduling-op counts, and the derived
metrics — cumulative instance hours, total network traffic (egress) cost,
average congestion delay — serialized as the same four JSON files
(``general.json`` / ``transfers.json`` / ``scheduler.json`` /
``host_usage.json``, ref ``resources/meter.py:108-133``).

Additions over the reference: wall-clock + decisions/sec counters for the
BENCH harness, ``summary()`` returning everything as a dict without
touching disk, and the serving-grade telemetry primitives behind
``pivot_tpu.serve`` — :class:`StreamingHistogram` (fixed-memory
log-bucketed percentiles) and :class:`SloMeter` (thread-safe decision
latency / queue depth / admission counters with a JSON snapshot).
"""

from __future__ import annotations

import json
import math
import os
import threading
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from pivot_tpu.obs.clock import ObsClock
from pivot_tpu.utils import LogMixin, ceil_bucket, floor_bucket

__all__ = ["Meter", "SloMeter", "StreamingHistogram"]


class Meter(LogMixin):
    def __init__(self, env, meta, clock: Optional[ObsClock] = None):
        self.env = env
        self.meta = meta
        #: The injected obs wall clock (round 14): a run that hands the
        #: SAME clock to its Meter and SloMeter gets snapshots that
        #: agree exactly on elapsed wall time — before, each kept a
        #: private perf_counter start and disagreed by construction.
        self.clock = clock or ObsClock()
        # host -> list of [start] / [start, end] busy intervals
        self._host_intervals: Dict[object, List[list]] = defaultdict(list)
        # route -> transfer key -> list of [start, end, chunk_mb] service
        # slots; keys are whatever ``route_check_in`` was handed (the Python
        # fabric passes Transfer objects, identity-keyed).
        self._route_slots: Dict[object, Dict[object, List[list]]] = defaultdict(dict)
        # host -> [(t, cpu_frac, mem_frac, disk_frac, gpu_frac)]
        self._usage: Dict[object, list] = defaultdict(list)
        self._data_transfers: List[dict] = []
        self._sched_turnovers: List[float] = []
        self._n_sched_ops = 0
        # Wasted sim-seconds of aborted executions (host crashes, spot
        # preemptions, proactive evictions) — the rework half of the
        # spot-survival cost accounting.  Always inside some billed busy
        # interval, so rework is a breakdown of instance-hours, never an
        # addition to them (audit_meter checks exactly that).
        self._rework_s = 0.0
        # Native network engines whose per-route stats replace per-slot
        # logs (``NativeNetworkEngine.metered_route_stats``).
        self._native_sources: List[object] = []

    def add_native_source(self, engine) -> None:
        self._native_sources.append(engine)

    def _native_stats(self):
        for engine in self._native_sources:
            yield from engine.metered_route_stats()

    # -- derived metrics -------------------------------------------------
    @property
    def runtime(self) -> float:
        return self.env.now

    @property
    def wall_clock(self) -> float:
        return self.clock.elapsed()

    @property
    def total_scheduling_ops(self) -> int:
        return self._n_sched_ops

    @property
    def cumulative_instance_hours(self) -> float:
        total = 0.0
        for intervals in self._host_intervals.values():
            for iv in intervals:
                if len(iv) == 2:
                    total += iv[1] - iv[0]
        return total / 3600.0

    @property
    def total_network_traffic_cost(self) -> float:
        """$ egress over all metered routes (ref ``meter.py:34-41``)."""
        cost = 0.0
        for route, transfers in self._route_slots.items():
            size = sum(
                slot[2]
                for slots in transfers.values()
                for slot in slots
                if len(slot) == 3
            )
            cost += self.meta.calc_network_traffic_cost(
                route.src.locality, route.dst.locality, size
            )
        for route, served_mb, _n, _gap in self._native_stats():
            cost += self.meta.calc_network_traffic_cost(
                route.src.locality, route.dst.locality, served_mb
            )
        return cost

    @property
    def average_congestion_delay(self) -> float:
        """Mean gap between consecutive service slots of a transfer."""
        delay, n = 0.0, 0
        for transfers in self._route_slots.values():
            n += len(transfers)
            for slots in transfers.values():
                for i in range(1, len(slots)):
                    delay += slots[i][0] - slots[i - 1][1]
        for _route, _mb, n_transfers, gap_sum in self._native_stats():
            n += n_transfers
            delay += gap_sum
        return delay / n if n else 0.0

    # -- recording hooks -------------------------------------------------
    def host_check_in(self, host) -> None:
        intervals = self._host_intervals[host]
        self._track_resource_usage(host)
        now = self.env.now
        last = intervals[-1] if intervals else None
        if last is None:
            intervals.append([now])
        elif len(last) == 2:
            if now > last[-1]:
                intervals.append([now])
            else:
                last.pop()  # reopen the touching interval (merge)

    def host_check_out(self, host) -> None:
        intervals = self._host_intervals[host]
        self._track_resource_usage(host)
        now = self.env.now
        if not intervals:
            raise RuntimeError("host check-out before any check-in")
        last = intervals[-1]
        if len(last) == 1:
            last.append(now)
        elif now > last[-1]:
            last[-1] = now

    def route_check_in(self, route, transfer) -> None:
        """``transfer`` is any per-transfer key — the Python fabric passes
        the Transfer object itself (identity-keyed: cheaper than minting
        id strings on the chunk-service hot path)."""
        self._route_slots[route].setdefault(transfer, []).append([self.env.now])

    def route_check_out(self, route, transfer, chunk_mb: float) -> None:
        self._route_slots[route][transfer][-1] += [self.env.now, chunk_mb]

    def add_data_transfer(
        self,
        timepoint: float,
        sources,
        dst,
        data_amt: float,
        total_delay: float,
        prop_delay: float,
        avg_bw: float,
        avg_egress_cost: float,
    ) -> None:
        self._data_transfers.append(
            {
                "timestamp": timepoint,
                "from": [[s.cloud, s.region, s.zone] for s in sources],
                "to": [dst.cloud, dst.region, dst.zone],
                "data_amt": data_amt,
                "total_delay": total_delay,
                "propagation_delay": prop_delay,
                "avg_bw": avg_bw,
                "avg_egress_cost": avg_egress_cost,
            }
        )

    def add_scheduling_turnover(self, latency: float) -> None:
        """Submit→placement latency of one task, in sim-seconds.

        The reference declares this hook but never calls it
        (``resources/meter.py:102-103``); here the global scheduler feeds
        it on every successful placement (wait-queue residency included),
        making it a live scheduling-latency / starvation metric."""
        self._sched_turnovers.append(latency)

    def increment_scheduling_ops(self, n_ops: int) -> None:
        self._n_sched_ops += n_ops

    def add_rework(self, seconds: float) -> None:
        """Sim-seconds of work an aborted execution wasted (staging +
        compute since its admission) — fed by every abort path (crash,
        spot abort, proactive eviction), both executor backends.
        Accumulated unclamped: a negative delta is an accounting bug, and
        ``audit_meter``'s negative-rework check is what must catch it."""
        self._rework_s += float(seconds)

    @property
    def rework_seconds(self) -> float:
        """Total wasted compute-seconds across aborted executions."""
        return self._rework_s

    _USAGE_DIMS = {"cpus": 1, "mem": 2, "disk": 3, "gpus": 4}

    def _track_resource_usage(self, host) -> None:
        r = host.resource
        self._usage[host].append(
            (
                self.env.now,
                (r.t_cpus - r.cpus) / r.t_cpus if r.t_cpus else 0.0,
                (r.t_mem - r.mem) / r.t_mem if r.t_mem else 0.0,
                (r.t_disk - r.disk) / r.t_disk if r.t_disk else 0.0,
                (r.t_gpus - r.gpus) / r.t_gpus if r.t_gpus else 0.0,
            )
        )

    # -- aggregation / persistence ---------------------------------------
    def host_usage_curve(self, sample_size: float = 100.0):
        """Time-bucketed count of busy hosts (ref ``plot_host_usage``)."""
        counter: Dict[tuple, set] = {}
        for host, intervals in self._host_intervals.items():
            for iv in intervals:
                if len(iv) != 2:
                    continue
                start = floor_bucket(iv[0], sample_size)
                end = ceil_bucket(iv[1], sample_size)
                cur = min(start + sample_size, end)
                while cur < end:
                    counter.setdefault((cur - sample_size, cur), set()).add(host)
                    cur += sample_size
        x = sorted(counter)
        return x, [len(counter[k]) for k in x]

    def resource_usage_curve(self, resource: str, sample_size: float = 100.0):
        """Time-bucketed mean normalized utilization of one dimension."""
        dim = self._USAGE_DIMS[resource]
        counter: Dict[float, Dict[object, list]] = {}
        for host, recs in self._usage.items():
            for rec in recs:
                counter.setdefault(floor_bucket(rec[0], sample_size), {}).setdefault(
                    host, []
                ).append(rec[dim])
        x = sorted(counter)
        y = [
            float(np.mean([np.mean(v) for v in counter[t].values()])) for t in x
        ]
        return x, y

    def avg_host_usage(self, sample_size: float = 100.0) -> float:
        _, counts = self.host_usage_curve(sample_size)
        return float(np.mean(counts)) if counts else 0.0

    def avg_resource_usage(self, resource: str, sample_size: float = 100.0) -> float:
        _, vals = self.resource_usage_curve(resource, sample_size)
        return float(np.mean(vals)) if vals else 0.0

    def summary(self) -> dict:
        return {
            "egress_cost": self.total_network_traffic_cost,
            "cum_instance_hours": self.cumulative_instance_hours,
            "rework_seconds": self._rework_s,
            "avg_congestion_delay": self.average_congestion_delay,
            "total_scheduling_ops": self._n_sched_ops,
            "avg_scheduling_turnover": self.average_scheduling_turnover,
            "sim_time": self.runtime,
            "wall_clock": self.wall_clock,
        }

    @property
    def average_scheduling_turnover(self) -> float:
        """Mean submit→placement latency (sim-seconds) across placements."""
        if not self._sched_turnovers:
            return 0.0
        return float(np.mean(self._sched_turnovers))

    def publish_metrics(self, registry, run: str = "default") -> None:
        """Publish this run's derived metrics into the unified registry
        (``pivot_tpu.obs.MetricsRegistry``), labeled by run — the batch
        half of the one-snapshot-shape contract (``SloMeter
        .publish_metrics`` is the serving half)."""
        g = [
            ("pivot_run_egress_cost_dollars",
             "total network egress cost over metered routes",
             self.total_network_traffic_cost),
            ("pivot_run_instance_hours",
             "cumulative billed instance hours",
             self.cumulative_instance_hours),
            ("pivot_run_rework_seconds",
             "sim-seconds of aborted-execution rework",
             self._rework_s),
            ("pivot_run_sim_seconds", "simulated time", self.runtime),
            ("pivot_run_wall_seconds",
             "wall seconds on the injected obs clock", self.wall_clock),
            ("pivot_run_avg_scheduling_turnover_seconds",
             "mean submit-to-placement latency (sim-seconds)",
             self.average_scheduling_turnover),
        ]
        for name, help_text, value in g:
            registry.gauge(name, help_text, labelnames=("run",))
            registry.set(name, value, run=run)
        registry.counter(
            "pivot_run_scheduling_ops_total",
            "placement decisions considered by the tick loop",
            labelnames=("run",),
        )
        registry.set(
            "pivot_run_scheduling_ops_total", self._n_sched_ops, run=run
        )

    def save(self, data_dir: str) -> None:
        """Write the reference-compatible four-file JSON layout."""
        os.makedirs(data_dir, exist_ok=True)
        with open(os.path.join(data_dir, "general.json"), "w") as f:
            json.dump(
                {
                    "egress_cost": self.total_network_traffic_cost,
                    "cum_instance_hours": self.cumulative_instance_hours,
                    "avg_scheduling_turnover": self.average_scheduling_turnover,
                },
                f,
            )
        with open(os.path.join(data_dir, "transfers.json"), "w") as f:
            json.dump(self._data_transfers, f)
        with open(os.path.join(data_dir, "scheduler.json"), "w") as f:
            json.dump(
                {
                    "turnovers": self._sched_turnovers,
                    "total_scheduling_ops": self._n_sched_ops,
                },
                f,
            )
        with open(os.path.join(data_dir, "host_usage.json"), "w") as f:
            x, y = self.host_usage_curve()
            json.dump({"timestamps": x, "n_hosts": y}, f)


class StreamingHistogram:
    """Fixed-memory log-bucketed histogram for unbounded value streams.

    The serving layer records one decision latency per scheduler tick and
    one queue-depth sample per arrival for the lifetime of the process —
    an always-on service cannot keep the raw samples the way
    ``Meter._sched_turnovers`` does for a finite batch run.  Geometric
    buckets (``bins_per_decade`` per power of ten between ``lo`` and
    ``hi``) give percentile estimates with bounded relative error
    (~``10^(1/bins_per_decade) − 1``, <4 % at the default 64) in O(1)
    memory and O(1) per record.

    Values below ``lo`` clamp into the first bucket, values above ``hi``
    into the last; exact ``min``/``max``/``sum``/``count`` moments are
    tracked alongside, so the snapshot's mean and extremes are exact even
    where the percentiles are bucketed.  Not thread-safe on its own —
    :class:`SloMeter` serializes access.
    """

    __slots__ = ("lo", "hi", "_scale", "_counts", "count", "_sum",
                 "_min", "_max")

    def __init__(
        self, lo: float = 1e-6, hi: float = 1e4, bins_per_decade: int = 64
    ):
        if not (0 < lo < hi):
            raise ValueError("need 0 < lo < hi")
        self.lo = lo
        self.hi = hi
        self._scale = bins_per_decade
        n = int(math.ceil((math.log10(hi) - math.log10(lo)) * bins_per_decade))
        self._counts = np.zeros(n + 1, dtype=np.int64)
        self.count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def record(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self._sum += v
        self._min = min(self._min, v)
        self._max = max(self._max, v)
        if v <= self.lo:
            idx = 0
        else:
            idx = int((math.log10(v) - math.log10(self.lo)) * self._scale) + 1
            idx = min(idx, len(self._counts) - 1)
        self._counts[idx] += 1

    def percentile(self, q: float) -> float:
        """Upper-edge estimate of the ``q``-th percentile (0 < q ≤ 100)."""
        if self.count == 0:
            return 0.0
        rank = max(int(math.ceil(q / 100.0 * self.count)), 1)
        cum = np.cumsum(self._counts)
        idx = int(np.searchsorted(cum, rank))
        if idx == 0:
            return min(self.lo, self._max)
        edge = self.lo * 10 ** (idx / self._scale)
        # An edge cannot overstate the true max (exactly tracked).
        return min(edge, self._max)

    def baseline(self) -> np.ndarray:
        """Bucket-count snapshot for windowed percentile queries — pair
        with :meth:`percentile_since`.  The autoscaler's breach detector
        needs *recent* latency, not lifetime latency: a service that ran
        calm for an hour would otherwise drown a fresh SLO breach in old
        samples."""
        return self._counts.copy()

    def percentile_since(self, baseline: np.ndarray, q: float) -> float:
        """Upper-edge ``q``-th percentile of the samples recorded since
        ``baseline`` was taken (0.0 when the window is empty).  The
        window's true max is unknown, so the estimate is the raw bucket
        edge — still bounded-relative-error."""
        delta = self._counts - baseline
        total = int(delta.sum())
        if total <= 0:
            return 0.0
        rank = max(int(math.ceil(q / 100.0 * total)), 1)
        idx = int(np.searchsorted(np.cumsum(delta), rank))
        if idx == 0:
            return self.lo
        return self.lo * 10 ** (idx / self._scale)

    @property
    def mean(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": int(self.count),
            "mean": self.mean,
            "min": self._min,
            "max": self._max,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }


class SloMeter(LogMixin):
    """Serving-grade telemetry for the online layer (``pivot_tpu.serve``).

    The batch :class:`Meter` is per-run and sim-time-centric; this meter
    is per-*service* and wall-clock-centric: decision latency (the wall
    duration of each placement call, batcher wait included), admission
    queue depth at each arrival, and admission-control counters
    (admitted / shed-by-reason / spilled / blocked / late injections).
    All hooks are thread-safe — sessions and the stream driver record
    concurrently.  :meth:`snapshot` exports everything JSON-ready.
    """

    #: Counter keys always present in the snapshot (tests rely on these).
    #: The round-7 self-healing keys: ``failed_jobs`` (dead-lettered
    #: applications reaped by a session), ``session_restarts`` /
    #: ``requeued`` (supervisor recoveries and the in-flight jobs they
    #: re-admitted), ``kernel_failures`` / ``degraded_decisions`` (device
    #: kernel faults absorbed by CPU-twin degradation).  Round-9
    #: multi-tenant keys: ``preempted`` / ``preempt_requeued`` (in-queue
    #: preemptions and their spill re-entries), ``preempt_requests`` /
    #: ``preempt_misses`` (attempts and already-placed refusals),
    #: ``scale_up_events`` / ``scale_down_events`` (autoscaler actions).
    COUNTERS = (
        "arrived", "admitted", "completed", "shed", "spilled",
        "blocked_waits", "late_injections", "decisions", "placed",
        "failed_jobs", "session_restarts", "requeued",
        "kernel_failures", "degraded_decisions",
        "preempted", "preempt_requeued", "preempt_requests",
        "preempt_misses", "scale_up_events", "scale_down_events",
        # Round-17 fused serve spans (``fuse_spans="slo"``): whole-span
        # dispatches and the simulator ticks they covered — one
        # decision-latency sample per span (the SLO-checkpoint
        # contract), span lengths in the ``span_length`` histogram.
        "span_dispatches", "span_ticks",
    )

    #: The dispatch-path mix section of the snapshot mirrors the
    #: ``DispatchBatcher.stats`` documented key set (the ``stats_out``
    #: contract of ``run_grid_lockstep`` — ``sched/batch.py``), so bench
    #: rows and soak reports can attribute how placement calls reached
    #: the device: coalesced flushes vs the single-live-slot fast path.
    DISPATCH_KEYS = (
        "runs", "dispatches", "device_calls", "coalesced", "max_group",
        "deadline_flushes", "single_fast_path", "mesh_dispatches",
        "mesh_fallbacks", "mesh_fallback_unshardable",
        "mesh_fallback_mixed_shapes", "mesh_fallback_indivisible",
        "ragged_merges", "ragged_rows", "ragged_pad_cells",
        "respawns", "retired_slots",
    )

    #: Per-tier counter keys (each tier's section of the snapshot).
    TIER_COUNTERS = (
        "arrived", "admitted", "completed", "failed_jobs", "shed",
        "spilled", "preempted", "decisions",
    )

    def __init__(self, clock: Optional[ObsClock] = None):
        self._lock = threading.Lock()
        #: Injected obs wall clock (round 14) — share one instance with
        #: the run's :class:`Meter` and the two snapshots agree exactly
        #: on elapsed wall time (they used to keep duplicate private
        #: ``perf_counter`` starts).
        self.clock = clock or ObsClock()
        self.counters: Dict[str, int] = {k: 0 for k in self.COUNTERS}
        self.shed_reasons: Dict[str, int] = {}
        # Wall seconds per placement call (decision latency SLO).
        self.decision_latency = StreamingHistogram(1e-6, 1e4)
        # Admitted-but-incomplete jobs at each arrival instant.
        self.queue_depth = StreamingHistogram(1.0, 1e7, bins_per_decade=32)
        # Ticks per fused serve span (``fuse_spans="slo"``): how much
        # simulator time each one-latency-sample dispatch covered.
        self.span_length = StreamingHistogram(1.0, 1e4, bins_per_decade=32)
        # Sim-time job sojourn: admission timestamp -> app completion.
        self.sojourn_sim = StreamingHistogram(1e-3, 1e9, bins_per_decade=32)
        #: Per-tier telemetry, lazily created on first record for a tier
        #: (single-tenant services never allocate any).  Each entry:
        #: counters dict + shed reasons + decision-latency / sojourn
        #: histograms, serialized under ``snapshot()["tiers"]``.
        self._tiers: Dict[int, dict] = {}
        #: Live reference to the serving batcher's stats dict (attached
        #: by ``ServeDriver.run``); ``None`` snapshots as all-zero.
        self._dispatch_stats: Optional[dict] = None

    def _tier(self, tier: int) -> dict:
        """Per-tier slot (lock held by caller)."""
        t = self._tiers.get(tier)
        if t is None:
            t = {
                "counters": {k: 0 for k in self.TIER_COUNTERS},
                "shed_reasons": {},
                "decision_latency": StreamingHistogram(1e-6, 1e4),
                "sojourn_sim": StreamingHistogram(
                    1e-3, 1e9, bins_per_decade=32
                ),
            }
            self._tiers[tier] = t
        return t

    def attach_dispatch_stats(self, stats: dict) -> None:
        """Point the snapshot's ``dispatch`` section at the live
        ``DispatchBatcher.stats`` dict (the documented key set) so soak
        reports and bench rows carry the dispatch-path mix — notably
        ``single_fast_path``, which tells a reader whether decisions
        were coalesced across sessions or served same-thread."""
        with self._lock:
            self._dispatch_stats = stats

    def count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def count_tier(self, tier: int, key: str, n: int = 1) -> None:
        """Per-tier counter (also bumps nothing globally — call
        :meth:`count` separately when a key exists at both scopes)."""
        with self._lock:
            c = self._tier(tier)["counters"]
            c[key] = c.get(key, 0) + n

    def record_shed(self, reason: str, tier: Optional[int] = None) -> None:
        with self._lock:
            self.counters["shed"] += 1
            self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
            if tier is not None:
                t = self._tier(tier)
                t["counters"]["shed"] += 1
                t["shed_reasons"][reason] = (
                    t["shed_reasons"].get(reason, 0) + 1
                )

    def record_decision(self, wall_s: float, n_tasks: int,
                        n_placed: int) -> None:
        """One placement call: wall latency + batch size + placements."""
        with self._lock:
            self.decision_latency.record(wall_s)
            self.counters["decisions"] += n_tasks
            self.counters["placed"] += n_placed

    def record_span_decision(self, wall_s: float, n_ticks: int,
                             n_tasks: int, n_placed: int) -> None:
        """One fused serve span (``fuse_spans="slo"``): the whole span
        is ONE decision-latency sample — the latency an admitted job
        actually experienced at the dispatch boundary — with the span
        length recorded separately so a reader can tell a 1-tick
        dispatch from a 32-tick one (the snapshot's ``span_length``
        section).  ``n_tasks`` counts the span's unique slots."""
        with self._lock:
            self.decision_latency.record(wall_s)
            self.span_length.record(max(n_ticks, 1))
            self.counters["span_dispatches"] += 1
            self.counters["span_ticks"] += n_ticks
            self.counters["decisions"] += n_tasks
            self.counters["placed"] += n_placed

    def record_decision_tier(self, tier: int, wall_s: float,
                             n_tasks: int = 0) -> None:
        """Attribute one placement call's wall latency to ``tier`` —
        called once per tier *present in the decided batch*, so a tier's
        histogram measures the latency its work actually experienced
        (mixed-tier batches count toward every tier they carried)."""
        with self._lock:
            t = self._tier(tier)
            t["decision_latency"].record(wall_s)
            t["counters"]["decisions"] += n_tasks

    def record_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth.record(depth)

    def record_sojourn(self, sim_s: float, tier: Optional[int] = None) -> None:
        with self._lock:
            self.sojourn_sim.record(sim_s)
            if tier is not None:
                self._tier(tier)["sojourn_sim"].record(sim_s)

    def tier_decision_baseline(self, tier: int) -> "np.ndarray":
        """Windowed-percentile baseline for ``tier``'s decision-latency
        histogram (see :meth:`StreamingHistogram.baseline`)."""
        with self._lock:
            return self._tier(tier)["decision_latency"].baseline()

    def tier_decision_p99_since(self, tier: int, baseline) -> float:
        """p99 decision latency of ``tier``'s samples since ``baseline``
        (0.0 for an empty window) — the autoscaler's breach signal."""
        with self._lock:
            return self._tier(tier)["decision_latency"].percentile_since(
                baseline, 99
            )

    def tier_counter(self, tier: int, key: str) -> int:
        with self._lock:
            t = self._tiers.get(tier)
            return 0 if t is None else t["counters"].get(key, 0)

    @property
    def wall_clock(self) -> float:
        return self.clock.elapsed()

    def snapshot(self) -> dict:
        """JSON-ready view of the service's SLO state at this instant."""
        with self._lock:
            stats = self._dispatch_stats or {}
            return {
                "wall_s": round(self.wall_clock, 4),
                "counters": dict(self.counters),
                "shed_reasons": dict(self.shed_reasons),
                "decision_latency_s": self.decision_latency.snapshot(),
                "queue_depth": self.queue_depth.snapshot(),
                "span_length": self.span_length.snapshot(),
                "sojourn_sim_s": self.sojourn_sim.snapshot(),
                # The documented DispatchBatcher stats key set, zeros
                # when the service never engaged a batcher — fixed
                # schema either way (tests assert it).
                "dispatch": {
                    k: int(stats.get(k, 0)) for k in self.DISPATCH_KEYS
                },
                "tiers": {
                    str(tier): {
                        "counters": dict(t["counters"]),
                        "shed_reasons": dict(t["shed_reasons"]),
                        "decision_latency_s": t["decision_latency"].snapshot(),
                        "sojourn_sim_s": t["sojourn_sim"].snapshot(),
                    }
                    for tier, t in sorted(self._tiers.items())
                },
            }

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=2)
        os.replace(tmp, path)

    @staticmethod
    def _publish_hist(registry, name: str, help_text: str,
                      hist: StreamingHistogram, **labels) -> None:
        registry.summary(name, help_text,
                         labelnames=tuple(sorted(labels)))
        registry.observe_summary(
            name,
            count=hist.count,
            total=hist._sum,
            quantiles={
                0.5: hist.percentile(50),
                0.95: hist.percentile(95),
                0.99: hist.percentile(99),
            },
            **labels,
        )

    def publish_metrics(self, registry) -> None:
        """Publish the service's SLO state into the unified registry
        (``pivot_tpu.obs.MetricsRegistry``) — counters, shed reasons,
        per-tier counters, the three latency/depth distributions as
        summaries, and the dispatch-path mix.  Idempotent (set-style):
        republishing a later snapshot overwrites, never double-counts.
        One snapshot shape for every consumer instead of five."""
        with self._lock:
            counters = dict(self.counters)
            shed = dict(self.shed_reasons)
            tiers = {
                tier: dict(t["counters"])
                for tier, t in sorted(self._tiers.items())
            }
            stats = dict(self._dispatch_stats or {})
        registry.counter(
            "pivot_serve_events_total",
            "admission/serve lifecycle counters "
            "(SloMeter.COUNTERS key set)",
            labelnames=("event",),
        )
        for key, value in counters.items():
            registry.set("pivot_serve_events_total", value, event=key)
        registry.counter(
            "pivot_serve_shed_total",
            "jobs shed, by recorded reason",
            labelnames=("reason",),
        )
        for reason, value in shed.items():
            registry.set("pivot_serve_shed_total", value, reason=reason)
        registry.counter(
            "pivot_serve_tier_events_total",
            "per-tier lifecycle counters (SloMeter.TIER_COUNTERS)",
            labelnames=("event", "tier"),
        )
        for tier, tc in tiers.items():
            for key, value in tc.items():
                registry.set(
                    "pivot_serve_tier_events_total", value,
                    event=key, tier=tier,
                )
        registry.counter(
            "pivot_serve_dispatch_total",
            "dispatch-path mix (DispatchBatcher documented stats keys)",
            labelnames=("key",),
        )
        for key in self.DISPATCH_KEYS:
            registry.set(
                "pivot_serve_dispatch_total", int(stats.get(key, 0)),
                key=key,
            )
        self._publish_hist(
            registry, "pivot_serve_decision_latency_seconds",
            "wall latency of each placement call (batcher wait "
            "included)", self.decision_latency,
        )
        self._publish_hist(
            registry, "pivot_serve_queue_depth",
            "admitted-but-incomplete jobs at each arrival",
            self.queue_depth,
        )
        self._publish_hist(
            registry, "pivot_serve_sojourn_sim_seconds",
            "admission-to-completion sim-time sojourn per job",
            self.sojourn_sim,
        )
        registry.gauge(
            "pivot_serve_wall_seconds",
            "service wall clock on the injected obs clock",
        )
        registry.set("pivot_serve_wall_seconds", self.wall_clock)
