"""Metrics / telemetry subsystem.

Capability parity with the reference ``Meter`` (``resources/meter.py:13-187``):
host busy-interval tracking with merging, per-route per-chunk service logs,
per-task data-transfer records, scheduling-op counts, and the derived
metrics — cumulative instance hours, total network traffic (egress) cost,
average congestion delay — serialized as the same four JSON files
(``general.json`` / ``transfers.json`` / ``scheduler.json`` /
``host_usage.json``, ref ``resources/meter.py:108-133``).

Additions over the reference: wall-clock + decisions/sec counters for the
BENCH harness, and ``summary()`` returning everything as a dict without
touching disk.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from typing import Dict, List

import numpy as np

from pivot_tpu.utils import LogMixin, ceil_bucket, floor_bucket

__all__ = ["Meter"]


class Meter(LogMixin):
    def __init__(self, env, meta):
        self.env = env
        self.meta = meta
        # host -> list of [start] / [start, end] busy intervals
        self._host_intervals: Dict[object, List[list]] = defaultdict(list)
        # route -> transfer key -> list of [start, end, chunk_mb] service
        # slots; keys are whatever ``route_check_in`` was handed (the Python
        # fabric passes Transfer objects, identity-keyed).
        self._route_slots: Dict[object, Dict[object, List[list]]] = defaultdict(dict)
        # host -> [(t, cpu_frac, mem_frac, disk_frac, gpu_frac)]
        self._usage: Dict[object, list] = defaultdict(list)
        self._data_transfers: List[dict] = []
        self._sched_turnovers: List[float] = []
        self._n_sched_ops = 0
        # Native network engines whose per-route stats replace per-slot
        # logs (``NativeNetworkEngine.metered_route_stats``).
        self._native_sources: List[object] = []
        self._wall_start = time.perf_counter()

    def add_native_source(self, engine) -> None:
        self._native_sources.append(engine)

    def _native_stats(self):
        for engine in self._native_sources:
            yield from engine.metered_route_stats()

    # -- derived metrics -------------------------------------------------
    @property
    def runtime(self) -> float:
        return self.env.now

    @property
    def wall_clock(self) -> float:
        return time.perf_counter() - self._wall_start

    @property
    def total_scheduling_ops(self) -> int:
        return self._n_sched_ops

    @property
    def cumulative_instance_hours(self) -> float:
        total = 0.0
        for intervals in self._host_intervals.values():
            for iv in intervals:
                if len(iv) == 2:
                    total += iv[1] - iv[0]
        return total / 3600.0

    @property
    def total_network_traffic_cost(self) -> float:
        """$ egress over all metered routes (ref ``meter.py:34-41``)."""
        cost = 0.0
        for route, transfers in self._route_slots.items():
            size = sum(
                slot[2]
                for slots in transfers.values()
                for slot in slots
                if len(slot) == 3
            )
            cost += self.meta.calc_network_traffic_cost(
                route.src.locality, route.dst.locality, size
            )
        for route, served_mb, _n, _gap in self._native_stats():
            cost += self.meta.calc_network_traffic_cost(
                route.src.locality, route.dst.locality, served_mb
            )
        return cost

    @property
    def average_congestion_delay(self) -> float:
        """Mean gap between consecutive service slots of a transfer."""
        delay, n = 0.0, 0
        for transfers in self._route_slots.values():
            n += len(transfers)
            for slots in transfers.values():
                for i in range(1, len(slots)):
                    delay += slots[i][0] - slots[i - 1][1]
        for _route, _mb, n_transfers, gap_sum in self._native_stats():
            n += n_transfers
            delay += gap_sum
        return delay / n if n else 0.0

    # -- recording hooks -------------------------------------------------
    def host_check_in(self, host) -> None:
        intervals = self._host_intervals[host]
        self._track_resource_usage(host)
        now = self.env.now
        last = intervals[-1] if intervals else None
        if last is None:
            intervals.append([now])
        elif len(last) == 2:
            if now > last[-1]:
                intervals.append([now])
            else:
                last.pop()  # reopen the touching interval (merge)

    def host_check_out(self, host) -> None:
        intervals = self._host_intervals[host]
        self._track_resource_usage(host)
        now = self.env.now
        if not intervals:
            raise RuntimeError("host check-out before any check-in")
        last = intervals[-1]
        if len(last) == 1:
            last.append(now)
        elif now > last[-1]:
            last[-1] = now

    def route_check_in(self, route, transfer) -> None:
        """``transfer`` is any per-transfer key — the Python fabric passes
        the Transfer object itself (identity-keyed: cheaper than minting
        id strings on the chunk-service hot path)."""
        self._route_slots[route].setdefault(transfer, []).append([self.env.now])

    def route_check_out(self, route, transfer, chunk_mb: float) -> None:
        self._route_slots[route][transfer][-1] += [self.env.now, chunk_mb]

    def add_data_transfer(
        self,
        timepoint: float,
        sources,
        dst,
        data_amt: float,
        total_delay: float,
        prop_delay: float,
        avg_bw: float,
        avg_egress_cost: float,
    ) -> None:
        self._data_transfers.append(
            {
                "timestamp": timepoint,
                "from": [[s.cloud, s.region, s.zone] for s in sources],
                "to": [dst.cloud, dst.region, dst.zone],
                "data_amt": data_amt,
                "total_delay": total_delay,
                "propagation_delay": prop_delay,
                "avg_bw": avg_bw,
                "avg_egress_cost": avg_egress_cost,
            }
        )

    def add_scheduling_turnover(self, latency: float) -> None:
        """Submit→placement latency of one task, in sim-seconds.

        The reference declares this hook but never calls it
        (``resources/meter.py:102-103``); here the global scheduler feeds
        it on every successful placement (wait-queue residency included),
        making it a live scheduling-latency / starvation metric."""
        self._sched_turnovers.append(latency)

    def increment_scheduling_ops(self, n_ops: int) -> None:
        self._n_sched_ops += n_ops

    _USAGE_DIMS = {"cpus": 1, "mem": 2, "disk": 3, "gpus": 4}

    def _track_resource_usage(self, host) -> None:
        r = host.resource
        self._usage[host].append(
            (
                self.env.now,
                (r.t_cpus - r.cpus) / r.t_cpus if r.t_cpus else 0.0,
                (r.t_mem - r.mem) / r.t_mem if r.t_mem else 0.0,
                (r.t_disk - r.disk) / r.t_disk if r.t_disk else 0.0,
                (r.t_gpus - r.gpus) / r.t_gpus if r.t_gpus else 0.0,
            )
        )

    # -- aggregation / persistence ---------------------------------------
    def host_usage_curve(self, sample_size: float = 100.0):
        """Time-bucketed count of busy hosts (ref ``plot_host_usage``)."""
        counter: Dict[tuple, set] = {}
        for host, intervals in self._host_intervals.items():
            for iv in intervals:
                if len(iv) != 2:
                    continue
                start = floor_bucket(iv[0], sample_size)
                end = ceil_bucket(iv[1], sample_size)
                cur = min(start + sample_size, end)
                while cur < end:
                    counter.setdefault((cur - sample_size, cur), set()).add(host)
                    cur += sample_size
        x = sorted(counter)
        return x, [len(counter[k]) for k in x]

    def resource_usage_curve(self, resource: str, sample_size: float = 100.0):
        """Time-bucketed mean normalized utilization of one dimension."""
        dim = self._USAGE_DIMS[resource]
        counter: Dict[float, Dict[object, list]] = {}
        for host, recs in self._usage.items():
            for rec in recs:
                counter.setdefault(floor_bucket(rec[0], sample_size), {}).setdefault(
                    host, []
                ).append(rec[dim])
        x = sorted(counter)
        y = [
            float(np.mean([np.mean(v) for v in counter[t].values()])) for t in x
        ]
        return x, y

    def avg_host_usage(self, sample_size: float = 100.0) -> float:
        _, counts = self.host_usage_curve(sample_size)
        return float(np.mean(counts)) if counts else 0.0

    def avg_resource_usage(self, resource: str, sample_size: float = 100.0) -> float:
        _, vals = self.resource_usage_curve(resource, sample_size)
        return float(np.mean(vals)) if vals else 0.0

    def summary(self) -> dict:
        return {
            "egress_cost": self.total_network_traffic_cost,
            "cum_instance_hours": self.cumulative_instance_hours,
            "avg_congestion_delay": self.average_congestion_delay,
            "total_scheduling_ops": self._n_sched_ops,
            "avg_scheduling_turnover": self.average_scheduling_turnover,
            "sim_time": self.runtime,
            "wall_clock": self.wall_clock,
        }

    @property
    def average_scheduling_turnover(self) -> float:
        """Mean submit→placement latency (sim-seconds) across placements."""
        if not self._sched_turnovers:
            return 0.0
        return float(np.mean(self._sched_turnovers))

    def save(self, data_dir: str) -> None:
        """Write the reference-compatible four-file JSON layout."""
        os.makedirs(data_dir, exist_ok=True)
        with open(os.path.join(data_dir, "general.json"), "w") as f:
            json.dump(
                {
                    "egress_cost": self.total_network_traffic_cost,
                    "cum_instance_hours": self.cumulative_instance_hours,
                    "avg_scheduling_turnover": self.average_scheduling_turnover,
                },
                f,
            )
        with open(os.path.join(data_dir, "transfers.json"), "w") as f:
            json.dump(self._data_transfers, f)
        with open(os.path.join(data_dir, "scheduler.json"), "w") as f:
            json.dump(
                {
                    "turnovers": self._sched_turnovers,
                    "total_scheduling_ops": self._n_sched_ops,
                },
                f,
            )
        with open(os.path.join(data_dir, "host_usage.json"), "w") as f:
            x, y = self.host_usage_curve()
            json.dump({"timestamps": x, "n_hosts": y}, f)
